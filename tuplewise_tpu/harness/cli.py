"""L6 — experiment CLI reproducing the paper-style figures/tables.

    python -m tuplewise_tpu.harness.cli variance --scheme repartitioned --n-rounds 4
    python -m tuplewise_tpu.harness.cli tradeoff-rounds --n-reps 200 --out results.jsonl
    python -m tuplewise_tpu.harness.cli tradeoff-pairs
    python -m tuplewise_tpu.harness.cli tradeoff-workers --workers 8 1000 125000
    python -m tuplewise_tpu.harness.cli triplet --n 2000
    python -m tuplewise_tpu.harness.cli train --dataset adult --steps 100
    python -m tuplewise_tpu.harness.cli train --checkpoint ck.npz --resume
    python -m tuplewise_tpu.harness.cli train-triplet --steps 50
    python -m tuplewise_tpu.harness.cli learning --n-workers 128 --repartition-every 25
    python -m tuplewise_tpu.harness.cli replay --n-events 20000 --budget 64
    echo '{"op":"insert","score":1.2,"label":1}' | python -m tuplewise_tpu.harness.cli serve

Each command prints JSON to stdout and can append JSONL via --out
[SURVEY §2 L6, §5.6]. ``serve`` is the online service loop (JSONL
request/response over stdin/stdout — transport-free so it runs
anywhere; put a socket server in front for network serving); ``replay``
is its benchmark twin (serving/replay.py).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

import numpy as np

from tuplewise_tpu.harness.variance import (
    VarianceConfig,
    run_variance_experiment,
    tradeoff_vs_pairs,
    tradeoff_vs_rounds,
    tradeoff_vs_workers,
    write_jsonl,
)


def _add_robustness_flags(p: argparse.ArgumentParser) -> None:
    """The batch-path fault-tolerance flags [ISSUE 4], shared by every
    long-running subcommand: checkpoint cadence, explicit resume, and
    deterministic chaos injection."""
    p.add_argument("--checkpoint", type=str, default=None,
                   help="atomic progress checkpoint (.npz); written "
                        "every --checkpoint-every units of progress")
    p.add_argument("--checkpoint-every", type=int, default=None)
    p.add_argument("--resume", action="store_true",
                   help="resume from an existing --checkpoint file "
                        "(bit-identical to the uninterrupted run); "
                        "without this flag a stale checkpoint is "
                        "removed and the run starts fresh")
    p.add_argument("--chaos-spec", type=str, default=None,
                   help="deterministic fault schedule (JSON inline, "
                        "@file, or *.json path) injected into the "
                        "batch-path hook points (train_step / mc_chunk "
                        "/ mesh_mc / checkpoint / estimator; action "
                        "'sigkill' at a checkpoint models preemption)")


def _chaos_from(args):
    spec = getattr(args, "chaos_spec", None)
    if not spec:
        return None
    from tuplewise_tpu.testing.chaos import FaultInjector

    return FaultInjector.from_spec(spec)


def _add_batch_obs_flags(p: argparse.ArgumentParser) -> None:
    """Observability flags for the batch-path subcommands [ISSUE 6]:
    span tracing of chunks/checkpoints and live metric snapshots."""
    p.add_argument("--trace-out", type=str, default=None,
                   help="export the span trace (train.chunk / "
                        "train.checkpoint / heal spans) here: *.jsonl "
                        "= span JSONL, else Chrome trace JSON")
    p.add_argument("--metrics-out", type=str, default=None,
                   help="append periodic registry snapshots (live "
                        "train_step/train_loss_last gauges + recovery "
                        "counters) as JSONL here while training")
    p.add_argument("--metrics-every", type=float, default=1.0,
                   help="seconds between --metrics-out snapshots")


def _batch_obs_from(args):
    """(tracer, registry, flusher) for a batch subcommand — all None
    when the flags are absent. Caller stops the flusher and exports
    the tracer via ``_finish_batch_obs``."""
    tracer = registry = flusher = None
    if getattr(args, "trace_out", None):
        from tuplewise_tpu.obs.tracing import Tracer

        tracer = Tracer()
    if getattr(args, "metrics_out", None):
        from tuplewise_tpu.obs import MetricsFlusher
        from tuplewise_tpu.utils.profiling import MetricsRegistry

        registry = MetricsRegistry()
        flusher = MetricsFlusher(
            registry, args.metrics_out, every_s=args.metrics_every,
            meta={"stage": args.cmd}).start()
    return tracer, registry, flusher


def _finish_batch_obs(args, tracer, flusher) -> None:
    if flusher is not None:
        flusher.stop()
    if tracer is not None:
        if args.trace_out.endswith(".jsonl"):
            tracer.export_jsonl(args.trace_out)
        else:
            tracer.export_chrome(args.trace_out)


def _add_budget_flags(p: argparse.ArgumentParser) -> None:
    """The per-step budget/recording flags shared by the learning and
    train subcommands — one definition, no drift."""
    p.add_argument("--pairs-per-worker", type=int, default=None)
    p.add_argument("--pair-design", default="swr",
                   choices=["swr", "swor", "bernoulli"],
                   help="per-step pair-budget design (ops.device_design)")
    p.add_argument("--loss-every", type=int, default=1,
                   help="record the surrogate loss every k steps; "
                        "0 = loss-free (grad-only kernel off step 0)")


def _add_variance_args(p: argparse.ArgumentParser) -> None:
    for f in dataclasses.fields(VarianceConfig):
        flag = "--" + f.name.replace("_", "-")
        if f.type is int or f.type == "int":
            p.add_argument(flag, type=int, default=f.default)
        elif f.type is float or f.type == "float":
            p.add_argument(flag, type=float, default=f.default)
        else:
            p.add_argument(flag, type=str, default=f.default)


def _cfg_from_args(args) -> VarianceConfig:
    names = {f.name for f in dataclasses.fields(VarianceConfig)}
    return VarianceConfig(
        **{k: v for k, v in vars(args).items() if k in names}
    )


def _emit(results, out):
    if isinstance(results, dict):
        results = [results]
    for r in results:
        print(json.dumps(r))
    if out:
        write_jsonl(results, out)


def _serve_stdin(cfg, chaos=None, obs=None, tenancy=None) -> int:
    """The ``serve`` loop: one JSONL request per stdin line, one JSONL
    response per stdout line (same order); final stats to stderr.

    ``obs`` [ISSUE 6]: the observability argparse namespace — span
    tracing (``--trace-out``), live metrics export (``--metrics-out`` /
    ``--metrics-every``), jax profiling (``--profile-dir``), and the
    flight-recorder dump path (``--flight-out``; with ``--snapshot-dir``
    the engine also auto-dumps next to the snapshots).

    ``tenancy`` [ISSUE 8]: a ``TenancyConfig`` switches the loop onto
    the multi-tenant fleet engine — requests carry a ``"tenant"``
    field (default tenant ``"default"``), admission rejections come
    back typed, and the exit summary gains the fleet block.
    """
    from tuplewise_tpu.obs import MetricsFlusher, service_report
    from tuplewise_tpu.obs.tracing import Tracer
    from tuplewise_tpu.serving import (
        BackpressureError, DeadlineExceededError, EngineClosedError,
        MicroBatchEngine, MultiTenantEngine, PoisonEventError,
        TenantRejectedError, TenantThrottledError,
    )
    from tuplewise_tpu.utils.profiling import trace as _jax_trace

    tracer = Tracer() if obs is not None and obs.trace_out else None
    flusher = None
    slo_monitor = None
    controller = None
    if tenancy is not None:
        engine_cm = MultiTenantEngine(cfg, tenancy, chaos=chaos,
                                      tracer=tracer)
    else:
        engine_cm = MicroBatchEngine(cfg, chaos=chaos, tracer=tracer)
    with engine_cm as eng:
        if obs is not None and getattr(obs, "slo_spec", None):
            # live SLO evaluation [ISSUE 7]: the monitor rides the
            # metrics flusher (observer-only when no --metrics-out)
            from tuplewise_tpu.obs.slo import SloMonitor

            slo_monitor = SloMonitor(
                obs.slo_spec, registry=eng.metrics, flight=eng.flight,
                context=dataclasses.asdict(cfg))
        if obs is not None and getattr(obs, "controller_spec", None):
            # close the loop [ISSUE 11]: the controller actuates on
            # the very signals the SLO monitor judges
            if slo_monitor is None:
                raise SystemExit(
                    "--controller-spec needs --slo-spec: the "
                    "controller rides the SLO monitor's signals")
            from tuplewise_tpu.serving.control import FleetController

            controller = FleetController(
                eng, obs.controller_spec).attach(slo_monitor)
        if obs is not None and (obs.metrics_out
                                or slo_monitor is not None):
            every = obs.metrics_every
            if slo_monitor is not None:
                short = slo_monitor.spec.shortest_window_s
                if short:
                    every = min(every, max(short / 4.0, 0.05))
            flusher = MetricsFlusher(
                eng.metrics, obs.metrics_out or None,
                every_s=every,
                meta={"stage": "serve"}, config=cfg,
                observers=([slo_monitor.observe_row]
                           if slo_monitor is not None else ())).start()
        profiler = None
        if obs is not None and (getattr(obs, "prof", False)
                                or getattr(obs, "prof_out", None)):
            # host-tax sampling profiler [ISSUE 14]: hard-off unless
            # asked for; the overhead guard keeps it <= 5%
            from tuplewise_tpu.obs.prof import SamplingProfiler

            profiler = SamplingProfiler(metrics=eng.metrics).start()
        with _jax_trace(obs.profile_dir if obs is not None else None):
            for line in sys.stdin:
                line = line.strip()
                if not line:
                    continue
                try:
                    req = json.loads(line)
                    op = req["op"]
                    if tenancy is not None:
                        tid = str(req.get("tenant", "default"))
                        if op == "insert":
                            fut = eng.insert(tid, req["score"],
                                             req["label"])
                            resp = {"ok": True, "tenant": tid,
                                    "inserted": int(fut.result(30.0))}
                        elif op == "score":
                            ranks = eng.score(
                                tid, req["score"]).result(30.0)
                            resp = {"ok": True, "tenant": tid,
                                    "rank": [None if np.isnan(r)
                                             else float(r)
                                             for r in np.atleast_1d(
                                                 ranks)]}
                        elif op == "query":
                            snap = eng.query(tid).result(30.0)
                            resp = {"ok": True, "tenant": tid,
                                    "auc_exact": snap.get("auc_exact"),
                                    "estimate_incomplete":
                                        snap.get("estimate_incomplete"),
                                    "state": snap}
                        elif op == "tenants":
                            resp = {"ok": True,
                                    "tenants": eng.fleet.tenants(),
                                    "fleet": eng.fleet.state()}
                        else:
                            resp = {"ok": False,
                                    "error": f"unknown op {op!r}"}
                    elif op == "insert":
                        fut = eng.insert(req["score"], req["label"])
                        resp = {"ok": True,
                                "inserted": int(fut.result(30.0))}
                    elif op == "score":
                        fut = eng.score(req["score"])
                        ranks = fut.result(30.0)
                        resp = {"ok": True,
                                "rank": [None if np.isnan(r) else float(r)
                                         for r in np.atleast_1d(ranks)]}
                    elif op == "query":
                        snap = eng.query().result(30.0)
                        resp = {"ok": True,
                                "auc_exact": snap.get("auc_exact"),
                                "estimate_incomplete":
                                    snap["estimate_incomplete"],
                                "state": snap.get("index")}
                    else:
                        resp = {"ok": False, "error": f"unknown op {op!r}"}
                except TenantThrottledError as e:
                    # control-plane shed [ISSUE 11]: typed, with the
                    # retry hint in the wire protocol — a client can
                    # back off instead of hammering a defending fleet
                    resp = {"ok": False, "tenant": e.tenant,
                            "retry_after_s": e.retry_after_s,
                            "error": f"tenant_throttled: {e}"}
                except TenantRejectedError as e:
                    resp = {"ok": False, "tenant": e.tenant,
                            "error": f"tenant_rejected: {e}"}
                except PoisonEventError as e:
                    resp = {"ok": False, "error": f"poison: {e}"}
                except BackpressureError as e:
                    resp = {"ok": False, "error": f"backpressure: {e}"}
                except DeadlineExceededError as e:
                    resp = {"ok": False, "error": f"deadline: {e}"}
                except EngineClosedError as e:
                    resp = {"ok": False, "error": f"closed: {e}"}
                except (KeyError, ValueError, json.JSONDecodeError) as e:
                    resp = {"ok": False, "error": f"bad request: {e}"}
                print(json.dumps(resp), flush=True)
        if profiler is not None:
            profiler.stop()
        if flusher is not None:
            flusher.stop()
        stats = eng.stats()
        flight = eng.flight
    # dump AFTER close so the file carries engine_closed + the final
    # snapshot's lifecycle events
    if obs is not None and obs.flight_out:
        flight.dump_to(obs.flight_out)
    m = stats["metrics"]
    if tracer is not None:
        if obs.trace_out.endswith(".jsonl"):
            tracer.export_jsonl(obs.trace_out)
        else:
            tracer.export_chrome(obs.trace_out)

    # exit summary: the load-shedding, pause, and recovery numbers an
    # operator greps for first, ahead of the full metrics dump — built
    # by the SAME report builder replay records use [ISSUE 6 satellite]
    summary = service_report(m, chaos=chaos, flight=flight,
                             slo=slo_monitor)
    if controller is not None:
        summary["controller"] = controller.state()
    if profiler is not None:
        from tuplewise_tpu.obs.prof import export_profile

        summary["prof_out"] = export_profile(
            profiler, getattr(obs, "prof_out", None))
        summary["prof_samples"] = profiler.samples
        summary["prof_overhead_fraction"] = profiler.overhead_fraction()
    print(json.dumps({"exit_summary": summary}), file=sys.stderr)
    print(json.dumps({"final_stats": m}), file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tuplewise-harness")
    sub = ap.add_subparsers(dest="cmd", required=True)

    for name in ("variance", "tradeoff-rounds", "tradeoff-pairs",
                 "tradeoff-workers"):
        p = sub.add_parser(name)
        _add_variance_args(p)
        p.add_argument("--out", type=str, default=None)
        if name == "variance":
            _add_robustness_flags(p)
            p.add_argument("--trace-dir", type=str, default=None,
                           help="write a jax.profiler trace here")
        if name == "tradeoff-rounds":
            p.add_argument("--rounds", type=int, nargs="+",
                           default=[1, 2, 4, 8, 16])
        if name == "tradeoff-pairs":
            p.add_argument("--pairs", type=int, nargs="+",
                           default=[100, 1000, 10_000, 100_000])
        if name == "tradeoff-workers":
            p.add_argument("--workers", type=int, nargs="+",
                           default=[2, 8, 32, 128])

    p = sub.add_parser("triplet")
    p.add_argument("--kernel", default="triplet_indicator")
    p.add_argument("--backend", default="jax")
    p.add_argument("--n", type=int, default=2000)
    p.add_argument("--n-pairs", type=int, default=20_000)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", type=str, default=None)
    _add_robustness_flags(p)

    p = sub.add_parser(
        "learning",
        help="one learning-trade-off cell: simulated-N distributed SGD "
             "with Monte-Carlo seeds and held-out AUC curves",
    )
    p.add_argument("--dataset", choices=["gaussians", "adult"],
                   default="gaussians")
    p.add_argument("--kernel", default="hinge")
    p.add_argument("--lr", type=float, default=0.3)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--n-workers", type=int, default=32)
    p.add_argument("--repartition-every", type=int, default=10,
                   help="0 = never repartition")
    _add_budget_flags(p)
    p.add_argument("--n-seeds", type=int, default=8)
    p.add_argument("--eval-every", type=int, default=20)
    p.add_argument("--n", type=int, default=1024,
                   help="gaussians: train rows per class; adult: total")
    p.add_argument("--n-test", type=int, default=8000)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", type=str, default=None)

    p = sub.add_parser("train")
    p.add_argument("--dataset", choices=["gaussians", "adult"],
                   default="adult")
    p.add_argument("--kernel", default="hinge")
    p.add_argument("--lr", type=float, default=0.3)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--n-workers", type=int, default=1)
    p.add_argument("--repartition-every", type=int, default=10,
                   help="0 = never repartition")
    _add_budget_flags(p)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--n", type=int, default=8000)
    p.add_argument("--out", type=str, default=None)
    _add_robustness_flags(p)
    _add_batch_obs_flags(p)

    p = sub.add_parser(
        "train-triplet",
        help="degree-3 metric-learning SGD on synthetic Gaussian "
             "classes (models.triplet_sgd) with the full "
             "checkpoint/resume + chaos robustness surface",
    )
    p.add_argument("--n", type=int, default=512,
                   help="rows per class (anchors/positives vs negatives)")
    p.add_argument("--dim", type=int, default=8)
    p.add_argument("--embed-dim", type=int, default=4)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--n-workers", type=int, default=1)
    p.add_argument("--repartition-every", type=int, default=10)
    p.add_argument("--triplets-per-worker", type=int, default=1024)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", type=str, default=None)
    _add_robustness_flags(p)
    _add_batch_obs_flags(p)

    def _add_serving_flags(p: argparse.ArgumentParser) -> None:
        """ServingConfig knobs shared by serve and replay."""
        p.add_argument("--kernel", default="auc")
        p.add_argument("--budget", type=int, default=64,
                       help="incomplete-U pairs per arrival")
        p.add_argument("--reservoir", type=int, default=4096)
        p.add_argument("--design", default="swr", choices=["swr", "swor"])
        p.add_argument("--window", type=int, default=None,
                       help="sliding window (arrivals); default unbounded")
        p.add_argument("--compact-every", type=int, default=512)
        p.add_argument("--engine", default="jax", choices=["jax", "numpy"],
                       help="exact-index count/compaction engine")
        p.add_argument("--mesh-shards", type=int, default=None,
                       help="shard the exact index's base runs over an "
                            "N-device mesh (per-shard searchsorted + "
                            "psum'd win counts); default single-host")
        p.add_argument("--bg-compact", action="store_true",
                       help="compact the exact index on a side thread "
                            "(double-buffered base run; no sort pause "
                            "on the request path)")
        p.add_argument("--delta-fraction", type=float, default=0.25,
                       help="sharded index delta compaction [ISSUE 5]: "
                            "minor compactions ship O(buffer) delta "
                            "runs and an on-mesh major merge folds "
                            "them into the base once their mass "
                            "exceeds this fraction of it; 0 restores "
                            "the full host-merge + re-placement path")
        p.add_argument("--max-delta-runs", type=int, default=64,
                       help="fold the delta run into the base after "
                            "this many minor compactions merged into "
                            "it, regardless of its size (safety bound;"
                            " --delta-fraction normally rules)")
        p.add_argument("--count-kernel", action="store_true",
                       help="run the count hot loop (searchsorted rank"
                            " of base + delta runs − tombstone "
                            "multiset) as ONE Pallas kernel invocation"
                            " per device per micro-batch [ISSUE 10]; "
                            "bit-identical integer counts, automatic "
                            "XLA fallback on kernel failure. On CPU "
                            "the kernel runs in interpret mode "
                            "(parity, not speed); "
                            "TUPLEWISE_SERVING_PALLAS=interpret|off "
                            "overrides")
        p.add_argument("--max-batch", type=int, default=256)
        p.add_argument("--flush-timeout-ms", type=float, default=2.0)
        p.add_argument("--queue-size", type=int, default=1024)
        p.add_argument("--policy", default="reject",
                       choices=["reject", "drop_oldest", "block"])
        p.add_argument("--deadline-ms", type=float, default=None,
                       help="fail requests older than this at dispatch "
                            "(typed DeadlineExceededError)")
        p.add_argument("--chaos-spec", type=str, default=None,
                       help="deterministic fault schedule (JSON inline, "
                            "@file, or *.json path) injected into the "
                            "serving stack's hook points "
                            "(testing.chaos.FaultInjector)")
        p.add_argument("--snapshot-dir", type=str, default=None,
                       help="crash-safe recovery directory: periodic "
                            "atomic index snapshots + an event-tail WAL")
        p.add_argument("--snapshot-every", type=int, default=4096,
                       help="events between snapshots")
        p.add_argument("--recover", action="store_true",
                       help="restore --snapshot-dir state (snapshot + "
                            "WAL tail) before serving")
        p.add_argument("--wal-fsync", default="snapshot",
                       choices=["snapshot", "batch"],
                       help="WAL durability: 'snapshot' (default) "
                            "flushes per batch and fsyncs only at "
                            "snapshots (survives SIGKILL; power loss "
                            "can drop the tail), 'batch' fsyncs every "
                            "append (closes the power-loss window at "
                            "per-batch latency cost — DESIGN §9)")
        # observability [ISSUE 6]
        p.add_argument("--trace-out", type=str, default=None,
                       help="export the span trace here: *.jsonl = "
                            "span JSONL (scripts/trace_summary.py), "
                            "anything else = Chrome trace-event JSON "
                            "(perfetto / chrome://tracing)")
        p.add_argument("--metrics-out", type=str, default=None,
                       help="append periodic whole-registry metric "
                            "snapshots (JSONL) here while serving, "
                            "e.g. results/metrics.jsonl")
        p.add_argument("--metrics-every", type=float, default=1.0,
                       help="seconds between --metrics-out snapshots")
        p.add_argument("--profile-dir", type=str, default=None,
                       help="bracket the run in a jax.profiler trace "
                            "written here (TensorBoard/perfetto)")
        p.add_argument("--flight-recorder-size", type=int, default=4096,
                       help="lifecycle-event ring capacity (the dump "
                            "lands next to --snapshot-dir snapshots "
                            "and/or at --flight-out)")
        p.add_argument("--flight-out", type=str, default=None,
                       help="dump the flight recorder (JSONL) here on "
                            "exit")
        p.add_argument("--tail-exemplar-ms", type=float, default=None,
                       help="tail exemplars [ISSUE 14]: an insert "
                            "whose measured latency reaches this "
                            "threshold auto-captures its full host-tax"
                            " ledger + trace id as a tail_exemplar "
                            "flight event (p99 forensics in one dump);"
                            " default: never")
        p.add_argument("--prof", action="store_true",
                       help="host-tax sampling profiler [ISSUE 14]: "
                            "periodic folded Python stacks of every "
                            "thread, <= 5%% guarded overhead (the "
                            "sampling interval widens itself past the "
                            "guard); hard-off without this flag")
        p.add_argument("--prof-out", type=str, default=None,
                       help="write the profile here (implies --prof): "
                            "*.collapsed/*.txt = folded stacks "
                            "(flamegraph/speedscope paste), anything "
                            "else = speedscope JSON; digest either "
                            "with scripts/trace_summary.py")
        p.add_argument("--slo-spec", type=str, default=None,
                       help="declarative SLO objectives (JSON inline, "
                            "@file, or *.json — obs.slo spec schema, "
                            "DESIGN §13) evaluated live against the "
                            "metrics snapshots; breaches emit "
                            "slo_breach flight events + slo_* gauges, "
                            "verdicts land in the exit summary / "
                            "replay record. Label wildcards "
                            "(insert_latency_s{tenant=*}) judge each "
                            "tenant of a fleet separately [ISSUE 8]")
        p.add_argument("--controller-spec", type=str, default=None,
                       help="SLO-driven control plane [ISSUE 11]: a "
                            "serving.control.ControllerConfig spec "
                            "(JSON inline, @file, *.json, or '{}' for "
                            "defaults) — a FleetController rides the "
                            "--slo-spec monitor's signals and closes "
                            "the loop: typed per-tenant throttling "
                            "before a breach (TenantThrottledError + "
                            "retry_after_s), flush-window/micro-batch "
                            "widening, DRR weight rebalance, mesh "
                            "grow/shrink, slope-based whale promotion."
                            " Every actuation is hysteretic, rate-"
                            "limited, budgeted, reversible, and flight-"
                            "evented with its triggering signal. "
                            "Requires --slo-spec")
        # multi-tenant fleet [ISSUE 8]
        p.add_argument("--tenants", type=int, default=1,
                       help="replay: synthetic tenants in the generated "
                            "stream (> 1 routes through the "
                            "MultiTenantEngine fleet path); serve: "
                            "ignored — pass --max-tenants instead")
        p.add_argument("--tenant-skew", type=float, default=1.0,
                       help="replay: Zipf exponent of the tenant "
                            "assignment (0 = uniform; 1 = classic "
                            "heavy tail)")
        p.add_argument("--max-tenants", type=int, default=None,
                       help="serve: run the multi-tenant fleet engine "
                            "with this tenant cap; requests carry a "
                            '"tenant" field. replay: fleet tenant cap '
                            "(default 1024)")
        p.add_argument("--tenant-quota", type=int, default=64,
                       help="fleet: max queued requests per tenant "
                            "(admission control; TenantRejectedError "
                            "past it)")
        p.add_argument("--tenant-weight", type=int, default=8,
                       help="fleet: requests per tenant per fair-"
                            "scheduling round (deficit round-robin "
                            "quantum)")
        p.add_argument("--idle-evict-s", type=float, default=None,
                       help="fleet: drop tenants idle longer than this "
                            "(default: never)")
        # incremental fleet hot path [ISSUE 9]
        p.add_argument("--whale-threshold", type=int, default=None,
                       help="fleet: promote a tenant to its own "
                            "delta-tiered ExactAucIndex once its live "
                            "event count reaches this (O(buffer) "
                            "compactions instead of the O(tenant) pack "
                            "splice; demotes on shrink; bit-identical "
                            "either way). Default: never promote")
        p.add_argument("--tenant-metric-cap", type=int, default=None,
                       help="fleet: at most this many tenants get "
                            "their own labeled metric series; later "
                            "tenants collapse into one "
                            "{tenant=__other__} series (bounds the "
                            "registry, MetricsFlusher rows, and SLO "
                            "wildcard fan-out at 100k-tenant scale). "
                            "Default: unbounded")
        p.add_argument("--seed", type=int, default=0)

    p = sub.add_parser(
        "serve",
        help="online service loop: JSONL requests on stdin "
             '({"op":"insert","score":s,"label":l} | {"op":"score",'
             '"score":s} | {"op":"query"}), JSONL responses on stdout',
    )
    _add_serving_flags(p)

    p = sub.add_parser(
        "doctor",
        help="post-hoc diagnosis of a run's observability artifacts "
             "(metrics.jsonl + flight.jsonl + span export): SLO + "
             "statistical-health verdicts, fault->recovery "
             "correlation, top self-time spans; the LAST stdout line "
             "is one machine-readable verdict JSON (exit 0 = "
             "healthy/recovered, 2 = degraded) [ISSUE 7]",
    )
    p.add_argument("--dir", type=str, default=None,
                   help="artifact directory (e.g. a --snapshot-dir "
                        "after SIGKILL): default filenames are probed "
                        "for anything not given explicitly")
    p.add_argument("--metrics", type=str, default=None,
                   help="metrics.jsonl (MetricsFlusher output)")
    p.add_argument("--flight", type=str, default=None,
                   help="flight-recorder dump (flight.jsonl)")
    p.add_argument("--spans", type=str, default=None,
                   help="span export (*.jsonl span JSONL or Chrome "
                        "trace JSON)")
    p.add_argument("--slo-spec", type=str, default=None,
                   help="SLO spec to re-evaluate over the metrics "
                        "history (default: the conservative built-in "
                        "doctor spec — no heal exhaustion, "
                        "availability budget)")
    p.add_argument("--top-spans", type=int, default=10)
    p.add_argument("--out", type=str, default=None,
                   help="also write the full report JSON here")
    p.add_argument("--quiet", action="store_true",
                   help="print only the one-line machine verdict")

    p = sub.add_parser(
        "check",
        help="static invariant checks [ISSUE 12/13]: lock-order/"
             "thread discipline, traced-code purity, telemetry cross-"
             "reference, compile-ladder discipline, config/CLI/doc "
             "drift, import cycles, PLUS the flow-sensitive dataflow "
             "tier — guard-inference race detection across thread "
             "roles and integer-exactness/int32-overflow "
             "certification of the count paths — findings "
             "suppressible only via the committed "
             "analysis/waivers.toml (DESIGN §17); exit 0 = clean "
             "modulo waivers, 1 = unwaived findings",
    )
    p.add_argument("--root", type=str, default=None,
                   help="repo root to analyze (default: the checkout "
                        "this package was imported from)")
    p.add_argument("--waivers", type=str, default=None,
                   help="waiver file (default: "
                        "tuplewise_tpu/analysis/waivers.toml under "
                        "the root)")
    p.add_argument("--json", action="store_true",
                   help="print the full JSON report instead of the "
                        "human summary")
    p.add_argument("--out", type=str, default=None,
                   help="also write the JSON report here (the CI "
                        "artifact)")
    p.add_argument("--strict", action="store_true",
                   help="stale waivers (matching nothing) fail the "
                        "run instead of warning")
    p.add_argument("--no-cache", action="store_true",
                   help="disable the content-sha parse cache "
                        "(.tuplewise_check_cache/) and reparse "
                        "every module [ISSUE 13]")
    p.add_argument("--diff", type=str, default=None, metavar="REF",
                   help="restrict findings to files changed vs this "
                        "git ref plus their reverse-dependency "
                        "closure — the fast pre-commit loop "
                        "(scripts/pre-commit.sh) [ISSUE 15]")
    p.add_argument("--jobs", type=int, default=None,
                   help="run the independent passes in N worker "
                        "processes (default: auto — cpu count, "
                        "serial on <= 2 cores) [ISSUE 15]")

    p = sub.add_parser(
        "replay",
        help="replay a synthetic Gaussian stream through the "
             "micro-batch engine; report events/s + latency percentiles",
    )
    _add_serving_flags(p)
    p.add_argument("--n-events", type=int, default=20_000)
    p.add_argument("--pos-frac", type=float, default=0.5)
    p.add_argument("--separation", type=float, default=1.0)
    p.add_argument("--chunk", type=int, default=1,
                   help="events per insert request (1 = per-event)")
    p.add_argument("--score-every", type=int, default=0)
    p.add_argument("--query-every", type=int, default=0)
    p.add_argument("--out", type=str, default=None)

    args = ap.parse_args(argv)

    if args.cmd == "doctor":
        from tuplewise_tpu.obs.doctor import main as doctor_main

        return doctor_main(args)

    if args.cmd == "check":
        from tuplewise_tpu.analysis.runner import main as check_main

        return check_main(args)

    if args.cmd in ("serve", "replay"):
        from tuplewise_tpu.serving import ServingConfig

        cfg = ServingConfig(
            kernel=args.kernel, budget=args.budget,
            reservoir=args.reservoir, design=args.design,
            window=args.window, compact_every=args.compact_every,
            engine=args.engine, mesh_shards=args.mesh_shards,
            bg_compact=args.bg_compact,
            delta_fraction=args.delta_fraction,
            max_delta_runs=args.max_delta_runs,
            count_kernel=args.count_kernel,
            max_batch=args.max_batch,
            flush_timeout_s=args.flush_timeout_ms / 1e3,
            queue_size=args.queue_size, policy=args.policy,
            deadline_s=(args.deadline_ms / 1e3
                        if args.deadline_ms is not None else None),
            snapshot_dir=args.snapshot_dir,
            snapshot_every=args.snapshot_every, recover=args.recover,
            wal_fsync=args.wal_fsync,
            flight_recorder_size=args.flight_recorder_size,
            tail_exemplar_ms=args.tail_exemplar_ms,
            seed=args.seed,
        )
        chaos = None
        if args.chaos_spec:
            from tuplewise_tpu.testing.chaos import FaultInjector

            chaos = FaultInjector.from_spec(args.chaos_spec)
        tenancy = None
        if (args.max_tenants
                or (args.cmd == "replay" and args.tenants > 1)):
            from tuplewise_tpu.serving import TenancyConfig

            tenancy = TenancyConfig(
                max_tenants=args.max_tenants or 1024,
                tenant_quota=args.tenant_quota,
                weight=args.tenant_weight,
                idle_evict_s=args.idle_evict_s,
                whale_threshold=args.whale_threshold,
                tenant_metric_cap=args.tenant_metric_cap)
        if args.cmd == "replay":
            if args.tenants > 1:
                # fleet load generation [ISSUE 8 satellite]: Zipf
                # tenant assignment through the MultiTenantEngine
                from tuplewise_tpu.serving import (
                    make_tenant_stream, replay_fleet,
                )

                scores, labels, tenants = make_tenant_stream(
                    args.n_events, args.tenants, skew=args.tenant_skew,
                    pos_frac=args.pos_frac,
                    separation=args.separation, seed=args.seed)
                _emit(
                    replay_fleet(scores, labels, tenants, config=cfg,
                                 tenancy=tenancy, chunk=args.chunk,
                                 chaos=chaos,
                                 metrics_out=args.metrics_out,
                                 metrics_every_s=args.metrics_every,
                                 flight_out=args.flight_out,
                                 slo_spec=args.slo_spec,
                                 controller_spec=args.controller_spec),
                    args.out,
                )
                return 0
            from tuplewise_tpu.serving import make_stream, replay

            scores, labels = make_stream(
                args.n_events, pos_frac=args.pos_frac,
                separation=args.separation, seed=args.seed)
            _emit(
                replay(scores, labels, config=cfg, chunk=args.chunk,
                       score_every=args.score_every,
                       query_every=args.query_every, chaos=chaos,
                       trace_out=args.trace_out,
                       metrics_out=args.metrics_out,
                       metrics_every_s=args.metrics_every,
                       profile_dir=args.profile_dir,
                       flight_out=args.flight_out,
                       slo_spec=args.slo_spec,
                       controller_spec=args.controller_spec,
                       prof=args.prof or None,
                       prof_out=args.prof_out),
                args.out,
            )
            return 0
        return _serve_stdin(cfg, chaos=chaos, obs=args, tenancy=tenancy)

    if args.cmd == "variance":
        from tuplewise_tpu.utils.checkpoint import prepare_resume

        prepare_resume(args.checkpoint, args.resume)
        _emit(
            run_variance_experiment(
                _cfg_from_args(args),
                checkpoint_path=args.checkpoint,
                checkpoint_every=args.checkpoint_every,
                trace_dir=args.trace_dir,
                chaos=_chaos_from(args),
            ),
            args.out,
        )
    elif args.cmd == "tradeoff-rounds":
        _emit(tradeoff_vs_rounds(_cfg_from_args(args), args.rounds), args.out)
    elif args.cmd == "tradeoff-pairs":
        _emit(tradeoff_vs_pairs(_cfg_from_args(args), args.pairs), args.out)
    elif args.cmd == "tradeoff-workers":
        _emit(
            tradeoff_vs_workers(_cfg_from_args(args), args.workers),
            args.out,
        )
    elif args.cmd == "triplet":
        from tuplewise_tpu.harness.triplet_experiment import (
            triplet_mnist_statistic,
        )
        from tuplewise_tpu.utils.checkpoint import prepare_resume

        prepare_resume(args.checkpoint, args.resume)
        _emit(
            triplet_mnist_statistic(
                kernel=args.kernel, backend=args.backend, n=args.n,
                n_pairs=args.n_pairs, seed=args.seed,
                checkpoint_path=args.checkpoint,
                chaos=_chaos_from(args),
            ),
            args.out,
        )
    elif args.cmd == "learning":
        from tuplewise_tpu.data import load_adult_splits, make_gaussian_splits
        from tuplewise_tpu.models.pairwise_sgd import TrainConfig, split_by_label
        from tuplewise_tpu.models.scorers import LinearScorer
        from tuplewise_tpu.models.sim_learner import (
            NEVER, curve_record, train_curves,
        )

        if args.dataset == "adult":
            X, y, Xte, yte, meta = load_adult_splits(
                n=args.n, seed=args.seed
            )
            Xp, Xn = split_by_label(X, y)
            Xp_te, Xn_te = split_by_label(Xte, yte)
        else:
            Xp, Xn, Xp_te, Xn_te = make_gaussian_splits(
                args.n, args.n_test, dim=10, separation=0.8,
                seed=args.seed,
            )
            meta = {"synthetic": True, "source": "gaussians"}
        scorer = LinearScorer(dim=Xp.shape[1])
        cfg = TrainConfig(
            kernel=args.kernel, lr=args.lr, steps=args.steps,
            n_workers=args.n_workers,
            repartition_every=args.repartition_every or NEVER,
            pairs_per_worker=args.pairs_per_worker,
            pair_design=args.pair_design,
            loss_every=args.loss_every or NEVER, seed=args.seed,
        )
        out = train_curves(
            scorer, scorer.init(args.seed), Xp, Xn, Xp_te, Xn_te, cfg,
            n_seeds=args.n_seeds, eval_every=args.eval_every,
        )
        _emit(
            dict(
                curve_record(cfg, out, args.n_seeds),
                config=dataclasses.asdict(cfg),
                dataset=args.dataset,
                data_meta=meta,
            ),
            args.out,
        )
    elif args.cmd == "train":
        from tuplewise_tpu.data import load_adult_splits, make_gaussian_splits
        from tuplewise_tpu.models.pairwise_sgd import (
            TrainConfig, evaluate_auc, split_by_label, train_pairwise,
        )
        from tuplewise_tpu.models.scorers import LinearScorer
        from tuplewise_tpu.models.sim_learner import (
            NEVER, last_recorded_loss,
        )

        if args.dataset == "adult":
            X, y, Xte, yte, meta = load_adult_splits(
                n=args.n, seed=args.seed
            )
            Xp, Xn = split_by_label(X, y)
            Xp_te, Xn_te = split_by_label(Xte, yte)
        else:
            Xp, Xn, Xp_te, Xn_te = make_gaussian_splits(
                args.n // 2, max(args.n // 8, 64), dim=5,
                separation=1.0, seed=args.seed,
            )
            meta = {"synthetic": True, "source": "gaussians",
                    "split": "fresh_draw"}
        scorer = LinearScorer(dim=Xp.shape[1])
        p0 = scorer.init(args.seed)
        cfg = TrainConfig(
            kernel=args.kernel, lr=args.lr, steps=args.steps,
            n_workers=args.n_workers,
            repartition_every=args.repartition_every or NEVER,
            pairs_per_worker=args.pairs_per_worker,
            pair_design=args.pair_design,
            loss_every=args.loss_every or NEVER, seed=args.seed,
        )
        from tuplewise_tpu.utils.checkpoint import (
            params_digest, prepare_resume,
        )

        prepare_resume(args.checkpoint, args.resume)
        tracer, registry, flusher = _batch_obs_from(args)
        params, hist = train_pairwise(
            scorer, p0, Xp, Xn, cfg,
            checkpoint_path=args.checkpoint,
            checkpoint_every=args.checkpoint_every,
            chaos=_chaos_from(args),
            tracer=tracer, metrics=registry,
        )
        _finish_batch_obs(args, tracer, flusher)
        _emit(
            {
                "config": dataclasses.asdict(cfg),
                "dataset": args.dataset,
                "data_meta": meta,
                "auc_train_before": evaluate_auc(scorer, p0, Xp, Xn),
                "auc_train": evaluate_auc(scorer, params, Xp, Xn),
                "auc_test_before": evaluate_auc(scorer, p0, Xp_te, Xn_te),
                "auc_test": evaluate_auc(scorer, params, Xp_te, Xn_te),
                # last RECORDED loss (None = never recorded past step
                # 0 or diverged — never a NaN JSON literal, and never
                # an earlier finite value masking divergence)
                "loss_first": float(hist["loss"][0]),
                "loss_last": last_recorded_loss(
                    hist["loss"], cfg.loss_every
                ),
                # bit-identity witness for resume/preemption parity
                # checks across processes [ISSUE 4]
                "params_sha256": params_digest(params),
                "recovery": hist.get("recovery"),
            },
            args.out,
        )
    elif args.cmd == "train-triplet":
        from tuplewise_tpu.data import make_gaussians
        from tuplewise_tpu.models.triplet_sgd import (
            TripletTrainConfig, evaluate_triplet_accuracy, init_embed,
            train_triplet,
        )
        from tuplewise_tpu.utils.checkpoint import (
            params_digest, prepare_resume,
        )

        Xc, Xo = make_gaussians(args.n, args.n, dim=args.dim,
                                separation=1.0, seed=args.seed)
        cfg = TripletTrainConfig(
            embed_dim=args.embed_dim, lr=args.lr, steps=args.steps,
            n_workers=args.n_workers,
            repartition_every=args.repartition_every,
            triplets_per_worker=args.triplets_per_worker,
            seed=args.seed,
        )
        prepare_resume(args.checkpoint, args.resume)
        tracer, registry, flusher = _batch_obs_from(args)
        params, hist = train_triplet(
            init_embed(args.dim, args.embed_dim, args.seed), Xc, Xo,
            cfg, checkpoint_path=args.checkpoint,
            checkpoint_every=args.checkpoint_every,
            chaos=_chaos_from(args),
            tracer=tracer, metrics=registry,
        )
        _finish_batch_obs(args, tracer, flusher)
        _emit(
            {
                "config": dataclasses.asdict(cfg),
                "dataset": "gaussians",
                "loss_first": float(hist["loss"][0]),
                "loss_last": float(hist["loss"][-1]),
                "triplet_acc": evaluate_triplet_accuracy(
                    params, Xc, Xo, n_triplets=4096, seed=args.seed),
                "params_sha256": params_digest(params),
                "recovery": hist.get("recovery"),
            },
            args.out,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
