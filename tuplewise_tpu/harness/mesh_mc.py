"""On-device Monte-Carlo for mesh configs [SURVEY §7 "Variance harness
cost"; VERDICT r1 next #4].

The host-loop path re-generates and re-packs data per repetition —
at n=10^7 the M-rep headline experiment would spend most of its
wall-clock off-device, contaminating the variance-vs-wallclock curve.
This runner keeps the WHOLE Monte-Carlo loop in one jitted program over
the mesh:

* data generation is itself distributed: each shard draws its own
  Gaussian score block from a per-(rep, shard) folded key — synthetic
  i.i.d. data needs no packing and no host↔device transfer at all;
* local / repartitioned rounds reshuffle ON DEVICE exactly like
  MeshBackend.one_round: a fresh permutation per round regathers the
  sharded global array into worker blocks (XLA inserts the all-to-all);
* complete statistics run the ppermute ring; incomplete samples within
  shards;
* reps run under `lax.map`, so M reps cost M compiled iterations with
  zero host round-trips in between.

Statistical contract: estimates are drawn from the SAME distribution as
looping the public mesh Estimator with fresh data per rep (generation,
partitioning, and estimator semantics are identical); the fold chains
differ, so individual values are not bit-equal to any host-loop run —
the variance harness only consumes the distribution.
"""

from __future__ import annotations

import numpy as np

from tuplewise_tpu.ops.kernels import get_kernel


def make_mesh_mc_runner(cfg, mesh=None, tile: int = 512):
    """Compiled rep-array -> estimate-array runner for diff kernels on
    Gaussian scores over a 1-D device mesh, or None when this config
    can't run fully on device (feature/triplet kernels, shard counts
    that don't divide n — the harness falls back to the host loop).
    """
    kernel = get_kernel(cfg.kernel)
    if kernel.kind != "diff" or not kernel.two_sample:
        return None

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tuplewise_tpu.ops import pair_tiles
    from tuplewise_tpu.parallel import ring
    from tuplewise_tpu.parallel.device_partition import draw_blocks
    from tuplewise_tpu.parallel.mesh import make_mesh
    from tuplewise_tpu.utils.rng import fold, root_key

    if mesh is None:
        mesh = make_mesh(cfg.n_workers)
    N = int(np.prod(mesh.devices.shape))
    if len(mesh.axis_names) != 1:
        return None  # harness sweeps 1-D worker counts
    n1, n2 = cfg.n_pos, cfg.n_neg
    if n1 % N or n2 % N:
        return None
    m1, m2 = n1 // N, n2 // N
    axis = mesh.axis_names[0]
    PA = P(axis)
    shard2 = NamedSharding(mesh, PA)
    tile_a, tile_b = min(tile, m1), min(tile, m2)
    # same impl selection as MeshBackend — the ring hot loop runs the
    # mask-aware Pallas kernel on TPU, the XLA scan elsewhere — with the
    # same TUPLEWISE_HARNESS_PALLAS=interpret|off override the jax
    # backend honors, so CI can exercise (and TPU can bypass) the
    # Pallas branches here too. The MESH's platform decides (it can
    # differ from the default backend's).
    from tuplewise_tpu.ops.pallas_pairs import resolve_pallas_mode

    use_pallas, interpret = resolve_pallas_mode(
        mesh.devices.flat[0].platform
    )
    impl = "pallas" if use_pallas else "xla"
    if use_pallas and not interpret:
        from tuplewise_tpu.ops.pallas_pairs import preferred_pair_tiles

        pa_, pb_ = preferred_pair_tiles(kernel, m1, m2)
        tile_a, tile_b = max(tile_a, pa_), max(tile_b, pb_)

    # ---- per-shard data generation (no packing, no transfer) --------- #
    def gen_body(key):
        w = lax.axis_index(axis)
        k1, k2 = jax.random.split(fold(key, "shard", w))
        s1 = jax.random.normal(k1, (1, m1), jnp.float32) + cfg.separation
        s2 = jax.random.normal(k2, (1, m2), jnp.float32)
        return s1, s2

    gen = jax.shard_map(
        gen_body, mesh=mesh, in_specs=P(), out_specs=(PA, PA),
        check_vma=False,
    )

    # ---- estimator bodies (mirror backends.mesh_backend) ------------- #
    def complete_body(a, b):
        s, c = ring.ring_pair_stats(
            kernel, a[0], b[0], axis_name=axis,
            tile_a=tile_a, tile_b=tile_b, impl=impl,
            interpret=interpret,
        )
        return s / c

    complete_smap = jax.shard_map(
        complete_body, mesh=mesh, in_specs=(PA, PA), out_specs=P(),
        check_vma=False,
    )

    def local_mean_body(a, b):
        if use_pallas:
            from tuplewise_tpu.ops.pallas_pairs import (
                pallas_masked_pair_sum,
            )

            s = pallas_masked_pair_sum(
                a[0], b[0], jnp.ones_like(a[0]), jnp.ones_like(b[0]),
                kernel=kernel, tile_a=tile_a, tile_b=tile_b,
                interpret=interpret,
            )
            # blocks are full (N*m == n), so the count is exactly m1*m2;
            # python float — the product can exceed int32 inside jit
            return (s / float(m1 * m2))[None]
        s, c = pair_tiles.pair_stats(
            kernel, a[0], b[0], tile_a=tile_a, tile_b=tile_b
        )
        return (s / c)[None]

    local_mean_smap = jax.shard_map(
        local_mean_body, mesh=mesh, in_specs=(PA, PA), out_specs=PA,
        check_vma=False,
    )

    def one_round(s1, s2, key):
        """On-device reshuffle + per-worker local means (the all-to-all
        regather of MeshBackend.one_round, minus fault plumbing)."""
        k1, k2 = jax.random.split(key)
        i1 = draw_blocks(k1, n1, N, cfg.partition_scheme)
        i2 = draw_blocks(k2, n2, N, cfg.partition_scheme)
        Ab = s1.reshape(n1).at[i1].get(out_sharding=shard2)
        Bb = s2.reshape(n2).at[i2].get(out_sharding=shard2)
        return jnp.mean(local_mean_smap(Ab, Bb))

    def incomplete_body(key, a, b):
        w = lax.axis_index(axis)
        kk = fold(key, "shard", w)
        per = -(-cfg.n_pairs // N)
        i, j = pair_tiles.sample_pair_indices(kk, m1, m2, per, False)
        vals = kernel.pair_elementwise(a[0, i], b[0, j], jnp)
        return lax.pmean(jnp.mean(vals, dtype=a.dtype), axis)

    incomplete_smap = jax.shard_map(
        incomplete_body, mesh=mesh, in_specs=(P(), PA, PA), out_specs=P(),
        check_vma=False,
    )

    def one_rep(rep):
        key = fold(root_key(cfg.seed), "mc_rep", rep)
        s1, s2 = gen(fold(key, "data"))
        if cfg.scheme == "complete":
            return complete_smap(s1, s2)
        if cfg.scheme == "local":
            return one_round(s1, s2, fold(key, "partition"))
        if cfg.scheme == "repartitioned":
            def body(carry, t):
                return carry + one_round(
                    s1, s2, fold(key, "partition", t)
                ), None

            total, _ = lax.scan(
                body, jnp.zeros((), jnp.float32), jnp.arange(cfg.n_rounds)
            )
            return total / cfg.n_rounds
        if cfg.scheme == "incomplete":
            return incomplete_smap(fold(key, "pairs"), s1, s2)
        raise ValueError(cfg.scheme)

    # lax.map (not vmap): each rep already fills the mesh; serializing
    # reps bounds live memory at one rep's working set
    return jax.jit(lambda reps: lax.map(one_rep, reps))
