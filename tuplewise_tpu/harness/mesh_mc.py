"""On-device Monte-Carlo for mesh configs [SURVEY §7 "Variance harness
cost"; VERDICT r1 next #4, r2 next #5].

The host-loop path re-generates and re-packs data per repetition —
at n=10^7 the M-rep headline experiment would spend most of its
wall-clock off-device, contaminating the variance-vs-wallclock curve.
This runner keeps the WHOLE Monte-Carlo loop in one jitted program over
the mesh:

* data generation is itself distributed: each shard draws its own
  Gaussian score block from a per-(rep, shard) folded key — synthetic
  i.i.d. data needs no packing and no host↔device transfer at all;
* local / repartitioned rounds reshuffle ON DEVICE exactly like
  MeshBackend.one_round: a fresh permutation per round regathers the
  sharded global array into worker blocks (XLA inserts the all-to-all);
* complete statistics run the ppermute ring; incomplete samples within
  shards;
* reps run under `lax.map`, so M reps cost M compiled iterations with
  zero host round-trips in between.

Coverage (r3 lifted every fallback): 1-D AND 2-D (dcn x ici) meshes,
shard counts that do NOT divide n (tail shards carry masked padding;
the ring runs mask-aware), one-sample feature kernels (scatter) with
global-id pair exclusion, and degree-3 triplet kernels (double ring
for complete; global-id anchor/positive exclusion) — every kernel kind
runs mesh-native; only non-mesh backends use the host loop.

Statistical contract: estimates are drawn from the SAME distribution as
looping the public mesh Estimator with fresh data per rep (generation,
partitioning, and estimator semantics are identical); the fold chains
differ, so individual values are not bit-equal to any host-loop run —
the variance harness only consumes the distribution.
"""

from __future__ import annotations

import numpy as np

from tuplewise_tpu.utils.compat import sharded_take
from tuplewise_tpu.ops.kernels import get_kernel


def _clamp_preferred(pref: int, base: int, m: int) -> int:
    """Take the measured-best tile only while its padding waste stays
    bounded: the masked kernel pads a block of m rows up to a full
    tile, so a preferred tile far beyond m would spend most lanes on
    zero-mask padding (ADVICE r2). Halving until tile < 2m caps the
    waste at <2x while keeping the preferred shape on big blocks."""
    t = max(base, pref)
    while t >= 2 * m and t > base:
        t //= 2
    return max(t, base)


def make_mesh_mc_runner(cfg, mesh=None, tile: int = 512,
                        triplet_tile: int = 16, chaos=None):
    """Compiled rep-array -> estimate-array runner for mesh configs on
    Gaussian data, or None when this config can't run fully on device
    (only meshes of >2 axes; every kernel kind — diff, feature pair,
    triplet — now runs mesh-native).

    ``mesh`` lets the caller place the runner on a SPECIFIC mesh — the
    elastic re-shard path [ISSUE 4] rebuilds the runner on a healed
    mesh of the same logical width; estimates depend only on (rep,
    logical shard index) fold chains, so the rebuilt runner's values
    are bit-identical to the original's. ``chaos``: a
    ``testing.chaos.FaultInjector`` fired at the ``mesh_mc`` hook
    before every dispatch of the compiled program (where a dead device
    actually surfaces as the dispatch raising).
    """
    kernel = get_kernel(cfg.kernel)
    trip = kernel.kind == "triplet"

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tuplewise_tpu.ops import pair_tiles
    from tuplewise_tpu.parallel import ring
    from tuplewise_tpu.parallel.device_partition import (
        draw_blocks, linear_shard_index,
    )
    from tuplewise_tpu.parallel.mesh import make_mesh
    from tuplewise_tpu.utils.rng import fold, root_key

    if mesh is None:
        mesh = make_mesh(cfg.n_workers)
    N = int(np.prod(mesh.devices.shape))
    axes = tuple(mesh.axis_names)
    if len(axes) > 2:
        return None

    def _with_chaos(runner):
        """Wrap the compiled program with the ``mesh_mc`` hook point
        (host-side, per dispatch — the injector's one-shot faults make
        a retried dispatch succeed deterministically)."""
        if chaos is None:
            return runner

        def chaotic(reps):
            chaos.fire("mesh_mc")
            return runner(reps)

        return chaotic
    one_sample = not kernel.two_sample
    n1 = cfg.n_pos
    n2 = n1 if one_sample else cfg.n_neg
    # static per-shard capacity; tail shards carry (cap*N - n) masked
    # padding rows when N does not divide n [VERDICT r2 next #5]
    cap1, cap2 = -(-n1 // N), -(-n2 // N)
    ragged = bool(n1 % N or n2 % N)
    m1, m2 = n1 // N, n2 // N          # full-block sizes for regathers
    PA = P(axes)
    shard2 = NamedSharding(mesh, PA)
    tile_a, tile_b = min(tile, cap1), min(tile, cap2)
    # same impl selection as MeshBackend — the ring hot loop runs the
    # mask-aware Pallas kernel on TPU, the XLA scan elsewhere — with the
    # same TUPLEWISE_HARNESS_PALLAS=interpret|off override the jax
    # backend honors, so CI can exercise (and TPU can bypass) the
    # Pallas branches here too. The MESH's platform decides (it can
    # differ from the default backend's).
    from tuplewise_tpu.ops.pallas_pairs import resolve_pallas_mode

    use_pallas, interpret = resolve_pallas_mode(
        mesh.devices.flat[0].platform
    )
    # triplet kernels route through the distance factorization
    # (ops.pallas_triplets) under the same platform/override gate
    use_pallas_trip = use_pallas and trip
    use_pallas = use_pallas and kernel.kind == "diff"
    impl = "pallas" if use_pallas else "xla"
    if use_pallas and not interpret:
        from tuplewise_tpu.ops.pallas_pairs import preferred_pair_tiles

        pa_, pb_ = preferred_pair_tiles(kernel, cap1, cap2)
        tile_a = _clamp_preferred(pa_, tile_a, cap1)
        tile_b = _clamp_preferred(pb_, tile_b, cap2)


    # ---- per-shard data generation (no packing, no transfer) --------- #
    # shard w holds global rows [w*cap, (w+1)*cap): flattening the
    # [N, cap] stack IS the global array, with padding (ids >= n) only
    # in the tail — so regathers below index it with global ids directly.
    # diff kernels consume scalar scores; feature kernels (scatter) get
    # [cap, dim] rows with the class shift on the first feature, the
    # same geometry data.make_gaussians gives the host loop.
    feat = (cfg.dim,) if kernel.kind != "diff" else ()

    def gen_body(key):
        w = linear_shard_index(axes)
        k1, k2 = jax.random.split(fold(key, "shard", w))
        s1 = jax.random.normal(k1, (1, cap1) + feat, jnp.float32)
        s2 = jax.random.normal(k2, (1, cap2) + feat, jnp.float32)
        if feat:
            s1 = s1.at[..., 0].add(cfg.separation)
        else:
            s1 = s1 + cfg.separation
        ids1 = w * cap1 + jnp.arange(cap1, dtype=jnp.int32)
        ids2 = w * cap2 + jnp.arange(cap2, dtype=jnp.int32)
        ma = (ids1 < n1).astype(jnp.float32)[None]
        mb = (ids2 < n2).astype(jnp.float32)[None]
        return s1, s2, ma, mb, ids1[None], ids2[None]

    gen = jax.shard_map(
        gen_body, mesh=mesh, in_specs=P(),
        out_specs=(PA, PA, PA, PA, PA, PA),
        check_vma=False,
    )

    def _data_key(rep_key):
        """Fresh draw per rep, or the frozen fix_data key (conditional
        Monte-Carlo over sampling randomness only)."""
        if getattr(cfg, "fix_data", False):
            return fold(root_key(cfg.seed), "data_fixed")
        return fold(rep_key, "data")

    # ---- designed incomplete (swor/bernoulli), measured -------------- #
    # [VERDICT r3 next #4; r4 next #6] DISTINCT tuple sets drawn ON
    # DEVICE per rep (ops.device_design — the single overdraw →
    # sort-dedup → subselect sampler shared with the learning side and
    # the jax-backend harness branch), replicated across the mesh, then
    # sharded [N, per] over workers exactly like MeshBackend.incomplete's
    # designed path: each worker regathers the rows of its sampled
    # tuples across shards (the priced communication), evaluates
    # locally, and psums the weighted mean. Fixed shapes (bernoulli's
    # Binomial size lives in the weight mask) -> one compile and ZERO
    # per-rep host syncs; the host sampler stays the oracle
    # (tests/test_sampling_designs.py pins distribution parity).
    if cfg.scheme == "incomplete" and getattr(cfg, "design", "swr") != "swr":
        from tuplewise_tpu.ops.device_design import (
            draw_pair_design_device, draw_triplet_design_device,
            shard_design_blocks,
        )

        B = cfg.n_pairs

        def designed_body(av, bv, w):
            vals = kernel.pair_elementwise(av[0], bv[0], jnp)
            s = lax.psum(jnp.sum(vals * w[0], dtype=jnp.float32), axes)
            c = lax.psum(jnp.sum(w[0], dtype=jnp.float32), axes)
            return s / c

        designed_smap = jax.shard_map(
            designed_body, mesh=mesh, in_specs=(PA, PA, PA),
            out_specs=P(), check_vma=False,
        )

        def designed_tri_body(av, pv, bv, w):
            vals = kernel.triplet_values(av[0], pv[0], bv[0], jnp)
            s = lax.psum(jnp.sum(vals * w[0], dtype=jnp.float32), axes)
            c = lax.psum(jnp.sum(w[0], dtype=jnp.float32), axes)
            return s / c

        designed_tri_smap = jax.shard_map(
            designed_tri_body, mesh=mesh, in_specs=(PA, PA, PA, PA),
            out_specs=P(), check_vma=False,
        )

        def designed_rep(rep):
            key = fold(root_key(cfg.seed), "mc_rep", rep)
            s1, s2, *_ = gen(_data_key(key))
            A = s1.reshape((N * cap1,) + feat)
            Bg = A if one_sample else s2.reshape((N * cap2,) + feat)
            kd = fold(key, "design")
            # floor_one: estimation semantics (bernoulli size >= 1)
            if trip:
                i, j, kk, w = draw_triplet_design_device(
                    kd, n1, n2, B, cfg.design, floor_one=True
                )
                pi, pj, pk, pw = shard_design_blocks((i, j, kk), w, N)
                return designed_tri_smap(
                    sharded_take(A, pi, shard2),
                    sharded_take(A, pj, shard2),
                    sharded_take(Bg, pk, shard2),
                    pw,
                )
            i, j, w = draw_pair_design_device(
                kd, n1, n1 - 1 if one_sample else n2, B, cfg.design,
                one_sample=one_sample, floor_one=True,
            )
            pi, pj, pw = shard_design_blocks((i, j), w, N)
            return designed_smap(
                sharded_take(A, pi, shard2),
                sharded_take(Bg, pj, shard2),
                pw,
            )

        return _with_chaos(jax.jit(lambda reps: lax.map(designed_rep, reps)))

    # ---- estimator bodies (mirror backends.mesh_backend) ------------- #
    def complete_body(a, b, ma, mb, ia, ib):
        if trip:
            trip_impl = "pallas" if use_pallas_trip else "xla"
            if len(axes) == 2:
                s, c = ring.ring_triplet_stats_2d(
                    kernel, a[0], b[0], mask_x=ma[0], mask_y=mb[0],
                    ids_x=ia[0], ici_axis=axes[1], dcn_axis=axes[0],
                    tile=triplet_tile, impl=trip_impl, interpret=interpret,
                )
            else:
                s, c = ring.ring_triplet_stats(
                    kernel, a[0], b[0], mask_x=ma[0], mask_y=mb[0],
                    ids_x=ia[0], axis_name=axes[0], tile=triplet_tile,
                    impl=trip_impl, interpret=interpret,
                )
            return s / c
        from tuplewise_tpu.ops.scatter_exact import (
            is_builtin_scatter, scatter_mesh_stats,
        )

        if is_builtin_scatter(kernel):
            # one O(d) psum of moments replaces the ring entirely
            # [VERDICT r3 next #7]; gen's global ids are distinct
            s, c = scatter_mesh_stats(
                a[0], ma[0], b[0], mb[0], axes=axes,
                one_sample=one_sample,
            )
            return s / c
        kw = dict(tile_a=tile_a, tile_b=tile_b, impl=impl,
                  interpret=interpret)
        # mask=None on padding-free shards certifies the unmasked
        # Pallas fast path (same contract as MeshBackend.complete)
        mask_a = ma[0] if ragged else None
        mask_b = mb[0] if ragged else None
        ids = dict(ids_a=ia[0], ids_b=ib[0]) if one_sample else {}
        if len(axes) == 2:
            s, c = ring.ring_pair_stats_2d(
                kernel, a[0], b[0], mask_a=mask_a, mask_b=mask_b,
                ici_axis=axes[1], dcn_axis=axes[0], **ids, **kw,
            )
        else:
            s, c = ring.ring_pair_stats(
                kernel, a[0], b[0], mask_a=mask_a, mask_b=mask_b,
                axis_name=axes[0], **ids, **kw,
            )
        return s / c

    complete_smap = jax.shard_map(
        complete_body, mesh=mesh, in_specs=(PA,) * 6, out_specs=P(),
        check_vma=False,
    )

    def local_mean_body(a, b, ia, ib):
        """Per-worker complete statistic on regathered FULL blocks
        ([N, m] with m = n // N — the random remainder is dropped by
        the permutation, so no masks are needed here)."""
        if trip:
            from tuplewise_tpu.ops.pallas_triplets import (
                triplet_stats_best,
            )

            s, c = triplet_stats_best(
                kernel, a[0], b[0], ids_x=ia[0], tile=triplet_tile,
                impl="pallas" if use_pallas_trip else "xla",
                interpret=interpret,
            )
            return (s / c)[None]
        if one_sample:
            from tuplewise_tpu.ops.scatter_exact import (
                is_builtin_scatter, scatter_pair_stats,
            )

            if is_builtin_scatter(kernel):
                s, c = scatter_pair_stats(
                    a[0], a[0], ids_a=ia[0], ids_b=ib[0]
                )
            else:
                s, c = pair_tiles.pair_stats(
                    kernel, a[0], a[0], ids_a=ia[0], ids_b=ib[0],
                    tile_a=min(tile_a, m1), tile_b=min(tile_b, m1),
                )
            return (s / c)[None]
        if use_pallas:
            # regathered blocks are FULL (remainder dropped), so the
            # unmasked interior/edge path applies [VERDICT r3 next #1]
            from tuplewise_tpu.ops.pallas_pairs import pallas_pair_sum_any

            s = pallas_pair_sum_any(
                a[0], b[0], kernel=kernel, tile_a=tile_a, tile_b=tile_b,
                interpret=interpret,
            )
            # python float — the product can exceed int32 inside jit
            return (s / float(m1 * m2))[None]
        s, c = pair_tiles.pair_stats(
            kernel, a[0], b[0], tile_a=tile_a, tile_b=tile_b
        )
        return (s / c)[None]

    local_mean_smap = jax.shard_map(
        local_mean_body, mesh=mesh, in_specs=(PA, PA, PA, PA),
        out_specs=PA, check_vma=False,
    )

    def one_round(s1, s2, key):
        """On-device reshuffle + per-worker local means (the all-to-all
        regather of MeshBackend.one_round, minus fault plumbing).
        Indices are drawn over the TRUE n, so padded tail rows are
        never gathered and ragged sizes drop a random remainder."""
        if one_sample:
            i1 = draw_blocks(key, n1, N, cfg.partition_scheme)
            Ab = sharded_take(s1.reshape((N * cap1,) + feat), i1, shard2)
            vals = local_mean_smap(Ab, Ab, i1, i1)
            return jnp.mean(vals)
        k1, k2 = jax.random.split(key)
        i1 = draw_blocks(k1, n1, N, cfg.partition_scheme)
        i2 = draw_blocks(k2, n2, N, cfg.partition_scheme)
        Ab = sharded_take(s1.reshape((N * cap1,) + feat), i1, shard2)
        Bb = sharded_take(s2.reshape((N * cap2,) + feat), i2, shard2)
        return jnp.mean(local_mean_smap(Ab, Bb, i1, i2))

    def incomplete_body(key, a, b):
        """Within-shard sampling on regathered full blocks (the blocks
        a/b arrive padding-free from one_round-style regathers)."""
        kk = fold(key, "shard", linear_shard_index(axes))
        per = -(-cfg.n_pairs // N)
        if trip:
            k1, k2 = jax.random.split(kk)
            i, j = pair_tiles.sample_pair_indices(k1, m1, m1, per, True)
            kn = jax.random.randint(k2, (per,), 0, m2)
            vals = kernel.triplet_values(a[0, i], a[0, j], b[0, kn], jnp)
            return lax.pmean(jnp.mean(vals, dtype=jnp.float32), axes)
        if one_sample:
            i, j = pair_tiles.sample_pair_indices(kk, m1, m1, per, True)
            vals = kernel.pair_elementwise(a[0, i], a[0, j], jnp)
        else:
            i, j = pair_tiles.sample_pair_indices(kk, m1, m2, per, False)
            vals = kernel.pair_elementwise(a[0, i], b[0, j], jnp)
        return lax.pmean(jnp.mean(vals, dtype=a.dtype), axes)

    incomplete_smap = jax.shard_map(
        incomplete_body, mesh=mesh, in_specs=(P(), PA, PA), out_specs=P(),
        check_vma=False,
    )

    def incomplete_rep(s1, s2, key):
        """Random packing (drop remainder) + within-shard sampling —
        the same semantics as MeshBackend.incomplete(design='swr')."""
        kp, ks = jax.random.split(key)
        if one_sample:
            i1 = draw_blocks(kp, n1, N, "swor")
            Ab = sharded_take(s1.reshape((N * cap1,) + feat), i1, shard2)
            return incomplete_smap(ks, Ab, Ab)
        k1, k2 = jax.random.split(kp)
        i1 = draw_blocks(k1, n1, N, "swor")
        i2 = draw_blocks(k2, n2, N, "swor")
        Ab = sharded_take(s1.reshape((N * cap1,) + feat), i1, shard2)
        Bb = sharded_take(s2.reshape((N * cap2,) + feat), i2, shard2)
        return incomplete_smap(ks, Ab, Bb)

    def one_rep(rep):
        key = fold(root_key(cfg.seed), "mc_rep", rep)
        s1, s2, ma, mb, ia, ib = gen(_data_key(key))
        if one_sample:
            s2, mb, ib = s1, ma, ia
        if cfg.scheme == "complete":
            return complete_smap(s1, s2, ma, mb, ia, ib)
        if cfg.scheme == "local":
            return one_round(s1, s2, fold(key, "partition"))
        if cfg.scheme == "repartitioned":
            def body(carry, t):
                return carry + one_round(
                    s1, s2, fold(key, "partition", t)
                ), None

            total, _ = lax.scan(
                body, jnp.zeros((), jnp.float32), jnp.arange(cfg.n_rounds)
            )
            return total / cfg.n_rounds
        if cfg.scheme == "incomplete":
            return incomplete_rep(s1, s2, fold(key, "pairs"))
        raise ValueError(cfg.scheme)

    # lax.map (not vmap): each rep already fills the mesh; serializing
    # reps bounds live memory at one rep's working set
    return _with_chaos(jax.jit(lambda reps: lax.map(one_rep, reps)))
