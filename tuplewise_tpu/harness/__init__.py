from tuplewise_tpu.harness.variance import (
    VarianceConfig,
    run_variance_experiment,
    tradeoff_vs_rounds,
    tradeoff_vs_pairs,
    tradeoff_vs_workers,
)
from tuplewise_tpu.harness.triplet_experiment import triplet_mnist_statistic

__all__ = [
    "VarianceConfig",
    "run_variance_experiment",
    "tradeoff_vs_rounds",
    "tradeoff_vs_pairs",
    "tradeoff_vs_workers",
    "triplet_mnist_statistic",
]
