"""L4 — Monte-Carlo variance harness and trade-off curves.

The experiment that produces the paper's figures [SURVEY §4.5]: repeat an
estimator M times over fresh data draws (and fresh partitions), report
empirical mean/variance and wall-clock, and sweep the communication
knobs — T repartition rounds, B sampled pairs — to trace the
variance-vs-communication trade-off [SURVEY §1.2, §6].

Monte-Carlo reps are VMAPPED on device for the synthetic-Gaussian score
experiments (the paper's core setting), not python-looped
[SURVEY §7 "Hard parts"]: data generation (jax.random, folded per-rep
keys), estimation, and the M-rep reduction compile into one XLA program.
Feature-kernel / real-data configs fall back to looping the public
Estimator API, so every backend/kernel combination is measurable.

Results serialize to JSONL with their full config [SURVEY §5.6, §5.9].
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Optional

import numpy as np

from tuplewise_tpu.data import make_gaussians, true_gaussian_auc
from tuplewise_tpu.estimators.estimator import Estimator
from tuplewise_tpu.ops.kernels import get_kernel


@dataclasses.dataclass(frozen=True)
class VarianceConfig:
    """One variance experiment [SURVEY §5.9: single dataclass + CLI]."""

    kernel: str = "auc"
    scheme: str = "complete"          # complete | local | repartitioned | incomplete
    backend: str = "jax"
    n_pos: int = 10_000
    n_neg: int = 10_000
    dim: int = 1
    separation: float = 1.0
    n_workers: int = 8
    n_rounds: int = 1                 # T (repartitioned)
    n_pairs: int = 10_000             # B (incomplete)
    design: str = "swr"               # incomplete tuple design
    # fix_data=True freezes ONE dataset (drawn from `seed`) and
    # Monte-Carlos over the sampling randomness only — the CONDITIONAL
    # variance Var(U~ | data), where the swor/bernoulli
    # finite-population reduction lives: unconditionally the design
    # difference is sigma_h^2/G, invisible against Var(U_n) ~ zeta/n at
    # any realistic n, but conditionally swor at B = G/2 HALVES the swr
    # variance [VERDICT r3 next #4]. Audited against exact closed forms
    # (s^2 = U(1-U) for the indicator kernel) in scripts/stat_check.py.
    fix_data: bool = False
    partition_scheme: str = "swor"
    n_reps: int = 100                 # M Monte-Carlo repetitions
    seed: int = 0

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _estimate_once(est: Estimator, cfg: VarianceConfig, rep: int) -> float:
    X, Y = make_gaussians(
        cfg.n_pos, cfg.n_neg, cfg.dim, cfg.separation,
        seed=cfg.seed * 1_000_003 + (0 if cfg.fix_data else rep),
    )
    kern = get_kernel(cfg.kernel)
    if kern.kind == "diff":
        s1, s2 = X[:, 0], Y[:, 0]      # score-difference kernels: scalars
    else:
        s1, s2 = X, Y                  # feature kernels need [n, d] rows
    if not kern.two_sample:
        s2 = None                      # one-sample: the API takes A only
    if cfg.scheme == "complete":
        return est.complete(s1, s2)
    if cfg.scheme == "local":
        return est.local_average(
            s1, s2, seed=rep, scheme=cfg.partition_scheme
        )
    if cfg.scheme == "repartitioned":
        return est.repartitioned(
            s1, s2, n_rounds=cfg.n_rounds, seed=rep,
            scheme=cfg.partition_scheme,
        )
    if cfg.scheme == "incomplete":
        return est.incomplete(
            s1, s2, n_pairs=cfg.n_pairs, seed=rep, design=cfg.design
        )
    raise ValueError(f"unknown scheme {cfg.scheme!r}")


def _make_vmapped_runner(cfg: VarianceConfig, mesh=None, chaos=None):
    """Compiled rep-array -> estimate-array runner for diff kernels on
    Gaussian scores (one XLA program for the whole Monte-Carlo batch),
    or None if this config isn't compilable end-to-end (feature
    kernels, the numpy oracle backend). Mesh configs get the
    mesh-native runner (harness.mesh_mc): generation, reshuffling, and
    estimation all stay on device across reps. Estimates depend only on
    the ABSOLUTE rep indices passed in, so callers may chunk the rep
    range freely (checkpoint/resume) without changing any value —
    and, for mesh configs, rebuild the runner on a healed mesh of the
    same logical width (elastic re-shard [ISSUE 4]) without changing
    any value either."""
    if cfg.backend == "mesh":
        from tuplewise_tpu.harness.mesh_mc import make_mesh_mc_runner

        return make_mesh_mc_runner(cfg, mesh=mesh, chaos=chaos)
    if cfg.backend != "jax" or get_kernel(cfg.kernel).kind not in (
            "diff", "triplet"):
        return None
    if get_kernel(cfg.kernel).kind == "triplet":
        # degree-3 Monte-Carlo is compilable for the incomplete scheme
        # (swr on device; swor/bernoulli host-designed + padded, as in
        # the pair branch below) [VERDICT r4 next #3]; other triplet
        # schemes loop the Estimator API
        if cfg.scheme != "incomplete":
            return None
        return _make_triplet_incomplete_runner(cfg)

    import jax
    import jax.numpy as jnp

    from tuplewise_tpu.ops import pair_tiles
    from tuplewise_tpu.utils.rng import fold, root_key

    kernel = get_kernel(cfg.kernel)
    n1, n2, N = cfg.n_pos, cfg.n_neg, cfg.n_workers
    tile = 512 if max(n1, n2) >= 512 else 128
    # On TPU the complete/local hot loops route through the mask-aware
    # Pallas kernel (all-ones masks; the count is exact by construction)
    # — ~1.5x the lax.scan path at n=10^6 and it vmaps across reps and
    # worker blocks. CPU (the 8-device test mesh) keeps the XLA scan:
    # interpret-mode Pallas is far slower than compiled XLA there.
    # TUPLEWISE_HARNESS_PALLAS=interpret|off overrides the platform
    # gate so CI can exercise this branch without a TPU.
    from tuplewise_tpu.ops.pallas_pairs import resolve_pallas_mode

    use_pallas, interpret = resolve_pallas_mode(
        jax.devices()[0].platform
    )

    def hot_pair_mean(a, b):
        m1, m2 = a.shape[0], b.shape[0]
        if use_pallas:
            # interior/edge-decomposed unmasked path: every row of the
            # full arrays is valid, so the mask multiply is paid only on
            # the thin edge strips at non-tile-divisible n (the n=10^7
            # headline case) [VERDICT r3 next #1]
            from tuplewise_tpu.ops.pallas_pairs import pallas_pair_sum_any

            s = pallas_pair_sum_any(
                a, b, kernel=kernel, interpret=interpret,
            )
            # python float, not int: m1*m2 can exceed int32 inside jit
            return s / float(m1 * m2)
        return pair_tiles.pair_mean(
            kernel, a, b, tile_a=min(tile, m1), tile_b=min(tile, m2)
        )

    def gen(key):
        k1, k2 = jax.random.split(key)
        s1 = jax.random.normal(k1, (n1,), jnp.float32) + cfg.separation
        s2 = jax.random.normal(k2, (n2,), jnp.float32)
        return s1, s2

    def data_key(rep_key):
        """Per-rep fresh draw, or the frozen fix_data key — the same
        stream scripts/stat_check.py reconstructs via fixed_dataset."""
        if cfg.fix_data:
            return fold(root_key(cfg.seed), "data_fixed")
        return fold(rep_key, "data")

    if cfg.scheme == "incomplete" and cfg.design != "swr":
        # Device-designed distinct tuple sets (swor/bernoulli) drawn
        # INSIDE the vmapped program (ops.device_design — the ONE copy
        # of the overdraw → sort-dedup → subselect machinery, shared
        # with the learning side) [VERDICT r4 next #6]: no per-rep host
        # sync, fixed shapes (bernoulli's Binomial size lives in the
        # weight mask), one compile for the whole Monte-Carlo batch.
        # The host sampler (parallel.partition) remains the oracle;
        # design-distribution parity is pinned in
        # tests/test_sampling_designs.py.
        from tuplewise_tpu.ops.device_design import (
            draw_pair_design_device,
        )

        def designed_rep(rep):
            key = fold(root_key(cfg.seed), "mc_rep", rep)
            s1, s2 = gen(data_key(key))
            # floor_one: estimation semantics (bernoulli size >= 1 —
            # the host oracle's documented behavior)
            i, j, w = draw_pair_design_device(
                fold(key, "design"), n1, n2, cfg.n_pairs, cfg.design,
                floor_one=True,
            )
            vals = kernel.diff(s1[i] - s2[j], jnp)
            return (jnp.sum(vals * w, dtype=jnp.float32)
                    / jnp.sum(w, dtype=jnp.float32))

        return jax.jit(jax.vmap(designed_rep))

    from tuplewise_tpu.parallel.device_partition import draw_blocks

    # The paper's trade-off regime is MANY workers with small per-worker
    # blocks (the local-vs-complete variance gap scales as
    # zeta_11/(n*m), m = per-worker rows — invisible unless m is tens)
    # [SURVEY §1.2]. Tiny blocks would drown a per-worker tiled kernel
    # in launch overhead, so small worker grids take one dense
    # broadcast over the [N, m1, m2] stack instead.
    dense_local = (n1 // N) * (n2 // N) <= 1 << 16

    def local_round(s1, s2, key):
        k1, k2 = jax.random.split(key)
        b1 = s1[draw_blocks(k1, n1, N, cfg.partition_scheme)]
        b2 = s2[draw_blocks(k2, n2, N, cfg.partition_scheme)]
        if dense_local:
            # equal block sizes make the mean over the [N, m1, m2]
            # grid equal the mean of per-worker means
            return jnp.mean(kernel.diff(b1[:, :, None] - b2[:, None, :], jnp))
        return jnp.mean(jax.vmap(hot_pair_mean)(b1, b2))

    def one_rep(rep):
        key = fold(root_key(cfg.seed), "mc_rep", rep)
        s1, s2 = gen(data_key(key))
        if cfg.scheme == "complete":
            return hot_pair_mean(s1, s2)
        if cfg.scheme == "local":
            return local_round(s1, s2, fold(key, "partition"))
        if cfg.scheme == "repartitioned":
            # sequential over rounds (lax.map, not vmap): each round's
            # gathered worker blocks are O(n) live memory, and a round
            # already saturates the chip — vmapping T rounds would
            # materialize T block sets at once (HBM blow-up at n=10^7,
            # T=16) for no throughput gain
            rounds = jax.lax.map(
                lambda t: local_round(s1, s2, fold(key, "partition", t)),
                jnp.arange(cfg.n_rounds),
            )
            return jnp.mean(rounds)
        if cfg.scheme == "incomplete":
            return pair_tiles.incomplete_pair_mean(
                kernel, fold(key, "pairs"), s1, s2, cfg.n_pairs, False
            )
        raise ValueError(cfg.scheme)

    return jax.jit(jax.vmap(one_rep))


def _make_triplet_incomplete_runner(cfg: VarianceConfig):
    """Vmapped Monte-Carlo for the degree-3 incomplete estimator
    [VERDICT r4 next #3]: gaussian FEATURE clouds (anchors/positives
    shifted by `separation`, negatives at the origin — the same fold
    chain fixed_dataset reconstructs), every design drawn ON DEVICE
    inside the vmapped program (swr via incomplete_triplet_mean;
    swor/bernoulli via ops.device_design, whose weight mask prices
    bernoulli's Binomial size at a fixed shape), so M reps compile once
    with no per-rep host sync. The conditional (fix_data=True) rows
    audit against the EXACT fpc closed forms with s^2 = U(1-U) and
    G = n1(n1-1)n2 (scripts/stat_check.py)."""
    import jax
    import jax.numpy as jnp

    from tuplewise_tpu.ops import pair_tiles
    from tuplewise_tpu.utils.rng import fold, root_key

    kernel = get_kernel(cfg.kernel)
    n1, n2 = cfg.n_pos, cfg.n_neg

    def gen(key):
        k1, k2 = jax.random.split(key)
        X = jax.random.normal(k1, (n1, cfg.dim), jnp.float32) + cfg.separation
        Y = jax.random.normal(k2, (n2, cfg.dim), jnp.float32)
        return X, Y

    def data_key(rep_key):
        if cfg.fix_data:
            return fold(root_key(cfg.seed), "data_fixed")
        return fold(rep_key, "data")

    if cfg.design == "swr":

        def one_rep(rep):
            key = fold(root_key(cfg.seed), "mc_rep", rep)
            X, Y = gen(data_key(key))
            return pair_tiles.incomplete_triplet_mean(
                kernel, fold(key, "pairs"), X, Y, cfg.n_pairs
            )

        return jax.jit(jax.vmap(one_rep))

    # distinct designs drawn on device inside the vmapped program —
    # the same single sampler as the pair branch and the learning side
    # (ops.device_design) [VERDICT r4 next #6]
    from tuplewise_tpu.ops.device_design import (
        draw_triplet_design_device,
    )

    def designed_rep(rep):
        key = fold(root_key(cfg.seed), "mc_rep", rep)
        X, Y = gen(data_key(key))
        # floor_one: estimation semantics (bernoulli size >= 1)
        i, j, k, w = draw_triplet_design_device(
            fold(key, "design"), n1, n2, cfg.n_pairs, cfg.design,
            floor_one=True,
        )
        vals = kernel.triplet_values(X[i], X[j], Y[k], jnp)
        return (jnp.sum(vals * w, dtype=jnp.float32)
                / jnp.sum(w, dtype=jnp.float32))

    return jax.jit(jax.vmap(designed_rep))


def fixed_dataset(cfg: VarianceConfig):
    """The frozen arrays a fix_data=True jax-backend run draws —
    bit-identical to the runner's on-device generation (same fold
    chain, same jax.random stream), so the results audit can compute
    EXACT conditional closed forms against the very dataset the
    committed rows used. Score vectors [n] for diff kernels; feature
    clouds [n, dim] for triplet kernels (the degree-3 runner's gen)."""
    import jax
    import jax.numpy as jnp

    from tuplewise_tpu.utils.rng import fold, root_key

    k1, k2 = jax.random.split(fold(root_key(cfg.seed), "data_fixed"))
    if get_kernel(cfg.kernel).kind == "triplet":
        X = jax.random.normal(
            k1, (cfg.n_pos, cfg.dim), jnp.float32) + cfg.separation
        Y = jax.random.normal(k2, (cfg.n_neg, cfg.dim), jnp.float32)
        return np.asarray(X), np.asarray(Y)
    s1 = jax.random.normal(k1, (cfg.n_pos,), jnp.float32) + cfg.separation
    s2 = jax.random.normal(k2, (cfg.n_neg,), jnp.float32)
    return np.asarray(s1), np.asarray(s2)


_SCHEMES = ("complete", "local", "repartitioned", "incomplete")


def run_variance_experiment(
    cfg: VarianceConfig,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: Optional[int] = None,
    trace_dir: Optional[str] = None,
    chaos=None,
    heal_retries: int = 2,
) -> dict:
    """M-rep Monte-Carlo [SURVEY §4.5]. Returns a JSON-serializable dict
    with mean, empirical variance, wall-clock, the config, and a
    ``recovery`` block (resume point + reshard/retry counters).

    Checkpoint/resume [SURVEY §5.5]: with ``checkpoint_path``, reps run
    in chunks of ``checkpoint_every`` and partial estimates persist after
    each chunk; an existing checkpoint resumes from its saved rep count
    (cfg.n_reps may grow across resumes; every other field must match).
    Per-rep estimates are keyed by absolute rep index, so chunked and
    straight runs produce identical estimate arrays — including across a
    SIGKILL-and-resume. Accumulated compute wall-clock carries across
    resumes.

    Elastic re-sharding [ISSUE 4]: a chunk that fails mid-sweep heals
    through ``parallel.self_heal.MeshHealer`` — probe
    (``faults.detect_dropped_workers`` or the chaos schedule's declared
    topology), rebuild the mesh at the SAME logical ``n_workers`` width
    over the surviving device pool, rebuild the compiled runner on it,
    retry with bounded jittered backoff (at most ``heal_retries``).
    Estimates depend only on (rep, logical shard) fold chains, so the
    healed sweep is bit-identical to a fault-free one. Non-mesh
    backends share the retry/backoff discipline without the reshard.
    ``chaos`` fires at ``mc_chunk`` (per chunk), ``mesh_mc`` (per
    compiled-program dispatch), and ``checkpoint`` (after each save —
    the ``sigkill`` action models preemption with durable state).
    """
    if cfg.scheme not in _SCHEMES:
        raise ValueError(
            f"unknown scheme {cfg.scheme!r}; choose one of {_SCHEMES}"
        )
    if (cfg.scheme in ("local", "repartitioned")
            and cfg.n_workers > min(cfg.n_pos, cfg.n_neg)):
        # m = n // N would be 0: empty worker blocks -> NaN estimates
        raise ValueError(
            f"n_workers={cfg.n_workers} exceeds the per-class sample "
            f"size ({cfg.n_pos}, {cfg.n_neg}); every worker needs at "
            f"least one row per class"
        )

    from tuplewise_tpu.utils.checkpoint import (
        iter_chunks, resume_progress, save_checkpoint,
    )

    start, ck = resume_progress(
        checkpoint_path, cfg.to_json(),
        progress_key="n_reps", requested=cfg.n_reps,
    )
    est_parts, wallclock = [], 0.0
    if ck is not None:
        est_parts = [ck["extra"]["estimates"]]
        wallclock = float(ck["extra"]["wallclock_s"])

    from tuplewise_tpu.parallel.self_heal import Backoff, MeshHealer

    mesh = None
    if cfg.backend == "mesh":
        from tuplewise_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(cfg.n_workers)

    # the runner/estimator live in a rebuildable cell: an elastic
    # re-shard rebuilds them on the healed mesh mid-sweep
    state: dict = {}

    def build(m):
        state["runner"] = _make_vmapped_runner(cfg, mesh=m, chaos=chaos)
        state["warmed"] = set()
        if state["runner"] is None:
            opts = {"mesh": m} if m is not None else {}
            state["est"] = Estimator(
                cfg.kernel, backend=cfg.backend,
                n_workers=cfg.n_workers, **opts
            )

    build(mesh)
    vmapped = state["runner"] is not None

    healer = None
    if heal_retries:
        if mesh is not None:
            import jax

            healer = MeshHealer(
                mesh, fixed_width=cfg.n_workers,
                pool=list(jax.devices()), chaos=chaos,
                backoff=Backoff(seed=cfg.seed))
        else:
            # non-mesh backends: shared retry/backoff, no reshard
            healer = MeshHealer(None, chaos=chaos,
                                backoff=Backoff(seed=cfg.seed))

    def on_heal(h):
        if h.mesh is not None:
            build(h.mesh)

    def run_chunk(m, chunk):
        if state["runner"] is not None:
            import jax.numpy as jnp

            reps = jnp.arange(m, m + chunk)
            if chunk not in state["warmed"]:
                # compile outside the timing window: wallclock stays
                # compute-only, which the variance-vs-wallclock
                # trade-off figure needs
                np.asarray(state["runner"](reps))
                state["warmed"].add(chunk)
            # host copy = synced
            return lambda: np.asarray(state["runner"](reps))
        est = state["est"]
        return lambda: np.asarray([
            _estimate_once(est, cfg, r) for r in range(m, m + chunk)
        ])

    from tuplewise_tpu.utils.profiling import annotate, timer, trace

    with trace(trace_dir):  # jax.profiler scope when requested [§5.2]
        for m, chunk in iter_chunks(start, cfg.n_reps, checkpoint_every):
            def attempt(m=m, chunk=chunk):
                if chaos is not None:
                    chaos.fire("mc_chunk")
                timed = run_chunk(m, chunk)  # warm-up outside the window
                # named span per chunk so the trace digest attributes
                # time to rep ranges, not one undifferentiated blob
                with timer() as t, annotate(f"mc_reps[{m}:{m + chunk}]"):
                    out = timed()
                return out, t["seconds"]

            if healer is not None:
                out, secs = healer.run(attempt, retries=heal_retries,
                                       on_heal=on_heal)
            else:
                out, secs = attempt()
            est_parts.append(out)
            wallclock += secs
            if checkpoint_path:
                save_checkpoint(
                    checkpoint_path,
                    step=m + chunk,
                    extra={
                        "estimates": np.concatenate(est_parts),
                        "wallclock_s": np.asarray(wallclock),
                    },
                    config=cfg.to_json(),
                )
                if chaos is not None:
                    # durable-state preemption point: a 'sigkill' here
                    # dies with exactly m + chunk reps recoverable
                    chaos.fire("checkpoint")
    estimates = np.concatenate(est_parts) if est_parts else np.empty(0)
    try:
        import jax

        # jax.random draws are PLATFORM-dependent (f32 normal synthesis
        # differs TPU vs CPU), so fix_data rows can only be regenerated
        # bit-identically on a matching host; the results audit
        # (scripts/stat_check.py) keys off this stamp
        platform = jax.default_backend()
    except Exception:
        platform = "host"
    result = {
        "config": cfg.to_json(),
        "platform": platform,
        "mean": float(np.mean(estimates)),
        "variance": float(np.var(estimates, ddof=1)),
        "std_error": float(np.std(estimates, ddof=1) / np.sqrt(cfg.n_reps)),
        "wallclock_s": wallclock,
        "vmapped": vmapped,
        "n_reps": cfg.n_reps,
        # fault-tolerance observability [ISSUE 4]: how this row was
        # produced — fresh or resumed, and what recovery fired
        "recovery": {
            "resumed_from": int(start),
            "reshard_events": healer.reshard_events if healer else 0,
            "retries_total": healer.retries_total if healer else 0,
            "mesh_workers": healer.n_workers if healer else None,
        },
    }
    if chaos is not None:
        result["recovery"]["chaos"] = chaos.snapshot()
    if trace_dir:
        result["trace_dir"] = trace_dir
    if cfg.kernel == "auc" and cfg.dim == 1:
        result["population_value"] = true_gaussian_auc(cfg.separation)
    return result


# --------------------------------------------------------------------- #
# trade-off curves [SURVEY §1.2: THE trade-off in the title]            #
# --------------------------------------------------------------------- #

def tradeoff_vs_rounds(cfg: VarianceConfig, rounds=(1, 2, 4, 8, 16)):
    """Variance (and wall-clock) vs number of repartitions T: the
    communication-buys-variance curve [SURVEY §1.2 item 3]."""
    out = []
    for T in rounds:
        c = dataclasses.replace(cfg, scheme="repartitioned", n_rounds=T)
        out.append(run_variance_experiment(c))
    return out


def tradeoff_vs_pairs(cfg: VarianceConfig, pairs=(100, 1000, 10_000, 100_000)):
    """Variance vs sampled-pair budget B [SURVEY §1.1 incomplete]."""
    out = []
    for B in pairs:
        c = dataclasses.replace(cfg, scheme="incomplete", n_pairs=B)
        out.append(run_variance_experiment(c))
    return out


def tradeoff_vs_workers(cfg: VarianceConfig, workers=(2, 8, 32)):
    """Local-average variance vs worker count N — what local averaging
    costs [SURVEY §1.2 item 2]. The deficit over the complete floor
    scales ~1/m with m = n/N per-worker rows, so sweeps should push N
    high enough that blocks get small (see RESULTS.md §3)."""
    bad = [N for N in workers if N > min(cfg.n_pos, cfg.n_neg)]
    if bad:
        # validate the whole sweep BEFORE spending compute on any of it
        raise ValueError(
            f"worker counts {bad} exceed the per-class sample size "
            f"({cfg.n_pos}, {cfg.n_neg}); every worker needs at least "
            f"one row per class"
        )
    out = []
    for N in workers:
        c = dataclasses.replace(cfg, scheme="local", n_workers=N)
        out.append(run_variance_experiment(c))
    return out


def write_jsonl(results, path: str) -> None:
    """Append results (list of dicts) as JSON lines [SURVEY §5.6]."""
    with open(path, "a") as f:
        for r in results:
            f.write(json.dumps(r) + "\n")
