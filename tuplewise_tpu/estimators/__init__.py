from tuplewise_tpu.estimators.estimator import Estimator
from tuplewise_tpu.estimators.streaming import StreamingEstimator
from tuplewise_tpu.estimators.variance import (
    two_sample_zetas,
    two_sample_variance,
    one_sample_zetas,
    one_sample_variance,
    incomplete_variance,
    local_average_variance,
    repartitioned_variance,
)

__all__ = [
    "Estimator",
    "StreamingEstimator",
    "two_sample_zetas",
    "two_sample_variance",
    "one_sample_zetas",
    "one_sample_variance",
    "incomplete_variance",
    "local_average_variance",
    "repartitioned_variance",
]
