"""L3 — the user-facing Estimator with the backend plugin boundary.

``Estimator(kernel=..., backend=...)`` [SURVEY §2 L3, §7 step 3;
BASELINE.json:5]. Semantics (what is estimated) are fixed here; execution
(how the tuple sums run: serial NumPy, tiled XLA, or SPMD over a TPU
mesh) is the backend's job.

Input convention:
* score-difference kernels ("auc", "hinge", "logistic") take 1-D *score*
  arrays — apply your scoring function first (see
  tuplewise_tpu.models.scorers), mirroring the reference's separation of
  scoring from kernel evaluation [SURVEY §1.1].
* feature kernels ("scatter", triplet kernels) take [n, d] feature arrays.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from tuplewise_tpu.backends.base import get_backend
from tuplewise_tpu.ops.kernels import get_kernel


class Estimator:
    """Distributed tuplewise (U-statistic) estimator [SURVEY §1.2].

    Args:
      kernel: kernel name or Kernel instance (L1 plugin).
      backend: "numpy" (serial oracle), "jax" (single-device XLA),
        or "mesh" (SPMD over a device mesh).
      n_workers: default number of (simulated or real) workers N.
      heal_retries: > 0 arms elastic self-healing for mesh backends
        [ISSUE 4]: a scheme call that fails (device death surfaces as
        the dispatch raising) probes the mesh, rebuilds it at the SAME
        shard count over the surviving device pool, rebuilds the
        backend on it, and retries with bounded jittered backoff
        (``parallel.self_heal.MeshHealer``). Values are unchanged by a
        reshard: backends re-pack inputs per call and every key folds
        from (seed, shard index), never from physical placement. 0
        (default) = no retry wrapper, zero overhead.
      chaos: a ``testing.chaos.FaultInjector`` fired at the
        ``estimator`` hook before each scheme call (and consulted for
        the declared dead-worker topology during a heal).
      **backend_opts: forwarded to the backend constructor
        (e.g. block_size, mesh).
    """

    def __init__(self, kernel="auc", backend: str = "numpy",
                 n_workers: Optional[int] = None, heal_retries: int = 0,
                 chaos=None, **backend_opts):
        self.kernel = get_kernel(kernel)
        self.backend_name = backend
        if (backend == "mesh" and "mesh" not in backend_opts
                and "n_workers" not in backend_opts and n_workers is not None):
            # one worker per chip: size the mesh from n_workers
            backend_opts["n_workers"] = n_workers
        self._backend_opts = dict(backend_opts)
        self.backend = get_backend(backend, self.kernel, **backend_opts)
        if hasattr(self.backend, "n_shards"):
            # mesh backends pin N to the mesh (one worker per chip); an
            # explicitly requested different N is a config error, not
            # something to silently override
            if n_workers is not None and n_workers != self.backend.n_shards:
                raise ValueError(
                    f"n_workers={n_workers} conflicts with the mesh's "
                    f"{self.backend.n_shards} shards (one worker per chip)"
                )
            self.n_workers = self.backend.n_shards
        else:
            self.n_workers = 1 if n_workers is None else int(n_workers)
        self.chaos = chaos
        self.heal_retries = int(heal_retries)
        self._healer = None
        if self.heal_retries and backend == "mesh":
            import jax

            from tuplewise_tpu.parallel.self_heal import MeshHealer

            self._healer = MeshHealer(
                self.backend.mesh, fixed_width=self.backend.n_shards,
                pool=list(jax.devices()), chaos=chaos)

    # ------------------------------------------------------------------ #
    def _call(self, fn):
        """Run one scheme call, optionally under the shared
        heal-and-retry protocol [ISSUE 4]."""
        def attempt():
            if self.chaos is not None:
                self.chaos.fire("estimator")
            return fn(self.backend)

        if self._healer is None:
            return attempt()
        return self._healer.run(attempt, retries=self.heal_retries,
                                on_heal=self._on_heal)

    def _on_heal(self, healer):
        """Rebuild the mesh backend on the healed mesh (same shard
        count — the experiment's N is semantic, so lost slots were
        backfilled from spares). Inputs are re-packed per call, so no
        other state needs re-placement."""
        opts = dict(self._backend_opts)
        opts.pop("mesh", None)
        opts.pop("n_workers", None)
        self.backend = get_backend("mesh", self.kernel,
                                   mesh=healer.mesh, **opts)

    # ------------------------------------------------------------------ #
    def _resolve_workers(self, n_workers: Optional[int]) -> int:
        n = self.n_workers if n_workers is None else n_workers
        if n < 1:
            raise ValueError(f"n_workers must be >= 1, got {n}")
        return n

    def _prep(self, A, B):
        """Validate shapes. Only the numpy oracle forces a host float64
        copy; device backends receive the input as-is (so jax arrays stay
        on device) and cast to their compute dtype themselves."""
        k = self.kernel

        def cast(x):
            if x is None:
                return None
            if self.backend_name == "numpy":
                return np.asarray(x, dtype=np.float64)
            return x if hasattr(x, "ndim") else np.asarray(x)

        A, B = cast(A), cast(B)
        if k.two_sample and B is None:
            raise ValueError(f"kernel {k.name!r} is two-sample: pass (A, B)")
        if not k.two_sample and B is not None:
            raise ValueError(f"kernel {k.name!r} is one-sample: pass A only")
        if k.kind == "diff":
            if A.ndim == 2 and A.shape[1] == 1:
                A = A[:, 0]
            if B is not None and B.ndim == 2 and B.shape[1] == 1:
                B = B[:, 0]
            if A.ndim != 1 or (B is not None and B.ndim != 1):
                raise ValueError(
                    f"kernel {k.name!r} operates on scalar scores; got "
                    f"shapes {A.shape}{'' if B is None else ', ' + str(B.shape)}. "
                    "Apply a scorer (tuplewise_tpu.models.scorers) first."
                )
        elif A.ndim != 2 or (B is not None and B.ndim != 2):
            raise ValueError(f"kernel {k.name!r} expects [n, d] features")
        return A, B

    # ------------------------------------------------------------------ #
    # the four estimator schemes [SURVEY §1.2]                            #
    # ------------------------------------------------------------------ #
    def complete(self, A, B=None) -> float:
        """Complete U_n — every tuple, the gold standard [SURVEY §1.2.1]."""
        A, B = self._prep(A, B)
        return float(self._call(lambda be: be.complete(A, B)))

    def local_average(self, A, B=None, *, seed: int = 0,
                      scheme: str = "swor",
                      n_workers: Optional[int] = None,
                      dropped_workers: tuple = ()) -> float:
        """U^loc_N — per-worker complete U, averaged; zero repartition
        cost, extra variance from ignored cross-worker tuples
        [SURVEY §1.2.2]. ``dropped_workers``: failed workers to exclude,
        renormalizing over survivors (parallel.faults, SURVEY §5.4)."""
        A, B = self._prep(A, B)
        return float(self._call(lambda be: be.local_average(
            A, B, n_workers=self._resolve_workers(n_workers),
            seed=seed, scheme=scheme, dropped_workers=dropped_workers)))

    def repartitioned(self, A, B=None, *, n_rounds: int, seed: int = 0,
                      scheme: str = "swor",
                      n_workers: Optional[int] = None,
                      dropped_workers: tuple = ()) -> float:
        """U_{N,T} — T reshuffle rounds of local averaging; communication
        buys variance [SURVEY §1.2.3]. ``dropped_workers``: failed
        workers excluded from every round (drop-and-renormalize)."""
        if n_rounds < 1:
            raise ValueError(f"n_rounds must be >= 1, got {n_rounds}")
        A, B = self._prep(A, B)
        return float(self._call(lambda be: be.repartitioned(
            A, B, n_workers=self._resolve_workers(n_workers),
            n_rounds=n_rounds, seed=seed, scheme=scheme,
            dropped_workers=dropped_workers)))

    def incomplete(self, A, B=None, *, n_pairs: int, seed: int = 0,
                   design: str = "swr") -> float:
        """U~_B — B sampled tuples [SURVEY §1.2.4]. ``design``:
        "swr" (with replacement, the default), "swor" (distinct tuples,
        finite-population variance reduction), or "bernoulli"
        (independent per-tuple inclusion at rate B/|grid|)."""
        if n_pairs < 1:
            raise ValueError(f"n_pairs must be >= 1, got {n_pairs}")
        A, B = self._prep(A, B)
        return float(self._call(lambda be: be.incomplete(
            A, B, n_pairs=n_pairs, seed=seed, design=design)))
