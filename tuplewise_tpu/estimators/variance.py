"""Closed-form U-statistic variance via the Hoeffding decomposition.

The paper's analysis machinery [SURVEY §1.1] — used as the statistical
test oracle [SURVEY §5.1]: empirical variances from the Monte-Carlo
harness must match these formulas on Gaussian data.

Population zeta components (two-sample, degree (1,1)):
    zeta_10 = Var( E[h(X,Y) | X] ),  zeta_01 = Var( E[h(X,Y) | Y] ),
    zeta_11 = Var( h(X,Y) )
    Var(U_n) = [ zeta_11 + (n2-1) zeta_10 + (n1-1) zeta_01 ] / (n1 n2)

Incomplete U with B tuples drawn with replacement:
    Var(U~_B) = Var(U_n) + (1/B) (zeta_11 - Var(U_n))     [SURVEY §1.1]

Given data here is a *sample*, the zetas are estimated empirically
(plug-in, blockwise); tests account for plug-in noise with tolerances.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from tuplewise_tpu.ops.kernels import Kernel, get_kernel

_BLOCK = 4096


def _pair_moments(kernel: Kernel, A, B) -> Tuple[np.ndarray, np.ndarray, float, float]:
    """Blockwise row means, col means, overall mean, mean of h^2."""
    n1, n2 = len(A), len(B)
    row_sum = np.zeros(n1)
    col_sum = np.zeros(n2)
    sq_sum = 0.0
    for i0 in range(0, n1, _BLOCK):
        a = A[i0 : i0 + _BLOCK]
        for j0 in range(0, n2, _BLOCK):
            m = np.asarray(kernel.pair_matrix(a, B[j0 : j0 + _BLOCK], np))
            row_sum[i0 : i0 + len(a)] += m.sum(axis=1)
            col_sum[j0 : j0 + m.shape[1]] += m.sum(axis=0)
            sq_sum += float(np.sum(m * m))
    row_mean = row_sum / n2
    col_mean = col_sum / n1
    mean = float(row_sum.sum() / (n1 * n2))
    return row_mean, col_mean, mean, sq_sum / (n1 * n2)


def two_sample_zetas(kernel, A, B) -> Tuple[float, float, float]:
    """Plug-in estimates of (zeta_10, zeta_01, zeta_11)."""
    kernel = get_kernel(kernel)
    row_mean, col_mean, mean, h2_mean = _pair_moments(kernel, A, B)
    z10 = float(np.var(row_mean))
    z01 = float(np.var(col_mean))
    z11 = h2_mean - mean**2
    return z10, z01, max(z11, 0.0)


def two_sample_variance_from_zetas(zetas, n1: int, n2: int) -> float:
    z10, z01, z11 = zetas
    return (z11 + (n2 - 1) * z10 + (n1 - 1) * z01) / (n1 * n2)


def two_sample_variance(kernel, A, B) -> float:
    """Var(U_n) for the complete two-sample U-statistic [SURVEY §1.1]."""
    return two_sample_variance_from_zetas(
        two_sample_zetas(kernel, A, B), len(A), len(B)
    )


def one_sample_zetas(kernel, A) -> Tuple[float, float]:
    """(zeta_1, zeta_2) for a symmetric one-sample degree-2 kernel."""
    kernel = get_kernel(kernel)
    n = len(A)
    row_sum = np.zeros(n)
    sq_sum = 0.0
    diag = np.zeros(n)
    diag_sq = 0.0
    for i0 in range(0, n, _BLOCK):
        a = A[i0 : i0 + _BLOCK]
        for j0 in range(0, n, _BLOCK):
            m = np.asarray(kernel.pair_matrix(a, A[j0 : j0 + _BLOCK], np))
            if i0 == j0:
                d = np.diagonal(m).copy()
                diag[i0 : i0 + len(d)] = d
                diag_sq += float(np.sum(d * d))
            row_sum[i0 : i0 + len(a)] += m.sum(axis=1)
            sq_sum += float(np.sum(m * m))
    # exclude the diagonal (i != j)
    row_mean = (row_sum - diag) / (n - 1)
    total = row_sum.sum() - diag.sum()
    mean = total / (n * (n - 1))
    h2_mean = (sq_sum - diag_sq) / (n * (n - 1))
    z1 = float(np.var(row_mean))
    z2 = max(h2_mean - mean**2, 0.0)
    return z1, z2


def one_sample_variance_from_zetas(zetas, n: int) -> float:
    z1, z2 = zetas
    return (2.0 / (n * (n - 1))) * (2.0 * (n - 2) * z1 + z2)


def one_sample_variance(kernel, A) -> float:
    """Var(U_n) = (2/(n(n-1))) [ 2(n-2) zeta_1 + zeta_2 ] [SURVEY §1.1]."""
    return one_sample_variance_from_zetas(one_sample_zetas(kernel, A), len(A))


def _zetas_and_sizes(kernel, A, B):
    """One pair-grid sweep; everything below derives from it."""
    kernel = get_kernel(kernel)
    if kernel.two_sample:
        return kernel, two_sample_zetas(kernel, A, B), (len(A), len(B))
    return kernel, one_sample_zetas(kernel, A), (len(A),)


def _complete_var(kernel, zetas, sizes) -> float:
    if kernel.two_sample:
        return two_sample_variance_from_zetas(zetas, *sizes)
    return one_sample_variance_from_zetas(zetas, sizes[0])


def _local_var(kernel, zetas, sizes, n_workers: int) -> float:
    """Var(U^loc_N) under proportional SWOR partitioning, fresh-draw
    approximation (accurate up to O(1/n) partition-coupling terms):
    each worker holds n/N points, workers treated independent, so
    Var = Var(U_{n/N}) / N [SURVEY §1.2 item 2]."""
    per = tuple(s // n_workers for s in sizes)
    if min(per) < 2:
        raise ValueError(
            f"n_workers={n_workers} leaves per-worker sample sizes {per}; "
            "need at least 2 points per worker and class for a local "
            "U-statistic"
        )
    return _complete_var(kernel, zetas, per) / n_workers


def local_variance_from_zetas(zetas, n1, n2, *, n_workers: int) -> float:
    """Zeta-level Var(U^loc_N) for two-sample statistics — the single
    source of truth shared by the data-level API and the results audit
    (scripts/stat_check.py)."""
    per = (n1 // n_workers, n2 // n_workers)
    if min(per) < 2:
        raise ValueError(
            f"n_workers={n_workers} leaves per-worker sizes {per}; need "
            "at least 2 rows per worker and class"
        )
    return two_sample_variance_from_zetas(zetas, *per) / n_workers


def repartitioned_variance_from_zetas(
    zetas, n1, n2, *, n_workers: int, n_rounds: int
) -> float:
    """Zeta-level Var(U_{N,T}): complete floor + deficit / T."""
    vc = two_sample_variance_from_zetas(zetas, n1, n2)
    v_loc = local_variance_from_zetas(zetas, n1, n2, n_workers=n_workers)
    return vc + max(v_loc - vc, 0.0) / n_rounds


def incomplete_variance_from_zetas(
    zetas, n1, n2, *, n_pairs: int, design: str = "swr"
) -> float:
    """Zeta-level Var(U~_B) by sampling design [SURVEY §1.1 incomplete;
    VERDICT r3 next #4].

    swr (with replacement): Var(U_n) + (zeta_11 - Var(U_n)) / B — the
    conditional-on-data sampling noise is s^2/B with E[s^2] =
    zeta_11 - Var(U_n) (total kernel variance minus the part the
    complete U already carries).

    swor (B DISTINCT tuples): simple random sampling without
    replacement from the G = n1*n2 grid multiplies the conditional
    term by the finite-population factor; with S^2 the (G-1)-ddof grid
    variance, Var(mean) = (S^2/B)(1 - B/G) and E[S^2] =
    (G/(G-1)) E[s^2], giving
        Var = Var(U_n) + (zeta_11 - Var(U_n)) * (G - B) / (B (G - 1)).
    At B = G this hits the complete floor exactly — the variance
    reduction the distinct designs exist for.

    bernoulli: realized size K ~ Binomial(G, B/G) then a uniform
    distinct K-set (parallel.partition.draw_pair_design); E over K of
    the swor form is the swor value up to O(1/B) relative corrections
    (CV^2 of K), far below the audit's z resolution.
    """
    vc = two_sample_variance_from_zetas(zetas, n1, n2)
    if design == "swr":
        return vc + (zetas[-1] - vc) / n_pairs
    if design in ("swor", "bernoulli"):
        grid = n1 * n2
        fpc = (grid - n_pairs) / (n_pairs * (grid - 1.0))
        return vc + (zetas[-1] - vc) * fpc
    raise ValueError(f"unknown sampling design {design!r}")


def conditional_incomplete_variance(
    grid_var: float, grid: int, *, n_pairs: int, design: str = "swr"
) -> float:
    """EXACT Var(U~_B | data) from the grid variance of the kernel
    values on a FIXED dataset (for the AUC indicator kernel,
    grid_var = U(1-U) with U the complete statistic — no plug-in).

    This is where the design choice lives [VERDICT r3 next #4]:
      swr        s^2 / B                     (s^2 = ddof-0 grid var)
      swor       (S^2/B)(1 - B/G),  S^2 = s^2 G/(G-1) — at B = G/2 the
                 conditional variance HALVES vs swr; at B = G it is 0
      bernoulli  E_K[swor(K)] over K ~ Binomial(G, B/G) — equals the
                 swor value up to O(1/B) relative corrections
    Unconditionally the difference is sigma_h^2/G, invisible against
    Var(U_n) ~ zeta_1/n; harness fix_data=True rows measure exactly
    this conditional quantity.
    """
    if design == "swr":
        return grid_var / n_pairs
    if design in ("swor", "bernoulli"):
        big_s2 = grid_var * grid / (grid - 1.0)
        return (big_s2 / n_pairs) * (1.0 - n_pairs / grid)
    raise ValueError(f"unknown sampling design {design!r}")


def incomplete_variance(kernel, A, B=None, *, n_pairs: int) -> float:
    """Var of the incomplete U-statistic with B tuples drawn with
    replacement: Var(U_n) + (zeta_11 - Var(U_n)) / B [SURVEY §1.1]."""
    kernel, zetas, sizes = _zetas_and_sizes(kernel, A, B)
    var_u = _complete_var(kernel, zetas, sizes)
    z_full = zetas[-1]  # zeta_11 (two-sample) / zeta_2 (one-sample)
    return var_u + (z_full - var_u) / n_pairs


def local_average_variance(kernel, A, B=None, *, n_workers: int) -> float:
    """Var(U^loc_N) — see :func:`_local_var` [SURVEY §1.2 item 2]."""
    kernel, zetas, sizes = _zetas_and_sizes(kernel, A, B)
    return _local_var(kernel, zetas, sizes, n_workers)


def repartitioned_variance(
    kernel, A, B=None, *, n_workers: int, n_rounds: int
) -> float:
    """Var(U_{N,T}) for T SWOR repartition rounds [SURVEY §1.2 item 3].

    Decompose Var(U^loc_N) = Var(U_n) + extra, where `extra` is the
    variance added by ignoring cross-worker tuples. Fresh reshuffles
    redraw the partition but NOT the data, so the U_n component is common
    across rounds while `extra` averages down:
        Var(U_{N,T}) ~= Var(U_n) + extra / T
    — the trade-off curve in the paper's title.
    """
    kernel, zetas, sizes = _zetas_and_sizes(kernel, A, B)
    var_complete = _complete_var(kernel, zetas, sizes)
    var_loc = _local_var(kernel, zetas, sizes, n_workers)
    extra = max(var_loc - var_complete, 0.0)
    return var_complete + extra / n_rounds
