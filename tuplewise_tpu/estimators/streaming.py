"""L3 — the streaming twin of ``Estimator``: one facade over the
serving-layer state machines.

``Estimator`` answers batch questions about arrays it is handed;
``StreamingEstimator`` absorbs a stream of (score, label) events and
answers at any time:

* ``auc()``       — EXACT AUC of everything observed (or of the sliding
                    window), via the incremental rank index — matches
                    the batch ``rank_auc`` / NumPy oracle on the same
                    prefix (serving/index.py).
* ``estimate()``  — the budgeted incomplete-U estimate of the kernel
                    mean (B pairs per arrival against reservoir
                    history) — the paper's variance-vs-budget knob in
                    the online regime (serving/streaming.py).

It is synchronous and single-threaded (library use, tests, notebooks);
the async micro-batched request path around the same state machines is
``serving.MicroBatchEngine``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from tuplewise_tpu.serving.index import ExactAucIndex
from tuplewise_tpu.serving.streaming import StreamingIncompleteU


class StreamingEstimator:
    """Online tuplewise estimator over a scored event stream.

    Args:
      kernel: two-sample score-difference kernel ("auc", "hinge",
        "logistic"). The exact index exists only for "auc" (the
        Mann-Whitney rank structure is what makes exactness cheap);
        other kernels still get the incomplete estimate.
      budget: incomplete-U pairs spent per arrival.
      reservoir: per-class reservoir capacity for the incomplete path.
      design: partner sampling design, "swr" or "swor".
      window: sliding window in arrivals for the exact index;
        None = unbounded.
      engine: exact-index count/compaction engine, "jax" or "numpy".
      seed: RNG seed for the incomplete path's partner draws.
      health: optional ``obs.health.EstimateHealth`` receiving every
        kernel-term batch — CI-width/variance tracking of the
        incomplete estimate [ISSUE 7]; ``health_report()`` renders it.
    """

    def __init__(self, kernel: str = "auc", *, budget: int = 64,
                 reservoir: int = 4096, design: str = "swr",
                 window: Optional[int] = None, compact_every: int = 512,
                 engine: str = "jax", seed: int = 0, health=None):
        self.kernel_name = kernel if isinstance(kernel, str) else kernel.name
        self.index = ExactAucIndex(
            window=window, compact_every=compact_every, engine=engine,
        ) if self.kernel_name == "auc" else None
        self.streaming = StreamingIncompleteU(
            kernel=kernel, budget=budget, reservoir=reservoir,
            design=design, seed=seed, health=health,
        )

    # ------------------------------------------------------------------ #
    def observe(self, score: float, label) -> None:
        """One event: a score and its binary label (truthy = positive)."""
        self.extend([score], [label])

    def extend(self, scores, labels) -> None:
        """A micro-batch of events, in arrival order."""
        scores = np.asarray(scores, dtype=np.float64).ravel()
        labels = np.asarray(labels).ravel().astype(bool)
        if self.index is not None:
            self.index.insert_batch(scores, labels)
        self.streaming.extend(scores, labels)

    # ------------------------------------------------------------------ #
    def auc(self) -> Optional[float]:
        """Exact AUC of the observed prefix/window; None before both
        classes appear (or for non-AUC kernels)."""
        return None if self.index is None else self.index.auc()

    def estimate(self) -> Optional[float]:
        """Budgeted incomplete-U estimate of the kernel mean."""
        return self.streaming.estimate()

    def score(self, scores) -> np.ndarray:
        """Fractional rank of candidate scores against current
        negatives (AUC kernel only)."""
        if self.index is None:
            raise ValueError("score() needs the exact index (kernel='auc')")
        return self.index.score_batch(scores)

    @property
    def n_pos(self) -> int:
        return self.index.n_pos if self.index is not None else \
            self.streaming._pos.seen

    @property
    def n_neg(self) -> int:
        return self.index.n_neg if self.index is not None else \
            self.streaming._neg.seen

    def health_report(self) -> Optional[dict]:
        """The CI-width monitor's state (None when no ``health`` was
        attached) — variance / std error / i.i.d. and batch-mean CI
        widths of the incomplete estimate."""
        h = self.streaming.health
        return None if h is None else h.state()

    def state(self) -> dict:
        out = {"kernel": self.kernel_name,
               "streaming": self.streaming.state()}
        if self.index is not None:
            out["index"] = self.index.state()
            out["auc"] = self.index.auc()
        out["estimate"] = self.streaming.estimate()
        return out
