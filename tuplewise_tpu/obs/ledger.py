"""Host-tax wave ledger [ISSUE 14 tentpole]: attribute every
microsecond of the insert request path.

The bench record is blunt — device counts are microseconds while CPU
insert p99 sits at milliseconds — and the ROADMAP's one-dispatch
serving core is justified entirely by the claim that host-side Python
dominates. This module *measures* that split: each insert micro-batch
("wave") decomposes its wall time into exhaustive, non-overlapping
buckets

* ``queue_wait``     — enqueue → batcher pickup (per request),
* ``lock_wait``      — waiting on the engine's estimator lock,
* ``host_python``    — plan assembly, per-tenant dict hops,
                       splice-merge, WAL append: everything on the
                       request thread that is neither a device call
                       nor a GC pause,
* ``dispatch``       — inside the jitted call until it returns (on
                       TPU: enqueue-only; on CPU jax, execution is
                       largely synchronous, so inline compute lands
                       here — see DESIGN §18),
* ``device_compute`` — from dispatch return to the blocking
                       host-transfer boundary (``np.asarray`` /
                       ``block_until_ready``),
* ``xla_compile``    — a dispatch whose (function, shape-ladder) key
                       was never seen before: the first-call / ladder-
                       growth compile, the runtime twin of the
                       ``compile_ladder`` static pass,
* ``gc_pause``       — cyclic-GC pauses on the wave's thread
                       (``gc.callbacks``),

with a hard invariant: per-request bucket sums equal the measured
insert latency EXACTLY (``host_python`` is the remainder after the
directly-measured buckets, so the tiling is 1.0 by construction — the
PR 6 stage-attribution discipline extended below the stage level).

Wiring: the engine opens a wave on its batcher thread
(:meth:`WaveLedger.begin_wave`); the dispatch boundaries in
``serving/index.py`` and ``parallel/sharded_counts.py`` wrap their
jitted calls in :func:`device_section` — a thread-local lookup, so a
dispatch outside any wave (compactor builds, prewarm compiles, score
waves) costs one ``getattr`` and records nothing. Compile detection is
first-seen per dispatch key in a process-global set, mirroring the
process-global jit caches: a warmed engine correctly reports zero
request-thread compiles.

Metrics (all through the engine's ``MetricsRegistry``, so they ride
``MetricsFlusher`` / SLO / doctor for free): ``host_tax_<bucket>_s``
histograms, ``host_tax_host_fraction`` / ``host_tax_device_fraction``
gauges, ``xla_compile_events_total`` / ``gc_pauses_total`` counters,
and a ``gc_pause_s`` histogram of individual pauses.
"""

from __future__ import annotations

import gc
import threading
import time
from typing import Dict, List, Optional

# bucket order is the tiling order the report/table renders
BUCKETS = ("queue_wait", "lock_wait", "host_python", "dispatch",
           "device_compute", "xla_compile", "gc_pause")

# thread-local active wave: device_section / the gc hook look it up
_ACTIVE = threading.local()

# process-global first-seen dispatch keys — compile caches (lru_cached
# jit factories, jax's own cache) are process-global, so "first call
# with this key" must be too; the lock serializes concurrent engines
_SEEN_LOCK = threading.Lock()
_SEEN: set = set()

_GC_LOCK = threading.Lock()
_GC_INSTALLED = False


def reset_seen() -> None:
    """Forget every seen dispatch key (tests: make compile-event
    classification deterministic per test)."""
    with _SEEN_LOCK:
        _SEEN.clear()


def _note_key(key) -> bool:
    """True exactly once per key process-wide (=> compile event)."""
    with _SEEN_LOCK:
        if key in _SEEN:
            return False
        _SEEN.add(key)
        return True


def _gc_hook(phase, info) -> None:
    """gc.callbacks hook: bill collection pauses to the active wave of
    the thread the collection ran on. Collections on non-wave threads
    (flusher, compactor) record nothing — they never pause the request
    path."""
    wave = getattr(_ACTIVE, "wave", None)
    if wave is None:
        return
    if phase == "start":
        wave._gc_t0 = time.perf_counter()
    elif wave._gc_t0 is not None:
        wave.gc_pauses.append(time.perf_counter() - wave._gc_t0)
        wave._gc_t0 = None


def _ensure_gc_hook() -> None:
    global _GC_INSTALLED
    with _GC_LOCK:
        if not _GC_INSTALLED:
            gc.callbacks.append(_gc_hook)
            _GC_INSTALLED = True


class _Wave:
    """Accumulator for one insert micro-batch; thread-confined to the
    batcher thread that opened it."""

    __slots__ = ("dispatch_s", "compute_s", "compile_s",
                 "compile_events", "gc_pauses", "_gc_t0")

    def __init__(self):
        self.dispatch_s = 0.0
        self.compute_s = 0.0
        self.compile_s = 0.0
        self.compile_events = 0
        self.gc_pauses: List[float] = []
        self._gc_t0: Optional[float] = None


class _DeviceSection:
    """Context manager wrapping one device dispatch::

        with device_section(("count", bb, qb)) as ds:
            out = jit_fn(args)      # dispatch (compile on first key)
            ds.dispatched()         # the call returned
            host = np.asarray(out)  # device compute + d2h, blocking

    [enter, dispatched] bills ``dispatch`` (or ``xla_compile`` when
    the key is first-seen); [dispatched, exit] bills
    ``device_compute``. No active wave on this thread => pure no-op.
    """

    __slots__ = ("_key", "_wave", "_t0", "_t_disp")

    def __init__(self, key):
        self._key = key
        self._wave = None
        self._t0 = 0.0
        self._t_disp = None

    def __enter__(self) -> "_DeviceSection":
        self._wave = getattr(_ACTIVE, "wave", None)
        if self._wave is not None:
            self._t_disp = None
            self._t0 = time.perf_counter()
        return self

    def dispatched(self) -> None:
        if self._wave is not None:
            self._t_disp = time.perf_counter()

    def __exit__(self, exc_type, exc, tb) -> bool:
        w = self._wave
        if w is not None:
            t1 = time.perf_counter()
            td = self._t_disp if self._t_disp is not None else t1
            if _note_key(self._key):
                w.compile_s += td - self._t0
                w.compile_events += 1
            else:
                w.dispatch_s += td - self._t0
            w.compute_s += max(0.0, t1 - td)
            self._wave = None
        return False


def device_section(key) -> _DeviceSection:
    """The one-line hook every dispatch boundary uses. ``key`` must be
    hashable and identify the compiled artifact (function family +
    every shape/ladder/mesh input of its jit cache key)."""
    return _DeviceSection(key)


class WaveLedger:
    """Per-engine host-tax accounting over insert waves.

    Always-on (unlike the sampling profiler): a wave costs a handful
    of ``perf_counter`` readings on top of the stage attribution the
    engine already pays, and the tiling invariant is the contract the
    perf gate and obs smoke assert on every run.
    """

    def __init__(self, metrics):
        self._h = {b: metrics.histogram(f"host_tax_{b}_s")
                   for b in BUCKETS}
        self._g_host = metrics.gauge("host_tax_host_fraction")
        self._g_dev = metrics.gauge("host_tax_device_fraction")
        self._c_compile = metrics.counter("xla_compile_events_total")
        self._c_gc = metrics.counter("gc_pauses_total")
        self._h_gc = metrics.histogram("gc_pause_s")
        self._c_waves = metrics.counter("host_tax_waves_total")
        # cumulative seconds for the fraction gauges; written only on
        # the batcher thread (finish_wave), read via the gauges
        self._host_s = 0.0
        self._device_s = 0.0
        self._total_s = 0.0
        _ensure_gc_hook()

    # ------------------------------------------------------------------ #
    def begin_wave(self) -> _Wave:
        """Open a wave on THIS thread; device sections and GC pauses
        on this thread now bill to it. Pair with :meth:`finish_wave`
        (or :meth:`abort_wave` on the failure path)."""
        w = _Wave()
        _ACTIVE.wave = w
        return w

    def abort_wave(self, wave: _Wave) -> None:
        """Clear the thread-local binding without recording — the wave
        failed and its requests got exceptions, not latencies."""
        if getattr(_ACTIVE, "wave", None) is wave:
            _ACTIVE.wave = None

    def finish_wave(self, wave: _Wave, *, t_start: float,
                    t_end: float, queue_waits,
                    t_lock_req: Optional[float] = None,
                    t_lock: Optional[float] = None) -> Dict[str, float]:
        """Close the wave and bill its buckets.

        ``queue_waits``: one enqueue→pickup interval per request in
        the wave (each request's measured insert latency is its
        queue_wait plus the shared [t_start, t_end] wave time, and the
        buckets tile exactly that). ``t_lock_req``/``t_lock`` bound
        the estimator-lock acquisition; omitted (fleet path) the lock
        wait stays inside ``host_python``. Returns this wave's bucket
        values (without the per-request queue_wait) — the tail-
        exemplar payload.
        """
        if getattr(_ACTIVE, "wave", None) is wave:
            _ACTIVE.wave = None
        total = max(0.0, t_end - t_start)
        lock_wait = 0.0
        if t_lock_req is not None and t_lock is not None:
            lock_wait = max(0.0, t_lock - t_lock_req)
        gc_s = sum(wave.gc_pauses)
        direct = (lock_wait + wave.dispatch_s + wave.compute_s
                  + wave.compile_s + gc_s)
        host_py = total - direct
        if host_py < 0.0:
            # a GC pause can overlap a device section (the collection
            # triggered inside dispatch-side Python): shave the
            # overlap off the gc bucket first, then off dispatch, so
            # the tiling stays exact instead of summing past 100%
            deficit = -host_py
            shaved = min(gc_s, deficit)
            gc_s -= shaved
            deficit -= shaved
            wave.dispatch_s = max(0.0, wave.dispatch_s - deficit)
            host_py = 0.0
        n = len(queue_waits)
        h = self._h
        qw_sum = sum(queue_waits)
        h["queue_wait"].observe_many(queue_waits)
        if n:
            # wave-shared buckets bill weighted (sum exact, ONE ring
            # sample per wave): observe_n's per-request sample copies
            # cost ~3-4% of serving throughput at max_batch fill, and
            # the host-tax p99 table wants the per-wave distribution
            # anyway. Zero-valued buckets still contribute their
            # (zero) weight so counts stay per-request everywhere.
            h["lock_wait"].observe_weighted(lock_wait, n)
            h["host_python"].observe_weighted(host_py, n)
            h["dispatch"].observe_weighted(wave.dispatch_s, n)
            h["device_compute"].observe_weighted(wave.compute_s, n)
            h["xla_compile"].observe_weighted(wave.compile_s, n)
            h["gc_pause"].observe_weighted(gc_s, n)
        if wave.compile_events:
            self._c_compile.inc(wave.compile_events)
        if wave.gc_pauses:
            self._c_gc.inc(len(wave.gc_pauses))
            for p in wave.gc_pauses:
                self._h_gc.observe(p)
        self._c_waves.inc()
        # fraction gauges: host = everything that is not device
        # compute or compile — queue/lock waits, Python, dispatch
        # glue, GC; the split the one-dispatch refactor must move
        self._host_s += qw_sum + n * (lock_wait + host_py
                                      + wave.dispatch_s + gc_s)
        self._device_s += n * wave.compute_s
        self._total_s += qw_sum + n * total
        if self._total_s > 0:
            self._g_host.set(self._host_s / self._total_s)
            self._g_dev.set(self._device_s / self._total_s)
        return {
            "lock_wait": lock_wait,
            "host_python": host_py,
            "dispatch": wave.dispatch_s,
            "device_compute": wave.compute_s,
            "xla_compile": wave.compile_s,
            "gc_pause": gc_s,
        }
