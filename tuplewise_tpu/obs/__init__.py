"""End-to-end observability substrate [ISSUE 6].

PRs 1-5 built a serving and batch stack that can only be understood
post-hoc, from exit summaries and scattered JSONL rows. This package is
the instrumentation layer every subsequent ROADMAP item (multi-tenant
SLOs, network front-end latency frontiers, variance-adaptive budgets)
builds on:

* ``tracing.Tracer``        — low-overhead span tracing: monotonic
                              clocks, explicit parent/child span ids,
                              thread-safe ring storage, hard-off by
                              default (call sites hold ``None`` and pay
                              one ``is not None`` check). Exports JSONL
                              and Chrome trace-event JSON so perfetto /
                              ``chrome://tracing`` render the serving
                              timeline directly.
* ``flight.FlightRecorder`` — a bounded structured ring of lifecycle
                              events (compactions, major merges, heals,
                              restarts, chaos injections, snapshot/WAL
                              seals, poison rejects, deadline expiries)
                              with sequence numbers and trace-id
                              correlation; dumped automatically on
                              crash / heal exhaustion / close and
                              persisted alongside recovery snapshots.
* ``metrics_export.MetricsFlusher`` — a side thread appending
                              whole-registry snapshots (wall/monotonic
                              timestamps, platform, config digest) to a
                              JSONL path at a fixed cadence — the live
                              view of a running serve/replay/train/
                              bench process.
* ``report``                — ONE report builder shared by the serve
                              exit summary and ``replay`` records, so
                              the recovery/chaos counters never drift
                              between the two again.

[ISSUE 7] adds the evaluation layer that turns the telemetry above
into verdicts:

* ``slo.SloMonitor``        — declarative SLO objectives (latency
                              quantiles, multi-window burn-rate error
                              budgets, counter caps, saturation) over
                              the existing metrics, judged live at
                              each flusher snapshot; breaches emit
                              ``slo_breach`` flight events and
                              ``slo_*`` gauges.
* ``health``                — statistical monitors of the estimate
                              itself: Welford CI-width tracking
                              (``EstimateHealth``), live-vs-oracle
                              drift (``DriftDetector``), shard-balance
                              skew (``shard_balance``).
* ``doctor``                — post-hoc diagnosis of a run's artifacts
                              (``tuplewise doctor``): SLO + health
                              verdicts, fault->recovery correlation,
                              top self-time spans, one machine-
                              readable verdict line for CI.

[ISSUE 14] adds the host-tax accounting layer (DESIGN §18):

* ``ledger.WaveLedger``     — per-micro-batch wall-clock ledger:
                              exhaustive non-overlapping buckets
                              (host Python / dispatch / device
                              compute / XLA compile / GC pause /
                              lock+queue wait) whose sums tile the
                              measured insert latency exactly;
                              ``device_section`` is the dispatch-
                              boundary hook.
* ``prof.SamplingProfiler`` — hard-off folded-stack sampler with a
                              <= 5% guarded overhead; exports
                              collapsed-stack and speedscope files
                              digested by ``scripts/trace_summary.py``.
"""

from tuplewise_tpu.obs.flight import FlightRecorder
from tuplewise_tpu.obs.health import (
    DriftDetector, EstimateHealth, shard_balance,
)
from tuplewise_tpu.obs.ledger import WaveLedger, device_section
from tuplewise_tpu.obs.metrics_export import MetricsFlusher, config_digest
from tuplewise_tpu.obs.prof import SamplingProfiler
from tuplewise_tpu.obs.report import (
    host_tax_block, recovery_counters, service_report,
)
from tuplewise_tpu.obs.slo import SloMonitor, SloSpec, evaluate_history
from tuplewise_tpu.obs.tracing import Span, Tracer

__all__ = [
    "DriftDetector",
    "EstimateHealth",
    "FlightRecorder",
    "MetricsFlusher",
    "SamplingProfiler",
    "SloMonitor",
    "SloSpec",
    "Span",
    "Tracer",
    "WaveLedger",
    "config_digest",
    "device_section",
    "evaluate_history",
    "host_tax_block",
    "recovery_counters",
    "service_report",
    "shard_balance",
]
