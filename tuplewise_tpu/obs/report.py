"""ONE report builder for serve exit summaries and replay records
[ISSUE 6 satellite].

Before this module, ``tuplewise serve``'s exit summary and
``serving.replay``'s record each hand-picked recovery/chaos counters
from the metrics snapshot — and drifted (replay's ``faults`` block
carried ``shard_retries_total`` but not ``major_merge_fallbacks``; the
serve summary the reverse). Both now call :func:`service_report` /
:func:`recovery_counters` on the same registry snapshot, and a parity
test pins the key sets together.

All inputs are the plain-dict output of ``MetricsRegistry.snapshot()``
— the builder never touches live objects, so it also works on a
metrics.jsonl row or a post-mortem snapshot.
"""

from __future__ import annotations

from typing import Optional

# the insert-latency decomposition [ISSUE 6 tentpole]: consecutive
# boundary timestamps in the engine's insert apply path, so the stage
# values of one request sum EXACTLY to its measured insert latency
INSERT_STAGES = ("queue_wait", "coalesce", "wal_append", "index_insert",
                 "stream_extend", "snapshot", "resolve")


def stage_metric(stage: str) -> str:
    return f"insert_stage_{stage}_s"


# the recovery/chaos counter set BOTH reports carry — extend here, and
# serve + replay + bench stay in lockstep
_RECOVERY_COUNTERS = (
    "reshard_events",
    "shard_retries_total",
    "bg_compactor_restarts",
    "batcher_restarts",
    "major_merge_fallbacks",
    "poison_rejects",
    "deadline_expired_total",
    # a MetricsFlusher.stop() that gave up waiting on a wedged
    # observer and left the in-flight flush to finish late [ISSUE 14
    # bugfix] — nonzero means an observer is slow enough to eat the
    # shutdown timeout
    "flusher_late_flushes_total",
)

# the host-tax bucket taxonomy [ISSUE 14]: the below-stage-level
# decomposition obs.ledger.WaveLedger bills (DESIGN §18). Kept here —
# next to INSERT_STAGES — so the report builder and the ledger can
# never disagree about the bucket set.
HOST_TAX_BUCKETS = ("queue_wait", "lock_wait", "host_python",
                    "dispatch", "device_compute", "xla_compile",
                    "gc_pause")


def host_tax_metric(bucket: str) -> str:
    return f"host_tax_{bucket}_s"


def _v(m: dict, name: str):
    return m.get(name, {}).get("value", 0)


def _p_ms(m: dict, name: str, q: str):
    v = m.get(name, {}).get(q)
    return None if v is None else v * 1e3


def recovery_counters(metrics: dict) -> dict:
    """The unified recovery/chaos counter block (replay's ``faults``
    block and part of the serve exit summary)."""
    return {name: _v(metrics, name) for name in _RECOVERY_COUNTERS}


def stage_p99_ms(metrics: dict) -> dict:
    """Per-stage insert-latency p99s (ms), one entry per stage that
    recorded at least one sample."""
    out = {}
    for stage in INSERT_STAGES:
        p = _p_ms(metrics, stage_metric(stage), "p99")
        if p is not None:
            out[stage] = p
    return out


def stage_attribution(metrics: dict) -> Optional[dict]:
    """How completely the stage decomposition accounts for measured
    insert latency: stage sums vs the ``insert_latency_s`` sum. The
    stages are consecutive intervals of each request's lifetime, so
    ``coverage`` is 1.0 up to float rounding — a materially lower
    value means an unattributed stage crept into the path."""
    total = metrics.get("insert_latency_s", {})
    if not total.get("count"):
        return None
    attributed = sum(
        metrics.get(stage_metric(s), {}).get("sum", 0.0)
        for s in INSERT_STAGES)
    return {
        "attributed_s": attributed,
        "measured_s": total["sum"],
        "coverage": (attributed / total["sum"]) if total["sum"] else None,
    }


def host_tax_block(metrics: dict) -> Optional[dict]:
    """The host-tax ledger summary [ISSUE 14]: fractions, coverage,
    compile/GC event counts, and per-bucket p99s — the block the serve
    exit summary, replay records, ``bench.py --streaming`` and the
    doctor all render from ONE builder. ``coverage`` is bucket sums
    over measured ``insert_latency_s`` sums: 1.0 up to float rounding
    by construction (the ledger's tiling invariant); materially less
    means an unattributed interval crept into the wave path. None when
    the snapshot predates the ledger (no waves recorded)."""
    if not metrics.get("host_tax_waves_total", {}).get("value"):
        return None
    total = metrics.get("insert_latency_s", {})
    attributed = sum(
        metrics.get(host_tax_metric(b), {}).get("sum", 0.0)
        for b in HOST_TAX_BUCKETS)
    batches = _v(metrics, "batches_total")
    compile_events = _v(metrics, "xla_compile_events_total")
    p99 = {}
    for b in HOST_TAX_BUCKETS:
        p = _p_ms(metrics, host_tax_metric(b), "p99")
        if p is not None:
            p99[b] = p
    return {
        "host_fraction": metrics.get(
            "host_tax_host_fraction", {}).get("value"),
        "device_fraction": metrics.get(
            "host_tax_device_fraction", {}).get("value"),
        "coverage": ((attributed / total["sum"])
                     if total.get("sum") else None),
        "attributed_s": attributed,
        "measured_s": total.get("sum", 0.0),
        "waves": _v(metrics, "host_tax_waves_total"),
        "compile_events": compile_events,
        "compile_events_per_1k_batches": (
            1e3 * compile_events / batches if batches else None),
        "gc_pauses": _v(metrics, "gc_pauses_total"),
        "gc_pause_p99_ms": _p_ms(metrics, "gc_pause_s", "p99"),
        "tail_exemplars": _v(metrics, "tail_exemplars_total"),
        "bucket_p99_ms": p99,
    }


def service_report(metrics: dict, chaos=None,
                   flight=None, slo=None) -> dict:
    """The shared serving report: load-shedding, compaction, transfer,
    latency (with per-stage p99 attribution), and recovery counters —
    the block ``tuplewise serve`` prints as its exit summary and
    ``replay`` embeds as ``report``.

    Args:
      metrics: ``MetricsRegistry.snapshot()`` output.
      chaos: optional ``FaultInjector`` — its ``snapshot()`` rides
        along under ``"chaos"``.
      flight: optional ``FlightRecorder`` — per-kind event counts ride
        along under ``"flight_events"``.
      slo: optional ``obs.slo.SloMonitor`` (or a prebuilt report dict)
        — verdicts ride along under ``"slo"`` [ISSUE 7].
    """
    report = {
        "rejected_total": _v(metrics, "rejected_total"),
        "dropped_total": _v(metrics, "dropped_total"),
        "compactions_total": _v(metrics, "compactions_total"),
        "compaction_pause_p99_ms": _p_ms(metrics, "compaction_pause_s",
                                         "p99"),
        "compaction_pause_max_ms": _p_ms(metrics, "compaction_pause_s",
                                         "max"),
        "insert_latency_p99_ms": _p_ms(metrics, "insert_latency_s",
                                       "p99"),
        "insert_stage_p99_ms": stage_p99_ms(metrics),
        "stage_attribution": stage_attribution(metrics),
        # host-tax ledger [ISSUE 14]: None on snapshots that predate
        # the ledger (old metrics.jsonl rows stay diagnosable)
        "host_tax": host_tax_block(metrics),
        "bytes_h2d": _v(metrics, "bytes_h2d"),
        "bytes_h2d_saved": _v(metrics, "bytes_h2d_saved"),
        "major_merges_total": _v(metrics, "major_merges_total"),
    }
    report.update(recovery_counters(metrics))
    # fleet block [ISSUE 8]: only when the metrics came from a
    # multi-tenant engine (single-tenant reports keep their key set)
    if "fleet_count_calls_total" in metrics:
        report["tenancy"] = {
            "tenants_live": _v(metrics, "tenants_live"),
            "tenants_created_total": _v(metrics,
                                        "tenants_created_total"),
            "tenants_evicted_total": _v(metrics,
                                        "tenants_evicted_total"),
            "tenant_rejected_total": _v(metrics,
                                        "tenant_rejected_total"),
            "fleet_count_calls": _v(metrics, "fleet_count_calls_total"),
            "fleet_compact_aborts": _v(metrics, "fleet_compact_aborts"),
            # incremental hot path [ISSUE 9]: whale lifecycle, pack
            # placement accounting, and the metric-cardinality cap
            "whale_promotions": _v(metrics, "fleet_whale_promotions"),
            "whale_demotions": _v(metrics, "fleet_whale_demotions"),
            "whales_live": _v(metrics, "fleet_whales"),
            "pack_replaces": _v(metrics, "pack_replaces_total"),
            "pack_full_replaces": _v(metrics,
                                     "pack_full_replaces_total"),
            "pack_occupancy": _v(metrics, "pack_occupancy"),
            "pack_stale_rows": _v(metrics, "pack_stale_rows"),
            "tenant_metric_collapsed": _v(metrics,
                                          "tenant_metric_collapsed"),
        }
    # control-plane block [ISSUE 11]: only when a FleetController ran
    # (controller-off reports keep their exact pre-controller key set)
    if "controller_actuations_total" in metrics:
        report["controller"] = {
            "actuations_total": _v(metrics,
                                   "controller_actuations_total"),
            "reverts_total": _v(metrics, "controller_reverts_total"),
            "tenant_throttled_total": _v(metrics,
                                         "tenant_throttled_total"),
            "throttled_now": _v(metrics, "controller_throttled_tenants"),
            "flush_scale": _v(metrics, "controller_flush_scale"),
            "max_batch": _v(metrics, "controller_max_batch"),
            "mesh_level": _v(metrics, "controller_mesh_level"),
        }
    if chaos is not None:
        report["chaos"] = chaos.snapshot()
    if flight is not None:
        report["flight_events"] = flight.counts()
    if slo is not None:
        report["slo"] = slo.report() if hasattr(slo, "report") else slo
    return report
