"""Statistical-health monitors for the streaming estimate [ISSUE 7
tentpole].

The source paper (arXiv:1906.09234) and the incomplete-U literature it
builds on (arXiv:1501.02629) price computation against the VARIANCE of
the estimate — so an observability layer that only watches latency is
watching half the system. This module watches the other half:

* :class:`EstimateHealth` — online variance / CI-width tracking for
  the streaming incomplete-U estimator. Welford's algorithm (count,
  mean, M2 updated per kernel batch) rather than the naive
  sum/sum-of-squares the estimator itself keeps: M2 accumulates
  *centered* squares, so the variance stays accurate when ``h`` hovers
  near a constant (AUC kernels emit {0, 0.5, 1} — the naive
  ``E[h^2] - E[h]^2`` cancels catastrophically as both terms approach
  the same magnitude). The CI half-width is

      half = z * sqrt(var / n)       (z = 1.96 at 95%)

  — the i.i.d. width; terms sharing an arrival or a reservoir slot are
  positively correlated, so the true width is wider. The monitor
  reports the i.i.d. number as the *optimistic bound* and its own
  batch-mean width (variance of per-batch means, which honors
  within-batch correlation) alongside; tests validate both against an
  offline NumPy recomputation over the retained terms.

* :class:`DriftDetector` — a windowed comparison of the live
  incomplete estimate against the exact oracle prefix (the exact AUC
  index IS the oracle for the statistic it shadows). A rolling mean of
  ``|live - oracle|`` above threshold means the budgeted estimate has
  wandered from the truth it is supposed to track — reservoir bug,
  poisoned history, or a budget too small for the drift rate of the
  stream.

* :func:`shard_balance` — skew statistics over per-shard occupancy
  (base + delta rows), exported by the sharded index as
  ``shard_skew`` / ``shard_balance_cv`` gauges: contiguous-slice
  placement keeps shards within one row of each other, so a skew
  materially above 1.0 means placement is broken, and the gauge is the
  early-warning surface the multi-tenant engine (ROADMAP) will lean on
  hard.
"""

from __future__ import annotations

import collections
import math
from typing import List, Optional, Sequence

import numpy as np

# two-sided normal critical values for the confidence levels anyone
# actually asks for; anything else falls back to 95%
_Z = {0.90: 1.6448536269514722, 0.95: 1.959963984540054,
      0.99: 2.5758293035489004}


class EstimateHealth:
    """Online variance / CI-width of the streaming estimate's kernel
    terms, fed one batch of ``h`` values at a time by
    ``StreamingIncompleteU.extend``.

    Args:
      confidence: two-sided CI level (0.90 / 0.95 / 0.99).
      metrics: optional ``MetricsRegistry`` receiving the live gauges
        ``estimate_ci_width`` / ``estimate_std_error`` /
        ``estimate_variance`` / ``estimate_terms``.
      retain_terms: keep every term in memory so
        :meth:`offline_check` can recompute the moments with NumPy —
        validation/tests only (unbounded memory by design; a service
        leaves it off).
    """

    def __init__(self, confidence: float = 0.95, metrics=None,
                 retain_terms: bool = False):
        if not 0.0 < confidence < 1.0:
            raise ValueError(
                f"confidence must be in (0, 1): {confidence}")
        self.confidence = confidence
        self.z = _Z.get(round(confidence, 2), _Z[0.95])
        # Welford state over individual kernel terms
        self.n = 0
        self.mean = 0.0
        self.m2 = 0.0
        # Welford state over per-batch means (captures within-batch
        # correlation the i.i.d. width ignores)
        self.n_batches = 0
        self.batch_mean = 0.0
        self.batch_m2 = 0.0
        self._terms: Optional[List[np.ndarray]] = \
            [] if retain_terms else None
        self._g = None
        if metrics is not None:
            self.bind(metrics)

    def bind(self, metrics) -> None:
        """Attach the registry the live gauges land in."""
        self._g = {
            "ci": metrics.gauge("estimate_ci_width"),
            "se": metrics.gauge("estimate_std_error"),
            "var": metrics.gauge("estimate_variance"),
            "n": metrics.gauge("estimate_terms"),
        }

    # ------------------------------------------------------------------ #
    def update(self, h: np.ndarray, s1: Optional[float] = None,
               s2: Optional[float] = None) -> None:
        """Fold one batch of kernel terms in. Chan et al.'s pairwise
        merge of (count, mean, M2): batch moments from the sum and
        sum-of-squares (``s2 - k*m^2`` loses nothing at float64 for
        O(1)-bounded kernel terms at batch size), then an O(1) merge
        into the global M2 — the merge is where long-stream
        cancellation lives, and it stays centered.

        ``s1``/``s2``: precomputed ``sum(h)`` / ``sum(h*h)``. The
        streaming estimator already reduces both for its own running
        sums and passes them in, so the hot path pays ZERO extra
        vector passes — only this O(1) merge."""
        h = np.asarray(h, dtype=np.float64).ravel()
        k = h.size
        if k == 0:
            return
        if s1 is None:
            s1 = float(h.sum())
        if s2 is None:
            s2 = float((h * h).sum())
        bm = s1 / k
        bm2 = max(s2 - k * bm * bm, 0.0)
        d = bm - self.mean
        n = self.n + k
        self.m2 += bm2 + d * d * self.n * k / n
        self.mean += d * k / n
        self.n = n
        # batch-mean moments (one scalar observation per batch)
        self.n_batches += 1
        d = bm - self.batch_mean
        self.batch_mean += d / self.n_batches
        self.batch_m2 += d * (bm - self.batch_mean)
        if self._terms is not None:
            self._terms.append(h.copy())
        if self._g is not None:
            self._g["ci"].set(self.ci_width() or 0.0)
            self._g["se"].set(self.std_error() or 0.0)
            self._g["var"].set(self.variance() or 0.0)
            self._g["n"].set(self.n)

    # ------------------------------------------------------------------ #
    def variance(self) -> Optional[float]:
        """Sample variance of the kernel terms (ddof=1)."""
        if self.n < 2:
            return None
        return self.m2 / (self.n - 1)

    def std_error(self) -> Optional[float]:
        """i.i.d. standard error of the running mean (optimistic: term
        correlation makes the true error larger)."""
        v = self.variance()
        if v is None:
            return None
        return math.sqrt(v / self.n)

    def ci_width(self) -> Optional[float]:
        """Full width (2 * half-width) of the two-sided i.i.d. CI."""
        se = self.std_error()
        if se is None:
            return None
        return 2.0 * self.z * se

    def batch_std_error(self) -> Optional[float]:
        """Standard error from per-batch means — honors within-batch
        correlation (batches are the independent units under the
        micro-batch semantics: a batch pairs against reservoir state
        frozen at batch start)."""
        if self.n_batches < 2:
            return None
        var = self.batch_m2 / (self.n_batches - 1)
        return math.sqrt(var / self.n_batches)

    def batch_ci_width(self) -> Optional[float]:
        se = self.batch_std_error()
        if se is None:
            return None
        return 2.0 * self.z * se

    def state(self) -> dict:
        return {
            "n_terms": self.n,
            "n_batches": self.n_batches,
            "mean": self.mean if self.n else None,
            "variance": self.variance(),
            "std_error": self.std_error(),
            "ci_width": self.ci_width(),
            "batch_std_error": self.batch_std_error(),
            "batch_ci_width": self.batch_ci_width(),
            "confidence": self.confidence,
        }

    # ------------------------------------------------------------------ #
    def offline_check(self) -> dict:
        """Recompute mean/variance/CI width from the retained raw terms
        with NumPy and report both alongside the absolute gaps — the
        validation the acceptance criterion pins. Requires
        ``retain_terms=True``."""
        if self._terms is None:
            raise RuntimeError(
                "offline_check() needs retain_terms=True")
        h = (np.concatenate(self._terms) if self._terms
             else np.empty(0))
        out = {"n_terms": int(h.size), "online": self.state()}
        if h.size < 2:
            out["offline"] = None
            return out
        var = float(np.var(h, ddof=1))
        se = math.sqrt(var / h.size)
        out["offline"] = {
            "mean": float(h.mean()),
            "variance": var,
            "std_error": se,
            "ci_width": 2.0 * self.z * se,
        }
        out["abs_err"] = {
            "mean": abs(out["offline"]["mean"] - self.mean),
            "variance": abs(out["offline"]["variance"]
                            - (self.variance() or 0.0)),
            "ci_width": abs(out["offline"]["ci_width"]
                            - (self.ci_width() or 0.0)),
        }
        return out


class DriftDetector:
    """Rolling |live - oracle| monitor for the budgeted estimate.

    Args:
      window: number of observations in the rolling window.
      threshold: rolling mean absolute gap that counts as drift.
      min_fill: observations required before the detector may fire
        (default: a full window) — a half-empty window is noise.
      metrics: optional registry receiving ``estimate_drift`` (the
        rolling gap) and ``drift_alerts_total``.
      flight: optional ``FlightRecorder`` receiving one
        ``health_drift`` event per ok->drifted transition.
    """

    def __init__(self, window: int = 256, threshold: float = 0.05,
                 min_fill: Optional[int] = None, metrics=None,
                 flight=None):
        if window < 1:
            raise ValueError(f"window must be >= 1: {window}")
        if threshold <= 0:
            raise ValueError(f"threshold must be > 0: {threshold}")
        self.window = window
        self.threshold = threshold
        self.min_fill = window if min_fill is None else min_fill
        self._gaps = collections.deque(maxlen=window)
        self._sum = 0.0
        self.drifting = False
        self.alerts = 0
        self.flight = flight
        self._g_drift = None
        self._c_alerts = None
        if metrics is not None:
            self._g_drift = metrics.gauge("estimate_drift")
            self._c_alerts = metrics.counter("drift_alerts_total")

    def observe(self, live: float, oracle: float) -> bool:
        """One (live estimate, oracle value) pair; returns True on the
        transition INTO drift."""
        gap = abs(float(live) - float(oracle))
        if len(self._gaps) == self.window:
            self._sum -= self._gaps[0]
        self._gaps.append(gap)
        self._sum += gap
        rolling = self._sum / len(self._gaps)
        if self._g_drift is not None:
            self._g_drift.set(rolling)
        was = self.drifting
        self.drifting = (len(self._gaps) >= self.min_fill
                         and rolling > self.threshold)
        fired = self.drifting and not was
        if fired:
            self.alerts += 1
            if self._c_alerts is not None:
                self._c_alerts.inc()
            if self.flight is not None:
                self.flight.record(
                    "health_drift", rolling_gap=rolling,
                    threshold=self.threshold, window=len(self._gaps))
        return fired

    @property
    def rolling_gap(self) -> Optional[float]:
        if not self._gaps:
            return None
        return self._sum / len(self._gaps)

    def state(self) -> dict:
        return {
            "rolling_gap": self.rolling_gap,
            "threshold": self.threshold,
            "window": self.window,
            "filled": len(self._gaps),
            "drifting": self.drifting,
            "alerts": self.alerts,
        }


def shard_balance(counts: Sequence[int]) -> dict:
    """Skew statistics over per-shard occupancy counts.

    ``skew`` = max / mean (1.0 is perfect balance; the contiguous-slice
    placement guarantees <= S/(S-eps) ~ 1 + 1/per, so anything
    materially above that is a placement bug). ``cv`` = population
    coefficient of variation, the scale-free imbalance number.
    """
    c = np.asarray(list(counts), dtype=np.float64)
    if c.size == 0 or c.sum() == 0:
        return {"shards": int(c.size), "max": 0, "min": 0,
                "mean": 0.0, "skew": 1.0, "cv": 0.0}
    mean = float(c.mean())
    return {
        "shards": int(c.size),
        "max": int(c.max()),
        "min": int(c.min()),
        "mean": mean,
        "skew": float(c.max() / mean) if mean else 1.0,
        "cv": float(c.std() / mean) if mean else 0.0,
    }
