"""Declarative SLO evaluation over live metric snapshots [ISSUE 7
tentpole].

PR 6 made the serving process emit thousands of metric rows; nothing
said "healthy" or "breached". This module closes that gap: a spec of
**objectives** over the metrics the stack already exports (no new
instrumentation), evaluated against successive ``MetricsFlusher``
snapshots, with SRE-style multi-window burn-rate error budgets.

Spec format (dict, JSON string, or ``@path`` / ``*.json`` path —
exactly the ``--chaos-spec`` convention)::

    {"objectives": [
      {"name": "insert_p99", "type": "latency",
       "metric": "insert_latency_s", "quantile": "p99",
       "threshold_ms": 50},
      {"name": "availability", "type": "error_rate",
       "errors": ["poison_rejects", "deadline_expired_total",
                  "rejected_total", "dropped_total"],
       "total": "requests_insert_total", "objective": 0.999,
       "windows": [{"window_s": 5, "burn": 10},
                   {"window_s": 30, "burn": 2}]},
      {"name": "no_heal_exhaustion", "type": "counter_max",
       "metric": "heal_exhausted_total", "max": 0},
      {"name": "queue_saturation", "type": "saturation",
       "metric": "queue_depth_live", "capacity": "queue_size",
       "max_fraction": 0.9}
    ]}

Objective types:

* ``latency``     — a histogram quantile (over the retained sample
                    window) vs ``threshold_ms``. Instantaneous: the
                    current reading either clears the bar or not.
* ``error_rate``  — a ratio of counter DELTAS over sliding time
                    windows: ``sum(errors)`` / ``total``, each
                    differenced between the snapshot at the window's
                    start and now. The error budget is ``1 -
                    objective``; each window's **burn rate** is
                    ``error_rate / budget``; the objective breaches
                    only when EVERY window exceeds its ``burn``
                    threshold — the classic multi-window AND that makes
                    the short window catch fast burns without paging on
                    a single bad tick, and the long window catch slow
                    leaks (Google SRE workbook ch. 5).
* ``counter_max`` — a cumulative counter must stay <= ``max``
                    (default 0): heal exhaustion, watchdog restarts —
                    events whose acceptable count is a constant.
* ``saturation``  — a live gauge vs a fraction of capacity.
                    ``capacity`` is a number, or a context key
                    (e.g. ``"queue_size"``) resolved from the config
                    mapping the monitor was built with.

**Label wildcards** [ISSUE 8 satellite]: a metric name may bind any
label value with ``*`` — ``insert_latency_s{tenant=*}`` evaluates the
objective against EVERY matching per-tenant series, so one spec line
covers a whole fleet. The objective breaches when any series does;
per-series breach gauges (``slo_breached{objective=...,tenant=...}``)
and a per-series breakdown in the report carry the attribution.
``error_rate`` objectives may use wildcard counter names too (matching
series are summed per window).

A breach TRANSITION (ok -> breached) records one ``slo_breach`` flight
event (trace-id correlated like every flight event) and increments
``slo_breaches_total{objective=...}``; the live state is exported as
``slo_breached{objective=...}`` / ``slo_burn_rate{objective=...}``
gauges — visible in the very metrics stream being judged, so the
flusher's JSONL doubles as the SLO timeline. ``report()`` renders the
final verdicts for exit summaries / replay records, and
``evaluate_history`` replays a metrics.jsonl post-hoc — what
``tuplewise doctor`` calls.
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

_TYPES = ("latency", "error_rate", "counter_max", "saturation")

# default error-rate burn windows: tuned for service runs measured in
# seconds-to-minutes (a replay, a CI smoke, a short serve) — spec
# authors override for production horizons
_DEFAULT_WINDOWS = ({"window_s": 5.0, "burn": 10.0},
                    {"window_s": 30.0, "burn": 2.0})


class SloSpecError(ValueError):
    """The SLO spec failed validation (unknown type, missing field)."""


def _v(m: dict, name: str, default=0):
    return m.get(name, {}).get("value", default)


def _is_wild(name) -> bool:
    return isinstance(name, str) and "=*" in name


def match_series(m: dict, pattern: str) -> List[Tuple[dict, dict]]:
    """Expand a label-wildcard metric pattern against a snapshot
    [ISSUE 8 satellite]: ``insert_latency_s{tenant=*}`` matches every
    ``insert_latency_s{tenant=...}`` series. Returns
    ``[(wild_labels, snapshot_entry)]`` — one per matching series,
    ``wild_labels`` holding the concrete values the ``*`` bound (the
    per-series identity the breach gauges are labeled with). Non-``*``
    labels in the pattern must match exactly."""
    from tuplewise_tpu.utils.profiling import parse_labeled_name

    base, want = parse_labeled_name(pattern)
    out = []
    for key, snap in m.items():
        b, lab = parse_labeled_name(key)
        if b != base or lab is None:
            continue
        if any(lab.get(k) != v for k, v in want.items() if v != "*"):
            continue
        if any(k not in lab for k, v in want.items() if v == "*"):
            continue
        out.append(({k: lab[k] for k, v in want.items() if v == "*"},
                    snap))
    return out


def _sum_v(m: dict, name: str) -> float:
    """Counter value, summing matching series for wildcard names."""
    if _is_wild(name):
        return sum(s.get("value", 0) for _, s in match_series(m, name))
    return _v(m, name)


class _Objective:
    """One parsed objective + its rolling breach state."""

    __slots__ = ("name", "type", "metric", "quantile", "threshold_ms",
                 "errors", "total", "objective", "windows", "max",
                 "capacity", "max_fraction", "breached_now",
                 "breaches_total", "last", "worst")

    def __init__(self, ent: dict):
        self.type = ent.get("type")
        if self.type not in _TYPES:
            raise SloSpecError(
                f"unknown objective type {self.type!r}; expected one of "
                f"{_TYPES}")
        self.name = ent.get("name")
        if not self.name:
            raise SloSpecError(f"objective missing 'name': {ent}")
        self.metric = ent.get("metric")
        self.quantile = ent.get("quantile", "p99")
        self.threshold_ms = ent.get("threshold_ms")
        self.errors = tuple(ent.get("errors", ()))
        self.total = ent.get("total")
        self.objective = ent.get("objective")
        self.windows = tuple(dict(w) for w in ent.get(
            "windows", _DEFAULT_WINDOWS))
        self.max = ent.get("max", 0)
        self.capacity = ent.get("capacity")
        self.max_fraction = ent.get("max_fraction", 0.9)
        if self.type == "latency":
            if not self.metric or self.threshold_ms is None:
                raise SloSpecError(
                    f"latency objective {self.name!r} needs 'metric' "
                    f"and 'threshold_ms'")
            if self.quantile not in ("p50", "p90", "p95", "p99", "max",
                                     "mean"):
                raise SloSpecError(
                    f"latency objective {self.name!r}: unknown quantile "
                    f"{self.quantile!r}")
        elif self.type == "error_rate":
            if not self.errors or not self.total:
                raise SloSpecError(
                    f"error_rate objective {self.name!r} needs 'errors' "
                    f"and 'total'")
            if not (self.objective is not None
                    and 0.0 < float(self.objective) < 1.0):
                raise SloSpecError(
                    f"error_rate objective {self.name!r} needs "
                    f"'objective' in (0, 1), got {self.objective!r}")
            for w in self.windows:
                if w.get("window_s", 0) <= 0 or w.get("burn", 0) <= 0:
                    raise SloSpecError(
                        f"error_rate objective {self.name!r}: each "
                        f"window needs window_s > 0 and burn > 0: {w}")
        elif self.type == "counter_max":
            if not self.metric:
                raise SloSpecError(
                    f"counter_max objective {self.name!r} needs 'metric'")
        elif self.type == "saturation":
            if not self.metric or self.capacity is None:
                raise SloSpecError(
                    f"saturation objective {self.name!r} needs 'metric' "
                    f"and 'capacity'")
        # rolling state
        self.breached_now = False
        self.breaches_total = 0
        self.last: dict = {}
        self.worst: Optional[float] = None


class SloSpec:
    """Parsed, validated SLO spec — a list of objectives."""

    def __init__(self, objectives: List[_Objective]):
        if not objectives:
            raise SloSpecError("SLO spec has no objectives")
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise SloSpecError(f"duplicate objective names: {names}")
        self.objectives = objectives

    @classmethod
    def from_spec(cls, spec) -> "SloSpec":
        """Build from a dict, a JSON string, or ``@path`` / ``.json``
        (the ``--chaos-spec`` convention)."""
        if isinstance(spec, SloSpec):
            return spec
        if isinstance(spec, str):
            s = spec.strip()
            if s.startswith("@"):
                with open(s[1:], "r", encoding="utf-8") as f:
                    spec = json.load(f)
            elif s.endswith(".json"):
                with open(s, "r", encoding="utf-8") as f:
                    spec = json.load(f)
            else:
                spec = json.loads(s)
        if not isinstance(spec, dict):
            raise SloSpecError(
                f"SLO spec must be a dict, got {type(spec)}")
        return cls([_Objective(e) for e in spec.get("objectives", ())])

    @property
    def longest_window_s(self) -> float:
        out = 0.0
        for o in self.objectives:
            if o.type == "error_rate":
                out = max(out, max(w["window_s"] for w in o.windows))
        return out

    @property
    def shortest_window_s(self) -> Optional[float]:
        out = None
        for o in self.objectives:
            if o.type == "error_rate":
                w = min(w["window_s"] for w in o.windows)
                out = w if out is None else min(out, w)
        return out


class SloMonitor:
    """Evaluates an :class:`SloSpec` against a stream of registry
    snapshots.

    Args:
      spec: anything ``SloSpec.from_spec`` accepts.
      registry: optional ``MetricsRegistry`` receiving the ``slo_*``
        gauges/counters (normally the very registry being judged).
      flight: optional ``FlightRecorder`` receiving one ``slo_breach``
        event per ok->breached transition.
      context: config mapping used to resolve symbolic capacities
        (e.g. ``{"queue_size": 1024}``).

    Wire ``observe_row`` as a ``MetricsFlusher`` observer for live
    evaluation, or call :func:`evaluate_history` on a finished
    metrics.jsonl.

    **Actuator hook** [ISSUE 11]: the monitor *judges*; an actuator
    *acts*. ``add_actuator(fn)`` registers a callable invoked after
    every evaluation with one signal bundle — the snapshot, the new
    breach transitions, and every objective's current state (value,
    threshold, burn, ``breached_now``) — the sibling of the
    ``MetricsFlusher`` observer hook this monitor itself rides. The
    serving control plane (``serving.control.FleetController``)
    attaches here, so "close the loop" costs no second timer thread
    and the controller sees exactly the snapshots the SLO verdicts are
    judged on. Actuator exceptions are swallowed and counted
    (``actuator_errors`` / ``last_actuator_error``) — an actuator must
    never take down the evaluation that drives it.
    """

    def __init__(self, spec, registry=None, flight=None,
                 context: Optional[dict] = None, actuators=()):
        self.spec = SloSpec.from_spec(spec)
        self.registry = registry
        self.flight = flight
        self.context = dict(context or {})
        # snapshot ring: (ts_mono, metrics) kept long enough to cover
        # the longest burn window (+1 entry so a full window always has
        # a "before" edge)
        self._ring: List[Tuple[float, dict]] = []
        self.evaluations = 0
        self.actuators = list(actuators)
        self.actuator_errors = 0
        self.last_actuator_error: Optional[str] = None

    def add_actuator(self, fn) -> None:
        """Register an actuator callable; it receives one dict per
        evaluation: ``{"ts_mono", "metrics", "transitions",
        "objectives": {name: {..last detail.., "type", "breached_now",
        "breaches_total"}}}``."""
        self.actuators.append(fn)

    # ------------------------------------------------------------------ #
    def observe_row(self, row: dict) -> None:
        """MetricsFlusher observer entry point: one flushed row."""
        self.observe(row["metrics"], row["ts_mono"])

    def observe(self, metrics: dict, ts_mono: float) -> List[dict]:
        """Evaluate every objective against this snapshot; returns the
        list of NEW breach events (ok -> breached transitions)."""
        self._ring.append((ts_mono, metrics))
        horizon = self.spec.longest_window_s
        while len(self._ring) > 2 and \
                self._ring[1][0] <= ts_mono - horizon:
            self._ring.pop(0)
        self.evaluations += 1
        transitions = []
        for o in self.spec.objectives:
            breached, detail = self._evaluate(o, metrics, ts_mono)
            o.last = detail
            val = detail.get("value")
            if val is not None and (o.worst is None
                                    or val > o.worst):
                o.worst = val
            if breached and not o.breached_now:
                o.breaches_total += 1
                ev = dict(detail, objective=o.name, type=o.type)
                transitions.append(ev)
                if self.flight is not None:
                    self.flight.record("slo_breach", **ev)
            o.breached_now = breached
            self._export(o, detail)
        if self.actuators:
            sig = {
                "ts_mono": ts_mono,
                "metrics": metrics,
                "transitions": transitions,
                "objectives": {
                    o.name: dict(o.last, type=o.type,
                                 breached_now=o.breached_now,
                                 breaches_total=o.breaches_total)
                    for o in self.spec.objectives},
            }
            for fn in self.actuators:
                try:
                    fn(sig)
                except Exception as e:  # noqa: BLE001 — see class doc
                    self.actuator_errors += 1
                    self.last_actuator_error = repr(e)
        return transitions

    # ------------------------------------------------------------------ #
    def _evaluate(self, o: _Objective, m: dict,
                  ts: float) -> Tuple[bool, dict]:
        if o.type == "latency":
            if _is_wild(o.metric):
                return self._evaluate_wild(o, m)
            snap = m.get(o.metric, {})
            v = snap.get(o.quantile)
            v_ms = None if v is None else v * 1e3
            return (v_ms is not None and v_ms > o.threshold_ms), {
                "value": v_ms, "threshold_ms": o.threshold_ms,
                "quantile": o.quantile, "metric": o.metric}
        if o.type == "counter_max":
            if _is_wild(o.metric):
                return self._evaluate_wild(o, m)
            v = _v(m, o.metric)
            return v > o.max, {"value": v, "max": o.max,
                               "metric": o.metric}
        if o.type == "saturation":
            cap = o.capacity
            if isinstance(cap, str):
                cap = self.context.get(cap)
            if not cap:
                return False, {"value": None, "capacity": o.capacity,
                               "note": "capacity unresolved"}
            if _is_wild(o.metric):
                return self._evaluate_wild(o, m, capacity=float(cap))
            frac = _v(m, o.metric) / float(cap)
            return frac > o.max_fraction, {
                "value": frac, "max_fraction": o.max_fraction,
                "capacity": cap, "metric": o.metric}
        # error_rate: counter deltas over each sliding window
        # (wildcard error/total names sum their matching series, so one
        # spec line covers a whole labeled fleet)
        budget = 1.0 - float(o.objective)
        burns = {}
        all_exceed = True
        for w in o.windows:
            then = self._at(ts - w["window_s"])
            if then is None:
                # not enough history to fill this window yet: compare
                # against the oldest snapshot we have (a conservative
                # shorter window), never against nothing
                then = self._ring[0][1] if self._ring else m
            derr = sum(_sum_v(m, e) - _sum_v(then, e) for e in o.errors)
            dtot = _sum_v(m, o.total) - _sum_v(then, o.total)
            rate = (derr / dtot) if dtot > 0 else 0.0
            burn = rate / budget if budget > 0 else float("inf")
            burns[f"{w['window_s']:g}s"] = {
                "error_rate": rate, "burn_rate": burn,
                "burn_threshold": w["burn"], "errors": derr,
                "total": dtot}
            if burn <= w["burn"]:
                all_exceed = False
        worst = max((b["burn_rate"] for b in burns.values()),
                    default=0.0)
        return all_exceed, {"value": worst, "budget": budget,
                            "windows": burns}

    def _evaluate_wild(self, o: _Objective, m: dict,
                       capacity: Optional[float] = None
                       ) -> Tuple[bool, dict]:
        """Label-wildcard evaluation [ISSUE 8 satellite]: one spec
        line fans out over every matching labeled series (e.g. every
        tenant). The objective breaches when ANY series breaches; the
        detail carries the per-series breakdown the per-series breach
        gauges and reports are built from."""
        series = {}
        worst = None
        any_breached = False
        for wild, snap in match_series(m, o.metric):
            if o.type == "latency":
                v = snap.get(o.quantile)
                val = None if v is None else v * 1e3
                breached = val is not None and val > o.threshold_ms
            elif o.type == "counter_max":
                val = snap.get("value", 0)
                breached = val > o.max
            else:   # saturation
                val = snap.get("value", 0) / capacity
                breached = val > o.max_fraction
            key = ",".join(f"{k}={wild[k]}" for k in sorted(wild))
            series[key] = {"value": val, "breached": breached,
                           "labels": wild}
            if val is not None and (worst is None or val > worst):
                worst = val
            any_breached = any_breached or breached
        detail = {"value": worst, "metric": o.metric,
                  "series": series,
                  "series_breached": sum(
                      1 for s in series.values() if s["breached"])}
        if o.type == "latency":
            detail["threshold_ms"] = o.threshold_ms
            detail["quantile"] = o.quantile
        elif o.type == "counter_max":
            detail["max"] = o.max
        else:
            detail["max_fraction"] = o.max_fraction
            detail["capacity"] = capacity
        return any_breached, detail

    def _at(self, ts: float) -> Optional[dict]:
        """The newest snapshot taken at or before ``ts`` (None when
        history does not reach back that far)."""
        best = None
        for t, m in self._ring:
            if t <= ts:
                best = m
            else:
                break
        return best

    def _export(self, o: _Objective, detail: dict) -> None:
        if self.registry is None:
            return
        labels = {"objective": o.name}
        self.registry.gauge("slo_breached", labels=labels).set(
            1.0 if o.breached_now else 0.0)
        if o.type == "error_rate":
            self.registry.gauge("slo_burn_rate", labels=labels).set(
                detail.get("value") or 0.0)
        # per-series breach gauges for wildcard objectives [ISSUE 8]:
        # `slo_breached{objective=...,tenant=...}` — the fleet surface
        # a dashboard/doctor groups by tenant
        for s in detail.get("series", {}).values():
            self.registry.gauge(
                "slo_breached",
                labels=dict(labels, **s["labels"])).set(
                1.0 if s["breached"] else 0.0)
        c = self.registry.counter("slo_breaches_total", labels=labels)
        c.inc(o.breaches_total - c.value)

    # ------------------------------------------------------------------ #
    def report(self) -> dict:
        """Final verdicts: per-objective state + the overall bit an
        exit summary / CI gate reads first."""
        objectives = {}
        for o in self.spec.objectives:
            objectives[o.name] = {
                "type": o.type,
                "breached_now": o.breached_now,
                "breaches_total": o.breaches_total,
                "worst": o.worst,
                "last": o.last,
            }
        any_ever = any(o.breaches_total for o in self.spec.objectives)
        any_now = any(o.breached_now for o in self.spec.objectives)
        return {
            "evaluations": self.evaluations,
            "healthy": not any_ever,
            "breached_now": any_now,
            "breached_ever": any_ever,
            "objectives": objectives,
        }


def evaluate_history(spec, rows: List[dict], registry=None,
                     flight=None, context=None) -> dict:
    """Replay a metrics.jsonl history (list of flusher rows, in order)
    through a fresh monitor and return its report — the post-hoc
    evaluation ``tuplewise doctor`` runs over a dead process's
    artifacts."""
    mon = SloMonitor(spec, registry=registry, flight=flight,
                     context=context)
    for row in rows:
        if "metrics" in row and "ts_mono" in row:
            mon.observe_row(row)
    return mon.report()


# the spec applied when a doctor run is given no --slo-spec: the
# invariants every serving config shares — terminal failures must not
# happen, and the process must not be shedding load wholesale. Latency
# is config-dependent, so the default judges none (spec authors add
# their own thresholds).
DEFAULT_DOCTOR_SPEC = {"objectives": [
    {"name": "no_heal_exhaustion", "type": "counter_max",
     "metric": "heal_exhausted_total", "max": 0},
    {"name": "availability", "type": "error_rate",
     "errors": ["rejected_total", "dropped_total",
                "deadline_expired_total"],
     "total": "requests_insert_total", "objective": 0.99,
     "windows": [{"window_s": 1.0, "burn": 10.0},
                 {"window_s": 10.0, "burn": 5.0}]},
]}
