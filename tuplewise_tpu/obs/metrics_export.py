"""Live metrics export: periodic whole-registry snapshots to JSONL
[ISSUE 6 tentpole].

The metrics registries built in PRs 1-5 are only ever read at exit —
a live serve process is a black box until it stops. The
:class:`MetricsFlusher` is a side thread that appends one registry
snapshot per cadence tick to a JSONL path, each stamped with wall AND
monotonic timestamps (wall for humans/joins, monotonic for rate
computations across NTP steps), the jax platform, and a config digest
(so rows from different configs never get silently averaged together).

Durability stance: appends are flushed (``write`` + ``flush``) but NOT
fsync'd — metrics are a lossy observability stream, not durable state;
an fsync per tick would put a disk sync on the observation path of the
very latency it reports. (The WAL keeps its own fsync policy; see
DESIGN §9.)

``flush()`` is also called once at ``start()`` and once at ``stop()``,
so even a short run leaves >= 2 snapshots — enough to difference.

[ISSUE 7] Two growth points on the same thread:

* **rotation** — ``max_bytes`` rolls ``metrics.jsonl`` to
  ``metrics.jsonl.1`` (one generation, replaced on the next roll) when
  an append pushes past the bound, so a long-running serve cannot grow
  the file without limit; the flushed-not-fsynced stance is unchanged.
* **observers** — callables invoked with each flushed row; the SLO
  monitor rides here, so "evaluate the SLOs" costs no second timer
  thread and judges exactly the snapshots the file records. ``path``
  may be ``None`` for an observer-only flusher (``--slo-spec`` without
  ``--metrics-out``).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Optional


# Config fields added AFTER the digest began stamping perf-history
# rows, mapped to their defaults. A field at its default is dropped
# from the digest blob, so rows recorded before the field existed keep
# joining runs that don't use it — an additive config evolution must
# not orphan the perf gate's committed history [ISSUE 10 satellite].
# A NON-default value still lands in the blob (different config =>
# different digest, as it should).
_ADDITIVE_DEFAULTS = {"count_kernel": False,
                      "tail_exemplar_ms": None}


def config_digest(config) -> str:
    """Short stable digest of a config mapping/dataclass — the join key
    that keeps metrics rows from different configs apart."""
    import dataclasses

    if dataclasses.is_dataclass(config) and not isinstance(config, type):
        config = dataclasses.asdict(config)
    if isinstance(config, dict):
        config = {k: v for k, v in config.items()
                  if not (k in _ADDITIVE_DEFAULTS
                          and v == _ADDITIVE_DEFAULTS[k])}
    blob = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:12]


def _platform() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:   # noqa: BLE001 — metrics must not require jax
        return "unknown"


class MetricsFlusher:
    """Side-thread JSONL appender for a ``MetricsRegistry``.

    Args:
      registry: the ``utils.profiling.MetricsRegistry`` to snapshot.
      path: JSONL output (parent dirs created; appended, not truncated
        — restarts of the same service extend one history file). None
        = observer-only: snapshots are built and handed to observers,
        nothing is written.
      every_s: cadence between snapshots.
      meta: extra fields stamped on every row (e.g. ``stage``); the
        platform and ``config_digest`` ride along automatically when
        ``config`` is given.
      config: config object/dict digested into ``config_digest``.
      max_bytes: roll ``path`` to ``path + ".1"`` when an append
        pushes past this size (None = never roll).
      observers: callables receiving each flushed row dict (on the
        flusher thread; exceptions are swallowed into
        ``last_flush_error`` — observation must not kill the flusher).

    Use as a context manager, or ``start()`` / ``stop()``.
    """

    def __init__(self, registry, path: Optional[str],
                 every_s: float = 1.0,
                 meta: Optional[dict] = None, config=None,
                 max_bytes: Optional[int] = None, observers=()):
        if every_s <= 0:
            raise ValueError(f"every_s must be > 0: {every_s}")
        if max_bytes is not None and max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1: {max_bytes}")
        self.registry = registry
        self.path = path
        self.every_s = every_s
        self.max_bytes = max_bytes
        self.observers = list(observers)
        self.rotations = 0
        self.meta = dict(meta or {})
        self.meta.setdefault("platform", _platform())
        if config is not None:
            self.meta.setdefault("config_digest", config_digest(config))
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()    # serializes appends
        self._f = None
        self.last_flush_error: Optional[str] = None
        # wedged-observer escape hatch [ISSUE 14 bugfix]: when stop()
        # gives up waiting on a flush stuck inside a slow observer,
        # the in-flight flush becomes the final row and closes the
        # file itself; the counter makes the event observable
        self._late = threading.Event()
        self._c_late = registry.counter("flusher_late_flushes_total")

    # ------------------------------------------------------------------ #
    def flush(self) -> int:
        """Append one snapshot row now; returns its seq number. Never
        raises (the error lands in ``last_flush_error``) — a full disk
        must not take the service down."""
        with self._lock:
            self._seq += 1
            row = {
                "seq": self._seq,
                "ts_wall": time.time(),
                "ts_mono": time.perf_counter(),
            }
            row.update(self.meta)
            row["metrics"] = self.registry.snapshot()
            try:
                if self.path is not None:
                    if self._f is None:
                        d = os.path.dirname(self.path)
                        if d:
                            os.makedirs(d, exist_ok=True)
                        self._f = open(self.path, "a", encoding="utf-8")
                    self._f.write(json.dumps(row) + "\n")
                    self._f.flush()
                    if (self.max_bytes is not None
                            and self._f.tell() >= self.max_bytes):
                        # roll AFTER a complete row: both generations
                        # always hold whole lines
                        self._f.close()
                        self._f = None
                        os.replace(self.path, self.path + ".1")
                        self.rotations += 1
            except Exception as e:   # noqa: BLE001 — lossy by design
                self.last_flush_error = repr(e)
            for obs in self.observers:
                try:
                    obs(row)
                except Exception as e:   # noqa: BLE001 — see docstring
                    self.last_flush_error = repr(e)
            if self._late.is_set() and self._f is not None:
                # stop() already returned without the final close
                # (this very flush was wedged in an observer): the
                # row above is the final row; release the file here
                self._f.close()
                self._f = None
            return self._seq

    def _run(self) -> None:
        while not self._stop.wait(self.every_s):
            self.flush()

    def start(self) -> "MetricsFlusher":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self.flush()     # row 1: the starting state
            self._thread = threading.Thread(
                target=self._run, name="tuplewise-metrics-flusher",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the flusher thread and write the final row.

        [ISSUE 14 bugfix] The final flush used to race a wedged
        observer: observers run under the flush lock, so a stop()
        while an observer hangs would block on that lock FOREVER
        (shutdown wedged behind the very observer the flusher exists
        to tolerate). Now the join is bounded: if the thread is still
        mid-flush after ``timeout``, stop() counts a
        ``flusher_late_flushes_total``, marks the in-flight flush as
        the final one (it closes the file when it completes), and
        returns — shutdown never inherits an observer's hang."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            if t.is_alive():
                self._c_late.inc()
                self.last_flush_error = (
                    "stop(): flusher thread still mid-flush after "
                    f"{timeout}s (wedged observer?) — final flush "
                    "left to the in-flight one")
                self._late.set()
                return
            self._thread = None
        self.flush()         # final row: the exit state
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self) -> "MetricsFlusher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
