"""Span tracing for the serving and batch paths [ISSUE 6 tentpole].

Design constraints, in priority order:

1. **Hard-off by default, near-zero cost.** Instrumented call sites
   hold ``tracer = None`` and pay exactly one ``is not None`` check per
   hook; no span object is ever allocated when tracing is off. (An
   enabled-but-cheap path also exists — ``Tracer(enabled=False)`` — so
   a tracer can be threaded unconditionally and flipped at runtime.)
2. **Monotonic clocks.** Span times are ``time.perf_counter()`` —
   wall-clock steps (NTP) must never produce negative durations. One
   (wall, monotonic) anchor pair captured at construction converts
   exported timestamps to an absolute timeline.
3. **Explicit parent/child ids.** Same-thread nesting is automatic (a
   thread-local span stack); cross-thread parenting — a batcher span
   continuing a request's trace, a compactor build owning its own
   trace — passes the parent ``Span`` (or starts a fresh trace)
   explicitly. No global context propagation magic.
4. **Thread-safe ring storage.** Completed spans land in a bounded ring
   (oldest dropped first); memory stays flat for long-lived services.

Export formats:

* ``export_jsonl(path)``  — one span per line: trace_id / span_id /
  parent_id / name / t0_s (monotonic, anchor-relative) / dur_s /
  thread / attrs. The format ``scripts/trace_summary.py`` digests.
* ``export_chrome(path)`` — Chrome trace-event JSON (``ph: "X"``
  complete events + thread-name metadata), loadable directly by
  perfetto / ``chrome://tracing``.

Usage::

    tr = Tracer()
    with tr.span("request.insert", n=3) as sp:   # new trace (no parent)
        with tr.span("queue_wait"):               # child of sp
            ...
    # cross-thread: hand `sp` to the worker
    with tr.span("batch.apply", parent=sp):
        ...
    tr.record_span("swap", t0, t1, parent=sp)     # retro-timed span
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Dict, List, Optional

# shared no-op context manager returned by maybe_span(None, ...) — the
# disabled path allocates nothing
class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def maybe_span(tracer: Optional["Tracer"], name: str, parent=None,
               **attrs):
    """``tracer.span(...)`` when a tracer is attached, else a shared
    no-op context manager — the one-line guard every instrumented call
    site uses so the disabled path costs a single ``is None`` check."""
    if tracer is None:
        return _NULL_SPAN
    return tracer.span(name, parent=parent, **attrs)


class Span:
    """One in-flight span; finished via the tracer (or as a context
    manager through ``Tracer.span``)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0",
                 "attrs", "thread")

    def __init__(self, trace_id: int, span_id: int,
                 parent_id: Optional[int], name: str, t0: float,
                 thread: str, attrs: Optional[dict]):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.thread = thread
        self.attrs = attrs


class Tracer:
    """Thread-safe span recorder with bounded ring storage.

    Args:
      capacity: max retained finished spans (oldest evicted first).
      enabled: ``False`` turns every call into a cheap no-op while
        keeping the object threadable through constructors.
    """

    def __init__(self, capacity: int = 1 << 16, enabled: bool = True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1: {capacity}")
        self.capacity = capacity
        self.enabled = enabled
        # (wall, monotonic) anchor: exported t0 is monotonic-relative;
        # the anchor converts to absolute wall time without ever using
        # wall clocks for durations
        self.wall_anchor = time.time()
        self.mono_anchor = time.perf_counter()
        self._ids = itertools.count(1)      # next() is atomic in CPython
        self._trace_ids = itertools.count(1)
        self._lock = threading.Lock()
        self._ring: List[dict] = []
        self._ring_pos = 0
        self.dropped = 0
        self._local = threading.local()

    # ------------------------------------------------------------------ #
    # context                                                            #
    # ------------------------------------------------------------------ #
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self) -> Optional[Span]:
        """The active span on THIS thread (None outside any span)."""
        st = getattr(self._local, "stack", None)
        return st[-1] if st else None

    def current_trace_id(self) -> Optional[int]:
        sp = self.current()
        return sp.trace_id if sp is not None else None

    def new_trace_id(self) -> int:
        """A fresh trace id (for correlating events recorded outside
        any span, e.g. a chaos injection between batches)."""
        return next(self._trace_ids)

    # ------------------------------------------------------------------ #
    # span lifecycle                                                     #
    # ------------------------------------------------------------------ #
    def start(self, name: str, parent: Optional[Span] = None,
              trace_id: Optional[int] = None,
              **attrs) -> Optional[Span]:
        """Open a span. Parent resolution: explicit ``parent`` wins,
        else the calling thread's active span, else a NEW trace root.
        Does NOT touch the thread-local stack — cross-thread holders
        finish it with :meth:`finish`."""
        if not self.enabled:
            return None
        if parent is None:
            parent = self.current()
        if parent is not None:
            tid = parent.trace_id
            pid = parent.span_id
        else:
            tid = trace_id if trace_id is not None \
                else next(self._trace_ids)
            pid = None
        return Span(tid, next(self._ids), pid, name,
                    time.perf_counter(),
                    threading.current_thread().name, attrs or None)

    def finish(self, span: Optional[Span],
               t1: Optional[float] = None) -> None:
        if span is None or not self.enabled:
            return
        t1 = time.perf_counter() if t1 is None else t1
        self._store({
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "name": span.name,
            "t0_s": span.t0 - self.mono_anchor,
            "dur_s": max(0.0, t1 - span.t0),
            "thread": span.thread,
            "attrs": span.attrs,
        })

    def span(self, name: str, parent: Optional[Span] = None, **attrs):
        """Context-manager form: pushes the span on this thread's stack
        (so nested ``span()`` calls become children) and records it on
        exit. An exception inside marks ``attrs["error"]``."""
        return _SpanCtx(self, name, parent, attrs)

    def record_span(self, name: str, t0: float, t1: float,
                    parent: Optional[Span] = None,
                    trace_id: Optional[int] = None, **attrs) -> None:
        """Record a retroactively-timed span (both endpoints are
        already-taken ``perf_counter`` readings) — queue-wait intervals
        and O(1) swap pauses are measured before anyone knows whether
        they deserve a span object."""
        if not self.enabled:
            return
        if parent is not None:
            tid, pid = parent.trace_id, parent.span_id
        else:
            tid = trace_id if trace_id is not None \
                else next(self._trace_ids)
            pid = None
        self._store({
            "trace_id": tid,
            "span_id": next(self._ids),
            "parent_id": pid,
            "name": name,
            "t0_s": t0 - self.mono_anchor,
            "dur_s": max(0.0, t1 - t0),
            "thread": threading.current_thread().name,
            "attrs": attrs or None,
        })

    def _store(self, rec: dict) -> None:
        with self._lock:
            if len(self._ring) < self.capacity:
                self._ring.append(rec)
            else:
                self._ring[self._ring_pos] = rec
                self._ring_pos = (self._ring_pos + 1) % self.capacity
                self.dropped += 1

    # ------------------------------------------------------------------ #
    # introspection / export                                             #
    # ------------------------------------------------------------------ #
    def spans(self) -> List[dict]:
        """Finished spans, oldest first (ring order restored)."""
        with self._lock:
            return (self._ring[self._ring_pos:]
                    + self._ring[: self._ring_pos])

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def export_jsonl(self, path: str) -> int:
        """One span per line; returns the number written."""
        spans = self.spans()
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps({
                "meta": {
                    "format": "tuplewise-spans-v1",
                    "wall_anchor": self.wall_anchor,
                    "dropped": self.dropped,
                    "n_spans": len(spans),
                }}) + "\n")
            for s in spans:
                f.write(json.dumps(s) + "\n")
        return len(spans)

    def export_chrome(self, path: str) -> int:
        """Chrome trace-event JSON (perfetto / chrome://tracing).

        Each OS thread becomes a ``tid`` lane with a ``thread_name``
        metadata event; spans are ``ph: "X"`` complete events with
        microsecond timestamps relative to the tracer's anchor.
        """
        spans = self.spans()
        tids: Dict[str, int] = {}
        events: List[dict] = [{
            "ph": "M", "name": "process_name", "pid": 1, "tid": 0,
            "args": {"name": "tuplewise"},
        }]
        for s in spans:
            tid = tids.get(s["thread"])
            if tid is None:
                tid = tids[s["thread"]] = len(tids) + 1
                events.append({
                    "ph": "M", "name": "thread_name", "pid": 1,
                    "tid": tid, "args": {"name": s["thread"]},
                })
        for s in spans:
            args = dict(s["attrs"] or {})
            args["trace_id"] = s["trace_id"]
            args["span_id"] = s["span_id"]
            if s["parent_id"] is not None:
                args["parent_id"] = s["parent_id"]
            events.append({
                "ph": "X",
                "name": s["name"],
                "pid": 1,
                "tid": tids[s["thread"]],
                "ts": s["t0_s"] * 1e6,
                "dur": s["dur_s"] * 1e6,
                "args": args,
            })
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {
                "format": "tuplewise-chrome-v1",
                "wall_anchor": self.wall_anchor,
                "dropped": self.dropped,
            },
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        return len(spans)


class _SpanCtx:
    """The context-manager behind ``Tracer.span`` — pushes onto the
    thread-local stack so nesting parents automatically."""

    __slots__ = ("_tracer", "_name", "_parent", "_attrs", "_span")

    def __init__(self, tracer: Tracer, name: str,
                 parent: Optional[Span], attrs: dict):
        self._tracer = tracer
        self._name = name
        self._parent = parent
        self._attrs = attrs
        self._span = None

    def __enter__(self) -> Optional[Span]:
        if not self._tracer.enabled:
            return None
        self._span = self._tracer.start(
            self._name, parent=self._parent, **self._attrs)
        self._tracer._stack().append(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._span is not None:
            if exc_type is not None:
                attrs = dict(self._span.attrs or {})
                attrs["error"] = exc_type.__name__
                self._span.attrs = attrs
            st = self._tracer._stack()
            if st and st[-1] is self._span:
                st.pop()
            self._tracer.finish(self._span)
        return False
