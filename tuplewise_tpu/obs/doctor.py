"""Post-hoc diagnosis of a run's observability artifacts [ISSUE 7
tentpole]: ``tuplewise doctor``.

A serve/replay/bench run (or its corpse, after SIGKILL) leaves exactly
three artifacts next to each other: ``metrics.jsonl`` (the flusher's
periodic registry snapshots), ``flight.jsonl`` (the lifecycle ring
dump) and a span export (JSONL or Chrome trace). The doctor reads
whatever subset exists and renders a verdict a human or a CI gate can
act on:

* **SLO verdicts** — the metrics history replayed through
  :mod:`tuplewise_tpu.obs.slo` (``--slo-spec``, or the conservative
  default spec: no heal exhaustion, availability error budget).
* **Health verdicts** — the statistical monitors' final gauges: CI
  width of the streaming estimate, drift alerts, shard skew.
* **Fault -> breach correlation** — every chaos injection / poison in
  the flight dump listed EXACTLY once, each tied to its recovery
  evidence (the batcher restart that followed it, the poison_reject
  that shed it, the heal round that re-placed the mesh) and, when a
  span export is present, to the span its trace id points at.
* **Top self-time spans** — where the wall-clock went (total minus
  direct-child time), so the breach and the hot path sit in one
  report.
* **Host-tax verdicts** [ISSUE 14] — the wave ledger's final gauges
  (host/device fraction, tiling coverage, compile + GC event counts)
  judged against the compile-churn and GC-in-p99 thresholds; a
  fallen-back count kernel (``count_kernel_fallbacks_total`` > 0) and
  the pack full-replace counters surface under ``kernel`` — a kernel
  serving correct counts through its XLA fallback used to read
  "healthy".

Verdict taxonomy (DESIGN §13):

* ``healthy``   — no faults observed, no SLO breach, no drift.
* ``recovered`` — failures happened (chaos or real) but every one is
                  tied to successful recovery evidence and no SLO
                  objective breached: the system did its job. CI
                  treats this as green — it is the *expected* verdict
                  for a chaos smoke.
* ``degraded``  — an SLO objective breached, a statistical monitor
                  fired, a fault has no recovery evidence, or the
                  process hit a terminal failure (heal exhaustion,
                  snapshot error). CI treats this as red.

The last stdout line of the CLI is one machine-readable JSON object
(``{"doctor_verdict": ...}``) — ``tail -n 1 | python -m json.tool`` is
the whole CI integration.
"""

from __future__ import annotations

import json
import os
from collections import defaultdict
from typing import List, Optional, Tuple

from tuplewise_tpu.obs.flight import FlightRecorder
from tuplewise_tpu.obs.slo import DEFAULT_DOCTOR_SPEC, evaluate_history

# artifact filenames probed (in order) when only a directory is given
_METRICS_NAMES = ("metrics.jsonl",)
_FLIGHT_NAMES = ("flight.jsonl", "obs_flight.jsonl")
_SPAN_NAMES = ("spans.jsonl", "obs_spans.jsonl", "trace.json",
               "obs_trace.json")

# host-tax verdict thresholds [ISSUE 14] (override via diagnose's
# ``context``): a steady-state service averaging MORE THAN ONE XLA
# compile per batch on its request thread has lost the prewarm/ladder
# discipline outright; a GC pause distribution whose p99 rivals the
# insert p99 means the collector IS the tail. Both are generous
# enough that the healthy CI smokes (short, warmup-free, so they DO
# pay their first-call ladder compiles inside the measured window)
# clear; a long-running serve should gate far tighter via context.
COMPILE_CHURN_PER_1K_BATCHES = 1000.0
GC_P99_FRACTION_OF_INSERT = 0.5
GC_MIN_PAUSES = 10


def load_metrics_rows(path: str) -> List[dict]:
    """Flusher rows, torn-tail tolerant (the file of a SIGKILLed
    process can end mid-line; keep what parses)."""
    rows = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                break
    return rows


def load_spans(path: str) -> List[dict]:
    """Spans from either export shape (span JSONL or Chrome trace
    JSON) — self-contained so the doctor works from any checkout/cwd,
    unlike the scripts/ summarizer."""
    if path.endswith(".jsonl"):
        spans = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    break    # torn tail
                if "meta" in rec:
                    continue
                spans.append(rec)
        return spans
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    spans = []
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        args = e.get("args", {})
        spans.append({
            "trace_id": args.get("trace_id"),
            "span_id": args.get("span_id"),
            "parent_id": args.get("parent_id"),
            "name": e["name"],
            "t0_s": e["ts"] / 1e6,
            "dur_s": e.get("dur", 0.0) / 1e6,
        })
    return spans


def top_self_spans(spans: List[dict], top_n: int = 10) -> List[dict]:
    """Per-name totals ordered by SELF time (total minus direct-child
    time) — the honest where-did-the-wall-clock-go list."""
    child_time: dict = defaultdict(float)
    for s in spans:
        if s.get("parent_id") is not None:
            child_time[s["parent_id"]] += s["dur_s"]
    agg: dict = defaultdict(lambda: {"n": 0, "total_s": 0.0,
                                     "self_s": 0.0})
    for s in spans:
        a = agg[s["name"]]
        a["n"] += 1
        a["total_s"] += s["dur_s"]
        a["self_s"] += max(0.0, s["dur_s"]
                           - child_time.get(s["span_id"], 0.0))
    out = [dict(name=n, **a) for n, a in agg.items()]
    out.sort(key=lambda a: -a["self_s"])
    return out[:top_n]


# --------------------------------------------------------------------- #
# fault -> recovery correlation                                          #
# --------------------------------------------------------------------- #

def _metric_value(rows: List[dict], name: str, default=0):
    if not rows:
        return default
    return rows[-1]["metrics"].get(name, {}).get("value", default)


def tenant_breakdown(metrics_rows: List[dict]) -> Optional[dict]:
    """Per-tenant diagnosis block [ISSUE 8]: every tenant-labeled
    series in the final snapshot grouped by tenant — insert p99,
    admission rejections, and any per-tenant SLO breach gauge
    (``slo_breached{objective=...,tenant=...}``). None when the run
    was single-tenant (no tenant-labeled metrics)."""
    if not metrics_rows:
        return None
    from collections import defaultdict

    from tuplewise_tpu.utils.profiling import parse_labeled_name

    m = metrics_rows[-1]["metrics"]
    out: dict = defaultdict(dict)
    for key, snap in m.items():
        base, labels = parse_labeled_name(key)
        if not labels or "tenant" not in labels:
            continue
        tid = labels["tenant"]
        if base == "insert_latency_s":
            p = snap.get("p99")
            out[tid]["insert_p99_ms"] = None if p is None else p * 1e3
            out[tid]["inserts"] = snap.get("count", 0)
        elif base == "tenant_rejected_total":
            out[tid]["rejected"] = snap.get("value", 0)
        elif base == "slo_breached":
            breached = out[tid].setdefault("slo_breached", [])
            if snap.get("value"):
                breached.append(labels.get("objective"))
    if not out:
        return None
    # bounded cardinality [ISSUE 9 satellite]: when tenant_metric_cap
    # collapsed tenants into the {tenant=__other__} series, surface how
    # many distinct tenants that one series hides
    collapsed = m.get("tenant_metric_collapsed", {}).get("value", 0)
    if collapsed and "__other__" in out:
        out["__other__"]["collapsed_tenants"] = int(collapsed)
    return dict(out)


def _span_for_trace(spans: List[dict], trace_id) -> Optional[str]:
    """The root-most span name of a trace id (None when the export
    does not carry the trace)."""
    members = [s for s in spans if s.get("trace_id") == trace_id]
    if not members:
        return None
    roots = [s for s in members if s.get("parent_id") is None]
    return (roots or members)[0]["name"]


def correlate_faults(flight_events: List[dict], metrics_rows: List[dict],
                     spans: List[dict]) -> List[dict]:
    """One entry per injected fault (chaos_inject, plus chaos_poison
    expanded per poisoned event position), each carrying its recovery
    evidence. ``resolved=False`` entries push the verdict to
    degraded."""
    faults = []
    by_kind: dict = defaultdict(list)
    for e in flight_events:
        by_kind[e["kind"]].append(e)

    def _after(kind: str, seq: int) -> Optional[dict]:
        for e in by_kind.get(kind, ()):
            if e["seq"] > seq:
                return e
        return None

    for e in by_kind.get("chaos_inject", ()):
        point = e.get("point")
        entry = {
            "kind": "chaos_inject", "point": point, "seq": e["seq"],
            "t_wall": e.get("t_wall"), "action": e.get("action"),
            "trace_id": e.get("trace_id"),
            "trace_span": _span_for_trace(spans, e.get("trace_id")),
        }
        resolution = evidence = None
        if e.get("action") == "delay":
            # a latency injection needs no recovery machinery — the
            # engine absorbs the stall; when tail exemplars fired
            # [ISSUE 14], THEY are the evidence the stall was seen
            resolution = "latency_absorbed"
            n_ex = len(by_kind.get("tail_exemplar", ()))
            evidence = ({"tail_exemplars": n_ex} if n_ex else None)
        elif point == "batcher":
            r = _after("batcher_restart", e["seq"])
            if r is not None:
                resolution = "batcher_restart"
                evidence = {"seq": r["seq"]}
            elif _metric_value(metrics_rows, "batcher_restarts") > 0:
                resolution = "batcher_restart"
                evidence = {"batcher_restarts": _metric_value(
                    metrics_rows, "batcher_restarts")}
        elif point == "compactor_build":
            r = _after("compaction", e["seq"])
            n_restarts = _metric_value(metrics_rows,
                                       "bg_compactor_restarts")
            if r is not None:
                resolution = "compaction_resumed"
                evidence = {"next_compaction_seq": r["seq"],
                            "bg_compactor_restarts": n_restarts}
            elif n_restarts > 0:
                resolution = "compactor_restarted"
                evidence = {"bg_compactor_restarts": n_restarts}
        elif point in ("sharded_count", "place_base"):
            r = _after("heal", e["seq"])
            if r is not None:
                resolution = "healed"
                evidence = {"seq": r["seq"],
                            "mesh_width": r.get("mesh_width")}
        elif point == "major_merge":
            r = (_after("major_merge_fallback", e["seq"])
                 or _after("major_merge", e["seq"]))
            if r is not None:
                resolution = r["kind"]
                evidence = {"seq": r["seq"]}
            elif _metric_value(metrics_rows,
                               "major_merge_fallbacks") > 0:
                resolution = "major_merge_fallback"
                evidence = {"major_merge_fallbacks": _metric_value(
                    metrics_rows, "major_merge_fallbacks")}
        elif point in ("train_step", "mc_chunk", "mesh_mc",
                       "estimator", "checkpoint", "dist_init"):
            r = _after("heal", e["seq"])
            if r is not None:
                resolution = "healed"
                evidence = {"seq": r["seq"]}
        entry["resolution"] = resolution
        entry["resolved"] = resolution is not None
        entry["evidence"] = evidence
        faults.append(entry)

    # poison injections: one fault entry PER poisoned stream position,
    # each resolved by the engine's edge validation (poison_reject
    # events / counter)
    rejects = by_kind.get("poison_reject", ())
    n_rejects = max(len(rejects),
                    _metric_value(metrics_rows, "poison_rejects"))
    n_poisoned = 0
    for e in by_kind.get("chaos_poison", ()):
        positions = e.get("at_events") or [None] * int(
            e.get("n_poisoned", 1))
        for pos in positions:
            n_poisoned += 1
            faults.append({
                "kind": "chaos_poison", "point": "poison",
                "seq": e["seq"], "t_wall": e.get("t_wall"),
                "at_event": pos, "trace_id": e.get("trace_id"),
                "trace_span": _span_for_trace(spans, e.get("trace_id")),
                "resolution": ("poison_rejected"
                               if n_poisoned <= n_rejects else None),
                "resolved": n_poisoned <= n_rejects,
                "evidence": {"poison_rejects": n_rejects},
            })
    faults.sort(key=lambda f: f["seq"])
    return faults


def correlate_actuations(flight_events: List[dict],
                         metrics_rows: List[dict]) -> Optional[dict]:
    """Control-plane attribution [ISSUE 11]: one entry per
    ``actuation`` flight event, each judged on the cause→action→effect
    chain the controller promises — a non-null triggering ``signal``
    (the cause) AND at least one metrics snapshot observed after the
    actuation (the effect window: a run that died before the
    post-actuation state was ever recorded cannot claim the actuation
    worked). ``attributed=False`` entries downgrade the verdict to
    ``degraded:unattributed_actuation`` — a controller that cannot
    explain WHY it turned a knob is itself a fault. None when the run
    had no controller (no actuation events)."""
    acts = [e for e in flight_events if e["kind"] == "actuation"]
    if not acts:
        return None
    mono_ts = sorted(r["ts_mono"] for r in metrics_rows
                     if "ts_mono" in r)
    # grace = one flusher cadence (median inter-row gap): the FINAL
    # flush runs its observers after writing its row, so an actuation
    # triggered by the last snapshot of a clean shutdown has its
    # evidence in that row, not after it. A run that died leaves its
    # post-crash actuations well outside one cadence.
    gaps = [b - a for a, b in zip(mono_ts, mono_ts[1:])]
    grace = sorted(gaps)[len(gaps) // 2] if gaps else 1.0
    entries = []
    by_knob: dict = defaultdict(int)
    for e in acts:
        sig = e.get("signal")
        has_signal = isinstance(sig, dict) and bool(sig) \
            and any(v is not None for v in sig.values())
        effect = bool(mono_ts) and (
            mono_ts[-1] >= e["t_mono"]
            or e["t_mono"] - mono_ts[-1] <= grace)
        entries.append({
            "seq": e["seq"], "t_wall": e.get("t_wall"),
            "knob": e.get("knob"), "action": e.get("action"),
            "signal": sig, "has_signal": has_signal,
            "effect_window": effect,
            "attributed": has_signal and effect,
        })
        by_knob[e.get("knob")] += 1
    return {
        "total": len(entries),
        "attributed": sum(1 for a in entries if a["attributed"]),
        "unattributed": sum(1 for a in entries
                            if not a["attributed"]),
        "by_knob": dict(by_knob),
        "events": entries,
    }


# --------------------------------------------------------------------- #
# diagnosis                                                              #
# --------------------------------------------------------------------- #

def _probe(run_dir: str, names: Tuple[str, ...]) -> Optional[str]:
    for n in names:
        p = os.path.join(run_dir, n)
        if os.path.exists(p):
            return p
    return None


def diagnose(metrics_path: Optional[str] = None,
             flight_path: Optional[str] = None,
             spans_path: Optional[str] = None,
             run_dir: Optional[str] = None,
             slo_spec=None, context: Optional[dict] = None,
             top_n: int = 10) -> dict:
    """Build the structured diagnosis report from whatever artifacts
    exist. ``run_dir`` probes default filenames for anything not given
    explicitly (the post-SIGKILL case: point it at --snapshot-dir)."""
    if run_dir:
        metrics_path = metrics_path or _probe(run_dir, _METRICS_NAMES)
        flight_path = flight_path or _probe(run_dir, _FLIGHT_NAMES)
        spans_path = spans_path or _probe(run_dir, _SPAN_NAMES)
    if not (metrics_path or flight_path):
        raise FileNotFoundError(
            "doctor needs at least a metrics.jsonl or a flight dump "
            f"(run_dir={run_dir!r})")

    metrics_rows = load_metrics_rows(metrics_path) if metrics_path \
        and os.path.exists(metrics_path) else []
    flight_events: List[dict] = []
    flight_header: dict = {}
    if flight_path and os.path.exists(flight_path):
        flight_header = FlightRecorder.load_dump(flight_path)
        flight_events = flight_header.pop("events")
    spans = load_spans(spans_path) if spans_path \
        and os.path.exists(spans_path) else []

    report: dict = {
        "artifacts": {
            "metrics": metrics_path, "flight": flight_path,
            "spans": spans_path,
            "metrics_rows": len(metrics_rows),
            "flight_events": len(flight_events),
            "spans_loaded": len(spans),
        },
    }

    # run window + identity from the metrics history
    if metrics_rows:
        first, last = metrics_rows[0], metrics_rows[-1]
        report["run"] = {
            "duration_s": last["ts_mono"] - first["ts_mono"],
            "platform": last.get("platform"),
            "config_digest": last.get("config_digest"),
            "stage": last.get("stage"),
            "events_total": _metric_value(metrics_rows, "events_total"),
        }

    # SLO verdicts over the metrics history
    slo_report = None
    if metrics_rows:
        slo_report = evaluate_history(
            slo_spec if slo_spec is not None else DEFAULT_DOCTOR_SPEC,
            metrics_rows, context=context)
    report["slo"] = slo_report

    # statistical-health verdicts: the monitors' final gauges
    m = metrics_rows[-1]["metrics"] if metrics_rows else {}

    def _g(name):
        return m.get(name, {}).get("value")

    health = {
        "estimate_ci_width": _g("estimate_ci_width"),
        "estimate_std_error": _g("estimate_std_error"),
        "estimate_terms": _g("estimate_terms"),
        "estimate_drift": _g("estimate_drift"),
        "drift_alerts": _g("drift_alerts_total") or 0,
        "shard_skew": _g("shard_skew"),
        "shard_balance_cv": _g("shard_balance_cv"),
    }
    report["health"] = health

    # host-tax ledger [ISSUE 14]: where the insert wall-clock went,
    # judged against the compile-churn / GC-tail thresholds (None and
    # omitted for pre-ledger artifacts)
    from tuplewise_tpu.obs.report import host_tax_block

    host_tax = host_tax_block(m) if m else None
    if host_tax is not None:
        ctx = context or {}
        churn_max = ctx.get("compile_churn_per_1k_batches",
                            COMPILE_CHURN_PER_1K_BATCHES)
        gc_frac = ctx.get("gc_p99_fraction_of_insert",
                          GC_P99_FRACTION_OF_INSERT)
        churn = host_tax.get("compile_events_per_1k_batches")
        host_tax["compile_churn"] = bool(
            churn is not None and churn > churn_max)
        ins_p99 = m.get("insert_latency_s", {}).get("p99")
        gc_p99_ms = host_tax.get("gc_pause_p99_ms")
        host_tax["gc_in_p99"] = bool(
            ins_p99 and gc_p99_ms is not None
            and (host_tax.get("gc_pauses") or 0) >= GC_MIN_PAUSES
            and gc_p99_ms >= gc_frac * ins_p99 * 1e3)
        report["host_tax"] = host_tax

    # silently-degraded serving paths [ISSUE 14 satellite]: a fallen-
    # back count kernel or a fleet stuck re-shipping full packs used
    # to read "healthy" because nothing surfaced the counters
    kernel = {
        "count_kernel_calls": _g("count_kernel_calls_total") or 0,
        "count_kernel_fallbacks": _g("count_kernel_fallbacks_total")
        or 0,
        "pack_replaces": _g("pack_replaces_total") or 0,
        "pack_full_replaces": _g("pack_full_replaces_total") or 0,
    }
    if any(kernel.values()):
        report["kernel"] = kernel

    # per-tenant breakdown [ISSUE 8]: fleet runs carry tenant-labeled
    # metrics; surface them grouped so the doctor answers "WHICH
    # tenant" in one read (None and omitted for single-tenant runs)
    tenants = tenant_breakdown(metrics_rows)
    if tenants is not None:
        report["tenants"] = tenants

    # fault -> breach correlation
    faults = correlate_faults(flight_events, metrics_rows, spans)
    report["faults"] = faults

    # control-plane attribution [ISSUE 11]: every actuation tied to
    # its triggering signal + an observed effect window (None and
    # omitted when the run had no controller)
    actuations = correlate_actuations(flight_events, metrics_rows)
    if actuations is not None:
        report["actuations"] = actuations
    kinds: dict = {}
    for e in flight_events:
        kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
    report["flight_summary"] = kinds

    report["top_self_spans"] = top_self_spans(spans, top_n)

    # the recovery counter block every exit summary carries, read from
    # the final snapshot — same builder, same keys (report parity)
    if metrics_rows:
        from tuplewise_tpu.obs.report import recovery_counters

        report["recovery_counters"] = recovery_counters(m)

    report["verdict"] = _verdict(report, kinds)
    report["verdict_line"] = verdict_line(report)
    return report


def _verdict(report: dict, kinds: dict) -> str:
    degraded = []
    slo = report.get("slo")
    if slo is not None and not slo["healthy"]:
        degraded.append("slo_breached")
    if report["health"]["drift_alerts"]:
        degraded.append("estimate_drift")
    if kinds.get("heal_exhausted"):
        degraded.append("heal_exhausted")
    if kinds.get("snapshot_error"):
        degraded.append("snapshot_error")
    # host-tax verdicts [ISSUE 14]: steady-state compiles on the
    # request thread / a GC tail rivaling the insert p99
    host_tax = report.get("host_tax")
    if host_tax is not None:
        if host_tax.get("compile_churn"):
            degraded.append("compile_on_request_thread")
        if host_tax.get("gc_in_p99"):
            degraded.append("gc_in_p99")
    # a fallen-back count kernel serves correct counts SLOWLY — that
    # is degradation, not health [ISSUE 14 satellite]
    if (report.get("kernel") or {}).get("count_kernel_fallbacks"):
        degraded.append("count_kernel_fallback")
    unresolved = [f for f in report["faults"] if not f["resolved"]]
    if unresolved:
        degraded.append(f"{len(unresolved)}_unresolved_faults")
    # an actuation without a triggering signal or an observed effect
    # window means the control plane acted unexplained [ISSUE 11]
    acts = report.get("actuations")
    if acts is not None and acts["unattributed"]:
        degraded.append("unattributed_actuation")
    if degraded:
        return "degraded:" + ",".join(degraded)
    # failures that DID happen and were recovered from
    had_failures = (bool(report["faults"])
                    or kinds.get("batcher_restart")
                    or kinds.get("heal"))
    return "recovered" if had_failures else "healthy"


def verdict_line(report: dict) -> dict:
    """The one-line machine-readable verdict (last stdout line of the
    CLI; ``tail -n 1`` is the whole CI integration)."""
    v = report["verdict"]
    slo = report.get("slo") or {}
    acts = report.get("actuations") or {}
    return {
        "doctor_verdict": v.split(":", 1)[0],
        "detail": v.split(":", 1)[1] if ":" in v else None,
        "healthy": v in ("healthy", "recovered"),
        "faults": len(report["faults"]),
        "faults_resolved": sum(1 for f in report["faults"]
                               if f["resolved"]),
        "slo_breaches": sum(
            o["breaches_total"]
            for o in slo.get("objectives", {}).values()),
        "drift_alerts": report["health"]["drift_alerts"],
        "actuations": acts.get("total", 0),
        "actuations_attributed": acts.get("attributed", 0),
        # the headline host-tax number [ISSUE 14]: the fraction the
        # one-dispatch refactor exists to move (None pre-ledger)
        "host_fraction": (report.get("host_tax")
                          or {}).get("host_fraction"),
    }


def main(args) -> int:
    """CLI entry point (argparse namespace from harness/cli.py):
    pretty report to stdout, the machine verdict as the LAST stdout
    line; exit 0 on healthy/recovered, 2 on degraded."""
    report = diagnose(
        metrics_path=args.metrics, flight_path=args.flight,
        spans_path=args.spans, run_dir=args.dir,
        slo_spec=args.slo_spec, top_n=args.top_spans)
    if args.out:
        d = os.path.dirname(args.out)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
    line = report.pop("verdict_line")
    if not args.quiet:
        print(json.dumps(report, indent=2))
    print(json.dumps(line))
    return 0 if line["healthy"] else 2
