"""Low-overhead sampling profiler [ISSUE 14 tentpole].

The wave ledger (:mod:`tuplewise_tpu.obs.ledger`) says WHICH bucket
the wall-clock went to; this profiler says WHERE IN THE CODE the
host-Python bucket burns — without instrumenting anything. A daemon
thread periodically snapshots every other thread's Python stack
(``sys._current_frames``), folds it (root→leaf, thread name as the
root frame), and counts occurrences.

Design stance, mirroring the Tracer [ISSUE 6]:

* **hard-off by default** — nothing samples unless a caller
  constructs and starts a profiler (``--prof`` on the CLI / bench);
  instrumented code paths hold no reference at all.
* **guarded overhead (<= 5%)** — every sampling pass measures its own
  cost; when the smoothed cost exceeds ``max_overhead`` of the
  sampling interval the interval doubles (up to 1 s). The guard makes
  "leave it on in production" a bounded decision, not a hope:
  ``overhead_fraction()`` reports the realized cost share and
  ``throttles`` how often the guard fired.
* **exports, not dashboards** — ``export_collapsed`` writes classic
  folded stacks (``a;b;c 42`` — flamegraph.pl / speedscope paste),
  ``export_speedscope`` a schema-valid speedscope "sampled" profile;
  ``scripts/trace_summary.py`` digests either into the host-tax
  table committed next to bench records.

Sampling is cooperative with the GIL: a sample sees each thread at a
bytecode boundary, which is exactly the resolution Python-level
host-tax questions need (C-level jax dispatch shows up as the jax
frame that called it).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

_MAX_DEPTH = 64


def _frame_name(code) -> str:
    """``pkg/mod.py:func`` with the path trimmed to its last three
    components — stable across checkouts, long enough to classify."""
    fn = code.co_filename.replace("\\", "/")
    tail = "/".join(fn.split("/")[-3:])
    return f"{tail}:{code.co_name}"


class SamplingProfiler:
    """Thread-based folded-stack sampler with a hard overhead guard.

    Args:
      hz: target sampling rate (the guard only ever LOWERS it).
      max_overhead: cap on (sampling cost / sampling interval); the
        interval doubles whenever the smoothed cost crosses it.
      metrics: optional ``MetricsRegistry`` — exports
        ``prof_samples_total`` / ``prof_throttles_total`` counters and
        a ``prof_overhead_fraction`` gauge so the profiler's own cost
        is itself observable.

    Use as a context manager, or ``start()`` / ``stop()``.
    """

    def __init__(self, hz: float = 97.0, max_overhead: float = 0.05,
                 metrics=None):
        if hz <= 0:
            raise ValueError(f"hz must be > 0: {hz}")
        if not 0.0 < max_overhead <= 1.0:
            raise ValueError(
                f"max_overhead must be in (0, 1]: {max_overhead}")
        self.hz = hz
        self.max_overhead = max_overhead
        self._interval = 1.0 / hz
        self._lock = threading.Lock()
        self._counts: Dict[Tuple[str, ...], int] = {}
        self._weights: Dict[Tuple[str, ...], float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cost_ema = 0.0
        self._cost_total = 0.0
        self._t_started: Optional[float] = None
        self._wall_total = 0.0
        self.samples = 0
        self.throttles = 0
        self._c_samples = self._c_throttles = self._g_overhead = None
        if metrics is not None:
            self._c_samples = metrics.counter("prof_samples_total")
            self._c_throttles = metrics.counter("prof_throttles_total")
            self._g_overhead = metrics.gauge("prof_overhead_fraction")

    # ------------------------------------------------------------------ #
    def _thread_names(self) -> Dict[int, str]:
        return {t.ident: t.name for t in threading.enumerate()
                if t.ident is not None}

    def sample_once(self) -> int:
        """Take one sample of every other thread; returns the number
        of stacks recorded. Public so tests (and the overhead guard's
        own cost accounting) can drive it deterministically."""
        own = threading.get_ident()
        names = self._thread_names()
        with self._lock:
            dt = self._interval
        stacks: List[Tuple[str, ...]] = []
        for tid, frame in sys._current_frames().items():
            if tid == own:
                continue
            rev: List[str] = []
            f = frame
            while f is not None and len(rev) < _MAX_DEPTH:
                rev.append(_frame_name(f.f_code))
                f = f.f_back
            rev.append(f"thread:{names.get(tid, tid)}")
            stacks.append(tuple(reversed(rev)))
        with self._lock:
            for st in stacks:
                self._counts[st] = self._counts.get(st, 0) + 1
                self._weights[st] = self._weights.get(st, 0.0) + dt
            self.samples += 1
        if self._c_samples is not None:
            self._c_samples.inc()
        return len(stacks)

    def _note_cost(self, cost: float) -> None:
        """The overhead guard: smooth the per-sample cost and double
        the interval whenever it crosses the cap. Factored out so the
        throttle law is unit-testable without a live thread."""
        throttled = False
        with self._lock:
            self._cost_total += cost
            self._cost_ema = 0.8 * self._cost_ema + 0.2 * cost
            if self._cost_ema > self.max_overhead * self._interval:
                self._interval = min(self._interval * 2.0, 1.0)
                self.throttles += 1
                throttled = True
        if throttled and self._c_throttles is not None:
            self._c_throttles.inc()
        if self._g_overhead is not None:
            self._g_overhead.set(self.overhead_fraction())

    def _run(self) -> None:
        while True:
            with self._lock:
                interval = self._interval
            if self._stop.wait(interval):
                return
            t0 = time.perf_counter()
            self.sample_once()
            self._note_cost(time.perf_counter() - t0)

    def start(self) -> "SamplingProfiler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            with self._lock:
                self._t_started = time.perf_counter()
            self._thread = threading.Thread(
                target=self._run, name="tuplewise-prof", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        with self._lock:
            if self._t_started is not None:
                self._wall_total += time.perf_counter() - self._t_started
                self._t_started = None

    def __enter__(self) -> "SamplingProfiler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------ #
    def overhead_fraction(self) -> float:
        """Realized sampling cost as a fraction of profiled wall time
        (0.0 before any sample)."""
        with self._lock:
            wall = self._wall_total
            if self._t_started is not None:
                wall += time.perf_counter() - self._t_started
            return (self._cost_total / wall) if wall > 0 else 0.0

    def folded(self) -> Dict[Tuple[str, ...], int]:
        """{stack tuple (root→leaf): sample count}."""
        with self._lock:
            return dict(self._counts)

    def export_collapsed(self, path: str) -> int:
        """Classic collapsed-stack lines (``a;b;c count``); returns
        the number of distinct stacks written."""
        with self._lock:
            items = sorted(self._counts.items(),
                           key=lambda kv: (-kv[1], kv[0]))
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            for stack, n in items:
                f.write(";".join(stack) + f" {n}\n")
        return len(items)

    def export_speedscope(self, path: str,
                          name: str = "tuplewise-prof") -> int:
        """speedscope "sampled" profile (https://speedscope.app);
        returns the number of samples written."""
        with self._lock:
            items = sorted(self._counts.items(),
                           key=lambda kv: (-kv[1], kv[0]))
            weights = dict(self._weights)
        frame_ix: Dict[str, int] = {}
        frames: List[dict] = []
        samples: List[List[int]] = []
        wlist: List[float] = []
        for stack, n in items:
            ixs = []
            for fr in stack:
                i = frame_ix.get(fr)
                if i is None:
                    i = frame_ix[fr] = len(frames)
                    frames.append({"name": fr})
                ixs.append(i)
            samples.append(ixs)
            wlist.append(weights.get(stack, 0.0))
        total = sum(wlist)
        doc = {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "name": name,
            "exporter": "tuplewise-prof",
            "shared": {"frames": frames},
            "profiles": [{
                "type": "sampled",
                "name": name,
                "unit": "seconds",
                "startValue": 0,
                "endValue": total,
                "samples": samples,
                "weights": wlist,
            }],
            "activeProfileIndex": 0,
        }
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        return len(samples)


def export_profile(prof: Optional[SamplingProfiler],
                   path: Optional[str]) -> Optional[str]:
    """Write ``path`` in the format its suffix names (``*.collapsed``
    / ``*.txt`` = folded stacks, anything else = speedscope JSON);
    no-op without a profiler or path. Returns the path written."""
    if prof is None or not path:
        return None
    if path.endswith((".collapsed", ".txt")):
        prof.export_collapsed(path)
    else:
        prof.export_speedscope(path)
    return path
