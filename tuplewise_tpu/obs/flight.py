"""Flight recorder: a bounded ring of structured lifecycle events
[ISSUE 6 tentpole].

Metrics answer "how many / how slow"; traces answer "where did this
request's time go"; the flight recorder answers the post-mortem
question: **what was the process doing when it died?** It keeps the
last N lifecycle events — compactions, major merges (+ fallbacks),
heals, restarts, chaos injections, snapshot/WAL seals, poison rejects,
deadline expiries — each stamped with a monotonic sequence number,
wall + monotonic timestamps, and the trace id active at record time
(when a :class:`~tuplewise_tpu.obs.tracing.Tracer` is attached), so a
dump line correlates directly with the exported span timeline.

Dump policy:

* **on demand** — ``dump()`` returns the events; ``dump_to(path)``
  writes JSONL (header line + one event per line).
* **automatically** — ``auto_dump()`` writes to the configured
  ``dump_path`` (no-op without one). The serving engine calls it on
  close, on a batcher crash/restart, and on heal exhaustion; the
  recovery manager calls it whenever a snapshot lands, so the dump
  file sits NEXT TO the snapshot a post-SIGKILL forensics session
  starts from.

Recording is one lock + one dict append — cheap enough to leave on
unconditionally (lifecycle events are rare by definition; the hot path
never records).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional


class FlightRecorder:
    """Bounded, thread-safe ring of lifecycle events.

    Args:
      capacity: events retained (oldest evicted first).
      tracer: optional :class:`~tuplewise_tpu.obs.tracing.Tracer`; when
        attached, each event records the trace id active on the
        recording thread (explicit ``trace_id=`` overrides).
      dump_path: where ``auto_dump()`` writes; None disables auto
        dumps (``dump_to`` still works).
    """

    def __init__(self, capacity: int = 4096, tracer=None,
                 dump_path: Optional[str] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1: {capacity}")
        self.capacity = capacity
        self.tracer = tracer
        self.dump_path = dump_path
        self._lock = threading.Lock()
        self._ring: List[dict] = []
        self._ring_pos = 0
        self._seq = 0
        self.dropped = 0
        self.last_dump_error: Optional[str] = None

    # ------------------------------------------------------------------ #
    def record(self, kind: str, trace_id: Optional[int] = None,
               **fields) -> int:
        """Record one event; returns its sequence number. ``fields``
        must be JSON-able (the dump is a forensics artifact, not an
        object store)."""
        if trace_id is None and self.tracer is not None:
            trace_id = self.tracer.current_trace_id()
        ev = {
            "kind": kind,
            "t_wall": time.time(),
            "t_mono": time.perf_counter(),
            "trace_id": trace_id,
        }
        if fields:
            ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            if len(self._ring) < self.capacity:
                self._ring.append(ev)
            else:
                self._ring[self._ring_pos] = ev
                self._ring_pos = (self._ring_pos + 1) % self.capacity
                self.dropped += 1
            return self._seq

    # ------------------------------------------------------------------ #
    def events(self, kind: Optional[str] = None) -> List[dict]:
        """Retained events in sequence order (optionally one kind)."""
        with self._lock:
            evs = (self._ring[self._ring_pos:]
                   + self._ring[: self._ring_pos])
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        return evs

    def counts(self) -> dict:
        """{kind: count} over the retained window — the cheap summary
        exit reports embed."""
        out: dict = {}
        for e in self.events():
            out[e["kind"]] = out.get(e["kind"], 0) + 1
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # ------------------------------------------------------------------ #
    def dump(self) -> dict:
        """The full dump as one JSON-able dict."""
        evs = self.events()
        with self._lock:
            # ``dropped`` is written under the lock in record(); the
            # guard-inference pass [ISSUE 13] flagged this read as the
            # one access outside it — a torn read here would ship a
            # wrong drop count into the forensics header
            dropped = self.dropped
        return {
            "format": "tuplewise-flight-v1",
            "dumped_at_wall": time.time(),
            "dumped_at_mono": time.perf_counter(),
            "n_events": len(evs),
            "dropped": dropped,
            "events": evs,
        }

    def dump_to(self, path: str) -> int:
        """Write the dump as JSONL (header line, then one event per
        line — greppable and torn-write-tolerant); returns the number
        of events written. Atomic via temp + rename so a crash mid-dump
        never destroys the previous dump."""
        d = self.dump()
        evs = d.pop("events")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps(d) + "\n")
            for e in evs:
                f.write(json.dumps(e) + "\n")
        os.replace(tmp, path)
        return len(evs)

    def auto_dump(self) -> bool:
        """Dump to the configured path; returns True on success. Never
        raises — forensics must not take down the thing it observes
        (the error lands in ``last_dump_error``)."""
        if not self.dump_path:
            return False
        try:
            self.dump_to(self.dump_path)
            return True
        except Exception as e:   # noqa: BLE001 — best-effort by design
            self.last_dump_error = repr(e)
            return False

    @staticmethod
    def load_dump(path: str) -> dict:
        """Read a ``dump_to`` file back into the ``dump()`` shape."""
        with open(path, "r", encoding="utf-8") as f:
            header = json.loads(f.readline())
            events = []
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    break    # torn tail: keep what survived
        header["events"] = events
        return header
