"""The NumPy reference backend — the frozen oracle path.

Serial, blockwise (never materializes the full pair grid), pure NumPy.
Implements the four estimator schemes of [SURVEY §1.2] exactly as the
call-stack traces in [SURVEY §4.1-4.3] describe. Every other backend is
tested against this one [SURVEY §5.1 "Oracle parity"]; per the north star
it stays untouched by TPU work (BASELINE.json:5).

Identity discipline: one-sample U-statistics range over pairs of
*distinct data points*. Under with-replacement ("swr") partitioning a
worker block can hold the same original point twice, so exclusion is done
on original indices (``ids``), not on block positions — positional-only
exclusion would bias swr local averages by a (1 - 1/n) factor.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from tuplewise_tpu.backends.base import register_backend
from tuplewise_tpu.ops.kernels import Kernel, get_kernel
from tuplewise_tpu.parallel.partition import (
    draw_pair_design,
    draw_triplet_design,
    partition_indices,
    partition_two_sample,
)

_BLOCK = 4096


@register_backend("numpy")
class NumpyBackend:
    """Serial oracle. All estimator methods return python floats."""

    name = "numpy"

    def __init__(self, kernel: Kernel, block_size: int = _BLOCK):
        self.kernel = get_kernel(kernel)
        self.block = int(block_size)

    # ------------------------------------------------------------------ #
    # primitives                                                          #
    # ------------------------------------------------------------------ #
    def _pair_stats(
        self,
        A: np.ndarray,
        B: np.ndarray,
        ids_a: Optional[np.ndarray] = None,
        ids_b: Optional[np.ndarray] = None,
    ) -> Tuple[float, int]:
        """(sum, count) of h over the A x B grid, tiled [SURVEY §4.1],
        skipping cells whose original indices coincide (if ids given)."""
        k, blk = self.kernel, self.block
        total, count = 0.0, 0
        for i0 in range(0, len(A), blk):
            a = A[i0 : i0 + blk]
            ia = None if ids_a is None else ids_a[i0 : i0 + blk]
            for j0 in range(0, len(B), blk):
                m = np.asarray(k.pair_matrix(a, B[j0 : j0 + blk], np))
                if ia is not None:
                    jb = ids_b[j0 : j0 + m.shape[1]]
                    valid = ia[:, None] != jb[None, :]
                    total += float(np.sum(m * valid))
                    count += int(np.sum(valid))
                else:
                    total += float(np.sum(m))
                    count += m.size
        return total, count

    def _triplet_stats(
        self,
        X: np.ndarray,
        Y: np.ndarray,
        ids_x: Optional[np.ndarray] = None,
    ) -> Tuple[float, int]:
        """(sum, count) of h(x_i, x_j, y_k) over i != j (by original id),
        all k [degree-(2,1), SURVEY §1.1]. O(n1^2 n2) — complete degree-3
        only ever runs at small n; incomplete is the practical path
        [SURVEY §7 step 7]."""
        k = self.kernel
        n1, n2 = len(X), len(Y)
        if ids_x is None:
            ids_x = np.arange(n1)
        total, count = 0.0, 0
        for i in range(n1):
            a = X[i : i + 1]
            vals = np.asarray(
                k.triplet_values(a[:, None, :], X[:, None, :], Y[None, :, :], np)
            )  # [n1, n2]
            valid = ids_x != ids_x[i]  # excludes j == i and duplicate draws
            total += float(np.sum(vals[valid]))
            count += int(np.sum(valid)) * n2
        return total, count

    # ------------------------------------------------------------------ #
    # estimator schemes                                                   #
    # ------------------------------------------------------------------ #
    def complete(self, A: np.ndarray, B: np.ndarray = None) -> float:
        """Complete U-statistic U_n — all tuples [SURVEY §1.1, §4.1]."""
        k = self.kernel
        if k.kind == "triplet":
            s, c = self._triplet_stats(A, B)
            return s / c
        if k.two_sample:
            s, c = self._pair_stats(A, B)
            return s / c
        ids = np.arange(len(A))
        s, c = self._pair_stats(A, A, ids, ids)  # excludes the diagonal
        return s / c

    def local_average(
        self,
        A: np.ndarray,
        B: np.ndarray = None,
        *,
        n_workers: int,
        seed: int = 0,
        scheme: str = "swor",
        dropped_workers: tuple = (),
    ) -> float:
        """U^loc_N: mean of per-worker complete U over a proportional
        partition [SURVEY §1.2 item 2, §4.2 inner loop]. Workers listed
        in ``dropped_workers`` are treated as failed: their contribution
        is dropped and the mean renormalizes over survivors
        (parallel.faults, SURVEY §5.4)."""
        rng = np.random.default_rng(seed)
        return self._local_average_once(
            A, B, n_workers, rng, scheme, dropped_workers
        )

    def _local_average_once(
        self, A, B, n_workers, rng, scheme, dropped_workers=()
    ) -> float:
        from tuplewise_tpu.parallel.faults import survivors

        k = self.kernel
        alive = survivors(n_workers, dropped_workers)
        vals = []
        # NOTE: the partition is always drawn over ALL n_workers (failed
        # workers' data is lost, not redistributed), then dropped entries
        # are skipped — matching real drop-and-renormalize semantics and
        # keeping the RNG stream identical with and without failures.
        if k.kind == "triplet":
            pi, ni = partition_two_sample(len(A), len(B), n_workers, rng, scheme)
            for w in alive:
                s, c = self._triplet_stats(A[pi[w]], B[ni[w]], ids_x=pi[w])
                vals.append(s / c)
        elif k.two_sample:
            pi, ni = partition_two_sample(len(A), len(B), n_workers, rng, scheme)
            for w in alive:
                s, c = self._pair_stats(A[pi[w]], B[ni[w]])
                vals.append(s / c)
        else:
            idx = partition_indices(len(A), n_workers, rng, scheme)
            for w in alive:
                s, c = self._pair_stats(A[idx[w]], A[idx[w]], idx[w], idx[w])
                vals.append(s / c)
        return float(np.mean(vals))

    def repartitioned(
        self,
        A: np.ndarray,
        B: np.ndarray = None,
        *,
        n_workers: int,
        n_rounds: int,
        seed: int = 0,
        scheme: str = "swor",
        dropped_workers: tuple = (),
    ) -> float:
        """U_{N,T}: average of T local-average rounds, one reshuffle per
        round — repartitions buy variance [SURVEY §1.2 item 3, §4.2].
        ``dropped_workers`` are excluded from every round (a failed
        worker stays failed; drop-and-renormalize per SURVEY §5.4)."""
        rng = np.random.default_rng(seed)
        ests = [
            self._local_average_once(
                A, B, n_workers, rng, scheme, dropped_workers
            )
            for _ in range(n_rounds)
        ]
        return float(np.mean(ests))

    def incomplete(
        self,
        A: np.ndarray,
        B: np.ndarray = None,
        *,
        n_pairs: int,
        seed: int = 0,
        design: str = "swr",
    ) -> float:
        """Incomplete U-statistic: B tuples drawn from the tuple grid
        [SURVEY §1.1, §4.3]. Sampling designs (the incomplete-U
        machinery of Clemencon/Colin/Bellet, PAPERS.md:6):

        * ``"swr"`` — B i.i.d. uniform draws WITH replacement (the
          paper's default; extra variance term Var(h)/B).
        * ``"swor"`` — B DISTINCT tuples (without replacement): same
          mean, variance reduced by the finite-population correction.
        * ``"bernoulli"`` — every tuple kept independently with
          probability B/|grid| (simulated exactly as a Binomial draw
          of the sample size, then a uniform distinct sample); the
          estimator divides by the REALIZED count.
        """
        k = self.kernel
        rng = np.random.default_rng(seed)
        if k.kind == "triplet":
            # all three designs via the shared degree-3 sampler; swr
            # reproduces the historical i / shifted-j / k call sequence
            i, j, kk = draw_triplet_design(
                rng, len(A), len(B), n_pairs, design
            )
            vals = k.triplet_values(A[i], A[j], B[kk], np)
            return float(np.mean(vals))
        one_sample = not k.two_sample
        n1 = len(A)
        n2 = n1 - 1 if one_sample else len(B)
        i, j = draw_pair_design(rng, n1, n2, n_pairs, design,
                                one_sample=one_sample)
        if one_sample:
            return float(np.mean(k.pair_elementwise(A[i], A[j], np)))
        return float(np.mean(k.pair_elementwise(A[i], B[j], np)))
