"""Single-device JAX/XLA backend [SURVEY §7 step 4].

Same estimator semantics as the NumPy oracle, executed as compiled XLA:

* pair/triplet sums stream through the tiled reductions in
  ops.pair_tiles (never materializing the grid);
* the N simulated workers of local-average / repartitioned schemes are a
  `jax.vmap` axis — the single-device rehearsal of the mesh backend's
  one-shard-per-chip layout;
* partitioning/repartitioning and incomplete sampling use `jax.random`
  with the fold_in key discipline of utils.rng;
* every entry point is `jax.jit`-compiled and cached per input shape.

Parity contract with the oracle [SURVEY §5.1]: exact (to dtype) for
complete statistics; statistical for anything that draws randomness,
since NumPy and JAX PRNGs cannot match bit-for-bit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tuplewise_tpu.backends.base import register_backend
from tuplewise_tpu.ops import pair_tiles
from tuplewise_tpu.ops.kernels import Kernel, get_kernel
from tuplewise_tpu.utils.rng import fold, root_key


@register_backend("jax")
class JaxBackend:
    """Single-device XLA execution of the four estimator schemes."""

    name = "jax"

    def __init__(
        self,
        kernel: Kernel,
        dtype=jnp.float32,
        tile_a: int = 1024,
        tile_b: int = 1024,
        triplet_tile: int = 128,
        impl: str = "xla",
        auc_fast: bool = True,
    ):
        """impl: "xla" (tiled lax.scan reductions, default) or "pallas"
        (hand-written TPU kernel for unmasked diff-kernel complete sums;
        falls back to XLA when sizes aren't tile multiples).
        auc_fast: complete() for the exact "auc" kernel uses the
        O(n log n) rank formulation (ops.rank_auc) instead of streaming
        the pair grid — identical value, orders of magnitude faster."""
        if impl not in ("xla", "pallas"):
            raise ValueError(f"impl must be 'xla' or 'pallas', got {impl!r}")
        self.kernel = get_kernel(kernel)
        self.dtype = dtype
        self.tile_a, self.tile_b = tile_a, tile_b
        self.triplet_tile = triplet_tile
        self.impl = impl
        self.auc_fast = auc_fast
        k = self.kernel

        # ---- complete ------------------------------------------------- #
        def complete_fn(A, B):
            if k.kind == "triplet":
                from tuplewise_tpu.ops.pallas_triplets import (
                    triplet_stats_best,
                )

                platform = jax.devices()[0].platform
                s, c = triplet_stats_best(
                    k, A, B, tile=triplet_tile,
                    impl=impl if platform in ("tpu", "cpu") else "xla",
                    interpret=platform == "cpu",
                )
            elif k.two_sample:
                from tuplewise_tpu.ops.kernels import auc_kernel

                # identity check, not name: a user kernel registered under
                # the name "auc" with a different diff_fn must NOT be
                # silently replaced by the rank formulation
                if auc_fast and k is auc_kernel:
                    from tuplewise_tpu.ops.rank_auc import rank_auc

                    return rank_auc(A, B)
                platform = jax.devices()[0].platform
                if (impl == "pallas" and k.kind == "diff"
                        and platform in ("tpu", "cpu")):  # gpu: XLA path
                    # interior/edge decomposition handles ANY sizes (and
                    # the SMEM row-block budget) [VERDICT r3 next #1]
                    from tuplewise_tpu.ops.pallas_pairs import (
                        pallas_pair_sum_any,
                    )

                    s = pallas_pair_sum_any(
                        A, B, kernel=k,
                        tile_a=tile_a, tile_b=tile_b,
                        interpret=platform == "cpu",
                    )
                    return s / jnp.asarray(
                        A.shape[0] * B.shape[0], s.dtype
                    )
                s, c = pair_tiles.pair_stats(
                    k, A, B, tile_a=tile_a, tile_b=tile_b
                )
            else:
                ids = jnp.arange(A.shape[0], dtype=jnp.int32)
                from tuplewise_tpu.ops.scatter_exact import (
                    is_builtin_scatter, scatter_pair_stats,
                )

                if is_builtin_scatter(k):
                    # polynomial kernel: exact O(n d) moment form, no
                    # pair grid at all [VERDICT r3 next #7]
                    s, c = scatter_pair_stats(
                        A, A, ids_a=ids, ids_b=ids
                    )
                else:
                    s, c = pair_tiles.pair_stats(
                        k, A, A, ids_a=ids, ids_b=ids,
                        tile_a=tile_a, tile_b=tile_b,
                    )
            return s / c.astype(s.dtype)

        self._complete = jax.jit(complete_fn)

        # ---- local average over a random partition -------------------- #
        def draw_blocks(key, n, n_workers, scheme):
            m = n // n_workers
            if scheme == "swor":
                idx = jax.random.permutation(key, n)[: n_workers * m]
                return idx.reshape(n_workers, m)
            return jax.random.randint(key, (n_workers, m), 0, n)

        def local_round(A, B, key, alive, n_workers, scheme):
            """One local-average round; workers are a vmap axis. ``alive``
            is a {0,1} float [n_workers] mask: dropped workers' values are
            excluded and the mean renormalizes over survivors
            (drop-and-renormalize, parallel.faults / SURVEY §5.4).
            Passed as a traced array so failure sets don't recompile."""
            if k.two_sample:  # incl. triplet (degree-(2,1))
                k1, k2 = jax.random.split(key)
                i1 = draw_blocks(k1, A.shape[0], n_workers, scheme)
                i2 = draw_blocks(k2, B.shape[0], n_workers, scheme)
                Ab, Bb = A[i1], B[i2]
                if k.kind == "triplet":
                    from tuplewise_tpu.ops.pallas_triplets import (
                        triplet_stats_best,
                    )

                    platform = jax.devices()[0].platform

                    def worker(a, b, ids):
                        s, c = triplet_stats_best(
                            k, a, b, ids_x=ids, tile=triplet_tile,
                            impl=impl if platform in ("tpu", "cpu")
                            else "xla",
                            interpret=platform == "cpu",
                        )
                        return s / c.astype(s.dtype)
                    vals = jax.vmap(worker)(Ab, Bb, i1.astype(jnp.int32))
                else:
                    def worker(a, b):
                        s, c = pair_tiles.pair_stats(
                            k, a, b, tile_a=tile_a, tile_b=tile_b
                        )
                        return s / c.astype(s.dtype)
                    vals = jax.vmap(worker)(Ab, Bb)
            else:
                idx = draw_blocks(key, A.shape[0], n_workers, scheme)
                Ab = A[idx]
                from tuplewise_tpu.ops.scatter_exact import (
                    is_builtin_scatter, scatter_pair_stats,
                )

                if is_builtin_scatter(k):
                    def worker(a, ids):
                        s, c = scatter_pair_stats(
                            a, a, ids_a=ids, ids_b=ids
                        )
                        return s / c.astype(s.dtype)
                else:
                    def worker(a, ids):
                        s, c = pair_tiles.pair_stats(
                            k, a, a, ids_a=ids, ids_b=ids,
                            tile_a=tile_a, tile_b=tile_b,
                        )
                        return s / c.astype(s.dtype)
                vals = jax.vmap(worker)(Ab, idx.astype(jnp.int32))
            alive = alive.astype(vals.dtype)
            return jnp.sum(vals * alive) / jnp.sum(alive)

        self._local = jax.jit(
            local_round, static_argnames=("n_workers", "scheme")
        )

        # ---- repartitioned: scan over T reshuffle rounds -------------- #
        def repartitioned_fn(A, B, key, alive, n_workers, n_rounds, scheme):
            def round_body(carry, t):
                kt = fold(key, "repartition_round", t)
                return carry + local_round(
                    A, B, kt, alive, n_workers, scheme
                ), None

            total, _ = lax.scan(
                round_body, jnp.zeros((), A.dtype), jnp.arange(n_rounds)
            )
            return total / n_rounds

        self._repart = jax.jit(
            repartitioned_fn,
            static_argnames=("n_workers", "n_rounds", "scheme"),
        )

        # ---- incomplete ----------------------------------------------- #
        def incomplete_fn(A, B, key, n_pairs):
            if k.kind == "triplet":
                return pair_tiles.incomplete_triplet_mean(k, key, A, B, n_pairs)
            if k.two_sample:
                return pair_tiles.incomplete_pair_mean(
                    k, key, A, B, n_pairs, one_sample=False
                )
            return pair_tiles.incomplete_pair_mean(
                k, key, A, A, n_pairs, one_sample=True
            )

        self._incomplete = jax.jit(
            incomplete_fn, static_argnames=("n_pairs",)
        )

        def gather_mean_fn(A, B, i, j):
            return jnp.mean(
                k.pair_elementwise(A[i], B[j], jnp), dtype=A.dtype
            )

        def gather_triplet_mean_fn(A, B, i, j, kk):
            return jnp.mean(
                k.triplet_values(A[i], A[j], B[kk], jnp), dtype=A.dtype
            )

        # host-designed samples (swor/bernoulli): indices come from the
        # shared NumPy sampler, only the kernel evaluation is on device
        self._gather_mean = jax.jit(gather_mean_fn)
        self._gather_triplet_mean = jax.jit(gather_triplet_mean_fn)

    # ------------------------------------------------------------------ #
    def _dev(self, A, B):
        A = jnp.asarray(A, self.dtype)
        B = None if B is None else jnp.asarray(B, self.dtype)
        return A, B

    def complete(self, A, B=None) -> float:
        A, B = self._dev(A, B)
        return float(self._complete(A, B if B is not None else A)
                     if self.kernel.two_sample else self._complete(A, A))

    def _alive(self, n_workers, dropped_workers):
        from tuplewise_tpu.parallel.faults import alive_mask

        return jnp.asarray(
            alive_mask(n_workers, dropped_workers), self.dtype
        )

    def local_average(self, A, B=None, *, n_workers, seed=0, scheme="swor",
                      dropped_workers=()):
        A, B = self._dev(A, B)
        key = fold(root_key(seed), "local_average")
        return float(self._local(
            A, B if B is not None else A, key,
            self._alive(n_workers, dropped_workers),
            n_workers=n_workers, scheme=scheme))

    def repartitioned(self, A, B=None, *, n_workers, n_rounds,
                      seed=0, scheme="swor", dropped_workers=()):
        A, B = self._dev(A, B)
        key = root_key(seed)
        return float(self._repart(
            A, B if B is not None else A, key,
            self._alive(n_workers, dropped_workers),
            n_workers=n_workers, n_rounds=n_rounds, scheme=scheme))

    def incomplete(self, A, B=None, *, n_pairs, seed=0, design="swr"):
        """B sampled tuples; design in {"swr", "swor", "bernoulli"}
        [SURVEY §1.1 incomplete]. "swr" samples on device inside the
        jitted program; the distinct-tuple designs draw indices with the
        shared host sampler (parallel.partition.draw_pair_design) and
        evaluate the kernel on device — index generation is O(B) host
        work, the O(B) kernel math stays compiled. (bernoulli's realized
        sample size varies, so each new size compiles once.)"""
        A, B = self._dev(A, B)
        if design != "swr":
            if self.kernel.kind == "triplet":
                from tuplewise_tpu.parallel.partition import (
                    draw_triplet_design,
                )

                i, j, kk = draw_triplet_design(
                    np.random.default_rng(seed), A.shape[0], B.shape[0],
                    n_pairs, design,
                )
                return float(self._gather_triplet_mean(
                    A, B, jnp.asarray(i), jnp.asarray(j),
                    jnp.asarray(kk)))
            from tuplewise_tpu.parallel.partition import draw_pair_design

            one_sample = not self.kernel.two_sample
            Bv = A if B is None else B
            n1 = A.shape[0]
            n2 = n1 - 1 if one_sample else Bv.shape[0]
            i, j = draw_pair_design(
                np.random.default_rng(seed), n1, n2, n_pairs, design,
                one_sample=one_sample,
            )
            return float(self._gather_mean(
                A, A if one_sample else Bv,
                jnp.asarray(i), jnp.asarray(j)))
        key = fold(root_key(seed), "incomplete")
        return float(self._incomplete(
            A, B if B is not None else A, key, n_pairs=n_pairs))
