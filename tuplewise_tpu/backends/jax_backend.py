"""Single-device JAX/XLA backend [SURVEY §7 step 4].

Same estimator semantics as the NumPy oracle, executed as compiled XLA:

* pair/triplet sums stream through the tiled reductions in
  ops.pair_tiles (never materializing the grid);
* the N simulated workers of local-average / repartitioned schemes are a
  `jax.vmap` axis — the single-device rehearsal of the mesh backend's
  one-shard-per-chip layout;
* partitioning/repartitioning and incomplete sampling use `jax.random`
  with the fold_in key discipline of utils.rng;
* every entry point is `jax.jit`-compiled and cached per input shape.

Parity contract with the oracle [SURVEY §5.1]: exact (to dtype) for
complete statistics; statistical for anything that draws randomness,
since NumPy and JAX PRNGs cannot match bit-for-bit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tuplewise_tpu.backends.base import register_backend
from tuplewise_tpu.ops import pair_tiles
from tuplewise_tpu.ops.kernels import Kernel, get_kernel
from tuplewise_tpu.utils.rng import fold, root_key


@register_backend("jax")
class JaxBackend:
    """Single-device XLA execution of the four estimator schemes."""

    name = "jax"

    def __init__(
        self,
        kernel: Kernel,
        dtype=jnp.float32,
        tile_a: int = 1024,
        tile_b: int = 1024,
        triplet_tile: int = 128,
        impl: str = "xla",
        auc_fast: bool = True,
    ):
        """impl: "xla" (tiled lax.scan reductions, default) or "pallas"
        (hand-written TPU kernel for unmasked diff-kernel complete sums;
        falls back to XLA when sizes aren't tile multiples).
        auc_fast: complete() for the exact "auc" kernel uses the
        O(n log n) rank formulation (ops.rank_auc) instead of streaming
        the pair grid — identical value, orders of magnitude faster."""
        if impl not in ("xla", "pallas"):
            raise ValueError(f"impl must be 'xla' or 'pallas', got {impl!r}")
        self.kernel = get_kernel(kernel)
        self.dtype = dtype
        self.tile_a, self.tile_b = tile_a, tile_b
        self.triplet_tile = triplet_tile
        self.impl = impl
        self.auc_fast = auc_fast
        k = self.kernel

        # ---- complete ------------------------------------------------- #
        def complete_fn(A, B):
            if k.kind == "triplet":
                from tuplewise_tpu.ops.pallas_triplets import (
                    triplet_stats_best,
                )

                platform = jax.devices()[0].platform
                s, c = triplet_stats_best(
                    k, A, B, tile=triplet_tile,
                    impl=impl if platform in ("tpu", "cpu") else "xla",
                    interpret=platform == "cpu",
                )
            elif k.two_sample:
                from tuplewise_tpu.ops.kernels import auc_kernel

                # identity check, not name: a user kernel registered under
                # the name "auc" with a different diff_fn must NOT be
                # silently replaced by the rank formulation
                if auc_fast and k is auc_kernel:
                    from tuplewise_tpu.ops.rank_auc import rank_auc

                    return rank_auc(A, B)
                platform = jax.devices()[0].platform
                if (impl == "pallas" and k.kind == "diff"
                        and platform in ("tpu", "cpu")):  # gpu: XLA path
                    # interior/edge decomposition handles ANY sizes (and
                    # the SMEM row-block budget) [VERDICT r3 next #1]
                    from tuplewise_tpu.ops.pallas_pairs import (
                        pallas_pair_sum_any,
                    )

                    s = pallas_pair_sum_any(
                        A, B, kernel=k,
                        tile_a=tile_a, tile_b=tile_b,
                        interpret=platform == "cpu",
                    )
                    return s / jnp.asarray(
                        A.shape[0] * B.shape[0], s.dtype
                    )
                s, c = pair_tiles.pair_stats(
                    k, A, B, tile_a=tile_a, tile_b=tile_b
                )
            else:
                ids = jnp.arange(A.shape[0], dtype=jnp.int32)
                from tuplewise_tpu.ops.scatter_exact import (
                    is_builtin_scatter, scatter_pair_stats,
                )

                if is_builtin_scatter(k):
                    # polynomial kernel: exact O(n d) moment form, no
                    # pair grid at all [VERDICT r3 next #7]
                    s, c = scatter_pair_stats(
                        A, A, ids_a=ids, ids_b=ids
                    )
                else:
                    s, c = pair_tiles.pair_stats(
                        k, A, A, ids_a=ids, ids_b=ids,
                        tile_a=tile_a, tile_b=tile_b,
                    )
            return s / c.astype(s.dtype)

        self._complete = jax.jit(complete_fn)

        # ---- local average over a random partition -------------------- #
        def draw_blocks(key, n, n_workers, scheme):
            m = n // n_workers
            if scheme == "swor":
                idx = jax.random.permutation(key, n)[: n_workers * m]
                return idx.reshape(n_workers, m)
            return jax.random.randint(key, (n_workers, m), 0, n)

        def local_round(A, B, key, alive, n_workers, scheme):
            """One local-average round; workers are a vmap axis. ``alive``
            is a {0,1} float [n_workers] mask: dropped workers' values are
            excluded and the mean renormalizes over survivors
            (drop-and-renormalize, parallel.faults / SURVEY §5.4).
            Passed as a traced array so failure sets don't recompile."""
            if k.two_sample:  # incl. triplet (degree-(2,1))
                k1, k2 = jax.random.split(key)
                i1 = draw_blocks(k1, A.shape[0], n_workers, scheme)
                i2 = draw_blocks(k2, B.shape[0], n_workers, scheme)
                Ab, Bb = A[i1], B[i2]
                if k.kind == "triplet":
                    from tuplewise_tpu.ops.pallas_triplets import (
                        triplet_stats_best,
                    )

                    platform = jax.devices()[0].platform

                    def worker(a, b, ids):
                        s, c = triplet_stats_best(
                            k, a, b, ids_x=ids, tile=triplet_tile,
                            impl=impl if platform in ("tpu", "cpu")
                            else "xla",
                            interpret=platform == "cpu",
                        )
                        return s / c.astype(s.dtype)
                    vals = jax.vmap(worker)(Ab, Bb, i1.astype(jnp.int32))
                else:
                    def worker(a, b):
                        s, c = pair_tiles.pair_stats(
                            k, a, b, tile_a=tile_a, tile_b=tile_b
                        )
                        return s / c.astype(s.dtype)
                    vals = jax.vmap(worker)(Ab, Bb)
            else:
                idx = draw_blocks(key, A.shape[0], n_workers, scheme)
                Ab = A[idx]
                from tuplewise_tpu.ops.scatter_exact import (
                    is_builtin_scatter, scatter_pair_stats,
                )

                if is_builtin_scatter(k):
                    def worker(a, ids):
                        s, c = scatter_pair_stats(
                            a, a, ids_a=ids, ids_b=ids
                        )
                        return s / c.astype(s.dtype)
                else:
                    def worker(a, ids):
                        s, c = pair_tiles.pair_stats(
                            k, a, a, ids_a=ids, ids_b=ids,
                            tile_a=tile_a, tile_b=tile_b,
                        )
                        return s / c.astype(s.dtype)
                vals = jax.vmap(worker)(Ab, idx.astype(jnp.int32))
            alive = alive.astype(vals.dtype)
            return jnp.sum(vals * alive) / jnp.sum(alive)

        self._local = jax.jit(
            local_round, static_argnames=("n_workers", "scheme")
        )

        # ---- repartitioned: scan over T reshuffle rounds -------------- #
        def repartitioned_fn(A, B, key, alive, n_workers, n_rounds, scheme):
            def round_body(carry, t):
                kt = fold(key, "repartition_round", t)
                return carry + local_round(
                    A, B, kt, alive, n_workers, scheme
                ), None

            total, _ = lax.scan(
                round_body, jnp.zeros((), A.dtype), jnp.arange(n_rounds)
            )
            return total / n_rounds

        self._repart = jax.jit(
            repartitioned_fn,
            static_argnames=("n_workers", "n_rounds", "scheme"),
        )

        # ---- incomplete ----------------------------------------------- #
        def incomplete_fn(A, B, key, n_pairs):
            if k.kind == "triplet":
                return pair_tiles.incomplete_triplet_mean(k, key, A, B, n_pairs)
            if k.two_sample:
                return pair_tiles.incomplete_pair_mean(
                    k, key, A, B, n_pairs, one_sample=False
                )
            return pair_tiles.incomplete_pair_mean(
                k, key, A, A, n_pairs, one_sample=True
            )

        self._incomplete = jax.jit(
            incomplete_fn, static_argnames=("n_pairs",)
        )

        def designed_fn(A, B, key, n_pairs, design):
            """Distinct-design incomplete mean, drawn AND evaluated on
            device in one jitted program (ops.device_design — the single
            overdraw → sort-dedup → subselect sampler shared with the
            learning side and the mesh paths) [VERDICT r4 next #6].
            Fixed shapes: bernoulli's Binomial size lives in the weight
            mask, so one compile serves every seed."""
            from tuplewise_tpu.ops.device_design import (
                draw_pair_design_device, draw_triplet_design_device,
            )

            # floor_one: estimation semantics — bernoulli's realized
            # size clamps at >= 1 so the mean stays defined (the host
            # oracle's documented behavior)
            if k.kind == "triplet":
                i, j, kk, w = draw_triplet_design_device(
                    key, A.shape[0], B.shape[0], n_pairs, design,
                    floor_one=True,
                )
                vals = k.triplet_values(A[i], A[j], B[kk], jnp)
            elif k.two_sample:
                i, j, w = draw_pair_design_device(
                    key, A.shape[0], B.shape[0], n_pairs, design,
                    floor_one=True,
                )
                vals = k.pair_elementwise(A[i], B[j], jnp)
            else:
                i, j, w = draw_pair_design_device(
                    key, A.shape[0], A.shape[0] - 1, n_pairs, design,
                    one_sample=True, floor_one=True,
                )
                vals = k.pair_elementwise(A[i], A[j], jnp)
            return (jnp.sum(vals * w, dtype=jnp.float32)
                    / jnp.sum(w, dtype=jnp.float32))

        self._designed = jax.jit(
            designed_fn, static_argnames=("n_pairs", "design")
        )

    # ------------------------------------------------------------------ #
    def _dev(self, A, B):
        A = jnp.asarray(A, self.dtype)
        B = None if B is None else jnp.asarray(B, self.dtype)
        return A, B

    def complete(self, A, B=None) -> float:
        A, B = self._dev(A, B)
        return float(self._complete(A, B if B is not None else A)
                     if self.kernel.two_sample else self._complete(A, A))

    def _alive(self, n_workers, dropped_workers):
        from tuplewise_tpu.parallel.faults import alive_mask

        return jnp.asarray(
            alive_mask(n_workers, dropped_workers), self.dtype
        )

    def local_average(self, A, B=None, *, n_workers, seed=0, scheme="swor",
                      dropped_workers=()):
        A, B = self._dev(A, B)
        key = fold(root_key(seed), "local_average")
        return float(self._local(
            A, B if B is not None else A, key,
            self._alive(n_workers, dropped_workers),
            n_workers=n_workers, scheme=scheme))

    def repartitioned(self, A, B=None, *, n_workers, n_rounds,
                      seed=0, scheme="swor", dropped_workers=()):
        A, B = self._dev(A, B)
        key = root_key(seed)
        return float(self._repart(
            A, B if B is not None else A, key,
            self._alive(n_workers, dropped_workers),
            n_workers=n_workers, n_rounds=n_rounds, scheme=scheme))

    def incomplete(self, A, B=None, *, n_pairs, seed=0, design="swr"):
        """B sampled tuples; design in {"swr", "swor", "bernoulli"}
        [SURVEY §1.1 incomplete]. Every design runs on device inside
        ONE jitted program: "swr" via the legacy uniform draws, the
        distinct designs via ops.device_design [VERDICT r4 next #6] —
        fixed shapes, one compile per (n_pairs, design), no host
        sampling sync. The host sampler (parallel.partition) remains
        the semantic oracle; distribution parity is pinned in
        tests/test_sampling_designs.py. Device designs bound the budget
        at 0.8 * grid (near-complete budgets belong to the complete
        estimator or the numpy backend's host sampler)."""
        A, B = self._dev(A, B)
        if design != "swr":
            return float(self._designed(
                A, B if B is not None else A,
                fold(root_key(seed), "design"),
                n_pairs=n_pairs, design=design,
            ))
        key = fold(root_key(seed), "incomplete")
        return float(self._incomplete(
            A, B if B is not None else A, key, n_pairs=n_pairs))
