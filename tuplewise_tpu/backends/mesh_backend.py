"""Multi-chip SPMD mesh backend — the real deliverable [SURVEY §7 step 5].

One data shard per chip on a 1-D `jax.sharding.Mesh` [SURVEY §5.8]:

* **complete** statistics run the `ring_pairs` primitive: shard blocks
  rotate around the ICI ring via `lax.ppermute`, each chip accumulates
  tiled pair sums against the visiting block, and a final `lax.psum`
  yields the global value (BASELINE.json:5's "ring all_gather" path).
* **local_average** computes within-shard sums only — zero cross-chip
  pair traffic, exactly the paper's communication-free estimator — and
  psums the per-worker means.
* **repartitioned** reshuffles ON DEVICE: a `lax.scan` over T rounds
  draws a fresh permutation per round, regathers the sharded global
  array into [N, m] worker blocks (XLA inserts the all-to-all), and
  psums local means — communication priced per round, as the paper
  prices it [SURVEY §1.2 item 3].
* **incomplete** samples pairs WITHIN each shard of a randomly packed
  partition (the paper's within-worker sampling [SURVEY §1.2 item 4]);
  random packing makes local pairs uniform over the global pair grid,
  so the estimator stays unbiased.

Multi-chip validation without hardware: the same code runs on
``--xla_force_host_platform_device_count`` virtual CPU devices
[SURVEY §5.1] and via __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tuplewise_tpu.utils.compat import sharded_take
from tuplewise_tpu.backends.base import register_backend
from tuplewise_tpu.ops import pair_tiles
from tuplewise_tpu.ops.kernels import Kernel, get_kernel
from tuplewise_tpu.parallel import ring
from tuplewise_tpu.parallel.mesh import make_mesh
from tuplewise_tpu.parallel.partition import pack_all
from tuplewise_tpu.utils.rng import fold, root_key


def row_sharding(mesh: Mesh) -> NamedSharding:
    """[S, ...] one-row-per-worker placement over every mesh axis — the
    block layout shared by the ring estimators and the serving index's
    sharded base runs (parallel.sharded_counts)."""
    return NamedSharding(mesh, P(tuple(mesh.axis_names)))


@register_backend("mesh")
class MeshBackend:
    """SPMD execution over a 1-D device mesh (one worker per chip)."""

    name = "mesh"

    def __init__(
        self,
        kernel: Kernel,
        mesh: Optional[Mesh] = None,
        n_workers: Optional[int] = None,
        dtype=jnp.float32,
        tile_a: int = 512,
        tile_b: int = 512,
        triplet_tile: int = 32,
        impl: str = "auto",
    ):
        """impl selects the ring hot-loop implementation for diff
        kernels: "pallas" (mask-aware hand-tiled kernel, ~4x the XLA
        scan per chip — ops.pallas_pairs), "xla" (checkpointed tile
        scan), or "auto" (pallas on TPU, xla elsewhere; the CPU test
        mesh exercises pallas via interpret mode only when asked
        explicitly, because interpret mode is slow)."""
        if impl not in ("auto", "xla", "pallas"):
            raise ValueError(f"impl must be auto|xla|pallas, got {impl!r}")
        self.kernel = get_kernel(kernel)
        self.mesh = mesh if mesh is not None else make_mesh(n_workers)
        self.n_shards = int(np.prod(self.mesh.devices.shape))
        self.dtype = dtype
        self.tile_a, self.tile_b = tile_a, tile_b
        self.triplet_tile = triplet_tile
        # the MESH's devices decide the platform, not the default
        # backend: a CPU mesh on a TPU-attached host must not compile
        # Mosaic kernels (and vice versa for interpret mode)
        mesh_platform = self.mesh.devices.flat[0].platform
        if impl == "auto":
            impl = "pallas" if mesh_platform == "tpu" else "xla"
        self.impl = impl
        self._interpret = mesh_platform != "tpu"
        k = self.kernel
        N = self.n_shards
        # all mesh axes together form the worker axis: 1-D ("w",) meshes
        # ride one ICI ring; 2-D ("dcn", "w") meshes use the hierarchical
        # double ring so block rotation stays on ICI [SURVEY §5.8]
        axes = tuple(self.mesh.axis_names)
        self._axes = axes
        if len(axes) > 2:
            raise ValueError(f"mesh must be 1-D or 2-D, got axes {axes}")
        PA = P(axes)  # shard axis 0 over every mesh axis

        shard2 = row_sharding(self.mesh)                  # [N, ...] blocks
        self._block_sharding = shard2

        # ---- complete: ring over the mesh ----------------------------- #
        def complete_body(a, ma, ia, b, mb, ib, no_masks=False):
            # local blocks arrive as [1, cap, ...]; drop the shard axis
            # axis names come from the mesh itself: the TRAILING axis is
            # the fast ICI ring, a leading axis (if any) is DCN — no
            # particular name is required.
            # no_masks (static) certifies the packing added NO padding
            # rows anywhere — n divided N exactly — so the ring may take
            # the unmasked Pallas fast path [VERDICT r2 next #3].
            pair_mask_a = None if no_masks else ma[0]
            pair_mask_b = None if no_masks else mb[0]
            from tuplewise_tpu.ops.scatter_exact import (
                is_builtin_scatter, scatter_mesh_stats,
            )

            if is_builtin_scatter(k):
                # polynomial kernel: the ENTIRE cross-shard statistic
                # is one O(d) psum of moments — no ring at all
                # [VERDICT r3 next #7]; the complete packing's global
                # ids are distinct, as the one_sample count requires
                s, c = scatter_mesh_stats(
                    a[0], ma[0], b[0], mb[0], axes=axes,
                    one_sample=not k.two_sample,
                )
            elif k.kind == "triplet" and len(axes) == 2:
                s, c = ring.ring_triplet_stats_2d(
                    k, a[0], b[0], mask_x=ma[0], mask_y=mb[0], ids_x=ia[0],
                    ici_axis=axes[1], dcn_axis=axes[0], tile=triplet_tile,
                    impl=impl, interpret=self._interpret,
                )
            elif k.kind == "triplet":
                s, c = ring.ring_triplet_stats(
                    k, a[0], b[0], mask_x=ma[0], mask_y=mb[0], ids_x=ia[0],
                    axis_name=axes[-1], tile=triplet_tile,
                    impl=impl, interpret=self._interpret,
                )
            elif len(axes) == 2:
                s, c = ring.ring_pair_stats_2d(
                    k, a[0], b[0],
                    mask_a=pair_mask_a, mask_b=pair_mask_b,
                    ids_a=None if k.two_sample else ia[0],
                    ids_b=None if k.two_sample else ib[0],
                    ici_axis=axes[1], dcn_axis=axes[0],
                    tile_a=tile_a, tile_b=tile_b, impl=impl,
                    interpret=self._interpret,
                )
            else:
                s, c = ring.ring_pair_stats(
                    k, a[0], b[0],
                    mask_a=pair_mask_a, mask_b=pair_mask_b,
                    ids_a=None if k.two_sample else ia[0],
                    ids_b=None if k.two_sample else ib[0],
                    axis_name=axes[0], tile_a=tile_a, tile_b=tile_b,
                    impl=impl, interpret=self._interpret,
                )
            return s, c

        @functools.partial(jax.jit, static_argnames="no_masks")
        def complete_fn(a, ma, ia, b, mb, ib, no_masks=False):
            s, c = jax.shard_map(
                functools.partial(complete_body, no_masks=no_masks),
                mesh=self.mesh,
                in_specs=(PA, PA, PA, PA, PA, PA),
                out_specs=(P(), P()),
                check_vma=False,
            )(a, ma, ia, b, mb, ib)
            return s / c

        self._complete = complete_fn

        # ---- local average / repartitioned ---------------------------- #
        from tuplewise_tpu.parallel.device_partition import draw_blocks as _draw

        def draw_blocks(key, n, scheme):
            return _draw(key, n, N, scheme)

        def local_mean_body(a, ia, b, ib):
            """Per-shard complete U on its local block; [1, m] blocks."""
            if k.kind == "triplet":
                from tuplewise_tpu.ops.pallas_triplets import (
                    triplet_stats_best,
                )

                s, c = triplet_stats_best(
                    k, a[0], b[0], ids_x=ia[0], tile=triplet_tile,
                    impl=impl, interpret=self._interpret,
                )
            elif k.two_sample:
                s, c = pair_tiles.pair_stats(
                    k, a[0], b[0], tile_a=tile_a, tile_b=tile_b
                )
            else:
                from tuplewise_tpu.ops.scatter_exact import (
                    is_builtin_scatter, scatter_pair_stats,
                )

                if is_builtin_scatter(k):
                    s, c = scatter_pair_stats(
                        a[0], a[0], ids_a=ia[0], ids_b=ib[0]
                    )
                else:
                    s, c = pair_tiles.pair_stats(
                        k, a[0], a[0], ids_a=ia[0], ids_b=ib[0],
                        tile_a=tile_a, tile_b=tile_b,
                    )
            return (s / c)[None]

        local_mean_smap = jax.shard_map(
            local_mean_body,
            mesh=self.mesh,
            in_specs=(PA, PA, PA, PA),
            out_specs=PA,
            check_vma=False,
        )

        def one_round(A, B, key, alive, n1, n2, scheme):
            """Gather fresh worker blocks (XLA shuffles across chips) and
            psum the per-worker means.

            A/B are zero-padded to a multiple of N; n1/n2 are the true
            sizes, so permutations range over real rows only and the
            remainder dropped each round is RANDOM (unbiased), matching
            the host partitioner's semantics.

            ``alive`` is a {0,1} float [N] mask: chips listed as dropped
            are excluded and the mean renormalizes over survivors
            (drop-and-renormalize, parallel.faults / SURVEY §5.4)."""
            if k.two_sample:
                k1, k2 = jax.random.split(key)
                i1 = draw_blocks(k1, n1, scheme)
                i2 = draw_blocks(k2, n2, scheme)
                # cross-shard regather: XLA lowers this to the all-to-all
                # shuffle that repartitioning prices [SURVEY §1.2 item 3]
                Ab = sharded_take(A, i1, shard2)
                Bb = sharded_take(B, i2, shard2)
                vals = local_mean_smap(Ab, i1, Bb, i2)
            else:
                # one-sample: ONE partition, same block and ids on both
                # sides so coincident-id pairs are excluded exactly as in
                # the oracle backend
                i1 = draw_blocks(key, n1, scheme)
                Ab = sharded_take(A, i1, shard2)
                vals = local_mean_smap(Ab, i1, Ab, i1)
            alive = alive.astype(vals.dtype)
            return jnp.sum(vals * alive) / jnp.sum(alive)

        self._local = jax.jit(
            one_round, static_argnames=("n1", "n2", "scheme")
        )

        def repart_fn(A, B, key, alive, n1, n2, n_rounds, scheme):
            def body(carry, t):
                kt = fold(key, "repartition_round", t)
                return carry + one_round(A, B, kt, alive, n1, n2, scheme), None

            total, _ = lax.scan(
                body, jnp.zeros((), dtype), jnp.arange(n_rounds)
            )
            return total / n_rounds

        self._repart = jax.jit(
            repart_fn, static_argnames=("n1", "n2", "n_rounds", "scheme")
        )

        # ---- incomplete: within-shard sampling ------------------------ #
        def incomplete_body(key, a, ma, ia, b, mb, ib, n_pairs):
            """[1, cap] blocks; sample n_pairs//N local tuples per shard.
            Padded rows are avoided by sampling from the valid prefix
            (both packers place valid rows first and pad only the tail
            — we sample indices < valid_count)."""
            del ma, mb  # blocks come from pack_partition: no padding
            from tuplewise_tpu.parallel.device_partition import (
                linear_shard_index,
            )

            kk = fold(key, "shard", linear_shard_index(axes))
            per = -(-n_pairs // N)  # ceil: draw AT LEAST n_pairs total
            a0, b0 = a[0], b[0]
            na = a.shape[1]
            nb = b.shape[1]
            if k.kind == "triplet":
                k1, k2 = jax.random.split(kk)
                i, j = pair_tiles.sample_pair_indices(k1, na, na, per, True)
                kn = jax.random.randint(k2, (per,), 0, nb)
                vals = k.triplet_values(a0[i], a0[j], b0[kn], jnp)
            elif k.two_sample:
                i, j = pair_tiles.sample_pair_indices(kk, na, nb, per, False)
                vals = k.pair_elementwise(a0[i], b0[j], jnp)
            else:
                i, j = pair_tiles.sample_pair_indices(kk, na, na, per, True)
                vals = k.pair_elementwise(a0[i], a0[j], jnp)
            del ia, ib
            return lax.pmean(jnp.mean(vals, dtype=a.dtype), axes)

        def incomplete_fn(key, a, ma, ia, b, mb, ib, n_pairs):
            return jax.shard_map(
                functools.partial(incomplete_body, n_pairs=n_pairs),
                mesh=self.mesh,
                in_specs=(P(), PA, PA, PA, PA, PA, PA),
                out_specs=P(),
                check_vma=False,
            )(key, a, ma, ia, b, mb, ib)

        self._incomplete = jax.jit(
            incomplete_fn, static_argnames=("n_pairs",)
        )

        # ---- incomplete with a DEVICE-designed GLOBAL tuple set ------- #
        # [VERDICT r4 next #6] ops.device_design draws the distinct
        # tuple set inside the jitted program (replicated — every chip
        # computes the same O(B log B) sort), the [L] draw pads/reshapes
        # to [N, per] worker blocks, and each worker regathers the rows
        # of ITS sampled tuples across shards (the .at[].get is the
        # priced communication) before local evaluation. The weighted
        # global mean prices exactly the realized tuple set (swor's
        # distinct count, bernoulli's Binomial draw); fixed shapes, one
        # compile per (n_pairs, design).
        def designed_body(av, bv, w):
            vals = k.pair_elementwise(av[0], bv[0], jnp)
            s = lax.psum(jnp.sum(vals * w[0], dtype=vals.dtype), axes)
            c = lax.psum(jnp.sum(w[0], dtype=vals.dtype), axes)
            return s / c

        designed_smap = jax.shard_map(
            designed_body, mesh=self.mesh, in_specs=(PA, PA, PA),
            out_specs=P(), check_vma=False,
        )

        def designed_triplet_body(av, pv, bv, w):
            vals = k.triplet_values(av[0], pv[0], bv[0], jnp)
            s = lax.psum(jnp.sum(vals * w[0], dtype=vals.dtype), axes)
            c = lax.psum(jnp.sum(w[0], dtype=vals.dtype), axes)
            return s / c

        designed_triplet_smap = jax.shard_map(
            designed_triplet_body, mesh=self.mesh,
            in_specs=(PA, PA, PA, PA), out_specs=P(), check_vma=False,
        )

        def designed_fn(Ag, Bg, key, n1, n2, n_pairs, design):
            from tuplewise_tpu.ops.device_design import (
                draw_pair_design_device, draw_triplet_design_device,
                shard_design_blocks,
            )

            # floor_one: estimation semantics (bernoulli size >= 1, the
            # host oracle's documented behavior — the mean stays defined)
            if k.kind == "triplet":
                i, j, kk, w = draw_triplet_design_device(
                    key, n1, n2, n_pairs, design, floor_one=True
                )
                pi, pj, pk, pw = shard_design_blocks(
                    (i, j, kk), w, N, dtype=self.dtype
                )
                return designed_triplet_smap(
                    sharded_take(Ag, pi, shard2),
                    sharded_take(Ag, pj, shard2),
                    sharded_take(Bg, pk, shard2),
                    pw,
                )
            one_sample = not k.two_sample
            i, j, w = draw_pair_design_device(
                key, n1, n1 - 1 if one_sample else n2, n_pairs, design,
                one_sample=one_sample, floor_one=True,
            )
            pi, pj, pw = shard_design_blocks((i, j), w, N,
                                             dtype=self.dtype)
            return designed_smap(
                sharded_take(Ag, pi, shard2),
                sharded_take(Bg, pj, shard2),
                pw,
            )

        self._designed = jax.jit(
            designed_fn,
            static_argnames=("n1", "n2", "n_pairs", "design"),
        )

    # ------------------------------------------------------------------ #
    # packing helpers (host side)                                        #
    # ------------------------------------------------------------------ #
    def _put(self, arr):
        return jax.device_put(jnp.asarray(arr), self._block_sharding)

    def _pack_complete(self, X):
        p, m, i = pack_all(np.asarray(X), self.n_shards)
        return (
            self._put(jnp.asarray(p, self.dtype)),
            self._put(jnp.asarray(m, self.dtype)),
            self._put(jnp.asarray(i)),
        )

    def _pack_partition(self, X, rng, scheme):
        """Random equal partition (remainder dropped), matching the
        NumPy backend's partitioner semantics."""
        from tuplewise_tpu.parallel.partition import partition_indices

        idx = partition_indices(len(X), self.n_shards, rng, scheme)
        p = np.asarray(X)[idx]
        return (
            self._put(jnp.asarray(p, self.dtype)),
            self._put(jnp.ones(idx.shape, self.dtype)),
            self._put(jnp.asarray(idx, jnp.int32)),
        )

    def _global(self, X):
        """Zero-padded worker-sharded global array (see
        parallel.device_partition.pad_put for the padding rationale)."""
        from tuplewise_tpu.parallel.device_partition import pad_put

        return pad_put(X, self.mesh, self.dtype)

    # ------------------------------------------------------------------ #
    # estimator schemes                                                  #
    # ------------------------------------------------------------------ #
    def complete(self, A, B=None) -> float:
        a, ma, ia = self._pack_complete(A)
        no_masks = len(A) % self.n_shards == 0
        if self.kernel.two_sample:
            b, mb, ib = self._pack_complete(B)
            no_masks = no_masks and len(B) % self.n_shards == 0
        else:
            b, mb, ib = a, ma, ia
        return float(self._complete(a, ma, ia, b, mb, ib,
                                    no_masks=no_masks))

    def _alive(self, dropped_workers):
        from tuplewise_tpu.parallel.faults import alive_mask

        return jnp.asarray(
            alive_mask(self.n_shards, dropped_workers), self.dtype
        )

    def local_average(self, A, B=None, *, n_workers=None, seed=0,
                      scheme="swor", dropped_workers=()):
        self._check_workers(n_workers)
        A, B = self._two(A, B)
        self._check_sizes(A, B)
        Ag = self._global(A)
        Bg = Ag if B is A else self._global(B)
        key = fold(root_key(seed), "local_average")
        return float(self._local(
            Ag, Bg, key, self._alive(dropped_workers),
            n1=len(A), n2=len(B), scheme=scheme))

    def repartitioned(self, A, B=None, *, n_workers=None, n_rounds,
                      seed=0, scheme="swor", dropped_workers=()):
        self._check_workers(n_workers)
        A, B = self._two(A, B)
        self._check_sizes(A, B)
        Ag = self._global(A)
        Bg = Ag if B is A else self._global(B)
        return float(self._repart(
            Ag, Bg, root_key(seed), self._alive(dropped_workers),
            n1=len(A), n2=len(B), n_rounds=n_rounds, scheme=scheme))

    def incomplete(self, A, B=None, *, n_pairs, seed=0, design="swr"):
        """Incomplete U over B sampled tuples [SURVEY §1.2.4].

        design="swr" samples WITHIN each shard of a random packing, on
        device inside the jitted program: each shard draws
        ceil(n_pairs / N) local tuples, so the total budget is n_pairs
        rounded UP to a multiple of N (never under-samples B).

        design="swor"/"bernoulli" draw the DISTINCT global tuple set ON
        DEVICE (ops.device_design — the one sampler shared with the jax
        backend, both harness runners, and the learning side
        [VERDICT r4 next #6]; degree 2 and 3 alike), then shard the
        tuple list over workers and regather each worker's sampled rows
        across shards (the priced communication) before the local
        kernel evaluation. The realized tuple count is honored through
        a weight mask at a FIXED shape (bernoulli's Binomial size never
        recompiles). The host sampler (parallel.partition) remains the
        oracle; distribution parity is pinned in
        tests/test_sampling_designs.py."""
        if design == "swr":
            rng = np.random.default_rng(seed)
            a, ma, ia = self._pack_partition(np.asarray(A), rng, "swor")
            if self.kernel.two_sample:
                b, mb, ib = self._pack_partition(np.asarray(B), rng, "swor")
            else:
                b, mb, ib = a, ma, ia
            key = fold(root_key(seed), "incomplete")
            return float(self._incomplete(
                key, a, ma, ia, b, mb, ib, n_pairs=n_pairs))
        A = np.asarray(A)
        Bv = A if B is None or not self.kernel.two_sample else np.asarray(B)
        Ag = self._global(A)
        Bg = Ag if Bv is A else self._global(Bv)
        return float(self._designed(
            Ag, Bg, fold(root_key(seed), "design"),
            n1=len(A), n2=len(Bv), n_pairs=n_pairs, design=design,
        ))

    # ------------------------------------------------------------------ #
    def _two(self, A, B):
        A = np.asarray(A)
        if self.kernel.two_sample:
            return A, np.asarray(B)
        return A, A

    def _check_sizes(self, A, B):
        if min(len(A), len(B)) < self.n_shards:
            raise ValueError(
                f"n={min(len(A), len(B))} too small for "
                f"{self.n_shards} workers"
            )

    def _check_workers(self, n_workers):
        if n_workers is not None and n_workers != self.n_shards:
            raise ValueError(
                f"mesh backend has {self.n_shards} shards (one worker per "
                f"chip); per-call n_workers={n_workers} is not supported — "
                "build the backend with a mesh of the desired size"
            )
