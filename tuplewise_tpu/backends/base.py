"""The Estimator(backend=...) boundary [SURVEY §7 step 3, BASELINE.json:5].

A backend owns *execution*: how pair/triplet sums are tiled, where
randomness comes from, and how per-worker results are aggregated. The
estimator semantics (complete / local-average / repartitioned /
incomplete, SURVEY §1.2) live above this boundary and are identical
across backends:

* ``numpy`` — the serial reference oracle (frozen semantics).
* ``cpp``   — oracle semantics with the pair loop in compiled C++
  (native/pair_sum.cpp via ctypes; OpenMP rows, deterministic fold).
* ``jax``   — single-device XLA: tiled `lax` loops, `jax.random`.
* ``mesh``  — multi-chip SPMD: `shard_map` over a 1-D or 2-D mesh,
  `ppermute` ring for cross-shard pairs, `psum` aggregation.

Every backend implements the same four estimator entry points with the
same statistical meaning, so oracle-parity tests are a for-loop over
backends [SURVEY §5.1].
"""

from __future__ import annotations

from typing import Callable, Dict

_BACKENDS: Dict[str, Callable] = {}


def register_backend(name: str):
    def deco(cls):
        _BACKENDS[name] = cls
        return cls
    return deco


_LAZY = {
    "numpy": "tuplewise_tpu.backends.numpy_backend",
    "cpp": "tuplewise_tpu.backends.cpp_backend",
    "jax": "tuplewise_tpu.backends.jax_backend",
    "mesh": "tuplewise_tpu.backends.mesh_backend",
}


def get_backend(name: str, kernel, **opts):
    # Import lazily so `numpy`-only use never imports jax.
    if name not in _BACKENDS and name in _LAZY:
        import importlib

        try:
            importlib.import_module(_LAZY[name])
        except ImportError as e:
            raise RuntimeError(
                f"backend {name!r} is registered but failed to import "
                f"({_LAZY[name]}): {e}"
            ) from e
    try:
        cls = _BACKENDS[name]
    except KeyError:
        raise KeyError(
            f"unknown backend {name!r}; available: "
            f"{sorted(set(_BACKENDS) | set(_LAZY))}"
        ) from None
    return cls(kernel, **opts)
