from tuplewise_tpu.backends.base import get_backend, register_backend

__all__ = ["get_backend", "register_backend"]
