"""Native C++ CPU backend — the accelerated host-side engine.

Same estimator semantics as the frozen NumPy oracle (it *subclasses*
NumpyBackend and swaps only the innermost pair reduction), with the hot
loop running in the compiled ``native/pair_sum.cpp`` engine: -O3,
OpenMP row parallelism, deterministic sequential Kahan fold. The oracle
stays untouched [SURVEY §6 "self-baseline"]; this backend exists so the
reference path itself has a serious native runtime, and as the fast
host-side check for large-n parity runs.

Falls back kernel-by-kernel: diff kernels (auc/hinge/logistic), the
scatter kernel, and the degree-3 triplet kernels dispatch to C++;
anything else (user-registered Python kernels) runs the inherited
NumPy path, so every kernel works.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from tuplewise_tpu.backends.base import register_backend
from tuplewise_tpu.backends.numpy_backend import NumpyBackend
from tuplewise_tpu.ops.kernels import Kernel

_DIFF_IDS = {"auc": 0, "hinge": 1, "logistic": 2}


def _native_triplet_spec(kernel: Kernel):
    """(native id, margin) for the C++ triplet engine, or None for the
    inherited NumPy path. Dispatch and margin introspection live in the
    SHARED builtin table (ops.kernels.builtin_triplet_spec — triplet_fn
    identity, never name, so a shadowing custom kernel is never routed
    to the built-in C++ formula)."""
    from tuplewise_tpu.ops.kernels import builtin_triplet_spec

    spec = builtin_triplet_spec(kernel)
    if spec is None:
        return None
    kind, margin = spec
    return {"indicator": 0, "hinge": 1}[kind], margin


def _i64p(x: Optional[np.ndarray]):
    if x is None:
        return None
    return x.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _dp(x: np.ndarray):
    return x.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


@register_backend("cpp")
class CppBackend(NumpyBackend):
    """NumPy-oracle semantics with the pair loop in compiled C++."""

    name = "cpp"

    def __init__(self, kernel: Kernel, block_size: int = 4096):
        super().__init__(kernel, block_size)
        from tuplewise_tpu.native import load_pair_lib

        self._lib = load_pair_lib()
        if self._lib is None:
            raise RuntimeError(
                "native pair library unavailable (no working g++?); "
                "use backend='numpy' instead"
            )
        # resolved once here so a kernel the native engine can't serve
        # surfaces (as a NumPy fallback) at construction, not mid-estimate
        self._triplet_spec = _native_triplet_spec(self.kernel)

    # The ONLY override: the innermost (sum, count) pair reduction.
    def _pair_stats(
        self,
        A: np.ndarray,
        B: np.ndarray,
        ids_a: Optional[np.ndarray] = None,
        ids_b: Optional[np.ndarray] = None,
    ) -> Tuple[float, int]:
        k = self.kernel
        use_ids = ids_a is not None
        ia = None if not use_ids else np.ascontiguousarray(ids_a, np.int64)
        ib = None if not use_ids else np.ascontiguousarray(ids_b, np.int64)
        out_sum = ctypes.c_double()
        out_count = ctypes.c_int64()

        if k.kind == "diff" and k.name in _DIFF_IDS:
            a = np.ascontiguousarray(A, np.float64)
            b = np.ascontiguousarray(B, np.float64)
            self._lib.pair_stats_diff(
                _DIFF_IDS[k.name], _dp(a), len(a), _dp(b), len(b),
                _i64p(ia), _i64p(ib), int(use_ids),
                ctypes.byref(out_sum), ctypes.byref(out_count),
            )
            return out_sum.value, int(out_count.value)

        if k.kind == "pair" and k.name == "scatter":
            a = np.ascontiguousarray(np.atleast_2d(A), np.float64)
            b = np.ascontiguousarray(np.atleast_2d(B), np.float64)
            self._lib.pair_stats_scatter(
                _dp(a), a.shape[0], _dp(b), b.shape[0], a.shape[1],
                _i64p(ia), _i64p(ib), int(use_ids),
                ctypes.byref(out_sum), ctypes.byref(out_count),
            )
            return out_sum.value, int(out_count.value)

        # unknown/custom kernels: inherited pure-NumPy blockwise path
        return super()._pair_stats(A, B, ids_a, ids_b)

    def _triplet_stats(
        self,
        X: np.ndarray,
        Y: np.ndarray,
        ids_x: Optional[np.ndarray] = None,
    ) -> Tuple[float, int]:
        if self._triplet_spec is None:  # custom triplet kernels: NumPy path
            return super()._triplet_stats(X, Y, ids_x)
        kid, margin = self._triplet_spec
        x = np.ascontiguousarray(np.atleast_2d(X), np.float64)
        y = np.ascontiguousarray(np.atleast_2d(Y), np.float64)
        ids = np.ascontiguousarray(
            np.arange(len(x)) if ids_x is None else ids_x, np.int64
        )
        out_sum = ctypes.c_double()
        out_count = ctypes.c_int64()
        self._lib.triplet_stats_native(
            kid, ctypes.c_double(margin),
            _dp(x), x.shape[0], _dp(y), y.shape[0], x.shape[1],
            _i64p(ids), ctypes.byref(out_sum), ctypes.byref(out_count),
        )
        return out_sum.value, int(out_count.value)
