"""Replay a scored event stream through the micro-batch engine.

The serving benchmark instrument: generate (or accept) a stream of
(score, label) events, submit them as individual requests from one or
more client threads — the engine's dynamic batcher does the coalescing
— and report sustained events/s, latency percentiles, batch fill,
backpressure counts, and final-estimate parity against the batch
oracle. Used by ``tuplewise replay``, ``bench.py --streaming``, and the
northstar ``serve`` stage.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import numpy as np

from tuplewise_tpu.obs.report import (
    recovery_counters, service_report,
    stage_attribution as _stage_attr, stage_p99_ms as _stage_p99_ms,
)
from tuplewise_tpu.serving.engine import (
    BackpressureError, EngineClosedError, MicroBatchEngine,
    PoisonEventError, ServingConfig,
)


def make_stream(n_events: int, pos_frac: float = 0.5,
                separation: float = 1.0, seed: int = 0):
    """Shuffled Gaussian score stream: positives ~ N(separation, 1),
    negatives ~ N(0, 1), labels i.i.d. Bernoulli(pos_frac)."""
    rng = np.random.default_rng(seed)
    labels = rng.random(n_events) < pos_frac
    scores = rng.standard_normal(n_events) + separation * labels
    return scores, labels


def make_tenant_stream(n_events: int, n_tenants: int, skew: float = 1.0,
                       pos_frac: float = 0.5, separation: float = 1.0,
                       seed: int = 0):
    """Multi-tenant synthetic stream [ISSUE 8 satellite]: the Gaussian
    score stream plus a per-event tenant assignment drawn from a Zipf
    law — tenant rank k gets probability ∝ ``1/k**skew`` (``skew=0`` =
    uniform), the classic heavy-tailed production shape where a few hot
    tenants dominate and a long tail stays nearly idle. Returns
    ``(scores, labels, tenant_ids)`` with string tenant ids
    ``"t0".."t{n-1}"`` in rank (hotness) order."""
    if n_tenants < 1:
        raise ValueError(f"n_tenants must be >= 1: {n_tenants}")
    if skew < 0:
        raise ValueError(f"skew must be >= 0: {skew}")
    rng = np.random.default_rng(seed)
    labels = rng.random(n_events) < pos_frac
    scores = rng.standard_normal(n_events) + separation * labels
    if n_tenants == 1:
        ks = np.zeros(n_events, dtype=np.int64)
    else:
        p = np.arange(1, n_tenants + 1, dtype=np.float64) ** (-skew)
        p /= p.sum()
        ks = rng.choice(n_tenants, size=n_events, p=p)
    tenants = np.asarray([f"t{k}" for k in ks])
    return scores, labels, tenants


def replay(scores, labels, config: Optional[ServingConfig] = None,
           score_every: int = 0, query_every: int = 0,
           chunk: int = 1, warmup: bool = False,
           max_inflight: Optional[int] = None, chaos=None,
           tracer=None, trace_out: Optional[str] = None,
           metrics_out: Optional[str] = None,
           metrics_every_s: float = 1.0,
           profile_dir: Optional[str] = None,
           flight_out: Optional[str] = None,
           slo_spec=None, controller_spec=None,
           run_id: Optional[str] = None,
           prof=None, prof_out: Optional[str] = None,
           **overrides) -> dict:
    """Drive the engine with one request per event (or per ``chunk``
    events) and return the measurement record.

    ``score_every`` / ``query_every``: interleave a score / query
    request every k events (0 = never) — the mixed-workload case the
    batcher's kind-run coalescing exists for.

    ``max_inflight``: bound the number of outstanding requests (the
    submitter waits for the oldest future past the bound). Unbounded
    submission saturates the queue, so latency percentiles measure
    BACKLOG, not per-event cost; a bounded closed loop is what exposes
    pause spikes (compaction) in p99 while keeping the engine busy.

    ``warmup=True`` replays the stream once through a throwaway engine
    first, so the timed run measures the steady state: the index's
    size-bucketed jitted shapes compile as the base runs grow through
    the bucket ladder, and a cold replay pays those one-time XLA
    compilations inside the timed window (a long-lived service never
    sees them again).

    ``chaos`` [ISSUE 3]: a ``testing.chaos.FaultInjector`` (or a spec
    accepted by ``FaultInjector.from_spec``) threaded through the
    engine's hook points; its ``poison`` schedule corrupts the stream
    at the scheduled event positions before submission (the engine's
    edge validation rejects them — that is the property under test).
    The record then carries a ``faults`` block with the recovery
    counters, and the oracle-parity guardrail is computed over the
    ADMITTED events only. Warmup runs stay chaos-free (an injector is
    single-shot state).

    Observability [ISSUE 6]: ``tracer`` (an ``obs.tracing.Tracer``) or
    ``trace_out`` (a path — a tracer is created; ``*.jsonl`` exports
    span JSONL, anything else Chrome trace JSON for perfetto) traces
    the full request path; ``metrics_out``/``metrics_every_s`` stream
    periodic registry snapshots via ``obs.MetricsFlusher``;
    ``profile_dir`` brackets the timed window in a ``jax.profiler``
    trace; ``flight_out`` dumps the engine's flight recorder after the
    run. The warmup pass stays untraced (it measures nothing).

    ``prof`` / ``prof_out`` [ISSUE 14]: the host-tax sampling
    profiler. ``prof`` is an ``obs.prof.SamplingProfiler`` instance
    (caller keeps it for extra exports) or truthy to create one; it
    brackets exactly the timed window (warmup stays unprofiled).
    ``prof_out`` writes folded stacks (``*.collapsed``/``*.txt``) or
    a speedscope JSON (anything else); the record carries
    ``prof_out`` / ``prof_samples`` / ``prof_overhead_fraction``.

    ``slo_spec`` [ISSUE 7]: anything ``obs.slo.SloSpec.from_spec``
    accepts. An ``SloMonitor`` rides the metrics flusher (an
    observer-only flusher is created when no ``metrics_out`` is given)
    and judges each snapshot live: breaches land as ``slo_breach``
    flight events and ``slo_*`` gauges, and the final verdicts as the
    record's ``slo`` block. ``run_id``: caller-chosen identity stamped
    into the record (bench/northstar stamp one per invocation so
    ``scripts/perf_gate.py`` can join history rows); the config digest
    is stamped unconditionally.
    """
    scores = np.asarray(scores, dtype=np.float64).ravel()
    labels = np.asarray(labels).ravel().astype(bool)
    n = len(scores)
    cfg = config or ServingConfig(**overrides)
    injector = None
    if chaos is not None:
        from tuplewise_tpu.testing.chaos import FaultInjector

        injector = FaultInjector.from_spec(chaos)
    if warmup:
        replay(scores, labels, config=cfg, score_every=score_every,
               query_every=query_every, chunk=chunk, warmup=False,
               max_inflight=max_inflight)
    if tracer is None and trace_out:
        from tuplewise_tpu.obs.tracing import Tracer

        tracer = Tracer()
    rejected = 0
    poison_rejected = 0
    admitted = np.ones(n, dtype=bool)
    futures = []
    flusher = None
    slo_monitor = None
    controller = None
    with MicroBatchEngine(cfg, chaos=injector, tracer=tracer) as eng:
        if slo_spec is not None:
            from tuplewise_tpu.obs.slo import SloMonitor

            slo_monitor = SloMonitor(
                slo_spec, registry=eng.metrics, flight=eng.flight,
                context=dataclasses.asdict(cfg))
        if controller_spec is not None:
            # control plane [ISSUE 11]: rides the SLO monitor's
            # actuator hook (the single-tenant engine gets the flush
            # knob; tenant/mesh knobs need the fleet)
            if slo_monitor is None:
                raise ValueError(
                    "controller_spec needs slo_spec: the controller "
                    "rides the SLO monitor's signals")
            from tuplewise_tpu.serving.control import FleetController

            controller = FleetController(
                eng, controller_spec).attach(slo_monitor)
        if metrics_out or slo_monitor is not None:
            from tuplewise_tpu.obs.metrics_export import MetricsFlusher

            every = metrics_every_s
            if slo_monitor is not None:
                # burn windows need several snapshots to fill: keep
                # the cadence comfortably under the shortest window
                short = slo_monitor.spec.shortest_window_s
                if short:
                    every = min(every, max(short / 4.0, 0.05))
            flusher = MetricsFlusher(
                eng.metrics, metrics_out or None, every_s=every,
                meta={"stage": "replay"}, config=cfg,
                observers=([slo_monitor.observe_row]
                           if slo_monitor is not None else ())).start()
        from tuplewise_tpu.utils.profiling import trace as _jax_trace

        profiler = None
        if prof is not None and prof is not False or prof_out:
            from tuplewise_tpu.obs.prof import SamplingProfiler

            profiler = (prof if isinstance(prof, SamplingProfiler)
                        else SamplingProfiler(metrics=eng.metrics))
            profiler.start()
        with _jax_trace(profile_dir):
            t0 = time.perf_counter()
            for i in range(0, n, chunk):
                j = min(i + chunk, n)
                sub = scores[i:j]
                if injector is not None:
                    sub, _ = injector.poison_batch(i, sub)
                try:
                    futures.append(eng.insert(sub, labels[i:j]))
                except PoisonEventError:
                    poison_rejected += j - i
                    admitted[i:j] = False
                except BackpressureError:
                    rejected += j - i
                    admitted[i:j] = False
                if max_inflight and len(futures) >= max_inflight:
                    try:
                        futures[len(futures) - max_inflight].result(
                            timeout=60.0)
                    except BackpressureError:
                        pass    # counted in the final wait below
                if score_every and (i // chunk) % score_every \
                        == score_every - 1:
                    try:
                        futures.append(eng.score(scores[i:j]))
                    except BackpressureError:
                        pass
                if query_every and (i // chunk) % query_every \
                        == query_every - 1:
                    try:
                        futures.append(eng.query())
                    except BackpressureError:
                        pass
            # wait for everything admitted (dropped futures raise)
            dropped = 0
            for f in futures:
                try:
                    f.result(timeout=60.0)
                except BackpressureError:
                    dropped += 1
            wall = time.perf_counter() - t0
        if profiler is not None:
            # stop INSIDE the engine scope: the profiled window is the
            # timed window, not the drain/close tail
            profiler.stop()
        if eng.index is not None and cfg.bg_compact:
            # settle in-flight background builds OUTSIDE the timed
            # window so compaction/pause fields are deterministic
            eng.index.wait_idle()
        if flusher is not None:
            flusher.stop()
        stats = eng.stats()
    # after close: the dump carries engine_closed + final-snapshot
    # lifecycle events too
    flight_counts = eng.flight.counts()
    if flight_out:
        eng.flight.dump_to(flight_out)

    lat = stats["metrics"]["request_latency_s"]
    ins = stats["metrics"].get("insert_latency_s", {})
    pause = stats["metrics"].get("compaction_pause_s", {})
    cbytes = stats["metrics"].get("compaction_bytes", {})
    major = stats["metrics"].get("major_merge_s", {})
    fill = stats["metrics"]["batch_fill"]
    applied = stats["metrics"]["events_total"]["value"]

    def _ms(snap, q):
        v = snap.get(q)
        return None if v is None else v * 1e3

    rec = {
        "n_events": n,
        "events_applied": int(applied),
        "events_rejected": int(rejected),
        "events_poison_rejected": int(poison_rejected),
        "requests_dropped": int(dropped),
        "wall_s": wall,
        "events_per_s": applied / wall if wall > 0 else None,
        "latency_p50_ms": _ms(lat, "p50"),
        "latency_p99_ms": _ms(lat, "p99"),
        # per-event insert latency: the compaction-pause story lives in
        # the gap between p50 and p99 of THIS histogram
        "insert_latency_p50_ms": _ms(ins, "p50"),
        "insert_latency_p95_ms": _ms(ins, "p95"),
        "insert_latency_p99_ms": _ms(ins, "p99"),
        "insert_latency_max_ms": _ms(ins, "max"),
        "compactions": pause.get("count", 0),
        "compaction_pause_p99_ms": _ms(pause, "p99"),
        "compaction_pause_max_ms": _ms(pause, "max"),
        # transfer accounting [ISSUE 5]: the host->device byte budget
        # of the index's compaction tiers — the serving-side analogue
        # of the paper's shuffle-bytes axis
        "bytes_h2d": stats["metrics"].get(
            "bytes_h2d", {}).get("value", 0),
        "bytes_h2d_saved": stats["metrics"].get(
            "bytes_h2d_saved", {}).get("value", 0),
        "bytes_per_compaction": cbytes.get("mean"),
        "major_merges": stats["metrics"].get(
            "major_merges_total", {}).get("value", 0),
        "major_merge_fallbacks": stats["metrics"].get(
            "major_merge_fallbacks", {}).get("value", 0),
        "major_merge_p99_ms": _ms(major, "p99"),
        "batches": stats["metrics"]["batches_total"]["value"],
        "mean_batch_fill": fill["mean"],
        # per-stage insert-latency attribution [ISSUE 6]: p99 per
        # stage, plus the coverage check (stage sums vs measured sums
        # — 1.0 up to float rounding by construction)
        "insert_stage_p99_ms": _stage_p99_ms(stats["metrics"]),
        "stage_attribution": _stage_attr(stats["metrics"]),
        "flight_events": flight_counts,
        "auc_exact": stats.get("auc_exact"),
        "estimate_incomplete": stats["estimate_incomplete"],
        "incomplete_pairs": stats["metrics"]["incomplete_pairs_total"][
            "value"],
        "index": stats.get("index"),
        "config": {
            "kernel": cfg.kernel, "budget": cfg.budget,
            "reservoir": cfg.reservoir, "design": cfg.design,
            "window": cfg.window, "max_batch": cfg.max_batch,
            "flush_timeout_s": cfg.flush_timeout_s,
            "queue_size": cfg.queue_size, "policy": cfg.policy,
            "engine": cfg.engine, "chunk": chunk,
            "mesh_shards": cfg.mesh_shards, "bg_compact": cfg.bg_compact,
            "delta_fraction": cfg.delta_fraction,
            "max_delta_runs": cfg.max_delta_runs,
        },
    }
    # perf-history identity [ISSUE 7 satellite]: the digest joins rows
    # of the same configuration across runs; run_id names the run
    from tuplewise_tpu.obs.metrics_export import config_digest

    rec["config_digest"] = config_digest(cfg)
    if run_id is not None:
        rec["run_id"] = run_id
    # the shared report [ISSUE 6 satellite]: ONE builder feeds both
    # this record and `tuplewise serve`'s exit summary, so the
    # recovery/chaos counters can never drift between them again
    rec["report"] = service_report(stats["metrics"], slo=slo_monitor)
    # host-tax ledger [ISSUE 14]: the headline split at top level (the
    # full block also rides rec["report"]["host_tax"])
    rec["host_tax"] = rec["report"]["host_tax"]
    if profiler is not None:
        from tuplewise_tpu.obs.prof import export_profile

        written = export_profile(profiler, prof_out)
        if written:
            rec["prof_out"] = written
        rec["prof_samples"] = profiler.samples
        rec["prof_overhead_fraction"] = profiler.overhead_fraction()
        rec["prof_throttles"] = profiler.throttles
    if slo_monitor is not None:
        rec["slo"] = slo_monitor.report()
    if controller is not None:
        rec["controller"] = controller.state()
    if trace_out and tracer is not None:
        if trace_out.endswith(".jsonl"):
            tracer.export_jsonl(trace_out)
        else:
            tracer.export_chrome(trace_out)
        rec["trace_out"] = trace_out
        rec["trace_spans"] = len(tracer)
    if metrics_out:
        rec["metrics_out"] = metrics_out
    if injector is not None:
        # the recovery counters an operator greps for after a chaos
        # run — the same unified block `tuplewise serve`'s exit summary
        # and the CI chaos smoke assert on [ISSUE 6 satellite]
        rec["faults"] = dict(recovery_counters(stats["metrics"]),
                             chaos=injector.snapshot())
        rec["n_admitted"] = int(admitted.sum())
        rec["shed_events"] = np.nonzero(~admitted)[0].tolist()

    # oracle parity of the final exact estimate (windowed: oracle over
    # the retained suffix; chaos: over the ADMITTED events — the index
    # never saw the shed ones) — cheap at replay scale, priceless as a
    # guardrail on every benchmark run
    if (cfg.kernel == "auc" and rejected == 0 and dropped == 0
            and rec["auc_exact"] is not None):
        adm_s, adm_l = scores[admitted], labels[admitted]
        w = cfg.window
        tail_s = adm_s if w is None else adm_s[-w:]
        tail_l = adm_l if w is None else adm_l[-w:]
        from tuplewise_tpu.models.metrics import auc_score

        rec["auc_oracle"] = auc_score(
            np.asarray(tail_s[tail_l], dtype=np.float32 if cfg.engine ==
                       "jax" else np.float64),
            np.asarray(tail_s[~tail_l], dtype=np.float32 if cfg.engine ==
                       "jax" else np.float64))
        rec["auc_abs_err"] = abs(rec["auc_exact"] - rec["auc_oracle"])
    return rec


def replay_fleet(scores, labels, tenants,
                 config: Optional[ServingConfig] = None,
                 tenancy=None, chunk: int = 1,
                 max_inflight: Optional[int] = None, chaos=None,
                 slo_spec=None, controller_spec=None,
                 metrics_out: Optional[str] = None,
                 metrics_every_s: float = 1.0,
                 flight_out: Optional[str] = None,
                 run_id: Optional[str] = None, warmup: bool = False,
                 oracle_check: bool = True, **overrides) -> dict:
    """Replay a tenant-assigned stream through a
    :class:`~tuplewise_tpu.serving.tenancy.MultiTenantEngine` and
    return the fleet measurement record [ISSUE 8].

    ``warmup=True`` replays once through a throwaway engine first so
    the timed run measures the steady state — the tenant-axis count
    kernels compile per (T_bucket, cap, q_bucket) ladder shape, and a
    long-lived fleet never sees those compiles again (same contract
    as :func:`replay`).

    The fleet twin of :func:`replay`: one insert request per ``chunk``
    consecutive events (each tagged with its event's tenant — chunks
    split at tenant boundaries so every request is single-tenant),
    bounded in-flight submission, admission-control counters
    (``TenantRejectedError`` shed events are recorded per tenant), a
    per-tenant insert-latency breakdown, and a per-tenant
    oracle-parity guardrail: every tenant's final exact AUC is
    compared against the batch oracle on exactly that tenant's
    admitted (windowed) events — the fleet MUST look like T
    independent single-tenant services, statistic-wise.

    ``slo_spec`` rides a metrics-flusher observer exactly as in
    :func:`replay`; label-wildcard objectives
    (``insert_latency_s{tenant=*}``) give the record's ``slo`` block a
    per-tenant breakdown.
    """
    from tuplewise_tpu.serving.tenancy import (
        MultiTenantEngine, TenancyConfig, TenantRejectedError,
        TenantThrottledError,
    )

    scores = np.asarray(scores, dtype=np.float64).ravel()
    labels = np.asarray(labels).ravel().astype(bool)
    tenants = np.asarray(tenants).ravel()
    n = len(scores)
    if len(tenants) != n:
        raise ValueError(
            f"tenants/scores length mismatch: {len(tenants)} vs {n}")
    cfg = config or ServingConfig(**overrides)
    ten_cfg = tenancy if tenancy is not None else TenancyConfig()
    injector = None
    if chaos is not None:
        from tuplewise_tpu.testing.chaos import FaultInjector

        injector = FaultInjector.from_spec(chaos)
    if warmup:
        replay_fleet(scores, labels, tenants, config=cfg,
                     tenancy=ten_cfg, chunk=chunk,
                     max_inflight=max_inflight, oracle_check=False)
    admitted = np.ones(n, dtype=bool)
    rejected = poison_rejected = tenant_rejected = 0
    tenant_throttled = 0
    futures = []
    flusher = None
    slo_monitor = None
    controller = None
    with MultiTenantEngine(cfg, ten_cfg, chaos=injector) as eng:
        if slo_spec is not None:
            from tuplewise_tpu.obs.slo import SloMonitor

            slo_monitor = SloMonitor(
                slo_spec, registry=eng.metrics, flight=eng.flight,
                context=dataclasses.asdict(cfg))
        if controller_spec is not None:
            # SLO-driven control plane [ISSUE 11]
            if slo_monitor is None:
                raise ValueError(
                    "controller_spec needs slo_spec: the controller "
                    "rides the SLO monitor's signals")
            from tuplewise_tpu.serving.control import FleetController

            controller = FleetController(
                eng, controller_spec).attach(slo_monitor)
        if metrics_out or slo_monitor is not None:
            from tuplewise_tpu.obs.metrics_export import MetricsFlusher

            every = metrics_every_s
            if slo_monitor is not None:
                short = slo_monitor.spec.shortest_window_s
                if short:
                    every = min(every, max(short / 4.0, 0.05))
            flusher = MetricsFlusher(
                eng.metrics, metrics_out or None, every_s=every,
                meta={"stage": "replay_fleet"}, config=cfg,
                observers=([slo_monitor.observe_row]
                           if slo_monitor is not None else ())).start()
        t0 = time.perf_counter()
        i = 0
        while i < n:
            # a request is single-tenant: cut the chunk at the next
            # tenant boundary (the engine coalesces ACROSS tenants)
            j = min(i + chunk, n)
            tid = tenants[i]
            while j > i + 1 and not np.all(tenants[i:j] == tid):
                j -= 1
            sub = scores[i:j]
            if injector is not None:
                sub, _ = injector.poison_batch(i, sub)
            try:
                futures.append(eng.insert(tid, sub, labels[i:j]))
            except PoisonEventError:
                poison_rejected += j - i
                admitted[i:j] = False
            except TenantThrottledError:
                # control-plane shed [ISSUE 11]: typed, retry-after-
                # hinted; the replay drops rather than retries, so the
                # oracle check runs over the admitted events only
                tenant_throttled += j - i
                admitted[i:j] = False
            except TenantRejectedError:
                tenant_rejected += j - i
                admitted[i:j] = False
            except BackpressureError:
                rejected += j - i
                admitted[i:j] = False
            if max_inflight and len(futures) >= max_inflight:
                try:
                    futures[len(futures) - max_inflight].result(
                        timeout=60.0)
                except (BackpressureError, EngineClosedError):
                    pass
            i = j
        dropped = 0
        for f in futures:
            try:
                f.result(timeout=60.0)
            except BackpressureError:
                dropped += 1
        wall = time.perf_counter() - t0
        if cfg.bg_compact:
            # settle in-flight background tenant builds OUTSIDE the
            # timed window so byte/pause accounting is deterministic
            eng.fleet.wait_idle()
        if flusher is not None:
            flusher.stop()
        stats = eng.stats()
        live = eng.fleet.tenants()
        tenant_stats = {t: eng.tenant_stats(t) for t in live}
    flight_counts = eng.flight.counts()
    if flight_out:
        eng.flight.dump_to(flight_out)

    m = stats["metrics"]
    ins = m.get("insert_latency_s", {})
    applied = m["events_total"]["value"]

    def _ms(snap, q):
        v = snap.get(q)
        return None if v is None else v * 1e3

    # per-tenant insert p99 from the labeled histograms [ISSUE 8]
    from tuplewise_tpu.utils.profiling import parse_labeled_name

    tenant_p99 = {}
    for key, snap in m.items():
        base, lab = parse_labeled_name(key)
        if base == "insert_latency_s" and lab and "tenant" in lab:
            p = snap.get("p99")
            if p is not None:
                tenant_p99[lab["tenant"]] = p * 1e3
    p99s = sorted(tenant_p99.values())
    rec = {
        "n_events": n,
        "n_tenants": int(len(np.unique(tenants))),
        "tenants_live": stats["tenants_live"],
        "events_applied": int(applied),
        "events_rejected": int(rejected),
        "events_tenant_rejected": int(tenant_rejected),
        "events_tenant_throttled": int(tenant_throttled),
        "events_poison_rejected": int(poison_rejected),
        "requests_dropped": int(dropped),
        "wall_s": wall,
        "events_per_s": applied / wall if wall > 0 else None,
        "insert_latency_p50_ms": _ms(ins, "p50"),
        "insert_latency_p95_ms": _ms(ins, "p95"),
        "insert_latency_p99_ms": _ms(ins, "p99"),
        "tenant_insert_p99_ms": (tenant_p99 if len(tenant_p99) <= 64
                                 else None),
        "tenant_insert_p99_max_ms": (p99s[-1] if p99s else None),
        "tenant_insert_p99_median_ms": (
            p99s[len(p99s) // 2] if p99s else None),
        "admission": {
            "tenant_rejected_total": m.get(
                "tenant_rejected_total", {}).get("value", 0),
            "tenant_throttled_total": m.get(
                "tenant_throttled_total", {}).get("value", 0),
            "rejected_total": m.get("rejected_total", {}).get("value", 0),
            "dropped_total": m.get("dropped_total", {}).get("value", 0),
            "tenants_created_total": m.get(
                "tenants_created_total", {}).get("value", 0),
            "tenants_evicted_total": m.get(
                "tenants_evicted_total", {}).get("value", 0),
        },
        "batches": m["batches_total"]["value"],
        "fleet_count_calls": m.get(
            "fleet_count_calls_total", {}).get("value", 0),
        # incremental-placement byte budget [ISSUE 9]: the dirty-row
        # saving the fleet_incremental bench cell prices
        "bytes_h2d": m.get("bytes_h2d", {}).get("value", 0),
        "bytes_h2d_saved": m.get("bytes_h2d_saved", {}).get("value", 0),
        "pack_replaces": m.get(
            "pack_replaces_total", {}).get("value", 0),
        "pack_full_replaces": m.get(
            "pack_full_replaces_total", {}).get("value", 0),
        "whale_promotions": m.get(
            "fleet_whale_promotions", {}).get("value", 0),
        "whale_demotions": m.get(
            "fleet_whale_demotions", {}).get("value", 0),
        "flight_events": flight_counts,
        "fleet": stats["fleet"],
        "config": {
            "budget": cfg.budget, "window": cfg.window,
            "max_batch": cfg.max_batch, "queue_size": cfg.queue_size,
            "policy": cfg.policy, "mesh_shards": cfg.mesh_shards,
            "chunk": chunk, "max_tenants": ten_cfg.max_tenants,
            "tenant_quota": ten_cfg.tenant_quota,
            "weight": ten_cfg.weight,
            "bg_compact": cfg.bg_compact,
            "whale_threshold": ten_cfg.whale_threshold,
            "tenant_metric_cap": ten_cfg.tenant_metric_cap,
        },
    }
    from tuplewise_tpu.obs.metrics_export import config_digest

    rec["config_digest"] = config_digest(cfg)
    if run_id is not None:
        rec["run_id"] = run_id
    rec["report"] = service_report(m, chaos=injector, slo=slo_monitor)
    rec["host_tax"] = rec["report"]["host_tax"]   # [ISSUE 14]
    if slo_monitor is not None:
        rec["slo"] = slo_monitor.report()
    if controller is not None:
        rec["controller"] = controller.state()
    if metrics_out:
        rec["metrics_out"] = metrics_out
    if injector is not None:
        rec["faults"] = dict(recovery_counters(m),
                             chaos=injector.snapshot())

    # per-tenant oracle parity [ISSUE 8 acceptance]: each tenant's
    # exact AUC vs the batch oracle over ITS admitted (windowed)
    # events — the fleet must be indistinguishable from T independent
    # single-tenant engines. Control-plane throttles are allowed:
    # admission-side sheds are excluded from the oracle by the
    # admitted mask, exactly like poison [ISSUE 11]
    if oracle_check and rejected == 0 and dropped == 0 \
            and tenant_rejected == 0:
        from tuplewise_tpu.models.metrics import auc_score

        worst = 0.0
        for tid in np.unique(tenants):
            mask = admitted & (tenants == tid)
            ts_, tl_ = scores[mask], labels[mask]
            if cfg.window is not None:
                ts_, tl_ = ts_[-cfg.window:], tl_[-cfg.window:]
            got = (tenant_stats.get(str(tid)) or {}).get("auc_exact")
            if got is None or not tl_.any() or tl_.all():
                continue
            want = auc_score(
                np.asarray(ts_[tl_], dtype=np.float32),
                np.asarray(ts_[~tl_], dtype=np.float32))
            worst = max(worst, abs(got - want))
        rec["tenant_auc_max_abs_err"] = worst
    return rec
