"""Multi-tenant serving fleet [ISSUE 8]: thousands of per-tenant
statistics multiplexed over ONE mesh.

The paper prices distributed tuplewise estimation for ONE statistic;
production traffic at the north-star scale is millions of users ≈
thousands of independent statistics (per-user/per-cohort AUC,
per-region windows). Spinning up one ``MicroBatchEngine`` +
``ExactAucIndex`` per tenant would mean one batcher thread, one device
placement, and one compiled-kernel family PER TENANT — none of which
scales past a few dozen. This module multiplexes the fleet:

* :class:`TenantFleetIndex` — the tenant-axis generalization of the
  sharded exact-AUC index. Every tenant's sorted base runs (both
  classes) live in ONE shared padded ``[S, T_bucket, cap]`` device
  buffer per class side (``parallel.sharded_counts.place_tenant_pack``)
  and ONE jitted vmapped searchsorted + psum
  (``tenant_count_fn``) serves a whole coalesced batch of tenants'
  queries — insert counts, eviction counts, and score ranks for every
  tenant the micro-batch touched, in one dispatch. Compile shapes
  follow the ``(T_bucket, cap, q_bucket)`` bucket ladder (powers of
  two per axis), never the live tenant count, so a fleet of 3 or 3000
  tenants reuses the same handful of compiled kernels. Host-side each
  tenant keeps the LSM discipline of the single-tenant index — small
  insert buffer, tombstones, arrival log, exact integer ``wins2`` —
  so every tenant's AUC is bit-identical to a dedicated
  ``ExactAucIndex`` fed the same events (the parity the tests pin at
  S=1/2/4 and under chaos heal).

* :class:`MultiTenantEngine` — the fleet request path: per-tenant FIFO
  queues with admission control (per-tenant quotas + a fleet-wide
  tenant cap, typed :class:`TenantRejectedError`) and a
  starvation-free weighted-fair (deficit-round-robin) drain order, so
  one hot tenant cannot monopolize the batcher. Tenant lifecycle:
  create-on-first-request, explicit drop, idle eviction
  (``idle_evict_s``). Per-tenant sliding windows, per-tenant
  incomplete-U streams (seeded per tenant, deterministically), and
  per-tenant observability via metric labels
  (``insert_latency_s{tenant=}``, ``tenant_rejected_total{tenant=}``)
  that the SLO layer's label-wildcard objectives
  (``insert_latency_s{tenant=*}``) judge per tenant.

* :class:`FleetRecoveryManager` — crash safety for the whole fleet
  through the existing WAL/snapshot machinery: WAL records carry a
  tenant tag (logical namespacing — one physical log, thousands of
  tenants cannot each own a file descriptor), snapshots capture every
  tenant's containers + wins2 + reservoir/RNG state, and recovery is
  per-tenant bit-identical across SIGKILL (same contract the
  single-tenant engine has carried since ISSUE 3).

Failure model: the host is authoritative for every tenant's runs — the
packed device buffers are a pure cache. Device loss heals through the
shared ``parallel.self_heal.MeshHealer`` (probe → re-place the packs →
bounded retry), and a crashed compaction aborts cleanly (containers
untouched, wins2 never touched by compaction) and retries at the next
trigger — so per-tenant results stay bit-identical to T independent
single-tenant engines under any chaos schedule the single-tenant
index survives.

**Incremental hot path** [ISSUE 9]: fleet maintenance is O(changed),
not O(fleet). (1) *Dirty-row placement* — a compaction, drop, or slot
reuse marks only the touched slots dirty and ``place_tenant_pack``
ships only those rows into the resident device shards (the
``place_base`` prev-trick generalized to the tenant axis); a re-place
with 1 dirty tenant of 256 ships ~1/256 of the pack.
(2) *Whale promotion* — the Zipf head that dominates real traffic
outgrows the pack trade (an O(n) host splice per compaction): a tenant
crossing ``whale_threshold`` live events transparently promotes to its
own delta-tiered :class:`~tuplewise_tpu.serving.index.ExactAucIndex`
(O(buffer) minors, tombstone evictions, on-mesh major merge) behind
the same API, and demotes back on shrink. Promotion is statistically
invisible — wins2 is a pure integer function of the event sequence, so
per-tenant results stay bit-identical through any
promote/demote/crash/recover interleaving (promotion state rides the
snapshot manifest; WAL replay re-derives it deterministically).
(3) *Off-batcher pack builds* — with ``bg_compact`` the per-tenant
splice moves to a side compactor thread (the PR 2 double-buffer +
atomic-swap protocol, tenant-granular): mutators only append to the
unclaimed buffer suffix while a build runs, and the request path's
worst pause is the swap. Small tenants still take the shared-pack
route — the trade PR 8 documented — but the whale no longer drags the
fleet, and metric cardinality is bounded (``tenant_metric_cap``
collapses beyond-cap tenants into one ``{tenant=__other__}`` series).
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import os
import threading
import time
from concurrent.futures import Future
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from tuplewise_tpu.obs.flight import FlightRecorder
from tuplewise_tpu.obs.ledger import WaveLedger
from tuplewise_tpu.obs.tracing import maybe_span
from tuplewise_tpu.serving.engine import (
    BackpressureError, DeadlineExceededError, EngineClosedError,
    PoisonEventError, ServingConfig,
)
from tuplewise_tpu.serving.index import _remove_sorted, _splice_merge
from tuplewise_tpu.serving.recovery import RecoveryManager
from tuplewise_tpu.serving.streaming import StreamingIncompleteU
from tuplewise_tpu.utils.checkpoint import check_config
from tuplewise_tpu.utils.profiling import MetricsRegistry


class TenantRejectedError(RuntimeError):
    """Admission control shed this request at the edge [ISSUE 8]:
    per-tenant queue quota exceeded, or the fleet is at its tenant
    cap. Carries the tenant id — multi-tenant shedding must be
    attributable."""

    def __init__(self, msg: str, tenant: Optional[str] = None):
        super().__init__(msg)
        self.tenant = tenant


class TenantThrottledError(RuntimeError):
    """The control plane shed this request BEFORE a breach
    [ISSUE 11]: the tenant is temporarily throttled (typically because
    its traffic is driving the fleet toward an SLO breach), and the
    caller should retry after ``retry_after_s`` seconds. Distinct from
    :class:`TenantRejectedError` (a static quota/cap verdict): a
    throttle is a *temporary, reversible* actuation with an explicit
    retry hint — the difference between "come back in 500 ms" and
    "you are over quota"."""

    def __init__(self, msg: str, tenant: Optional[str] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.tenant = tenant
        self.retry_after_s = retry_after_s


@dataclasses.dataclass(frozen=True)
class TenancyConfig:
    """Fleet-level knobs layered over a :class:`ServingConfig`.

    Args:
      max_tenants: hard cap on live tenants; creating past it raises
        :class:`TenantRejectedError` (admission control, not a crash).
      tenant_quota: max queued (unapplied) requests per tenant; the
        per-tenant arm of admission control — one flooding tenant
        exhausts its own quota, not the shared queue.
      weight: requests a tenant may contribute per fair-scheduling
        round (deficit round-robin quantum). Every pending tenant is
        served up to ``weight`` requests before any tenant is served
        again — starvation-free by construction.
      idle_evict_s: drop tenants idle longer than this (None = never).
        Eviction frees the tenant's slot; its pack row goes stale
        harmlessly (rows are per-tenant independent) and is rebuilt
        when the slot is reused.
      min_tenant_bucket: floor of the T_bucket compile-shape ladder.
      tenant_metrics: export per-tenant labeled metrics
        (``insert_latency_s{tenant=}`` etc.). On by default.
      tenant_metric_cap: bound per-tenant metric cardinality
        [ISSUE 9 satellite]: at most this many tenants get their own
        labeled series; later tenants collapse into ONE
        ``{tenant=__other__}`` series (first-come keeps its label —
        stable, no re-labeling churn), so a 100k-tenant fleet cannot
        blow up the registry, the MetricsFlusher rows, or the SLO
        wildcard fan-out. None (default) = unbounded.
      whale_threshold: promote a tenant to its own delta-tiered
        ``ExactAucIndex`` once its live event count reaches this
        [ISSUE 9 tentpole]; None (default) = never promote.
      whale_demote_fraction: demote a promoted tenant once its live
        event count shrinks below ``whale_threshold * fraction``
        (hysteresis so a tenant oscillating at the threshold does not
        thrash promote/demote).
    """

    max_tenants: int = 1024
    tenant_quota: int = 64
    weight: int = 8
    idle_evict_s: Optional[float] = None
    min_tenant_bucket: int = 8
    tenant_metrics: bool = True
    tenant_metric_cap: Optional[int] = None
    whale_threshold: Optional[int] = None
    whale_demote_fraction: float = 0.5

    def __post_init__(self):
        if self.max_tenants < 1:
            raise ValueError(f"max_tenants must be >= 1: {self.max_tenants}")
        if self.tenant_quota < 1:
            raise ValueError(
                f"tenant_quota must be >= 1: {self.tenant_quota}")
        if self.weight < 1:
            raise ValueError(f"weight must be >= 1: {self.weight}")
        if self.idle_evict_s is not None and self.idle_evict_s <= 0:
            raise ValueError(
                f"idle_evict_s must be > 0: {self.idle_evict_s}")
        if self.min_tenant_bucket < 1:
            raise ValueError(
                f"min_tenant_bucket must be >= 1: {self.min_tenant_bucket}")
        if self.tenant_metric_cap is not None \
                and self.tenant_metric_cap < 1:
            raise ValueError(
                f"tenant_metric_cap must be >= 1: "
                f"{self.tenant_metric_cap}")
        if self.whale_threshold is not None and self.whale_threshold < 2:
            raise ValueError(
                f"whale_threshold must be >= 2: {self.whale_threshold}")
        if not 0.0 <= self.whale_demote_fraction < 1.0:
            raise ValueError(
                f"whale_demote_fraction must be in [0, 1): "
                f"{self.whale_demote_fraction}")


def tenant_seed(base_seed: int, tid: str) -> int:
    """Deterministic per-tenant RNG seed (stable across processes —
    ``hash()`` is salted per interpreter, so it cannot be used here)."""
    h = hashlib.sha256(f"{base_seed}:{tid}".encode("utf-8")).digest()
    return int.from_bytes(h[:8], "big")


class _TenantStat:
    """One tenant's host-authoritative exact-AUC state: the
    single-tenant index's LSM containers, minus the device fields (the
    fleet packs own those) and the delta tier.

    ``idx`` is the whale escape hatch [ISSUE 9]: a promoted tenant's
    state lives in its own :class:`ExactAucIndex` (containers here stay
    empty, the pack row goes +inf) and every read/write routes there.

    ``building`` + the per-side ``snap_*`` prefix lengths implement the
    off-batcher compaction claim [ISSUE 9]: while a background build
    owns a side's snapshotted prefixes, mutators only append to the
    suffix and evictions only remove from it (else tombstone) — the
    same double-buffer discipline as the single-tenant index."""

    __slots__ = ("tid", "slot", "pos_base", "neg_base", "pos_buf",
                 "neg_buf", "pos_tomb", "neg_tomb", "log", "wins2",
                 "n_evicted", "n_compactions", "last_active", "idx",
                 "building", "snap_pos_buf", "snap_neg_buf",
                 "snap_pos_tomb", "snap_neg_tomb")

    def __init__(self, tid: str, slot: int, dtype):
        self.tid = tid
        self.slot = slot
        self.pos_base = np.empty(0, dtype=dtype)
        self.neg_base = np.empty(0, dtype=dtype)
        self.pos_buf: List[float] = []
        self.neg_buf: List[float] = []
        self.pos_tomb: List[float] = []
        self.neg_tomb: List[float] = []
        self.log: Deque[Tuple[float, bool]] = collections.deque()
        self.wins2 = 0              # exact: Python int never overflows
        self.n_evicted = 0
        self.n_compactions = 0
        self.last_active = time.monotonic()
        self.idx = None             # promoted whale index [ISSUE 9]
        self.building = False
        self.snap_pos_buf = 0
        self.snap_neg_buf = 0
        self.snap_pos_tomb = 0
        self.snap_neg_tomb = 0

    def side(self, pos: bool):
        if pos:
            return self.pos_base, self.pos_buf, self.pos_tomb
        return self.neg_base, self.neg_buf, self.neg_tomb

    def snap(self, pos: bool) -> Tuple[int, int]:
        """(buf, tomb) prefix lengths claimed by an in-flight build."""
        if pos:
            return self.snap_pos_buf, self.snap_pos_tomb
        return self.snap_neg_buf, self.snap_neg_tomb

    def pending(self) -> Tuple[int, int]:
        """(buf, tomb) entries NOT already claimed by a build — what a
        new compaction would consume."""
        return (len(self.pos_buf) + len(self.neg_buf)
                - self.snap_pos_buf - self.snap_neg_buf,
                len(self.pos_tomb) + len(self.neg_tomb)
                - self.snap_pos_tomb - self.snap_neg_tomb)

    def size(self, pos: bool) -> int:
        base, buf, tomb = self.side(pos)
        return len(base) + len(buf) - len(tomb)

    def values(self, pos: bool) -> np.ndarray:
        """Current class multiset (oracle/debug path, O(n))."""
        base, buf, tomb = self.side(pos)
        out = np.sort(np.concatenate(
            [base, np.asarray(buf, dtype=base.dtype)]), kind="stable")
        return _remove_sorted(out, list(tomb))


class _Pack:
    """One class side's shared device buffer + its placement geometry.

    ``dirty_slots`` tracks WHICH tenant rows changed since the resident
    placement [ISSUE 9] — the next ``_ensure_packs`` ships only those
    rows when the geometry allows; ``dirty_all`` (mesh change, restore,
    T_bucket growth) forces the full ship. ``row_events`` records the
    run length placed per slot — the occupancy/stale-row gauges read
    it."""

    __slots__ = ("dev", "cap", "t_bucket", "dirty_all", "dirty_slots",
                 "row_events")

    def __init__(self):
        self.dev = None
        self.cap = 0
        self.t_bucket = 0
        self.dirty_all = True
        self.dirty_slots: set = set()
        self.row_events: List[int] = []

    @property
    def dirty(self) -> bool:
        return self.dirty_all or bool(self.dirty_slots)

    def mark_all(self) -> None:
        self.dirty_all = True
        self.dirty_slots.clear()

    def mark(self, slot: int) -> None:
        if not self.dirty_all:
            self.dirty_slots.add(slot)


class TenantFleetIndex:
    """Exact per-tenant AUC for a fleet, counted through shared packs.

    Args:
      window: per-tenant sliding window (arrivals); None = unbounded.
      compact_every: per-tenant buffer/tombstone size triggering that
        tenant's compaction (host splice + pack re-place).
      shards: None = single-device packs; an int S >= 1 shards every
        tenant's runs over an S-device mesh (the per-tenant contiguous
        slices of ``place_tenant_pack``); counts stay bit-identical at
        every S — additivity over partitions is per-tenant-row here.
      mesh: an existing 1-D mesh (overrides ``shards``).
      metrics / chaos / tracer / flight: the usual observability and
        fault-injection hooks; the count path fires ``sharded_count``,
        placements fire ``place_base``, compactions fire
        ``compactor_build`` — the same points the single-tenant stack
        uses, so one chaos spec drives both.
    """

    def __init__(self, window: Optional[int] = None,
                 compact_every: int = 512,
                 shards: Optional[int] = None, mesh=None,
                 metrics=None, chaos=None, shard_retries: int = 3,
                 retry_backoff_s: float = 0.02,
                 probe_timeout_s: float = 5.0,
                 min_tenant_bucket: int = 8,
                 bg_compact: bool = False,
                 whale_threshold: Optional[int] = None,
                 whale_demote_fraction: float = 0.5,
                 incremental_placement: bool = True,
                 count_kernel: bool = False,
                 tracer=None, flight=None):
        if window is not None and window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        if compact_every < 1:
            raise ValueError(f"compact_every must be >= 1: {compact_every}")
        if mesh is not None:
            shards = int(np.prod(mesh.devices.shape))
        if shards is not None and shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        if whale_threshold is not None and whale_threshold < 2:
            raise ValueError(
                f"whale_threshold must be >= 2: {whale_threshold}")
        self.window = window
        self.compact_every = compact_every
        self.shards = shards
        self.min_tenant_bucket = min_tenant_bucket
        self.bg_compact = bg_compact
        self.whale_threshold = whale_threshold
        self.whale_demote_fraction = whale_demote_fraction
        # demotion hysteresis floor; 0 = only explicit demote()
        self._demote_below = (
            int(whale_threshold * whale_demote_fraction)
            if whale_threshold is not None else 0)
        self.incremental_placement = incremental_placement
        self.dtype = np.float32
        # Pallas-fused fleet counts [ISSUE 10]: ONE tenant-axis kernel
        # invocation per device per coalesced micro-batch instead of
        # the vmapped searchsorted quartet; opt-in + env override via
        # the shared resolver, automatic XLA fallback inside the
        # dispatcher (tenant_pack_counts)
        self.count_kernel = bool(count_kernel)
        self._ck = False
        self._ck_interp = False
        if count_kernel or os.environ.get("TUPLEWISE_SERVING_PALLAS"):
            import jax

            from tuplewise_tpu.ops.pallas_modes import (
                resolve_serving_counts_mode,
            )

            self._ck, self._ck_interp = resolve_serving_counts_mode(
                jax.default_backend(), count_kernel)
        self.chaos = chaos
        self.shard_retries = shard_retries
        self.tracer = tracer
        self.flight = flight
        self._mesh = mesh
        if shards is not None and mesh is None:
            from tuplewise_tpu.parallel.mesh import make_mesh

            self._mesh = make_mesh(shards)
        self._slots: List[Optional[_TenantStat]] = []
        self._free: List[int] = []
        self._by_tid: Dict[str, _TenantStat] = {}
        self._pos_pack = _Pack()
        self._neg_pack = _Pack()
        self._lock = threading.RLock()
        # signals background-build completion (wait_idle drains on it)
        self._cv = threading.Condition(self._lock)
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        # ONE jitted batched count per coalesced multi-tenant batch —
        # this counter is the assertable witness [ISSUE 8 acceptance]
        self._c_count_calls = self.metrics.counter(
            "fleet_count_calls_total")
        self._c_count_tenants = self.metrics.counter(
            "fleet_count_tenant_queries_total")
        self._c_compactions = self.metrics.counter("compactions_total")
        self._c_compact_aborts = self.metrics.counter(
            "fleet_compact_aborts")
        self._h_pause = self.metrics.histogram("compaction_pause_s")
        self._g_tenants = self.metrics.gauge("fleet_tenants")
        self._g_mesh = self.metrics.gauge("mesh_width")
        self._g_mesh.set(shards if shards is not None else 0)
        self._c_heal_exhausted = self.metrics.counter(
            "heal_exhausted_total")
        self.metrics.counter("reshard_events")
        self.metrics.counter("shard_retries_total")
        self.metrics.histogram("recovery_time_s")
        # incremental-placement accounting [ISSUE 9]: every pack
        # (re)placement counts, full ships separately — the dirty-row
        # saving is (replaces - full) with bytes_h2d_saved > 0
        self._c_replaces = self.metrics.counter("pack_replaces_total")
        self._c_full_replaces = self.metrics.counter(
            "pack_full_replaces_total")
        self._g_occupancy = self.metrics.gauge("pack_occupancy")
        self._g_stale = self.metrics.gauge("pack_stale_rows")
        # whale promotion lifecycle [ISSUE 9]
        self._c_promotions = self.metrics.counter(
            "fleet_whale_promotions")
        self._c_demotions = self.metrics.counter("fleet_whale_demotions")
        self._c_promote_aborts = self.metrics.counter(
            "fleet_whale_promote_aborts")
        self._g_whales = self.metrics.gauge("fleet_whales")
        self._c_bg_restarts = self.metrics.counter(
            "bg_compactor_restarts")
        # fused-count observability [ISSUE 10]
        self.metrics.counter("count_kernel_calls_total")
        self.metrics.counter("count_kernel_fallbacks_total")
        # prewarm bookkeeping [ISSUE 10 satellite]: query buckets seen
        # so far × pack geometry — the off-batcher build path warms
        # the count fns for them so compiles stay off the request
        # thread (the single-index _warm_counts discipline)
        self._q_buckets: set = set()
        self._warmed: set = set()
        self.last_compactor_error = None
        self._healer = None
        if shards is not None:
            import jax

            from tuplewise_tpu.parallel.self_heal import Backoff, MeshHealer

            # pool = current mesh devices first (so a shrink+regrow
            # restores the same devices), spares after — what lets the
            # control plane GROW the mesh past its initial width
            # [ISSUE 11]; heal-shrink semantics are unchanged (shrink
            # rebuilds over the CURRENT mesh's survivors, never the
            # pool)
            mesh_devs = list(self._mesh.devices.flat)
            pool = mesh_devs + [d for d in jax.devices()
                                if d not in mesh_devs]
            self._healer = MeshHealer(
                self._mesh, pool=pool, chaos=chaos,
                probe_timeout_s=probe_timeout_s, metrics=self.metrics,
                backoff=Backoff(base_s=retry_backoff_s, cap_s=1.0),
                tracer=tracer, flight=flight)
        self._closed = False
        if bg_compact:
            import queue

            self._jobs: "queue.Queue[Optional[_TenantStat]]" = \
                queue.Queue()
            self._compactor = threading.Thread(
                target=self._compact_worker,
                name="tuplewise-fleet-compactor", daemon=True)
            self._compactor.start()

    # ------------------------------------------------------------------ #
    # tenant lifecycle                                                   #
    # ------------------------------------------------------------------ #
    @property
    def n_tenants(self) -> int:
        with self._lock:
            return len(self._by_tid)

    def tenants(self) -> List[str]:
        with self._lock:
            return list(self._by_tid)

    def has(self, tid: str) -> bool:
        with self._lock:
            return tid in self._by_tid

    def create(self, tid: str) -> _TenantStat:
        """Create (or return) a tenant. A reused slot marks the packs
        dirty — its row still holds the evicted tenant's values; a
        fresh slot inside the current T_bucket is already an all-+inf
        (empty) row, so no re-place is needed until data lands."""
        with self._lock:
            st = self._by_tid.get(tid)
            if st is not None:
                return st
            if self._free:
                slot = self._free.pop()
                self._pos_pack.mark(slot)
                self._neg_pack.mark(slot)
            else:
                slot = len(self._slots)
                self._slots.append(None)
            st = _TenantStat(tid, slot, self.dtype)
            self._slots[slot] = st
            self._by_tid[tid] = st
            self._g_tenants.set(len(self._by_tid))
            if self.flight is not None:
                self.flight.record("tenant_created", tenant=tid,
                                   slot=slot)
            return st

    def drop(self, tid: str) -> bool:
        """Remove a tenant; its slot is recycled. The slot is marked
        dirty in BOTH packs so the next placement reclaims its device
        row (ships one +inf row) — before ISSUE 9 the stale row stayed
        resident until the next full re-place, which the occupancy
        gauges (and a shard-balance verdict reading them) miscounted
        as live data."""
        with self._lock:
            st = self._by_tid.pop(tid, None)
            if st is None:
                return False
            if st.idx is not None:
                st.idx.close()
                st.idx = None
                self._g_whales.set(self._n_whales())
            self._slots[st.slot] = None
            self._free.append(st.slot)
            self._pos_pack.mark(st.slot)
            self._neg_pack.mark(st.slot)
            self._refresh_pack_gauges()
            self._g_tenants.set(len(self._by_tid))
            if self.flight is not None:
                self.flight.record("tenant_evicted", tenant=tid,
                                   slot=st.slot, events=len(st.log))
            return True

    def _n_whales(self) -> int:
        return sum(1 for st in self._by_tid.values()
                   if st.idx is not None)

    def _refresh_pack_gauges(self) -> None:
        """``pack_occupancy`` = device rows holding a LIVE tenant's
        data; ``pack_stale_rows`` = rows still holding data whose slot
        is no longer live (dropped/promoted, not yet reclaimed by a
        re-place) — the truth a shard-balance verdict needs (caller
        holds the lock) [ISSUE 9 satellite]."""
        occ = stale = 0
        for pack in (self._pos_pack, self._neg_pack):
            for slot, n in enumerate(pack.row_events):
                if not n:
                    continue
                st = (self._slots[slot]
                      if slot < len(self._slots) else None)
                if st is not None and st.idx is None:
                    occ += 1
                else:
                    stale += 1
        self._g_occupancy.set(occ)
        self._g_stale.set(stale)

    def idle_tenants(self, idle_s: float) -> List[str]:
        now = time.monotonic()
        with self._lock:
            return [tid for tid, st in self._by_tid.items()
                    if now - st.last_active > idle_s]

    # ------------------------------------------------------------------ #
    # the ONE-call fleet count                                           #
    # ------------------------------------------------------------------ #
    def _t_bucket(self) -> int:
        from tuplewise_tpu.parallel.sharded_counts import tenant_bucket

        return tenant_bucket(len(self._slots),
                             min_bucket=self.min_tenant_bucket)

    def _ensure_packs(self) -> None:
        """(Re)place dirty packs from the host-authoritative runs
        (caller holds the lock; runs inside the heal retry loop so a
        placement onto a dead device heals like a count would).

        Dirty-ROW path [ISSUE 9]: when only some slots changed and the
        geometry is stable, ``place_tenant_pack`` ships just those
        rows into the resident shards; a T_bucket change (or disabled
        ``incremental_placement``) forces the full ship and counts it
        in ``pack_full_replaces_total``."""
        from tuplewise_tpu.parallel.sharded_counts import place_tenant_pack

        tb = self._t_bucket()
        for pack, pos in ((self._pos_pack, True), (self._neg_pack, False)):
            if not pack.dirty and pack.dev is not None \
                    and pack.t_bucket == tb:
                continue
            runs = [(s.pos_base if pos else s.neg_base)
                    if s is not None and s.idx is None
                    else np.empty(0, dtype=self.dtype)
                    for s in self._slots]
            dirty = None
            if (self.incremental_placement and not pack.dirty_all
                    and pack.dev is not None and pack.t_bucket == tb):
                dirty = sorted(pack.dirty_slots)
            with maybe_span(self.tracer, "fleet.place_pack",
                            side="pos" if pos else "neg",
                            tenants=len(self._by_tid),
                            dirty=(len(dirty) if dirty is not None
                                   else -1)):
                pack.dev, pack.cap, shipped = place_tenant_pack(
                    self._mesh, runs, tb, self.dtype,
                    prev=(pack.dev, pack.cap, pack.t_bucket),
                    dirty=dirty, metrics=self.metrics,
                    chaos=self.chaos)
            full_bytes = ((self.shards or 1) * tb * pack.cap
                          * np.dtype(self.dtype).itemsize)
            self._c_replaces.inc()
            if shipped >= full_bytes:
                self._c_full_replaces.inc()
            pack.t_bucket = tb
            pack.dirty_all = False
            pack.dirty_slots.clear()
            pack.row_events = [len(r) for r in runs]
        self._refresh_pack_gauges()

    def _on_heal(self, healer) -> None:
        """Adopt the (possibly narrower) healed mesh and rebuild the
        packs — a pure cache rebuild from the host runs."""
        self._mesh = healer.mesh
        self.shards = healer.n_workers
        self._g_mesh.set(self.shards)
        self._pos_pack.mark_all()
        self._neg_pack.mark_all()

    def resize_shards(self, shards: int) -> bool:
        """Control-plane mesh re-width [ISSUE 11]: rebuild the 1-D
        mesh at ``shards`` workers from the healer's device pool and
        re-place the packs at the next count — counts are additive
        over any partition, so per-tenant results are BIT-IDENTICAL at
        every width (the same invariant device-loss healing relies
        on). Returns True when the width changed; False for unsharded
        fleets, no-op widths, or widths the surviving pool cannot
        supply. Promoted whales keep their existing mesh reference
        (their devices are still alive — a resize is an actuation, not
        a failure); new whales adopt the resized mesh."""
        with self._lock:
            if self._healer is None:
                return False
            if not self._healer.resize(shards):
                return False
            self._on_heal(self._healer)
            return True

    def _fleet_base_counts(self, q_vs_neg: List[np.ndarray],
                           q_vs_pos: List[np.ndarray],
                           slots: List[int]):
        """Base-run counts for every tenant's queries in ONE jitted
        call. ``q_vs_neg[i]`` / ``q_vs_pos[i]`` are tenant
        ``slots[i]``'s query values against the neg / pos pack; returns
        per-input (less, leq) int64 arrays. Caller holds the lock."""
        from tuplewise_tpu.parallel.self_heal import HealExhaustedError
        from tuplewise_tpu.parallel.sharded_counts import (
            next_bucket, tenant_pack_counts,
        )

        longest = max((len(q) for q in q_vs_neg + q_vs_pos), default=0)
        if longest == 0:
            z = [np.zeros(0, dtype=np.int64) for _ in slots]
            return list(z), list(z), list(z), list(z)
        qb = next_bucket(longest)
        self._q_buckets.add(qb)
        tb = self._t_bucket()
        qn = np.zeros((tb, qb), dtype=self.dtype)
        qp = np.zeros((tb, qb), dtype=self.dtype)
        for i, slot in enumerate(slots):
            if len(q_vs_neg[i]):
                qn[slot, : len(q_vs_neg[i])] = q_vs_neg[i]
            if len(q_vs_pos[i]):
                qp[slot, : len(q_vs_pos[i])] = q_vs_pos[i]

        def attempt():
            self._ensure_packs()
            return tenant_pack_counts(
                self._mesh, self._pos_pack.dev, self._pos_pack.cap,
                self._neg_pack.dev, self._neg_pack.cap, tb, qn, qp,
                self.dtype, chaos=self.chaos,
                kernel=(self._ck_interp if self._ck else None),
                metrics=self.metrics)

        try:
            with maybe_span(self.tracer, "fleet.count",
                            tenants=len(slots)):
                if self._healer is not None:
                    out = self._healer.run(attempt,
                                           retries=self.shard_retries,
                                           on_heal=self._on_heal)
                else:
                    out = attempt()
        except HealExhaustedError as e:
            self._c_heal_exhausted.inc()
            if self.flight is not None:
                self.flight.record("heal_exhausted", error=repr(e))
                self.flight.auto_dump()
            raise
        self._c_count_calls.inc()
        self._c_count_tenants.inc(len(slots))
        less_n, leq_n, less_p, leq_p = out
        ln, qn_out, lp, qp_out = [], [], [], []
        for i, slot in enumerate(slots):
            kn, kp = len(q_vs_neg[i]), len(q_vs_pos[i])
            ln.append(less_n[slot, :kn])
            qn_out.append(leq_n[slot, :kn])
            lp.append(less_p[slot, :kp])
            qp_out.append(leq_p[slot, :kp])
        return ln, qn_out, lp, qp_out

    # ------------------------------------------------------------------ #
    # host-side exact arithmetic (mirrors ExactAucIndex._counts)         #
    # ------------------------------------------------------------------ #
    def _host_adjust(self, q: np.ndarray, base_less: np.ndarray,
                     base_leq: np.ndarray, buf: List[float],
                     tomb: List[float]):
        """(less, eq) vs the CURRENT class multiset: device base counts
        corrected by the host buffer (+) and tombstones (−) — the same
        signed-multiset additivity the single-tenant index uses, so
        the integers are identical."""
        less = base_less.astype(np.int64, copy=True)
        eq = (base_leq - base_less).astype(np.int64)
        for vals, sign in ((buf, 1), (tomb, -1)):
            if not vals:
                continue
            arr = np.sort(np.asarray(vals, dtype=self.dtype))
            l2 = np.searchsorted(arr, q, side="left").astype(np.int64)
            r2 = np.searchsorted(arr, q, side="right").astype(np.int64)
            less += sign * l2
            eq += sign * (r2 - l2)
        return less, eq

    @staticmethod
    def _cross2_arrays(p: np.ndarray, n: np.ndarray) -> int:
        if len(p) == 0 or len(n) == 0:
            return 0
        ns = np.sort(n)
        less = np.searchsorted(ns, p, side="left").astype(np.int64)
        leq = np.searchsorted(ns, p, side="right").astype(np.int64)
        return int(2 * less.sum() + (leq - less).sum())

    # ------------------------------------------------------------------ #
    # mutation                                                           #
    # ------------------------------------------------------------------ #
    def insert_batch(self, tid: str, scores, labels) -> int:
        """Single-tenant convenience over :meth:`apply_inserts`."""
        return self.apply_inserts([(tid, scores, labels)])[0]

    def apply_inserts(
        self, items: List[Tuple[str, np.ndarray, np.ndarray]],
    ) -> List[int]:
        """Insert one coalesced batch per tenant — every tenant's
        new-vs-old counts AND window-eviction counts ride ONE jitted
        fleet count. Items must name distinct tenants (the engine
        coalesces per tenant first); returns events inserted per item.

        Exactness: wins2 is a pure integer function of each tenant's
        admitted event sequence (pair sets are order- and
        batching-free), so per-tenant results are bit-identical to a
        dedicated single-tenant index fed the same events — the parity
        the fleet tests pin.
        """
        with self._lock:
            return self._apply_inserts_locked(items)

    def _apply_inserts_locked(self, items) -> List[int]:
        plans = []
        seen = set()
        out_by_slot: Dict[int, int] = {}
        order: List[int] = []
        touched: List[_TenantStat] = []
        for tid, scores, labels in items:
            st = self._by_tid.get(tid)
            if st is None:
                st = self.create(tid)
            if st.slot in seen:
                raise ValueError(
                    f"duplicate tenant {tid!r} in one apply — coalesce "
                    "per tenant first")
            seen.add(st.slot)
            order.append(st.slot)
            touched.append(st)
            scores = np.asarray(scores, dtype=self.dtype).ravel()
            labels = np.asarray(labels).ravel().astype(bool)
            if scores.shape != labels.shape:
                raise ValueError(
                    f"scores/labels length mismatch: {scores.shape} vs "
                    f"{labels.shape}")
            if len(scores) and not np.all(np.isfinite(scores)):
                raise ValueError("scores must be finite")
            if st.idx is not None:
                # whale route [ISSUE 9]: the promoted tenant's own
                # delta-tiered index — O(log n) jitted counts, O(b)
                # minors (off-thread under bg_compact), never the
                # shared-pack splice
                out_by_slot[st.slot] = st.idx.insert_batch(scores,
                                                           labels)
                st.last_active = time.monotonic()
                continue
            p_new = scores[labels]
            n_new = scores[~labels]
            # window-eviction plan: the oldest overflow arrivals of
            # (current log ++ this batch, in order) leave the window —
            # values known BEFORE the device call, so their base counts
            # share it with the insert queries
            p_out: List[float] = []
            n_out: List[float] = []
            n_evict = 0
            if self.window is not None:
                n_evict = max(0, len(st.log) + len(scores) - self.window)
            if n_evict:
                import itertools

                pool = itertools.chain(
                    st.log, zip(scores.tolist(), labels.tolist()))
                for v, is_pos in itertools.islice(pool, n_evict):
                    (p_out if is_pos else n_out).append(v)
            p_out_arr = np.asarray(p_out, dtype=self.dtype)
            n_out_arr = np.asarray(n_out, dtype=self.dtype)
            plans.append((st, scores, labels, p_new, n_new,
                          p_out_arr, n_out_arr, n_evict))
        if plans:
            ln, lqn, lp, lqp = self._fleet_base_counts(
                [np.concatenate([p[3], p[5]]) for p in plans],
                [np.concatenate([p[4], p[6]]) for p in plans],
                [p[0].slot for p in plans])
            for i, plan in enumerate(plans):
                out_by_slot[plan[0].slot] = self._fold_plan(
                    plan, ln[i], lqn[i], lp[i], lqp[i])
        for plan in plans:
            self._maybe_compact(plan[0])
        self._check_whales(touched)
        return [out_by_slot[slot] for slot in order]

    def _maybe_compact(self, st: _TenantStat) -> None:
        """Trigger a tenant compaction when the UNCLAIMED buffer or
        tombstone mass crosses the threshold (lock held). With
        ``bg_compact`` the build is enqueued to the side compactor
        [ISSUE 9]; a dead worker (crashed build) is restarted and the
        trigger falls back to the synchronous splice this once — the
        single-tenant watchdog discipline."""
        buf_pending, tomb_pending = st.pending()
        if (buf_pending < self.compact_every
                and tomb_pending < self.compact_every):
            return
        if self.bg_compact:
            if self._ensure_compactor():
                self._submit_compact(st)
                return
        if not st.building:
            self._compact_tenant(st)

    def _check_whales(self, sts: List[_TenantStat]) -> None:
        """Promote pack tenants crossing the threshold; demote whales
        that shrank below the hysteresis floor (lock held)."""
        if self.whale_threshold is None:
            return
        for st in sts:
            if st.idx is None and len(st.log) >= self.whale_threshold:
                self._promote(st)
            elif (st.idx is not None
                    and st.idx.n_events < self._demote_below):
                self._demote(st)

    def _fold_plan(self, plan, less_n, leq_n, less_p, leq_p) -> int:
        """Apply one tenant's insert + eviction with host-exact
        integer arithmetic (lock held). The device supplied base
        counts for [p_new ++ p_out] vs neg and [n_new ++ n_out] vs
        pos; buffers/tombstones adjust on the host at the right
        container state (pre-insert for the insert term, post-insert
        for the eviction term — exactly the single-tenant order)."""
        (st, scores, labels, p_new, n_new, p_out, n_out, n_evict) = plan
        kp, kn = len(p_new), len(n_new)
        # --- insert: new-vs-old (containers pre-insert) --------------- #
        less, eq = self._host_adjust(p_new, less_n[:kp], leq_n[:kp],
                                     st.neg_buf, st.neg_tomb)
        d = int(2 * less.sum() + eq.sum())
        less2, eq2 = self._host_adjust(n_new, less_p[:kn], leq_p[:kn],
                                       st.pos_buf, st.pos_tomb)
        greater = st.size(True) - less2 - eq2
        d += int(2 * greater.sum() + eq2.sum())
        d += self._cross2_arrays(p_new, n_new)
        st.wins2 += d
        st.pos_buf.extend(p_new.tolist())
        st.neg_buf.extend(n_new.tolist())
        for s, is_pos in zip(scores.tolist(), labels.tolist()):
            st.log.append((s, is_pos))
        # --- eviction: inclusion-exclusion (containers post-insert) --- #
        if n_evict:
            less, eq = self._host_adjust(p_out, less_n[kp:], leq_n[kp:],
                                         st.neg_buf, st.neg_tomb)
            d = int(2 * less.sum() + eq.sum())
            less2, eq2 = self._host_adjust(n_out, less_p[kn:], leq_p[kn:],
                                           st.pos_buf, st.pos_tomb)
            greater = st.size(True) - less2 - eq2
            d += int(2 * greater.sum() + eq2.sum())
            d -= self._cross2_arrays(p_out, n_out)
            st.wins2 -= d
            for _ in range(n_evict):
                v, is_pos = st.log.popleft()
                buf = st.pos_buf if is_pos else st.neg_buf
                snap_buf, _ = st.snap(is_pos)
                try:
                    # only the UNSNAPSHOTTED suffix is removable in
                    # place: an in-flight background build owns the
                    # prefix and will merge those copies into the new
                    # base — tombstone instead [ISSUE 9]
                    i = buf.index(v, snap_buf)
                    buf.pop(i)
                except ValueError:
                    (st.pos_tomb if is_pos else st.neg_tomb).append(v)
            st.n_evicted += n_evict
        st.last_active = time.monotonic()
        return len(scores)

    def _compact_tenant(self, st: _TenantStat) -> None:
        """Synchronous tenant compaction (lock held): fold the
        buffers/tombstones into the sorted bases and mark THE SLOT
        dirty in the touched packs — the next placement ships only
        this tenant's rows [ISSUE 9]. A chaos-injected crash aborts
        CLEANLY: containers untouched, wins2 never touched by
        compaction, retried at the next trigger."""
        if self.chaos is not None:
            try:
                self.chaos.fire("compactor_build")
            except Exception as e:   # noqa: BLE001 — injected crash
                self._c_compact_aborts.inc()
                self.last_compactor_error = repr(e)
                if self.flight is not None:
                    self.flight.record("compaction_abort",
                                       tenant=st.tid, error=repr(e))
                return
        t0 = time.perf_counter()
        with maybe_span(self.tracer, "fleet.compact", tenant=st.tid):
            for pos in (True, False):
                base, buf, tomb = st.side(pos)
                if not buf and not tomb:
                    continue
                merged = _remove_sorted(
                    _splice_merge(base, np.sort(
                        np.asarray(buf, dtype=self.dtype))),
                    list(tomb))
                if pos:
                    st.pos_base, st.pos_buf, st.pos_tomb = merged, [], []
                    self._pos_pack.mark(st.slot)
                else:
                    st.neg_base, st.neg_buf, st.neg_tomb = merged, [], []
                    self._neg_pack.mark(st.slot)
        st.n_compactions += 1
        self._c_compactions.inc()
        self._h_pause.observe(time.perf_counter() - t0)
        if self.flight is not None:
            self.flight.record("compaction", tier="tenant",
                               tenant=st.tid,
                               base_events=len(st.pos_base)
                               + len(st.neg_base))

    # ------------------------------------------------------------------ #
    # off-batcher pack builds [ISSUE 9]                                  #
    # ------------------------------------------------------------------ #
    def _ensure_compactor(self) -> bool:
        """Watchdog (lock held): True when the side compactor thread is
        alive; a dead worker (crashed build) is restarted and False
        returned so the caller compacts synchronously this once."""
        if not self.bg_compact:
            return False
        if self._compactor.is_alive():
            return True
        if not self._closed:
            self._c_bg_restarts.inc()
            self._compactor = threading.Thread(
                target=self._compact_worker,
                name="tuplewise-fleet-compactor", daemon=True)
            self._compactor.start()
        return False

    def _submit_compact(self, st: _TenantStat) -> None:
        """Claim the tenant's consumable prefixes and enqueue a build
        (lock held); no-op while one is in flight."""
        if st.building:
            return
        st.building = True
        st.snap_pos_buf = len(st.pos_buf)
        st.snap_neg_buf = len(st.neg_buf)
        st.snap_pos_tomb = len(st.pos_tomb)
        st.snap_neg_tomb = len(st.neg_tomb)
        self._jobs.put(st)

    def _compact_worker(self) -> None:
        while True:
            st = self._jobs.get()
            if st is None:
                return
            try:
                self._bg_build(st)
            except BaseException as e:
                # roll back the claim: buffers still hold every value
                # (prefixes trim only at the swap) and wins2 was never
                # touched — the next trigger re-compacts. The watchdog
                # restarts the thread and counts it.
                with self._cv:
                    st.snap_pos_buf = st.snap_neg_buf = 0
                    st.snap_pos_tomb = st.snap_neg_tomb = 0
                    st.building = False
                    self._c_compact_aborts.inc()
                    self.last_compactor_error = repr(e)
                    if self.flight is not None:
                        self.flight.record("compaction_abort",
                                           tenant=st.tid,
                                           error=repr(e))
                    self._cv.notify_all()
                return

    def _bg_build(self, st: _TenantStat) -> None:
        """One off-batcher tenant build: splice the CLAIMED prefixes
        into fresh bases with the lock released (inserts keep landing
        in the suffix), then swap atomically and mark the slot dirty —
        the request path's only pause is the swap [ISSUE 9]."""
        if self.chaos is not None:
            self.chaos.fire("compactor_build")
        with self._cv:
            pos_base, neg_base = st.pos_base, st.neg_base
            buf_p = list(st.pos_buf[: st.snap_pos_buf])
            buf_n = list(st.neg_buf[: st.snap_neg_buf])
            tomb_p = list(st.pos_tomb[: st.snap_pos_tomb])
            tomb_n = list(st.neg_tomb[: st.snap_neg_tomb])
        with maybe_span(self.tracer, "fleet.bg_compact",
                        tenant=st.tid,
                        n_buf=len(buf_p) + len(buf_n)):
            merged_p = _remove_sorted(
                _splice_merge(pos_base, np.sort(
                    np.asarray(buf_p, dtype=self.dtype))), tomb_p)
            merged_n = _remove_sorted(
                _splice_merge(neg_base, np.sort(
                    np.asarray(buf_n, dtype=self.dtype))), tomb_n)
        with self._cv:
            t0 = time.perf_counter()
            st.pos_base, st.neg_base = merged_p, merged_n
            del st.pos_buf[: st.snap_pos_buf]
            del st.neg_buf[: st.snap_neg_buf]
            del st.pos_tomb[: st.snap_pos_tomb]
            del st.neg_tomb[: st.snap_neg_tomb]
            st.snap_pos_buf = st.snap_neg_buf = 0
            st.snap_pos_tomb = st.snap_neg_tomb = 0
            st.building = False
            self._pos_pack.mark(st.slot)
            self._neg_pack.mark(st.slot)
            st.n_compactions += 1
            self._c_compactions.inc()
            # the swap is the only pause the request path can observe
            self._h_pause.observe(time.perf_counter() - t0)
            if self.flight is not None:
                self.flight.record("compaction", tier="tenant_bg",
                                   tenant=st.tid,
                                   base_events=len(merged_p)
                                   + len(merged_n))
            buf_pending, tomb_pending = st.pending()
            if (not self._closed
                    and (buf_pending >= self.compact_every
                         or tomb_pending >= self.compact_every)):
                self._submit_compact(st)
            self._cv.notify_all()
        # still on the compactor thread: pre-compile the count fns for
        # the geometry the next request-path count will see [ISSUE 10]
        self._warm_fleet_counts()

    def _warm_fleet_counts(self) -> None:
        """Best-effort prewarm of the fleet KERNEL count fn for the
        CURRENT pack geometry × every query bucket observed so far —
        called on the side compactor thread after a build, so a new
        kernel trace/compile lands there instead of on the request
        thread [ISSUE 10 satellite]. Kernel mode only: the XLA fns
        are globally lru-cached and cheap to hit cold, and warming
        them here would add a wasted dispatch per build to every
        kernel-off fleet (the pre-PR-10 behavior had none). No
        metrics: warm dispatches must not inflate the
        one-call-per-batch witness."""
        if not self._ck:
            return
        from tuplewise_tpu.parallel.sharded_counts import (
            tenant_pack_counts,
        )

        with self._lock:
            tb = self._pos_pack.t_bucket
            cap_p, cap_n = self._pos_pack.cap, self._neg_pack.cap
            pos_dev, neg_dev = self._pos_pack.dev, self._neg_pack.dev
            qbs = sorted(self._q_buckets)
        if pos_dev is None or neg_dev is None or not tb:
            return
        for qb in qbs:
            key = (self._ck, tb, cap_p, cap_n, qb)
            if key in self._warmed:
                continue
            try:
                tenant_pack_counts(
                    self._mesh, pos_dev, cap_p, neg_dev, cap_n, tb,
                    np.zeros((tb, qb), dtype=self.dtype),
                    np.zeros((tb, qb), dtype=self.dtype), self.dtype,
                    kernel=(self._ck_interp if self._ck else None))
                self._warmed.add(key)
            except Exception:   # noqa: BLE001 — warming is best-effort
                return

    def wait_idle(self, timeout: float = 30.0) -> None:
        """Block until no background tenant build is queued or in
        flight (measurement code calls it so byte/pause accounting is
        deterministic)."""
        if not self.bg_compact:
            return
        deadline = time.monotonic() + timeout
        with self._cv:
            while any(st is not None and st.building
                      for st in self._slots) or not self._jobs.empty():
                self._ensure_compactor()
                if (not self._cv.wait(timeout=0.25)
                        and time.monotonic() >= deadline):
                    raise TimeoutError("fleet background compaction "
                                       "stuck")

    def close(self, timeout: float = 10.0) -> None:
        """Stop the side compactor and close every whale index."""
        if self._closed:
            return
        self._closed = True
        if self.bg_compact:
            self._jobs.put(None)
            self._compactor.join(timeout=timeout)
        with self._lock:
            for st in self._by_tid.values():
                if st.idx is not None:
                    st.idx.close(timeout=timeout)

    # ------------------------------------------------------------------ #
    # whale promotion / demotion [ISSUE 9]                               #
    # ------------------------------------------------------------------ #
    def _make_whale_index(self):
        """A dedicated delta-tiered exact index for one promoted
        tenant: the PR 5 machinery (O(buffer) minors, tombstone
        evictions, on-mesh major merge) on the fleet's mesh, sharing
        the fleet's registry/chaos/observability hooks."""
        from tuplewise_tpu.serving.index import ExactAucIndex

        kw = dict(window=self.window, compact_every=self.compact_every,
                  engine="jax", metrics=self.metrics, chaos=self.chaos,
                  bg_compact=self.bg_compact,
                  shard_retries=self.shard_retries,
                  count_kernel=self.count_kernel,
                  tracer=self.tracer, flight=self.flight)
        if self._mesh is not None:
            kw["mesh"] = self._mesh
        return ExactAucIndex(**kw)

    def promote(self, tid: str) -> bool:
        """Explicitly promote a tenant (the automatic path triggers at
        ``whale_threshold``); returns False when absent or already
        promoted."""
        with self._lock:
            st = self._by_tid.get(tid)
            if st is None or st.idx is not None:
                return False
            return self._promote(st)

    def demote(self, tid: str) -> bool:
        """Explicitly demote a promoted tenant back into the shared
        pack (the automatic path triggers below the hysteresis
        floor)."""
        with self._lock:
            st = self._by_tid.get(tid)
            if st is None or st.idx is None:
                return False
            self._demote(st)
            return True

    def _promote(self, st: _TenantStat) -> bool:
        """Move a pack tenant's state into its own index (lock held).
        All fallible work — index construction, state seeding, device
        placement — happens BEFORE the handoff, so a chaos fault
        mid-promotion aborts cleanly (pack state untouched, counted in
        ``fleet_whale_promote_aborts``) and the next trigger retries.
        Statistically invisible: wins2/log transfer verbatim and every
        count is a pure function of the multiset."""
        # a build in flight owns the containers — promote next trigger
        if st.building:
            return False
        idx = None
        try:
            idx = self._make_whale_index()
            idx.seed_state(st.values(True), st.values(False),
                           list(st.log), st.wins2,
                           n_evicted=st.n_evicted)
        except Exception as e:    # noqa: BLE001 — abort cleanly
            self._c_promote_aborts.inc()
            if self.flight is not None:
                self.flight.record("whale_promote_abort", tenant=st.tid,
                                   error=repr(e))
            if idx is not None:
                try:
                    idx.close()
                except Exception:     # noqa: BLE001 — best-effort
                    pass
            return False
        st.idx = idx
        st.pos_base = np.empty(0, dtype=self.dtype)
        st.neg_base = np.empty(0, dtype=self.dtype)
        st.pos_buf, st.neg_buf = [], []
        st.pos_tomb, st.neg_tomb = [], []
        st.log = collections.deque()
        st.wins2 = 0
        # reclaim the pack row (ships one +inf row at next placement)
        self._pos_pack.mark(st.slot)
        self._neg_pack.mark(st.slot)
        self._c_promotions.inc()
        self._g_whales.set(self._n_whales())
        self._refresh_pack_gauges()
        if self.flight is not None:
            self.flight.record("whale_promoted", tenant=st.tid,
                               events=idx.n_events)
        return True

    def _demote(self, st: _TenantStat) -> None:
        """Fold a shrunken whale back into the shared pack (lock
        held): the index's exact state transfers verbatim into the
        tenant's containers, the slot re-places at the next count."""
        idx = st.idx
        pos, neg, log, wins2, n_evicted = idx.export_state()
        st.idx = None
        idx.close()
        st.pos_base = np.asarray(pos, dtype=self.dtype)
        st.neg_base = np.asarray(neg, dtype=self.dtype)
        st.pos_buf, st.neg_buf = [], []
        st.pos_tomb, st.neg_tomb = [], []
        st.log = collections.deque(log)
        st.wins2 = wins2
        st.n_evicted = n_evicted
        self._pos_pack.mark(st.slot)
        self._neg_pack.mark(st.slot)
        self._c_demotions.inc()
        self._g_whales.set(self._n_whales())
        self._refresh_pack_gauges()
        if self.flight is not None:
            self.flight.record("whale_demoted", tenant=st.tid,
                               events=len(st.log))

    # ------------------------------------------------------------------ #
    # queries                                                            #
    # ------------------------------------------------------------------ #
    def apply_scores(
        self, items: List[Tuple[str, np.ndarray]],
    ) -> List[np.ndarray]:
        """Fractional ranks vs each tenant's negatives for a coalesced
        multi-tenant score batch — ONE jitted fleet count (promoted
        whales answer from their own index)."""
        with self._lock:
            plans = []
            out_by_pos: Dict[int, np.ndarray] = {}
            for i, (tid, q) in enumerate(items):
                st = self._by_tid.get(tid)
                if st is None:
                    st = self.create(tid)
                q = np.asarray(q, dtype=self.dtype).ravel()
                if st.idx is not None:
                    out_by_pos[i] = st.idx.score_batch(q)
                    st.last_active = time.monotonic()
                else:
                    plans.append((i, st, q))
            if plans:
                empty = np.zeros(0, dtype=self.dtype)
                ln, lqn, _, _ = self._fleet_base_counts(
                    [q for _, _, q in plans], [empty for _ in plans],
                    [st.slot for _, st, _ in plans])
                for k, (i, st, q) in enumerate(plans):
                    n_neg = st.size(False)
                    if n_neg == 0:
                        out_by_pos[i] = np.full(len(q), np.nan)
                        continue
                    less, eq = self._host_adjust(
                        q, ln[k], lqn[k], st.neg_buf, st.neg_tomb)
                    out_by_pos[i] = (less + 0.5 * eq) / float(n_neg)
                    st.last_active = time.monotonic()
            return [out_by_pos[i] for i in range(len(items))]

    def is_whale(self, tid: str) -> bool:
        with self._lock:
            st = self._by_tid.get(tid)
            return st is not None and st.idx is not None

    def wins2(self, tid: str) -> int:
        with self._lock:
            st = self._by_tid[tid]
            return st.idx._wins2 if st.idx is not None else st.wins2

    def auc(self, tid: str) -> Optional[float]:
        with self._lock:
            st = self._by_tid.get(tid)
            if st is None:
                return None
            if st.idx is not None:
                return st.idx.auc()
            np_, nn = st.size(True), st.size(False)
            if np_ == 0 or nn == 0:
                return None
            return st.wins2 / (2.0 * np_ * nn)

    def oracle_values(self, tid: str) -> Tuple[np.ndarray, np.ndarray]:
        with self._lock:
            st = self._by_tid[tid]
            if st.idx is not None:
                return st.idx.oracle_values()
            return st.values(True), st.values(False)

    def tenant_state(self, tid: str) -> Optional[dict]:
        with self._lock:
            st = self._by_tid.get(tid)
            if st is None:
                return None
            if st.idx is not None:
                return {
                    "tenant": tid,
                    "n_pos": st.idx.n_pos,
                    "n_neg": st.idx.n_neg,
                    "n_events": st.idx.n_events,
                    "auc": st.idx.auc(),
                    "n_compactions": st.idx.n_compactions,
                    "n_evicted": st.idx.n_evicted,
                    "promoted": True,
                }
            return {
                "tenant": tid,
                "n_pos": st.size(True),
                "n_neg": st.size(False),
                "n_events": len(st.log),
                "auc": self.auc(tid),
                "n_compactions": st.n_compactions,
                "n_evicted": st.n_evicted,
                "promoted": False,
            }

    def state(self) -> dict:
        with self._lock:
            return {
                "tenants": len(self._by_tid),
                "slots": len(self._slots),
                "t_bucket": self._t_bucket(),
                "shards": self.shards,
                "window": self.window,
                "pack_caps": {"pos": self._pos_pack.cap,
                              "neg": self._neg_pack.cap},
                "count_calls": self._c_count_calls.value,
                "whales": self._n_whales(),
                "whale_threshold": self.whale_threshold,
                "bg_compact": self.bg_compact,
                "incremental_placement": self.incremental_placement,
                "last_compactor_error": self.last_compactor_error,
            }


# --------------------------------------------------------------------- #
# fleet request path                                                     #
# --------------------------------------------------------------------- #

class _FleetRequest:
    __slots__ = ("kind", "tenant", "scores", "labels", "future",
                 "t_enqueue", "span")

    def __init__(self, kind: str, tenant: str, scores, labels,
                 span=None):
        self.kind = kind
        self.tenant = tenant
        self.scores = scores
        self.labels = labels
        self.future: Future = Future()
        self.t_enqueue = time.perf_counter()
        self.span = span


class MultiTenantEngine:
    """Micro-batched fleet engine: per-tenant queues, admission
    control, weighted-fair scheduling, one batcher thread, one mesh.

    The single-tenant :class:`~tuplewise_tpu.serving.engine.
    MicroBatchEngine` semantics hold per tenant — per-tenant event
    order, exact per-tenant AUC, per-tenant windows/streams — while
    the shared resources (queue capacity, batcher, device packs) are
    governed fleet-wide:

    * **admission** — ``submit`` raises :class:`TenantRejectedError`
      when the tenant's queued-request quota or the fleet tenant cap is
      exceeded (typed, counted globally and per tenant), and the
      global ``queue_size``/``policy`` backpressure applies on top.
    * **fair scheduling** — the batcher drains per-tenant FIFOs in
      deficit-round-robin order (up to ``TenancyConfig.weight``
      requests per tenant per round), so every pending tenant is
      served each round regardless of one tenant's flood.
    * **lifecycle** — tenants are created on first request (or
      explicitly via :meth:`create_tenant`), dropped explicitly, or
      evicted after ``idle_evict_s`` of inactivity.

    Use as a context manager (or call ``close()``). ``close()`` fails
    every unapplied request with an :class:`~tuplewise_tpu.serving.
    engine.EngineClosedError` carrying the owning tenant id.
    """

    _KINDS = ("insert", "score", "query")

    def __init__(self, config: Optional[ServingConfig] = None,
                 tenancy: Optional[TenancyConfig] = None,
                 chaos=None, tracer=None, **overrides):
        if config is None:
            config = ServingConfig(**overrides)
        elif overrides:
            config = dataclasses.replace(config, **overrides)
        if config.kernel != "auc":
            raise ValueError(
                "MultiTenantEngine serves the exact AUC fleet; "
                f"kernel={config.kernel!r} is not supported")
        self.config = config
        self.tenancy = tenancy if tenancy is not None else TenancyConfig()
        self.chaos = chaos
        self.tracer = tracer
        self.metrics = MetricsRegistry()
        self.flight = FlightRecorder(
            capacity=config.flight_recorder_size, tracer=tracer,
            dump_path=(os.path.join(config.snapshot_dir, "flight.jsonl")
                       if config.snapshot_dir else None))
        if chaos is not None:
            chaos.attach(flight=self.flight, tracer=tracer)
        self.fleet = TenantFleetIndex(
            window=config.window, compact_every=config.compact_every,
            shards=config.mesh_shards, metrics=self.metrics,
            chaos=chaos,
            min_tenant_bucket=self.tenancy.min_tenant_bucket,
            bg_compact=config.bg_compact,
            whale_threshold=self.tenancy.whale_threshold,
            whale_demote_fraction=self.tenancy.whale_demote_fraction,
            count_kernel=config.count_kernel,
            tracer=tracer, flight=self.flight)
        # bounded metric cardinality [ISSUE 9 satellite]: tenants past
        # tenant_metric_cap share ONE {tenant=__other__} series
        self._labeled_tenants: set = set()
        self._collapsed_tenants: set = set()
        self._g_collapsed = self.metrics.gauge("tenant_metric_collapsed")
        self._streams: Dict[str, StreamingIncompleteU] = {}
        m = self.metrics
        self._c_req = {k: m.counter(f"requests_{k}_total")
                       for k in self._KINDS}
        self._c_rejected = m.counter("rejected_total")
        self._c_dropped = m.counter("dropped_total")
        self._c_tenant_rejected = m.counter("tenant_rejected_total")
        self._c_tenants_created = m.counter("tenants_created_total")
        self._c_tenants_evicted = m.counter("tenants_evicted_total")
        self._c_batches = m.counter("batches_total")
        self._c_events = m.counter("events_total")
        self._c_pairs = m.counter("incomplete_pairs_total")
        self._c_poison = m.counter("poison_rejects")
        self._c_batcher_restarts = m.counter("batcher_restarts")
        self._c_deadline = m.counter("deadline_expired_total")
        # control-plane shedding [ISSUE 11]: typed, per-tenant,
        # BEFORE a breach — mirrors the tenant_rejected plumbing
        self._c_throttled = m.counter("tenant_throttled_total")
        self._h_latency = m.histogram("request_latency_s")
        self._h_insert_lat = m.histogram("insert_latency_s")
        self._h_fill = m.histogram(
            "batch_fill", buckets=[i / 16 for i in range(1, 17)])
        self._g_depth = m.gauge("queue_depth_live")
        self._g_live = m.gauge("tenants_live")
        # host-tax wave ledger [ISSUE 14]: the fleet's insert waves
        # get the same below-stage decomposition as the single-tenant
        # engine (the per-tenant dict hops + pack splice ARE the
        # host_python bucket the one-dispatch refactor targets); the
        # fleet path takes its lock inside apply_inserts, so lock wait
        # stays inside host_python here
        self.ledger = WaveLedger(m)
        self._c_exemplars = m.counter("tail_exemplars_total")
        self._pending: Dict[str, Deque[_FleetRequest]] = {}
        self._rotation: List[str] = []
        self._n_pending = 0
        self._inflight = 0
        self._cv = threading.Condition()
        self._closed = False
        self._last_idle_check = time.monotonic()
        # control-plane overrides [ISSUE 11]: the FleetController's
        # reversible actuations. All default-empty, so a controller-off
        # engine takes the exact pre-ISSUE-11 paths (the `.get(tid,
        # default)` reads below resolve to today's static config).
        self._throttles: Dict[str, Tuple[float, float]] = {}
        self._tenant_weights: Dict[str, int] = {}
        self._tenant_quotas: Dict[str, int] = {}
        self._recovery = None
        if config.snapshot_dir:
            self._recovery = FleetRecoveryManager(
                config.snapshot_dir,
                snapshot_every=config.snapshot_every,
                wal_fsync=config.wal_fsync, tracer=tracer,
                flight=self.flight)
            if config.recover:
                self._recovery.recover(self)
            else:
                self._recovery.start_fresh()
        self._worker = threading.Thread(
            target=self._supervise, name="tuplewise-fleet-batcher",
            daemon=True)
        self._worker.start()
        # deadline reaper [ISSUE 11 bugfix]: the fleet twin of the
        # single-engine timer — over-deadline pending requests fail
        # typed on a timer, not only when the batcher gets to them
        self._reaper = None
        if config.deadline_s is not None:
            self._reaper = threading.Thread(
                target=self._reap_expired,
                name="tuplewise-fleet-reaper", daemon=True)
            self._reaper.start()

    # ------------------------------------------------------------------ #
    # tenant lifecycle                                                   #
    # ------------------------------------------------------------------ #
    def _metric_tenant(self, tid: str) -> str:
        """The label value a tenant's metrics use: its own id until
        ``tenant_metric_cap`` distinct tenants are labeled, then
        ``__other__`` [ISSUE 9 satellite]. First-come keeps its label
        (stable — no re-labeling churn); the collapsed-tenant count
        exports as the ``tenant_metric_collapsed`` gauge so doctor's
        tenant breakdown can report how much the cap hid."""
        cap = self.tenancy.tenant_metric_cap
        if cap is None:
            return tid
        if tid in self._labeled_tenants:
            return tid
        if len(self._labeled_tenants) < cap:
            self._labeled_tenants.add(tid)
            return tid
        if tid not in self._collapsed_tenants:
            self._collapsed_tenants.add(tid)
            self._g_collapsed.set(len(self._collapsed_tenants))
        return "__other__"

    def _ensure_tenant(self, tid: str):
        """Create-on-first-request under the tenant cap (admission)."""
        if self.fleet.has(tid):
            return
        if self.fleet.n_tenants >= self.tenancy.max_tenants:
            self._c_tenant_rejected.inc()
            if self.tenancy.tenant_metrics:
                self.metrics.counter(
                    "tenant_rejected_total",
                    labels={"tenant": self._metric_tenant(tid)}).inc()
            raise TenantRejectedError(
                f"fleet at max_tenants={self.tenancy.max_tenants}; "
                f"tenant {tid!r} not admitted", tenant=tid)
        self.create_tenant(tid)

    def create_tenant(self, tid: str) -> None:
        self.fleet.create(tid)
        if tid not in self._streams:
            self._streams[tid] = StreamingIncompleteU(
                kernel=self.config.kernel, budget=self.config.budget,
                reservoir=self.config.reservoir,
                design=self.config.design,
                seed=tenant_seed(self.config.seed, tid))
            self._c_tenants_created.inc()
        self._g_live.set(self.fleet.n_tenants)

    def drop_tenant(self, tid: str) -> bool:
        """Explicit removal (lifecycle API; also the idle-eviction
        path). Pending requests for the tenant still apply — only the
        statistic state is dropped, so the tenant re-creates cleanly
        on its next request."""
        dropped = self.fleet.drop(tid)
        self._streams.pop(tid, None)
        if dropped:
            self._c_tenants_evicted.inc()
            self._g_live.set(self.fleet.n_tenants)
        return dropped

    def _maybe_evict_idle(self) -> None:
        idle_s = self.tenancy.idle_evict_s
        if idle_s is None:
            return
        now = time.monotonic()
        if now - self._last_idle_check < min(idle_s, 1.0):
            return
        self._last_idle_check = now
        for tid in self.fleet.idle_tenants(idle_s):
            with self._cv:
                busy = tid in self._pending
            if not busy:
                self.drop_tenant(tid)

    # ------------------------------------------------------------------ #
    # control-plane actuation surface [ISSUE 11]                         #
    # ------------------------------------------------------------------ #
    def throttle_tenant(self, tid: str,
                        retry_after_s: float = 0.5) -> None:
        """Shed ``tid``'s NEW requests for ``retry_after_s`` seconds
        with a typed :class:`TenantThrottledError` carrying the retry
        hint. Auto-expires (reversible by construction); re-issue to
        extend. Already-queued requests still apply — a throttle
        affects admission, never applied state."""
        with self._cv:
            self._throttles[str(tid)] = (
                time.monotonic() + retry_after_s, retry_after_s)

    def clear_throttles(self, tid: Optional[str] = None) -> int:
        """Lift one tenant's throttle (or all); returns how many."""
        with self._cv:
            if tid is not None:
                return 1 if self._throttles.pop(str(tid), None) else 0
            n = len(self._throttles)
            self._throttles.clear()
            return n

    def throttled_tenants(self) -> List[str]:
        now = time.monotonic()
        with self._cv:
            return [t for t, (until, _) in self._throttles.items()
                    if until > now]

    def set_tenant_weight(self, tid: str,
                          weight: Optional[int]) -> None:
        """Override one tenant's DRR quantum (None restores the
        config default) — the controller's fairness rebalance knob."""
        with self._cv:
            if weight is None:
                self._tenant_weights.pop(str(tid), None)
            else:
                self._tenant_weights[str(tid)] = max(1, int(weight))

    def set_tenant_quota(self, tid: str,
                         quota: Optional[int]) -> None:
        """Override one tenant's queued-request quota (None restores
        the config default)."""
        with self._cv:
            if quota is None:
                self._tenant_quotas.pop(str(tid), None)
            else:
                self._tenant_quotas[str(tid)] = max(1, int(quota))

    def pending_by_tenant(self) -> Dict[str, int]:
        """Queued (unapplied) request counts per tenant — the
        controller's who-is-flooding-the-queue signal."""
        with self._cv:
            return {t: len(dq) for t, dq in self._pending.items()}

    def _check_throttle(self, tenant: str) -> None:
        th = self._throttles.get(tenant)
        if th is None:
            return
        until, _ = th
        remaining = until - time.monotonic()
        if remaining <= 0:
            with self._cv:
                # expired: drop it (unless re-issued meanwhile)
                if self._throttles.get(tenant, (0, 0))[0] <= \
                        time.monotonic():
                    self._throttles.pop(tenant, None)
            return
        self._c_throttled.inc()
        if self.tenancy.tenant_metrics:
            self.metrics.counter(
                "tenant_throttled_total",
                labels={"tenant": self._metric_tenant(tenant)}).inc()
        self.flight.record("tenant_throttled", tenant=tenant,
                           retry_after_s=remaining)
        raise TenantThrottledError(
            f"tenant {tenant!r} throttled by the control plane; "
            f"retry after {remaining:.3f}s", tenant=tenant,
            retry_after_s=remaining)

    def _reap_expired(self) -> None:
        """Fleet deadline timer [ISSUE 11 bugfix]: fail over-deadline
        pending requests typed and REMOVE them from their tenant
        queues, so a wedged or idle batcher cannot let them rot (and
        their quota slots free up)."""
        deadline = self.config.deadline_s
        interval = min(max(deadline / 4.0, 0.005), 0.25)
        while not self._closed:
            time.sleep(interval)
            now = time.perf_counter()
            expired: List[_FleetRequest] = []
            with self._cv:
                for tid in list(self._pending):
                    dq = self._pending[tid]
                    keep = collections.deque(
                        r for r in dq
                        if now - r.t_enqueue <= deadline)
                    if len(keep) != len(dq):
                        expired.extend(
                            r for r in dq
                            if now - r.t_enqueue > deadline)
                        self._n_pending -= len(dq) - len(keep)
                        if keep:
                            self._pending[tid] = keep
                        else:
                            del self._pending[tid]
                            self._rotation.remove(tid)
                if expired:
                    self._cv.notify_all()   # capacity freed
            for r in expired:
                if r.future.done():
                    continue
                try:
                    r.future.set_exception(DeadlineExceededError(
                        f"request expired after "
                        f"{now - r.t_enqueue:.3f}s in queue "
                        f"(deadline_s={deadline}, tenant={r.tenant})"))
                except Exception:   # noqa: BLE001 — lost the race
                    continue
                self._c_deadline.inc()
                self.flight.record(
                    "deadline_expired", kind_req=r.kind,
                    tenant=r.tenant, waited_s=now - r.t_enqueue)
                self._finish(r, now)

    # ------------------------------------------------------------------ #
    # request side                                                       #
    # ------------------------------------------------------------------ #
    def submit(self, kind: str, tenant, scores=None,
               labels=None) -> Future:
        """Enqueue one request for ``tenant``; returns its Future.

        Raises :class:`TenantRejectedError` (admission),
        :class:`~tuplewise_tpu.serving.engine.BackpressureError`
        (global queue policy), :class:`~tuplewise_tpu.serving.engine.
        PoisonEventError` (edge validation) — all before the request
        can consume shared batcher time.
        """
        if kind not in self._KINDS:
            raise ValueError(f"unknown request kind {kind!r}")
        tenant = str(tenant)
        if self._closed:
            raise EngineClosedError(
                f"engine is closed (tenant={tenant})", tenant=tenant)
        # control-plane shed [ISSUE 11]: the cheapest possible edge —
        # before validation, before tenant creation, before any shared
        # resource is touched
        self._check_throttle(tenant)
        if kind == "insert":
            scores, labels = self._validate_insert(tenant, scores, labels)
        elif kind == "score":
            scores = np.atleast_1d(np.asarray(scores, dtype=np.float64))
        self._ensure_tenant(tenant)
        span = None
        if self.tracer is not None:
            span = self.tracer.start(f"request.{kind}", parent=None)
        req = _FleetRequest(kind, tenant, scores, labels, span=span)
        if span is not None:
            span.t0 = req.t_enqueue
        self._c_req[kind].inc()
        with self._cv:
            dq = self._pending.get(tenant)
            quota = self._tenant_quotas.get(tenant,
                                            self.tenancy.tenant_quota)
            if dq is not None and len(dq) >= quota:
                self._c_tenant_rejected.inc()
                if self.tenancy.tenant_metrics:
                    self.metrics.counter(
                        "tenant_rejected_total",
                        labels={"tenant":
                                self._metric_tenant(tenant)}).inc()
                raise TenantRejectedError(
                    f"tenant {tenant!r} queue quota "
                    f"({quota}) exceeded",
                    tenant=tenant)
            while self._n_pending >= self.config.queue_size:
                if self.config.policy == "reject":
                    self._c_rejected.inc()
                    raise BackpressureError(
                        f"fleet queue full ({self.config.queue_size}); "
                        f"request rejected (tenant={tenant})")
                if self.config.policy == "drop_oldest":
                    self._drop_oldest_locked()
                    continue
                # block: wait for capacity; a close() must unblock us
                self._cv.wait(timeout=0.05)
                if self._closed:
                    raise EngineClosedError(
                        "engine closed while blocked on queue capacity "
                        f"(tenant={tenant})", tenant=tenant)
            if dq is None:
                dq = self._pending[tenant] = collections.deque()
                self._rotation.append(tenant)
            dq.append(req)
            self._n_pending += 1
            # live queue depth at submit too [ISSUE 11]: the
            # saturation objective (and the controller riding it) must
            # see backlog as it BUILDS, not only when the batcher next
            # drains — one attribute store under the lock already held
            self._g_depth.set(self._n_pending)
            self._cv.notify_all()
        return req.future

    def _drop_oldest_locked(self) -> None:
        """drop_oldest across tenants: shed the head of the LONGEST
        per-tenant queue — freshness for everyone, and the flooding
        tenant pays first."""
        if not self._pending:
            return
        tid = max(self._pending, key=lambda t: len(self._pending[t]))
        old = self._pending[tid].popleft()
        if not self._pending[tid]:
            del self._pending[tid]
            self._rotation.remove(tid)
        self._n_pending -= 1
        self._c_dropped.inc()
        if not old.future.done():
            old.future.set_exception(BackpressureError(
                f"dropped by a newer request (drop_oldest, "
                f"tenant={old.tenant})"))

    def _validate_insert(self, tenant, scores, labels):
        scores = np.atleast_1d(np.asarray(scores, dtype=np.float64))
        labels = np.atleast_1d(np.asarray(labels))
        msg = None
        if scores.shape != labels.shape:
            msg = (f"insert: scores/labels shape mismatch: "
                   f"{scores.shape} vs {labels.shape}")
        elif len(scores) and not np.all(np.isfinite(scores)):
            msg = "insert: non-finite score(s) rejected"
        elif labels.dtype.kind == "f" and len(labels) \
                and not np.all(np.isfinite(labels)):
            msg = "insert: non-finite label(s) rejected"
        if msg is not None:
            self._c_poison.inc()
            self.flight.record("poison_reject", reason=msg,
                               tenant=tenant)
            raise PoisonEventError(f"{msg} (tenant={tenant})")
        return scores, labels

    def insert(self, tenant, scores, labels) -> Future:
        return self.submit("insert", tenant, scores, labels)

    def score(self, tenant, scores) -> Future:
        return self.submit("score", tenant, scores)

    def query(self, tenant) -> Future:
        return self.submit("query", tenant)

    def flush(self, timeout: Optional[float] = 30.0) -> None:
        """Barrier: everything enqueued so far is applied on return."""
        deadline = time.monotonic() + (timeout or 30.0)
        with self._cv:
            while (self._n_pending or self._inflight) \
                    and not self._closed:
                self._cv.wait(timeout=0.05)
                if time.monotonic() >= deadline:
                    raise TimeoutError("fleet flush timed out")

    # ------------------------------------------------------------------ #
    # batcher side                                                       #
    # ------------------------------------------------------------------ #
    def _supervise(self) -> None:
        while True:
            try:
                self._run()
                return
            except BaseException as e:
                if self._closed:
                    return
                self._c_batcher_restarts.inc()
                self.flight.record("batcher_restart", error=repr(e))
                self.flight.auto_dump()

    def _run(self) -> None:
        while True:
            if self.chaos is not None:
                self.chaos.fire("batcher")
            batch = self._next_batch()
            if batch is None:
                self._fail_pending()
                return
            if batch:
                try:
                    self._dispatch(batch)
                finally:
                    with self._cv:
                        self._inflight = 0
                        self._cv.notify_all()
            self._maybe_evict_idle()

    def _next_batch(self) -> Optional[List[_FleetRequest]]:
        with self._cv:
            while self._n_pending == 0:
                if self._closed:
                    return None
                self._cv.wait(timeout=0.05)
            if self._closed:
                # close() fails unapplied requests (tenant-attributed)
                # instead of serving them late
                return None
            deadline = time.perf_counter() + self.config.flush_timeout_s
            while (self._n_pending < self.config.max_batch
                   and not self._closed):
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
            batch = self._drr_take(self.config.max_batch)
            # the gauge tracks REMAINING backlog: set post-take (and
            # at every submit), so a drained queue reads low instead
            # of holding the last pre-drain peak — the saturation
            # objective (and the controller) must see recovery too
            # [ISSUE 11]
            self._g_depth.set(self._n_pending)
            self._inflight = len(batch)
            self._cv.notify_all()    # capacity freed: wake producers
            return batch

    def _drr_take(self, n: int) -> List[_FleetRequest]:
        """Deficit-round-robin drain (lock held): every pending tenant
        is served up to ``weight`` requests per round before any
        tenant is served twice — the starvation-free order."""
        out: List[_FleetRequest] = []
        while len(out) < n and self._rotation:
            tid = self._rotation.pop(0)
            dq = self._pending.get(tid)
            if dq is None:
                continue
            # per-tenant quantum override [ISSUE 11]: the controller's
            # fairness rebalance; absent = the static config weight
            w = self._tenant_weights.get(tid, self.tenancy.weight)
            take = min(w, n - len(out), len(dq))
            for _ in range(take):
                out.append(dq.popleft())
            self._n_pending -= take
            if dq:
                self._rotation.append(tid)
            else:
                del self._pending[tid]
        return out

    @staticmethod
    def _waves(batch: List[_FleetRequest]):
        """Split a drained batch into kind waves that preserve each
        tenant's submission order: per tenant, consecutive same-kind
        segments; wave i = every tenant's i-th segment, grouped by
        kind. Inserts across tenants in one wave coalesce into one
        fleet count."""
        segs: Dict[str, List[Tuple[str, List[_FleetRequest]]]] = {}
        for r in batch:
            runs = segs.setdefault(r.tenant, [])
            if runs and runs[-1][0] == r.kind:
                runs[-1][1].append(r)
            else:
                runs.append((r.kind, [r]))
        depth = max((len(v) for v in segs.values()), default=0)
        for i in range(depth):
            wave: Dict[str, List[Tuple[str, List[_FleetRequest]]]] = {
                "insert": [], "score": [], "query": []}
            for tid, runs in segs.items():
                if i < len(runs):
                    kind, reqs = runs[i]
                    wave[kind].append((tid, reqs))
            yield wave

    def _dispatch(self, batch: List[_FleetRequest]) -> None:
        self._c_batches.inc()
        self._h_fill.observe(len(batch) / self.config.max_batch)
        for wave in self._waves(batch):
            # umbrella exception path [ISSUE 15]: the apply helpers
            # fail their own dispatch errors, but an exception in the
            # post-apply resolve/metrics code — or in tenant_stats on
            # the query path, which had NO handler at all — must still
            # fail every unresolved future in the wave. Stranded
            # futures hang their callers until timeout and the
            # supervisor restart hides the cause; the lifecycle pass's
            # future-leak rule pins this umbrella. The done() guards
            # keep resolution single-shot against the reaper.
            try:
                if wave["insert"]:
                    self._apply_insert_wave(wave["insert"])
                if wave["score"]:
                    self._apply_score_wave(wave["score"])
                for tid, reqs in wave["query"]:
                    snap = self.tenant_stats(tid)
                    for r in reqs:
                        if not r.future.done():
                            r.future.set_result(snap)
                        self._finish(r)
            except Exception as e:      # fail the wave, keep serving
                for group in (wave["insert"], wave["score"],
                              wave["query"]):
                    for _tid, reqs in group:
                        for r in reqs:
                            if not r.future.done():
                                r.future.set_exception(e)
                                self._finish(r)

    def _finish(self, r: _FleetRequest,
                now: Optional[float] = None) -> None:
        now = now if now is not None else time.perf_counter()
        self._h_latency.observe(now - r.t_enqueue)
        if self.tracer is not None and r.span is not None:
            self.tracer.finish(r.span, now)
            r.span = None

    def _apply_insert_wave(self, groups) -> None:
        """One wave of per-tenant insert runs → ONE fleet count +
        per-tenant stream extends; futures resolve per request."""
        t_start = time.perf_counter()
        # host-tax wave [ISSUE 14]: opened before the per-tenant
        # concat/dict work so plan assembly bills to host_python
        wave = self.ledger.begin_wave()
        try:
            self._apply_insert_wave_ledgered(groups, t_start, wave)
        finally:
            self.ledger.abort_wave(wave)

    def _apply_insert_wave_ledgered(self, groups, t_start: float,
                                    wave) -> None:
        items = []
        for tid, reqs in groups:
            scores = np.concatenate([r.scores for r in reqs])
            labels = np.concatenate(
                [r.labels for r in reqs]).astype(bool)
            items.append((tid, scores, labels))
        with maybe_span(self.tracer, "fleet.insert_wave",
                        n_tenants=len(items)):
            try:
                if self._recovery is not None:
                    for tid, scores, labels in items:
                        self._recovery.record(scores, labels, tenant=tid)
                self.fleet.apply_inserts(items)
                for tid, scores, labels in items:
                    spent = self._streams[tid].extend(scores, labels)
                    self._c_pairs.inc(spent)
                    self._c_events.inc(len(scores))
                if self._recovery is not None:
                    self._recovery.maybe_snapshot(self)
            except Exception as e:
                for _, reqs in groups:
                    for r in reqs:
                        if not r.future.done():
                            r.future.set_exception(e)
                        self._finish(r)
                return
        now = time.perf_counter()
        # close the host-tax wave [ISSUE 14] at the resolve boundary:
        # per-request buckets tile [enqueue, resolve] exactly
        n_reqs = sum(len(reqs) for _, reqs in groups)
        buckets = self.ledger.finish_wave(
            wave, t_start=t_start, t_end=now,
            queue_waits=[t_start - r.t_enqueue
                         for _, reqs in groups for r in reqs])
        th = self.config.tail_exemplar_ms
        for tid, reqs in groups:
            h_tenant = None
            if self.tenancy.tenant_metrics:
                mt = self._metric_tenant(tid)
                h_tenant = self.metrics.histogram(
                    "insert_latency_s", labels={"tenant": mt})
                # per-tenant event counter [ISSUE 11]: the traffic-
                # SLOPE signal the controller differentiates for shed
                # ordering and preemptive whale promotion (the latency
                # histogram counts REQUESTS, not events)
                self.metrics.counter(
                    "tenant_events_total", labels={"tenant": mt}).inc(
                    sum(len(r.scores) for r in reqs))
            for r in reqs:
                if not r.future.done():
                    r.future.set_result(len(r.scores))
                lat = now - r.t_enqueue
                self._h_insert_lat.observe(lat)
                if h_tenant is not None:
                    h_tenant.observe(lat)
                if th is not None and lat * 1e3 >= th:
                    # tenant-attributed tail exemplar [ISSUE 14]
                    self._c_exemplars.inc()
                    self.flight.record(
                        "tail_exemplar", kind_req="insert", tenant=tid,
                        trace_id=(r.span.trace_id
                                  if r.span is not None else None),
                        lat_ms=lat * 1e3, n_events=len(r.scores),
                        n_requests=n_reqs,
                        buckets=dict(buckets,
                                     queue_wait=t_start - r.t_enqueue))
                self._finish(r, now)

    def _apply_score_wave(self, groups) -> None:
        items = []
        for tid, reqs in groups:
            items.append((tid,
                          np.concatenate([r.scores for r in reqs])))
        try:
            ranks = self.fleet.apply_scores(items)
        except Exception as e:
            for _, reqs in groups:
                for r in reqs:
                    if not r.future.done():
                        r.future.set_exception(e)
                    self._finish(r)
            return
        for (tid, reqs), rk in zip(groups, ranks):
            off = 0
            for r in reqs:
                n = len(r.scores)
                if not r.future.done():
                    r.future.set_result(rk[off:off + n])
                off += n
                self._finish(r)

    def _fail_pending(self) -> None:
        """Fail every queued request with a tenant-attributed
        EngineClosedError (the fleet twin of the ISSUE 8 bugfix)."""
        with self._cv:
            pending = list(self._pending.items())
            self._pending.clear()
            self._rotation.clear()
            self._n_pending = 0
            self._cv.notify_all()
        for tid, dq in pending:
            for r in dq:
                if not r.future.done():
                    r.future.set_exception(EngineClosedError(
                        "engine closed before the request was applied "
                        f"(tenant={tid})", tenant=tid))
                self._finish(r)

    # ------------------------------------------------------------------ #
    def tenant_stats(self, tid: str) -> dict:
        out = dict(self.fleet.tenant_state(tid) or {"tenant": tid})
        st = self._streams.get(tid)
        if st is not None:
            out["estimate_incomplete"] = st.estimate()
            out["streaming"] = st.state()
        out["auc_exact"] = out.pop("auc", None)
        return out

    def stats(self) -> dict:
        return {
            "metrics": self.metrics.snapshot(),
            "fleet": self.fleet.state(),
            "tenants_live": self.fleet.n_tenants,
        }

    def close(self, timeout: float = 10.0) -> None:
        if self._closed:
            return
        self._closed = True
        with self._cv:
            self._cv.notify_all()
        self._worker.join(timeout=timeout)
        self._fail_pending()
        if self._recovery is not None:
            self._recovery.checkpoint_and_close(self)
        self.fleet.close(timeout=timeout)
        self.flight.record("engine_closed")
        self.flight.auto_dump()

    def __enter__(self) -> "MultiTenantEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# --------------------------------------------------------------------- #
# fleet crash safety                                                     #
# --------------------------------------------------------------------- #

def _fleet_compat_config(config: ServingConfig,
                         tenancy: TenancyConfig) -> dict:
    return {
        "kernel": config.kernel, "budget": config.budget,
        "reservoir": config.reservoir, "design": config.design,
        "window": config.window, "seed": config.seed,
        "max_tenants": tenancy.max_tenants,
    }


def capture_fleet_snapshot_state(engine) -> Tuple[dict, dict]:
    """Consistent cut of EVERY tenant's state (batcher thread, fleet
    lock): containers + log as arrays keyed by a dense tenant index,
    wins2 (decimal strings) + RNG states + the tenant-id manifest in
    the JSON config block. Promoted whales [ISSUE 9] snapshot their
    OWN index's containers through the shared single-index capture
    (``recovery.capture_index_arrays``) under the same ``t{i}_``
    prefix; the manifest's ``promoted`` flags + per-whale meta let the
    restore rebuild the promotion state exactly."""
    from tuplewise_tpu.serving.recovery import capture_index_arrays
    from tuplewise_tpu.utils.rng import capture_np_rng

    fleet = engine.fleet
    extra: dict = {}
    cfg = dict(_fleet_compat_config(engine.config, engine.tenancy))
    tids, wins2, rngs, counters = [], [], [], []
    promoted, whale_meta = [], []
    with fleet._lock:
        for st in fleet._slots:
            if st is None:
                continue
            i = len(tids)
            tids.append(st.tid)
            if st.idx is not None:
                meta = capture_index_arrays(st.idx, extra,
                                            prefix=f"t{i}_")
                promoted.append(True)
                whale_meta.append(meta)
                wins2.append(meta["wins2"])
                counters.append([meta["n_evicted"],
                                 meta["n_compactions"]])
            else:
                promoted.append(False)
                whale_meta.append(None)
                wins2.append(str(st.wins2))
                counters.append([st.n_evicted, st.n_compactions])
                for name, pos in (("pos", True), ("neg", False)):
                    base, buf, tomb = st.side(pos)
                    extra[f"t{i}_{name}_base"] = np.asarray(
                        base, dtype=fleet.dtype)
                    extra[f"t{i}_{name}_buf"] = np.asarray(
                        buf, dtype=fleet.dtype)
                    extra[f"t{i}_{name}_tomb"] = np.asarray(
                        tomb, dtype=fleet.dtype)
                extra[f"t{i}_log_scores"] = np.asarray(
                    [v for v, _ in st.log], dtype=fleet.dtype)
                extra[f"t{i}_log_labels"] = np.asarray(
                    [p for _, p in st.log], dtype=bool)
            stream = engine._streams[st.tid]
            extra[f"t{i}_stream_sums"] = np.asarray(
                [stream._sum_h, stream._sum_h2], dtype=np.float64)
            extra[f"t{i}_stream_counts"] = np.asarray(
                [stream._n_terms, stream.n_arrivals], dtype=np.int64)
            for rname, res in (("rpos", stream._pos),
                               ("rneg", stream._neg)):
                extra[f"t{i}_{rname}_items"] = res.items[: res.size].copy()
                extra[f"t{i}_{rname}_meta"] = np.asarray(
                    [res.size, res.seen], dtype=np.int64)
            rngs.append(capture_np_rng(stream._rng))
    cfg["tenants"] = tids
    cfg["wins2"] = wins2
    cfg["tenant_counters"] = counters
    cfg["rng_states"] = rngs
    cfg["promoted"] = promoted
    cfg["whale_meta"] = whale_meta
    return extra, cfg


def restore_fleet_snapshot(directory: str, engine) -> Optional[int]:
    """Restore a fleet snapshot into a fresh engine; returns the
    snapshot's event seq (None when no snapshot exists)."""
    from tuplewise_tpu.utils.checkpoint import load_checkpoint
    from tuplewise_tpu.utils.rng import restore_np_rng

    ck = load_checkpoint(os.path.join(directory, "snapshot.npz"))
    if ck is None:
        return None
    cfg, extra = ck["config"], ck["extra"]
    want = _fleet_compat_config(engine.config, engine.tenancy)
    check_config({k: cfg.get(k) for k in want}, want)
    fleet = engine.fleet
    promoted = cfg.get("promoted") or [False] * len(cfg["tenants"])
    whale_meta = cfg.get("whale_meta") or [None] * len(cfg["tenants"])
    with fleet._lock:
        for i, tid in enumerate(cfg["tenants"]):
            engine.create_tenant(tid)
            st = fleet._by_tid[tid]
            if promoted[i]:
                # rebuild the whale's own index from its captured
                # containers [ISSUE 9] — same restore the single-
                # tenant engine runs, under the t{i}_ prefix
                from tuplewise_tpu.serving.recovery import (
                    restore_index_arrays,
                )

                idx = fleet._make_whale_index()
                restore_index_arrays(idx, extra, whale_meta[i],
                                     prefix=f"t{i}_")
                st.idx = idx
                fleet._g_whales.set(fleet._n_whales())
            else:
                for name, pos in (("pos", True), ("neg", False)):
                    base = extra[f"t{i}_{name}_base"].astype(
                        fleet.dtype)
                    buf = extra[f"t{i}_{name}_buf"].astype(
                        fleet.dtype).tolist()
                    tomb = extra[f"t{i}_{name}_tomb"].astype(
                        fleet.dtype).tolist()
                    if pos:
                        st.pos_base, st.pos_buf, st.pos_tomb = \
                            base, buf, tomb
                    else:
                        st.neg_base, st.neg_buf, st.neg_tomb = \
                            base, buf, tomb
                st.log = collections.deque(zip(
                    extra[f"t{i}_log_scores"].astype(
                        fleet.dtype).tolist(),
                    [bool(b) for b in extra[f"t{i}_log_labels"]]))
                st.wins2 = int(cfg["wins2"][i])
                st.n_evicted, st.n_compactions = (
                    int(x) for x in cfg["tenant_counters"][i])
            stream = engine._streams[tid]
            stream._sum_h, stream._sum_h2 = (
                float(x) for x in extra[f"t{i}_stream_sums"])
            stream._n_terms, stream.n_arrivals = (
                int(x) for x in extra[f"t{i}_stream_counts"])
            for rname, res in (("rpos", stream._pos),
                               ("rneg", stream._neg)):
                size, seen = (int(x) for x in extra[f"t{i}_{rname}_meta"])
                res.items[:size] = extra[f"t{i}_{rname}_items"]
                res.size, res.seen = size, seen
            restore_np_rng(stream._rng, cfg["rng_states"][i])
        fleet._pos_pack.mark_all()
        fleet._neg_pack.mark_all()
    return int(ck["step"])


class FleetRecoveryManager(RecoveryManager):
    """The fleet's recovery manager: same WAL/segment/async-writer
    protocol, fleet-shaped capture/restore, tenant-tagged replay."""

    def _capture(self, engine):
        return capture_fleet_snapshot_state(engine)

    def _restore(self, engine):
        return restore_fleet_snapshot(self.directory, engine)

    def _replay_entry(self, engine, rec: dict) -> None:
        tid = str(rec.get("t", "default"))
        scores = np.asarray(rec["s"], dtype=np.float64)
        labels = np.asarray(rec["l"], dtype=bool)
        engine.create_tenant(tid)
        engine.fleet.apply_inserts([(tid, scores, labels)])
        engine._streams[tid].extend(scores, labels)
