"""Crash-safe serving recovery: atomic snapshots + a replayable event
tail [ISSUE 3; snapshot writes moved off the batcher thread in ISSUE 4].

The exact index is pure deterministic state: wins2 and the containers
are a function of the admitted event sequence, independent of batching
(that independence is the index's core contract). So crash safety
decomposes into two durable artifacts:

* **Snapshot** — a single-file ``.npz`` of the full estimator state
  (base runs, delta runs + tombstone multiset [ISSUE 5], buffers,
  tombstones, arrival log, wins2 as a decimal
  string — it is an unbounded Python int — plus the incomplete-U sums,
  reservoirs, and host RNG state via ``utils.rng.capture_np_rng``),
  written through ``utils.checkpoint.save_checkpoint`` (fsync'd temp +
  atomic rename: a snapshot either exists completely or not at all).
* **WAL** — an append-only JSONL write-ahead log of admitted insert
  batches, flushed to the OS before the batch is applied. A SIGKILL
  cannot lose an admitted event: file data written via ``write()``
  survives process death. ``wal_fsync="batch"`` additionally fsyncs
  every append, extending the guarantee to machine power loss at
  per-batch latency cost (the documented trade of DESIGN §9; the
  default ``"snapshot"`` fsyncs durable state only when a snapshot
  lands). Each entry carries its absolute event sequence number, so
  replay after a snapshot at seq S skips entries below S — pruning
  racing a crash is harmless.

**Snapshot writes are asynchronous** [ISSUE 4 satellite]: the batcher
thread only *captures* the state (host-array copies under the engine
lock — the atomic handoff) and *seals* the live WAL into a segment
file; the expensive part — ``np.savez`` + fsync + rename — runs on a
side writer thread, so inserts proceed during a slow snapshot. The WAL
is segment-structured to make that safe under concurrent appends:

    events.wal              — the live log (appends land here)
    events.wal.upto<SEQ>    — sealed segments; every entry's seq < SEQ

At capture time (seq = S) the live log is sealed as ``upto S`` and a
fresh live log opened; once the snapshot at S durably lands, the
writer deletes every segment whose name-seq <= S (their entries are
all inside the snapshot). A crash at ANY point leaves snapshot +
segments + live log that replay back to the exact pre-crash state:
replay walks segments in seq order, then the live log, skipping
entries below the snapshot's seq.

Recovery = restore the snapshot, replay the tail. Both operations are
bit-exact: wins2 round-trips through its decimal string, scores
round-trip through JSON's shortest-repr floats, and replaying the tail
runs the *same* ``insert_batch`` integer-count updates the live path
runs — so every post-recovery prefix AUC matches the uninterrupted run
bit-for-bit (``tests/test_chaos_serving.py`` asserts it, including
across a real SIGKILL).
"""

from __future__ import annotations

import collections
import json
import os
import queue
import threading
from typing import Iterator, List, Optional, Tuple

import numpy as np

from tuplewise_tpu.utils.checkpoint import (
    check_config, load_checkpoint, save_checkpoint,
)

SNAPSHOT_FILE = "snapshot.npz"
WAL_FILE = "events.wal"
_SEG_SEP = ".upto"


class EventLog:
    """Append-only JSONL WAL of admitted insert batches.

    ``fsync=True`` (``wal_fsync="batch"``) forces every append to disk
    — durable against power loss, at per-batch fsync latency; the
    default flush-only append survives process death (SIGKILL) but
    rides the page cache.
    """

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._f = open(path, "a", encoding="utf-8")

    def append(self, seq: int, scores: np.ndarray,
               labels: np.ndarray, tenant: Optional[str] = None) -> None:
        rec = {"seq": int(seq),
               "s": [float(x) for x in scores],
               "l": [int(bool(x)) for x in labels]}
        if tenant is not None:
            # tenant namespacing [ISSUE 8]: one physical log, logically
            # namespaced by the tenant tag (thousands of tenants cannot
            # each own a file descriptor); replay groups by it
            rec["t"] = str(tenant)
        self._f.write(json.dumps(rec) + "\n")
        # flush past the process boundary: survives SIGKILL; fsync
        # additionally survives power loss (wal_fsync="batch")
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())

    def seal(self, upto_seq: int) -> str:
        """Rotate the live log aside as an immutable segment holding
        only entries with seq < ``upto_seq``, and reopen a fresh live
        log. Called by the snapshot capture (batcher thread) so the
        async writer can later delete exactly the entries the landed
        snapshot covers, while new appends keep flowing."""
        self._f.close()
        seg = f"{self.path}{_SEG_SEP}{int(upto_seq):020d}"
        os.replace(self.path, seg)
        self._f = open(self.path, "w", encoding="utf-8")
        return seg

    def truncate(self) -> None:
        """Start a fresh live log (synchronous-snapshot path: every
        entry is already inside the snapshot that just landed)."""
        self._f.close()
        self._f = open(self.path, "w", encoding="utf-8")

    def close(self) -> None:
        self._f.close()

    @staticmethod
    def segments(path: str) -> List[Tuple[int, str]]:
        """Sealed (seq, segment_path) pairs for a live-log path, in
        ascending seq order."""
        d, name = os.path.split(path)
        prefix = name + _SEG_SEP
        out = []
        for fn in os.listdir(d or "."):
            if not fn.startswith(prefix):
                continue
            try:
                seq = int(fn[len(prefix):])
            except ValueError:
                continue
            out.append((seq, os.path.join(d, fn)))
        return sorted(out)

    @staticmethod
    def replay_records(path: str) -> Iterator[dict]:
        """Yield raw WAL records (``seq``/``s``/``l`` plus the optional
        tenant tag ``t``); a torn final line (the crash interrupted the
        write) ends the replay cleanly."""
        if not os.path.exists(path):
            return
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    return

    @staticmethod
    def replay(path: str) -> Iterator[Tuple[int, np.ndarray, np.ndarray]]:
        """Yield (seq, scores, labels) entries (tenant tags dropped)."""
        for rec in EventLog.replay_records(path):
            yield (int(rec["seq"]),
                   np.asarray(rec["s"], dtype=np.float64),
                   np.asarray(rec["l"], dtype=bool))

    @staticmethod
    def replay_all_records(path: str) -> Iterator[dict]:
        """Raw records from sealed segments (seq order) then the live
        log — the full surviving tail regardless of where a crash
        landed."""
        for _, seg in EventLog.segments(path):
            yield from EventLog.replay_records(seg)
        yield from EventLog.replay_records(path)

    @staticmethod
    def replay_all(path: str) -> Iterator[Tuple[int, np.ndarray, np.ndarray]]:
        """(seq, scores, labels) over segments then the live log."""
        for rec in EventLog.replay_all_records(path):
            yield (int(rec["seq"]),
                   np.asarray(rec["s"], dtype=np.float64),
                   np.asarray(rec["l"], dtype=bool))


def _compat_config(config) -> dict:
    """The config keys a snapshot must agree on to be resumable —
    anything that changes what the recovered state MEANS."""
    return {
        "kernel": config.kernel, "budget": config.budget,
        "reservoir": config.reservoir, "design": config.design,
        "window": config.window, "engine": config.engine,
        "seed": config.seed,
    }


def capture_index_arrays(idx, extra: dict, prefix: str = "") -> dict:
    """Capture ONE exact index's containers into ``extra`` under
    ``prefix`` and return its meta dict (wins2 as a decimal string —
    it is an unbounded Python int — plus the lifecycle counters).
    Factored out of :func:`capture_snapshot_state` in ISSUE 9 so the
    fleet can snapshot a promoted whale tenant's delta-tiered index
    through the SAME protocol the single-tenant engine uses."""
    with idx._cv:
        for name, side in (("pos", idx._pos), ("neg", idx._neg)):
            # base arrays are rebound, never mutated in place
            # (compaction swaps a NEW merged array in), so aliasing
            # is a consistent capture with no O(n) copy
            extra[f"{prefix}{name}_base"] = np.asarray(side.base,
                                                       dtype=idx.dtype)
            extra[f"{prefix}{name}_buf"] = np.asarray(side.buf,
                                                      dtype=idx.dtype)
            extra[f"{prefix}{name}_tomb"] = np.asarray(side.tomb,
                                                       dtype=idx.dtype)
            # delta-compaction state [ISSUE 5]: the host-
            # authoritative consolidated delta run (plus its
            # fold-trigger minor count) and the sorted tombstone
            # multiset; device placements are a pure cache rebuilt
            # on restore
            extra[f"{prefix}{name}_delta_run"] = np.asarray(
                side.delta_run, dtype=idx.dtype)
            extra[f"{prefix}{name}_delta_minors"] = np.asarray(
                [side.delta_minors], dtype=np.int64)
            extra[f"{prefix}{name}_tomb_run"] = np.asarray(
                side.tomb_run, dtype=idx.dtype)
        extra[f"{prefix}log_scores"] = np.asarray(
            [v for v, _ in idx._log], dtype=idx.dtype)
        extra[f"{prefix}log_labels"] = np.asarray(
            [p for _, p in idx._log], dtype=bool)
        return {
            "wins2": str(idx._wins2),
            "n_compactions": idx.n_compactions,
            "n_evicted": idx.n_evicted,
            "n_major_merges": idx.n_major_merges,
        }


def restore_index_arrays(idx, extra: dict, meta: dict,
                         prefix: str = "") -> None:
    """Restore ONE exact index's containers from a capture made by
    :func:`capture_index_arrays` (same ``prefix``), then rebuild the
    device placements (a pure cache)."""
    with idx._cv:
        for name, side in (("pos", idx._pos), ("neg", idx._neg)):
            side.base = extra[f"{prefix}{name}_base"].astype(idx.dtype)
            side.buf = extra[f"{prefix}{name}_buf"].astype(
                idx.dtype).tolist()
            side.tomb = extra[f"{prefix}{name}_tomb"].astype(
                idx.dtype).tolist()
            # delta run + tombstone multiset [ISSUE 5]; absent in
            # pre-delta snapshots (empty defaults keep them loadable)
            dr = extra.get(f"{prefix}{name}_delta_run")
            side.delta_run = (dr.astype(idx.dtype) if dr is not None
                              else np.empty(0, dtype=idx.dtype))
            dm = extra.get(f"{prefix}{name}_delta_minors")
            side.delta_minors = int(dm[0]) if dm is not None else 0
            tr = extra.get(f"{prefix}{name}_tomb_run")
            side.tomb_run = (tr.astype(idx.dtype) if tr is not None
                             else np.empty(0, dtype=idx.dtype))
        idx._log = collections.deque(zip(
            extra[f"{prefix}log_scores"].astype(idx.dtype).tolist(),
            [bool(b) for b in extra[f"{prefix}log_labels"]]))
        idx._wins2 = int(meta["wins2"])
        idx.n_compactions = int(meta.get("n_compactions", 0))
        idx.n_evicted = int(meta.get("n_evicted", 0))
        idx.n_major_merges = int(meta.get("n_major_merges", 0))
        for side in (idx._pos, idx._neg):
            side.placed_base = None   # force a fresh placement
            idx._place(side)
            idx._replace_deltas(side)


def capture_snapshot_state(engine) -> Tuple[dict, dict]:
    """The atomic handoff [ISSUE 4 satellite]: copy the engine's full
    estimator state into host arrays (cheap — no serialization, no
    disk) and return (extra, cfg) for a writer to persist. Runs on the
    batcher thread under the engine lock, so the capture is a
    consistent cut at the current event seq."""
    # lazy: utils.rng imports jax, and this module now rides the
    # numpy-only import path via serving.tenancy [ISSUE 8]
    from tuplewise_tpu.utils.rng import capture_np_rng

    extra = {}
    cfg = dict(_compat_config(engine.config))
    idx = engine.index
    if idx is not None:
        cfg.update(capture_index_arrays(idx, extra))
    st = engine.streaming
    extra["stream_sums"] = np.asarray([st._sum_h, st._sum_h2],
                                      dtype=np.float64)
    extra["stream_counts"] = np.asarray(
        [st._n_terms, st.n_arrivals], dtype=np.int64)
    for name, res in (("rpos", st._pos), ("rneg", st._neg)):
        extra[f"{name}_items"] = res.items[: res.size].copy()
        extra[f"{name}_meta"] = np.asarray([res.size, res.seen],
                                           dtype=np.int64)
    cfg["rng_state"] = capture_np_rng(st._rng)
    return extra, cfg


def write_snapshot(directory: str, *, seq: int, extra: dict,
                   cfg: dict) -> None:
    """Persist a captured state atomically (fsync'd temp + rename)."""
    save_checkpoint(os.path.join(directory, SNAPSHOT_FILE),
                    step=seq, extra=extra, config=cfg)


def save_snapshot(directory: str, *, seq: int, engine) -> None:
    """Capture + write in one (synchronous) call."""
    extra, cfg = capture_snapshot_state(engine)
    write_snapshot(directory, seq=seq, extra=extra, cfg=cfg)


def restore_snapshot(directory: str, engine) -> Optional[int]:
    """Restore a snapshot into a freshly-constructed engine; returns
    the snapshot's event seq, or None when no snapshot exists. Raises
    if the stored config is incompatible with the engine's (resuming a
    different experiment would silently corrupt the statistic)."""
    from tuplewise_tpu.utils.rng import restore_np_rng

    ck = load_checkpoint(os.path.join(directory, SNAPSHOT_FILE))
    if ck is None:
        return None
    cfg, extra = ck["config"], ck["extra"]
    check_config(
        {k: cfg.get(k) for k in _compat_config(engine.config)},
        _compat_config(engine.config))
    idx = engine.index
    if idx is not None and "pos_base" in extra:
        restore_index_arrays(idx, extra, cfg)
    st = engine.streaming
    st._sum_h, st._sum_h2 = (float(x) for x in extra["stream_sums"])
    st._n_terms, st.n_arrivals = (int(x) for x in extra["stream_counts"])
    for name, res in (("rpos", st._pos), ("rneg", st._neg)):
        size, seen = (int(x) for x in extra[f"{name}_meta"])
        res.items[:size] = extra[f"{name}_items"]
        res.size, res.seen = size, seen
    restore_np_rng(st._rng, cfg["rng_state"])
    return int(ck["step"])


class RecoveryManager:
    """Owns a recovery directory: the WAL, the snapshot cadence, the
    async writer, and the recover-on-start protocol. One per engine;
    capture/record calls arrive on the batcher thread (or before the
    worker starts) — the internal lock only coordinates with the side
    writer thread."""

    def __init__(self, directory: str, snapshot_every: int = 4096,
                 wal_fsync: str = "snapshot",
                 snapshot_async: bool = True, tracer=None, flight=None):
        if wal_fsync not in ("snapshot", "batch"):
            raise ValueError(
                f"wal_fsync must be 'snapshot' or 'batch': {wal_fsync!r}")
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.snapshot_every = snapshot_every
        self.wal_fsync = wal_fsync
        self.snapshot_async = snapshot_async
        # observability [ISSUE 6]: snapshot/WAL lifecycle goes to the
        # flight recorder; captures/writes become spans. The flight
        # ring is ALSO dumped whenever a snapshot lands, so the
        # forensics file next to snapshot.npz is never older than the
        # state it explains.
        self.tracer = tracer
        self.flight = flight
        self._wal: Optional[EventLog] = None
        self._seq = 0
        self._since_snapshot = 0
        self._lock = threading.Lock()
        self._inflight = False          # one async write at a time
        self._jobs: "queue.Queue[Optional[tuple]]" = queue.Queue()
        self._writer: Optional[threading.Thread] = None
        self.last_snapshot_error: Optional[str] = None
        self._write_test_hook = None    # tests: called before the write

    def _wal_path(self) -> str:
        return os.path.join(self.directory, WAL_FILE)

    def _open_wal(self) -> EventLog:
        return EventLog(self._wal_path(), fsync=self.wal_fsync == "batch")

    # ------------------------------------------------------------------ #
    def start_fresh(self) -> None:
        """A non-recovering start owns the directory: stale state from
        a previous run must not leak into a later --recover."""
        snap = os.path.join(self.directory, SNAPSHOT_FILE)
        if os.path.exists(snap):
            os.unlink(snap)
        for _, seg in EventLog.segments(self._wal_path()):
            os.unlink(seg)
        self._wal = self._open_wal()
        self._wal.truncate()

    # the engine-shape seam [ISSUE 8]: a manager subclass (the
    # multi-tenant fleet's) swaps what a snapshot captures/restores and
    # how a WAL record is re-applied, while the WAL/segment/async-writer
    # protocol stays ONE implementation
    def _capture(self, engine) -> Tuple[dict, dict]:
        return capture_snapshot_state(engine)

    def _restore(self, engine) -> Optional[int]:
        return restore_snapshot(self.directory, engine)

    def _replay_entry(self, engine, rec: dict) -> None:
        scores = np.asarray(rec["s"], dtype=np.float64)
        labels = np.asarray(rec["l"], dtype=bool)
        if engine.index is not None:
            engine.index.insert_batch(scores, labels)
        engine.streaming.extend(scores, labels)

    def recover(self, engine) -> int:
        """Snapshot + tail replay (sealed segments, then the live
        log); returns the recovered event seq."""
        seq = self._restore(engine) or 0
        for rec in EventLog.replay_all_records(self._wal_path()):
            if int(rec["seq"]) < seq:
                continue    # already inside the snapshot
            self._replay_entry(engine, rec)
            seq = int(rec["seq"]) + len(rec["s"])
        self._seq = seq
        self._wal = self._open_wal()
        return seq

    # ------------------------------------------------------------------ #
    def record(self, scores: np.ndarray, labels: np.ndarray,
               tenant: Optional[str] = None) -> None:
        self._wal.append(self._seq, scores, labels, tenant=tenant)
        self._seq += len(scores)
        self._since_snapshot += len(scores)

    def maybe_snapshot(self, engine) -> None:
        if self._since_snapshot < self.snapshot_every:
            return
        if not self.snapshot_async:
            self.snapshot(engine)
            return
        with self._lock:
            if self._inflight:
                # a slow write is still landing: keep serving (and keep
                # accruing _since_snapshot); the next insert after it
                # lands triggers the capture
                return
            self._inflight = True
        # the atomic handoff: capture host copies + seal the live WAL
        # on this (batcher) thread — cheap; the np.savez + fsync +
        # rename runs on the writer thread
        from tuplewise_tpu.obs.tracing import maybe_span

        seq = self._seq
        with maybe_span(self.tracer, "snapshot.capture", seq=seq):
            extra, cfg = self._capture(engine)
            self._wal.seal(seq)
        if self.flight is not None:
            self.flight.record("wal_seal", seq=seq)
        self._since_snapshot = 0
        self._ensure_writer()
        self._jobs.put((seq, extra, cfg))

    def snapshot(self, engine) -> None:
        """Synchronous capture + write (close path, and the
        ``snapshot_async=False`` escape hatch)."""
        extra, cfg = self._capture(engine)
        write_snapshot(self.directory, seq=self._seq, extra=extra,
                       cfg=cfg)
        if self.flight is not None:
            self.flight.record("snapshot_landed", seq=self._seq,
                               mode="sync")
            self.flight.auto_dump()
        self._prune_segments(self._seq)
        # safe to prune only AFTER the snapshot atomically landed; a
        # crash in between leaves WAL entries below seq, which replay
        # skips
        self._wal.truncate()
        self._since_snapshot = 0

    # ------------------------------------------------------------------ #
    # side writer thread [ISSUE 4 satellite]                             #
    # ------------------------------------------------------------------ #
    def _ensure_writer(self) -> None:
        if self._writer is None or not self._writer.is_alive():
            self._writer = threading.Thread(
                target=self._write_worker, name="tuplewise-snapshotter",
                daemon=True)
            self._writer.start()

    def _write_worker(self) -> None:
        while True:
            job = self._jobs.get()
            try:
                if job is None:
                    return
                seq, extra, cfg = job
                try:
                    from tuplewise_tpu.obs.tracing import maybe_span

                    if self._write_test_hook is not None:
                        self._write_test_hook(seq)
                    with maybe_span(self.tracer, "snapshot.write",
                                    seq=seq):
                        write_snapshot(self.directory, seq=seq,
                                       extra=extra, cfg=cfg)
                    if self.flight is not None:
                        self.flight.record("snapshot_landed", seq=seq,
                                           mode="async")
                        # forensics freshness: the dump next to
                        # snapshot.npz reflects at least this seal
                        self.flight.auto_dump()
                    self._prune_segments(seq)
                except BaseException as e:   # noqa: BLE001 — kept, not raised
                    # a failed write loses nothing: the sealed segments
                    # it would have pruned still replay over the OLD
                    # snapshot; record the error for stats()/operators
                    self.last_snapshot_error = repr(e)
                    if self.flight is not None:
                        self.flight.record("snapshot_error", seq=seq,
                                           error=repr(e))
            finally:
                with self._lock:
                    self._inflight = False
                self._jobs.task_done()

    def _prune_segments(self, landed_seq: int) -> None:
        """Delete sealed segments fully covered by the snapshot that
        just landed (name-seq <= landed seq: every entry is < it)."""
        for seq, seg in EventLog.segments(self._wal_path()):
            if seq <= landed_seq:
                try:
                    os.unlink(seg)
                except OSError:
                    pass    # already pruned (or raced a fresh start)

    def _drain_writer(self) -> None:
        """Block until every queued async write has landed (or
        failed) — ordering guard so a final synchronous snapshot can
        never be overwritten by an older async one."""
        if self._writer is not None:
            self._jobs.join()

    def checkpoint_and_close(self, engine) -> None:
        """Graceful shutdown: drain the async writer, take one final
        snapshot so restart is tail-free, then release the WAL."""
        if self._wal is None:
            return
        self._drain_writer()
        if self._since_snapshot:
            self.snapshot(engine)
        if self._writer is not None and self._writer.is_alive():
            self._jobs.put(None)
            self._writer.join(timeout=10.0)
        self._wal.close()
        self._wal = None

    @property
    def seq(self) -> int:
        return self._seq
