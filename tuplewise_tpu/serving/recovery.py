"""Crash-safe serving recovery: atomic snapshots + a replayable event
tail [ISSUE 3].

The exact index is pure deterministic state: wins2 and the containers
are a function of the admitted event sequence, independent of batching
(that independence is the index's core contract). So crash safety
decomposes into two durable artifacts:

* **Snapshot** — a single-file ``.npz`` of the full estimator state
  (base runs, buffers, tombstones, arrival log, wins2 as a decimal
  string — it is an unbounded Python int — plus the incomplete-U sums,
  reservoirs, and host RNG state), written through
  ``utils.checkpoint.save_checkpoint`` (fsync'd temp + atomic rename:
  a snapshot either exists completely or not at all).
* **WAL** — an append-only JSONL write-ahead log of admitted insert
  batches, flushed to the OS before the batch is applied. A SIGKILL
  cannot lose an admitted event: file data written via ``write()``
  survives process death. Each entry carries its absolute event
  sequence number, so replay after a snapshot at seq S skips entries
  below S — truncation racing a crash is harmless.

Recovery = restore the snapshot, replay the tail. Both operations are
bit-exact: wins2 round-trips through its decimal string, scores
round-trip through JSON's shortest-repr floats, and replaying the tail
runs the *same* ``insert_batch`` integer-count updates the live path
runs — so every post-recovery prefix AUC matches the uninterrupted run
bit-for-bit (``tests/test_chaos_serving.py`` asserts it, including
across a real SIGKILL).
"""

from __future__ import annotations

import collections
import json
import os
from typing import Iterator, Optional, Tuple

import numpy as np

from tuplewise_tpu.utils.checkpoint import (
    check_config, load_checkpoint, save_checkpoint,
)

SNAPSHOT_FILE = "snapshot.npz"
WAL_FILE = "events.wal"


class EventLog:
    """Append-only JSONL WAL of admitted insert batches."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a", encoding="utf-8")

    def append(self, seq: int, scores: np.ndarray,
               labels: np.ndarray) -> None:
        rec = {"seq": int(seq),
               "s": [float(x) for x in scores],
               "l": [int(bool(x)) for x in labels]}
        self._f.write(json.dumps(rec) + "\n")
        # flush past the process boundary: survives SIGKILL (os.fsync
        # would additionally survive power loss, at per-batch cost —
        # the snapshot path IS fsync'd, so a machine crash loses at
        # most the tail since the last snapshot)
        self._f.flush()

    def truncate(self) -> None:
        """Start a fresh log (called right after a snapshot lands)."""
        self._f.close()
        self._f = open(self.path, "w", encoding="utf-8")

    def close(self) -> None:
        self._f.close()

    @staticmethod
    def replay(path: str) -> Iterator[Tuple[int, np.ndarray, np.ndarray]]:
        """Yield (seq, scores, labels) entries; a torn final line (the
        crash interrupted the write) ends the replay cleanly."""
        if not os.path.exists(path):
            return
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    return
                yield (int(rec["seq"]),
                       np.asarray(rec["s"], dtype=np.float64),
                       np.asarray(rec["l"], dtype=bool))


def _compat_config(config) -> dict:
    """The config keys a snapshot must agree on to be resumable —
    anything that changes what the recovered state MEANS."""
    return {
        "kernel": config.kernel, "budget": config.budget,
        "reservoir": config.reservoir, "design": config.design,
        "window": config.window, "engine": config.engine,
        "seed": config.seed,
    }


def save_snapshot(directory: str, *, seq: int, engine) -> None:
    """Capture the engine's full estimator state atomically."""
    extra = {}
    cfg = dict(_compat_config(engine.config))
    idx = engine.index
    if idx is not None:
        with idx._cv:
            for name, side in (("pos", idx._pos), ("neg", idx._neg)):
                extra[f"{name}_base"] = np.asarray(side.base,
                                                   dtype=idx.dtype)
                extra[f"{name}_buf"] = np.asarray(side.buf,
                                                  dtype=idx.dtype)
                extra[f"{name}_tomb"] = np.asarray(side.tomb,
                                                   dtype=idx.dtype)
            extra["log_scores"] = np.asarray(
                [v for v, _ in idx._log], dtype=idx.dtype)
            extra["log_labels"] = np.asarray(
                [p for _, p in idx._log], dtype=bool)
            # wins2 is an unbounded Python int: a decimal string is the
            # only exact serialization
            cfg["wins2"] = str(idx._wins2)
            cfg["n_compactions"] = idx.n_compactions
            cfg["n_evicted"] = idx.n_evicted
    st = engine.streaming
    extra["stream_sums"] = np.asarray([st._sum_h, st._sum_h2],
                                      dtype=np.float64)
    extra["stream_counts"] = np.asarray(
        [st._n_terms, st.n_arrivals], dtype=np.int64)
    for name, res in (("rpos", st._pos), ("rneg", st._neg)):
        extra[f"{name}_items"] = res.items[: res.size].copy()
        extra[f"{name}_meta"] = np.asarray([res.size, res.seen],
                                           dtype=np.int64)
    cfg["rng_state"] = st._rng.bit_generator.state
    save_checkpoint(os.path.join(directory, SNAPSHOT_FILE),
                    step=seq, extra=extra, config=cfg)


def restore_snapshot(directory: str, engine) -> Optional[int]:
    """Restore a snapshot into a freshly-constructed engine; returns
    the snapshot's event seq, or None when no snapshot exists. Raises
    if the stored config is incompatible with the engine's (resuming a
    different experiment would silently corrupt the statistic)."""
    ck = load_checkpoint(os.path.join(directory, SNAPSHOT_FILE))
    if ck is None:
        return None
    cfg, extra = ck["config"], ck["extra"]
    check_config(
        {k: cfg.get(k) for k in _compat_config(engine.config)},
        _compat_config(engine.config))
    idx = engine.index
    if idx is not None and "pos_base" in extra:
        with idx._cv:
            for name, side in (("pos", idx._pos), ("neg", idx._neg)):
                side.base = extra[f"{name}_base"].astype(idx.dtype)
                side.buf = extra[f"{name}_buf"].astype(
                    idx.dtype).tolist()
                side.tomb = extra[f"{name}_tomb"].astype(
                    idx.dtype).tolist()
            idx._log = collections.deque(zip(
                extra["log_scores"].astype(idx.dtype).tolist(),
                [bool(b) for b in extra["log_labels"]]))
            idx._wins2 = int(cfg["wins2"])
            idx.n_compactions = int(cfg["n_compactions"])
            idx.n_evicted = int(cfg["n_evicted"])
            idx._place(idx._pos)
            idx._place(idx._neg)
    st = engine.streaming
    st._sum_h, st._sum_h2 = (float(x) for x in extra["stream_sums"])
    st._n_terms, st.n_arrivals = (int(x) for x in extra["stream_counts"])
    for name, res in (("rpos", st._pos), ("rneg", st._neg)):
        size, seen = (int(x) for x in extra[f"{name}_meta"])
        res.items[:size] = extra[f"{name}_items"]
        res.size, res.seen = size, seen
    st._rng.bit_generator.state = cfg["rng_state"]
    return int(ck["step"])


class RecoveryManager:
    """Owns a recovery directory: the WAL, the snapshot cadence, and
    the recover-on-start protocol. One per engine; all calls arrive on
    the batcher thread (or before the worker starts), so no lock."""

    def __init__(self, directory: str, snapshot_every: int = 4096):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.snapshot_every = snapshot_every
        self._wal: Optional[EventLog] = None
        self._seq = 0
        self._since_snapshot = 0

    # ------------------------------------------------------------------ #
    def start_fresh(self) -> None:
        """A non-recovering start owns the directory: stale state from
        a previous run must not leak into a later --recover."""
        snap = os.path.join(self.directory, SNAPSHOT_FILE)
        if os.path.exists(snap):
            os.unlink(snap)
        self._wal = EventLog(os.path.join(self.directory, WAL_FILE))
        self._wal.truncate()

    def recover(self, engine) -> int:
        """Snapshot + tail replay; returns the recovered event seq."""
        seq = restore_snapshot(self.directory, engine) or 0
        for s0, scores, labels in EventLog.replay(
                os.path.join(self.directory, WAL_FILE)):
            if s0 < seq:
                continue    # already inside the snapshot
            if engine.index is not None:
                engine.index.insert_batch(scores, labels)
            engine.streaming.extend(scores, labels)
            seq = s0 + len(scores)
        self._seq = seq
        self._wal = EventLog(os.path.join(self.directory, WAL_FILE))
        return seq

    # ------------------------------------------------------------------ #
    def record(self, scores: np.ndarray, labels: np.ndarray) -> None:
        self._wal.append(self._seq, scores, labels)
        self._seq += len(scores)
        self._since_snapshot += len(scores)

    def maybe_snapshot(self, engine) -> None:
        if self._since_snapshot >= self.snapshot_every:
            self.snapshot(engine)

    def snapshot(self, engine) -> None:
        save_snapshot(self.directory, seq=self._seq, engine=engine)
        # safe to prune only AFTER the snapshot atomically landed; a
        # crash in between leaves WAL entries below seq, which replay
        # skips
        self._wal.truncate()
        self._since_snapshot = 0

    def checkpoint_and_close(self, engine) -> None:
        """Graceful shutdown: one final snapshot so restart is
        tail-free, then release the WAL handle."""
        if self._wal is None:
            return
        if self._since_snapshot:
            self.snapshot(engine)
        self._wal.close()
        self._wal = None

    @property
    def seq(self) -> int:
        return self._seq
