"""Incremental exact-AUC index — the serving-side twin of ops.rank_auc.

The batch fast path (``ops/rank_auc.py``) sorts once and binary-searches;
a service cannot re-sort 10^7 scores per arrival. This index keeps the
Mann-Whitney statistic *incrementally exact* under inserts and
sliding-window evictions by maintaining the integer pair-win count

    wins2 = sum over current (p, n) pairs of  2*1{p > n} + 1{p = n}

as a Python int (arbitrary precision — exact to any n), so

    AUC = wins2 / (2 * n_pos * n_neg)

matches the batch ``rank_auc`` / NumPy midrank oracle on the same
multiset to one final float division. Every mutation updates wins2 with
*counts* (binary searches), never with float accumulation, so the
estimate is bit-stable across compaction boundaries by construction:
compaction moves values between containers and never touches wins2.

Per class the container is LSM-shaped:

    base: sorted array  (searchsorted: O(log n))
    buf:  small unsorted recent-insert buffer (linear scan, bounded)
    tomb: evicted values still physically inside base (negative counts)

so an insert is O(log n + |buf|) with |buf| bounded by
``compact_every``; when a buffer fills, a *compaction* merges it into
the base run with one padded size-bucketed jitted sort (engine="jax")
or a host merge (engine="numpy"). Counts against base run through a
bucket-padded jitted searchsorted pair, keeping the steady-state hot
path inside XLA with O(log n) distinct compiled shapes.

Scores must be finite (the +inf bucket padding relies on it).
"""

from __future__ import annotations

import collections
import functools
from typing import Deque, List, Optional, Tuple

import numpy as np

_MIN_BUCKET = 256


def _next_bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return b


def _remove_sorted(arr: np.ndarray, values: List[float]) -> np.ndarray:
    """Remove one occurrence per entry of ``values`` from sorted
    ``arr`` in a single pass (duplicate values consume consecutive
    slots). Every value must be present — tombstones reference scores
    that were inserted."""
    if not values:
        return arr
    idxs = []
    prev, run = None, 0
    for t in sorted(values):
        run = run + 1 if t == prev else 0
        prev = t
        i = int(np.searchsorted(arr, t, side="left")) + run
        assert i < len(arr) and arr[i] == t, "tombstone value not present"
        idxs.append(i)
    return np.delete(arr, idxs)


@functools.lru_cache(maxsize=None)
def _jit_count_fn(base_bucket: int, q_bucket: int):
    """(sorted base padded with +inf, queries padded) -> (less, leq)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(base, queries):
        less = jnp.searchsorted(base, queries, side="left")
        leq = jnp.searchsorted(base, queries, side="right")
        return less, leq

    return f


@functools.lru_cache(maxsize=None)
def _jit_sort_fn(bucket: int):
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda x: jnp.sort(x))


class _ClassSide:
    """One class's LSM container: sorted base + buffer + tombstones."""

    def __init__(self, dtype):
        self.dtype = dtype
        self.base = np.empty(0, dtype=dtype)
        self.buf: List[float] = []
        self.tomb: List[float] = []

    @property
    def size(self) -> int:
        return len(self.base) + len(self.buf) - len(self.tomb)

    def values(self) -> np.ndarray:
        """Current multiset as an array (oracle/debug path, O(n))."""
        out = np.concatenate(
            [self.base, np.asarray(self.buf, dtype=self.dtype)]
        )
        out = np.sort(out, kind="stable")
        return _remove_sorted(out, self.tomb)


class ExactAucIndex:
    """Streaming exact AUC with O(log n) amortized inserts.

    Args:
      window: retain only the last ``window`` arrivals (across both
        classes); None = unbounded.
      compact_every: buffer/tombstone size that triggers a compaction.
      engine: "jax" — bucket-padded jitted searchsorted + compaction
        sort (values stored float32, jax's default precision); "numpy" —
        host searchsorted (values stored float64).
    """

    def __init__(self, window: Optional[int] = None,
                 compact_every: int = 512, engine: str = "jax"):
        if engine not in ("jax", "numpy"):
            raise ValueError(f"engine must be 'jax' or 'numpy': {engine!r}")
        if window is not None and window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        if compact_every < 1:
            raise ValueError(f"compact_every must be >= 1: {compact_every}")
        self.window = window
        self.compact_every = compact_every
        self.engine = engine
        self.dtype = np.float32 if engine == "jax" else np.float64
        self._pos = _ClassSide(self.dtype)
        self._neg = _ClassSide(self.dtype)
        # arrival order for window eviction: (value, is_pos)
        self._log: Deque[Tuple[float, bool]] = collections.deque()
        self._wins2 = 0          # exact: Python int never overflows
        self.n_compactions = 0
        self.n_evicted = 0

    # ------------------------------------------------------------------ #
    # counting primitives (all integer-exact)                            #
    # ------------------------------------------------------------------ #
    def _base_counts(self, side: _ClassSide,
                     q: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(less, leq) counts of each query against side.base."""
        if len(side.base) == 0 or len(q) == 0:
            z = np.zeros(len(q), dtype=np.int64)
            return z, z
        if self.engine == "jax":
            bb = _next_bucket(len(side.base))
            qb = _next_bucket(len(q))
            base_p = np.full(bb, np.inf, dtype=self.dtype)
            base_p[: len(side.base)] = side.base
            q_p = np.zeros(qb, dtype=self.dtype)
            q_p[: len(q)] = q
            less, leq = _jit_count_fn(bb, qb)(base_p, q_p)
            return (np.asarray(less)[: len(q)].astype(np.int64),
                    np.asarray(leq)[: len(q)].astype(np.int64))
        less = np.searchsorted(side.base, q, side="left")
        leq = np.searchsorted(side.base, q, side="right")
        return less.astype(np.int64), leq.astype(np.int64)

    def _counts(self, side: _ClassSide,
                q: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(less, eq) of each query against side's CURRENT multiset."""
        q = np.asarray(q, dtype=self.dtype)
        less, leq = self._base_counts(side, q)
        eq = leq - less
        for vals, sign in ((side.buf, 1), (side.tomb, -1)):
            if not vals:
                continue
            arr = np.sort(np.asarray(vals, dtype=self.dtype))
            l2 = np.searchsorted(arr, q, side="left").astype(np.int64)
            r2 = np.searchsorted(arr, q, side="right").astype(np.int64)
            less += sign * l2
            eq += sign * (r2 - l2)
        return less, eq

    def _cross2(self, p_vals: np.ndarray, n_side: _ClassSide) -> int:
        """sum over p of 2*count_less(p in negs) + count_eq: the wins2
        contribution of positives ``p_vals`` against class ``n_side``."""
        if len(p_vals) == 0 or n_side.size == 0:
            return 0
        less, eq = self._counts(n_side, p_vals)
        return int(2 * less.sum() + eq.sum())

    def _cross2_rev(self, n_vals: np.ndarray, p_side: _ClassSide) -> int:
        """wins2 contribution of pairs (p in p_side, n in n_vals): the
        flipped count — per negative, 2*count_pos_greater + count_pos_eq
        — from the same (less, eq) container searches."""
        if len(n_vals) == 0 or p_side.size == 0:
            return 0
        less, eq = self._counts(p_side, n_vals)
        greater = p_side.size - less - eq
        return int(2 * greater.sum() + eq.sum())

    @staticmethod
    def _cross2_arrays(p: np.ndarray, n: np.ndarray) -> int:
        """wins2 between two plain arrays (intra-batch pairs)."""
        if len(p) == 0 or len(n) == 0:
            return 0
        ns = np.sort(n)
        less = np.searchsorted(ns, p, side="left").astype(np.int64)
        leq = np.searchsorted(ns, p, side="right").astype(np.int64)
        return int(2 * less.sum() + (leq - less).sum())

    # ------------------------------------------------------------------ #
    # mutation                                                           #
    # ------------------------------------------------------------------ #
    def insert_batch(self, scores, labels) -> int:
        """Insert arrivals in order; returns the number inserted.

        ``labels`` truthy = positive class. The pair statistic after the
        call equals the batch statistic over (old set) ∪ (batch) — pair
        sets are order-free — then window eviction trims to the last
        ``window`` arrivals.
        """
        scores = np.asarray(scores, dtype=self.dtype).ravel()
        labels = np.asarray(labels).ravel().astype(bool)
        if scores.shape != labels.shape:
            raise ValueError(
                f"scores/labels length mismatch: {scores.shape} vs "
                f"{labels.shape}")
        if len(scores) and not np.all(np.isfinite(scores)):
            raise ValueError("scores must be finite")
        p_new = scores[labels]
        n_new = scores[~labels]
        # new-vs-old (old sets untouched so far), then new-vs-new
        d = self._cross2(p_new, self._neg)
        d += self._cross2_rev(n_new, self._pos)
        d += self._cross2_arrays(p_new, n_new)
        self._wins2 += d
        self._pos.buf.extend(p_new.tolist())
        self._neg.buf.extend(n_new.tolist())
        for s, is_pos in zip(scores.tolist(), labels.tolist()):
            self._log.append((s, is_pos))
        if self.window is not None and len(self._log) > self.window:
            self._evict(len(self._log) - self.window)
        self._maybe_compact()
        return len(scores)

    def _evict(self, count: int) -> None:
        """Remove the ``count`` oldest arrivals from the statistic."""
        p_out: List[float] = []
        n_out: List[float] = []
        for _ in range(count):
            v, is_pos = self._log.popleft()
            (p_out if is_pos else n_out).append(v)
        p_arr = np.asarray(p_out, dtype=self.dtype)
        n_arr = np.asarray(n_out, dtype=self.dtype)
        # pairs with >= 1 evicted endpoint, inclusion-exclusion: the
        # P_e x N_e block is inside both cross terms (containers still
        # hold the evicted values here, as the identity requires)
        d = self._cross2(p_arr, self._neg)
        d += self._cross2_rev(n_arr, self._pos)
        d -= self._cross2_arrays(p_arr, n_arr)
        self._wins2 -= d
        for side, vals in ((self._pos, p_out), (self._neg, n_out)):
            for v in vals:
                try:
                    side.buf.remove(v)
                except ValueError:
                    side.tomb.append(v)
        self.n_evicted += count

    def _maybe_compact(self) -> None:
        for side in (self._pos, self._neg):
            if (len(side.buf) >= self.compact_every
                    or len(side.tomb) >= self.compact_every):
                self._compact_side(side)

    def compact(self) -> None:
        """Force both sides into a single sorted base run."""
        for side in (self._pos, self._neg):
            if side.buf or side.tomb:
                self._compact_side(side)

    def _compact_side(self, side: _ClassSide) -> None:
        merged = np.concatenate(
            [side.base, np.asarray(side.buf, dtype=self.dtype)])
        n = len(merged)
        if n:
            if self.engine == "jax":
                b = _next_bucket(n)
                padded = np.full(b, np.inf, dtype=self.dtype)
                padded[:n] = merged
                merged = np.asarray(_jit_sort_fn(b)(padded))[:n]
            else:
                merged = np.sort(merged, kind="stable")
        side.base = _remove_sorted(merged, side.tomb)
        side.buf = []
        side.tomb = []
        self.n_compactions += 1

    # ------------------------------------------------------------------ #
    # queries                                                            #
    # ------------------------------------------------------------------ #
    @property
    def n_pos(self) -> int:
        return self._pos.size

    @property
    def n_neg(self) -> int:
        return self._neg.size

    @property
    def n_events(self) -> int:
        return len(self._log)

    def auc(self) -> Optional[float]:
        """Exact AUC of the current window; None until both classes
        have at least one member."""
        if self.n_pos == 0 or self.n_neg == 0:
            return None
        return self._wins2 / (2.0 * self.n_pos * self.n_neg)

    def score_batch(self, scores) -> np.ndarray:
        """Fractional rank of each score against current negatives:
        (count_less + 0.5*count_eq) / n_neg — exactly the per-positive
        quantity ops.rank_auc averages. NaN when no negatives yet."""
        q = np.asarray(scores, dtype=self.dtype).ravel()
        if self.n_neg == 0:
            return np.full(len(q), np.nan)
        less, eq = self._counts(self._neg, q)
        return (less + 0.5 * eq) / float(self.n_neg)

    def oracle_values(self) -> Tuple[np.ndarray, np.ndarray]:
        """(pos, neg) multisets of the current window — feed these to
        the batch oracle in parity tests. O(n); not a hot path."""
        return self._pos.values(), self._neg.values()

    def state(self) -> dict:
        return {
            "n_pos": self.n_pos,
            "n_neg": self.n_neg,
            "n_events": self.n_events,
            "auc": self.auc(),
            "n_compactions": self.n_compactions,
            "n_evicted": self.n_evicted,
            "buf_pos": len(self._pos.buf),
            "buf_neg": len(self._neg.buf),
            "engine": self.engine,
            "window": self.window,
        }
