"""Incremental exact-AUC index — the serving-side twin of ops.rank_auc.

The batch fast path (``ops/rank_auc.py``) sorts once and binary-searches;
a service cannot re-sort 10^7 scores per arrival. This index keeps the
Mann-Whitney statistic *incrementally exact* under inserts and
sliding-window evictions by maintaining the integer pair-win count

    wins2 = sum over current (p, n) pairs of  2*1{p > n} + 1{p = n}

as a Python int (arbitrary precision — exact to any n), so

    AUC = wins2 / (2 * n_pos * n_neg)

matches the batch ``rank_auc`` / NumPy midrank oracle on the same
multiset to one final float division. Every mutation updates wins2 with
*counts* (binary searches), never with float accumulation, so the
estimate is bit-stable across compaction boundaries by construction:
compaction moves values between containers and never touches wins2.

Per class the container is LSM-shaped:

    base: sorted array  (searchsorted: O(log n))
    buf:  small unsorted recent-insert buffer (linear scan, bounded)
    tomb: evicted values still physically inside base (negative counts)

so an insert is O(log n + |buf|) with |buf| bounded by
``compact_every``; when a buffer fills, a *compaction* merges it into
the base run. Counts against base run through a bucket-padded jitted
searchsorted pair, keeping the steady-state hot path inside XLA with
O(log n) distinct compiled shapes.

**Sharded base runs** (``shards=S``): the sorted base is split into S
contiguous slices, one per device of a 1-D mesh (``parallel.mesh`` +
the mesh backend's row placement); each count query runs a per-shard
jitted ``searchsorted`` and psums the integer counts over the mesh
(``parallel.sharded_counts``). Counting is additive over any multiset
partition and integer sums are exact, so the sharded counts — and
therefore wins2 and every AUC — are bit-identical to the single-host
index at every mesh size. The online path scales like the batch ring:
per-shard log-time work plus one reduction.

**Background compaction** (``bg_compact=True``): the merge sort moves
to a side thread with a double-buffered base run. On trigger, the
compactor snapshots (base, buf prefix, tomb prefix) under the lock,
builds the merged run off-lock (the buffer keeps absorbing inserts),
then atomically swaps the new base in and trims the consumed prefixes.
The insert path never blocks on a sort again — its worst pause is the
O(1) pointer swap, recorded in the ``compaction_pause_s`` histogram
(which, in synchronous mode, records full merge durations instead: the
two modes are directly comparable in ``bench.py --streaming``).
Evictions racing a build only remove physical copies from the
*unsnapshotted* buffer suffix; anything else becomes a tombstone
applied at the NEXT build, so the snapshot the compactor merges is
immutable. wins2 is always updated synchronously on the caller's
thread — compaction (foreground or background) never touches it, so
prefix AUCs are bit-identical to the synchronous index under any
interleaving.

**Fault tolerance** [ISSUE 3]: the host is authoritative for the base
runs — the device shards are a pure cache — so a dead/hung mesh device
is survivable: a failed sharded count runs the shared heal-and-retry
protocol (``parallel.self_heal.MeshHealer``, factored out in ISSUE 4
so the batch path shares it): probe the mesh, re-place the runs over
the surviving devices, retry with bounded backoff (``reshard_events`` /
``recovery_time_s`` metrics; bit-identical counts by additivity). A
crashed background build rolls back its snapshot claim (the statistic
is untouched — compaction never writes wins2) and a watchdog restarts
the compactor thread (``bg_compactor_restarts``), falling back to
synchronous compaction for that trigger. Chaos schedules
(``testing.chaos.FaultInjector``) drive both paths deterministically
in tests and CI.

**Delta compaction** [ISSUE 5]: under a mesh, steady-state compaction
cost is proportional to the DELTA, not the base. The paper's whole
trade-off (Vogel et al.) prices communication against statistical
cost, and the PR 2 compaction paid the one cost the analysis warns
against: a full O(n) host splice-merge plus an O(n) host→device
re-placement per O(b) merge buffer. With ``delta_fraction > 0`` (the
default) a sharded index compacts in three tiers:

* **minor** — splice the pending buffer into a small sorted *delta
  run* and place only that run's O(|delta|) bytes on-mesh (bounded by
  ``max_delta_runs`` buffers — never the base); the count kernel sums
  ``base + delta`` under one psum. Exactness is free: counting is
  additive over any partition of the multiset into sorted runs. The
  delta stays ONE consolidated run so every compiled shape follows
  the two bucket ladders, never the compactor's transient backlog.
  Evictions of values living in the base or the delta run become
  tombstones consolidated into a sorted *tombstone multiset*
  (``tomb_run``) whose counts are SUBTRACTED — additivity over signed
  multisets, so every prefix stays bit-identical.
* **major** — when ``|delta| > delta_fraction·|base|`` (or
  ``max_delta_runs`` minors have been folded into the run), merge the
  delta into the base ON-MESH: the host plans per-shard merge
  windows, the jitted kernel all_gathers the (small) delta, exchanges
  base boundary blocks with mesh neighbors (``lax.ppermute``), and
  sorts each output row in place — ZERO host→device bytes (the host
  updates its authoritative copy with the single-allocation splice
  merge). S=1 meshes and plans that would need more than a one-hop
  exchange fall back to the host merge + full re-placement.
* **full** — explicit ``compact()`` or a tombstone multiset outgrowing
  ``delta_fraction·|base|``: everything folds into one run on the
  host and is re-placed (the PR 2 path, kept as the fallback engine).

Every placement is byte-accounted: ``bytes_h2d`` / ``bytes_h2d_saved``
counters and a per-minor ``compaction_bytes`` histogram, plus
``major_merge_s`` / ``major_merges_total`` — the serving-side shuffle
budget, reported by the serve exit summary, ``replay`` records, and
``bench.py --streaming``.

Scores must be finite (the +inf bucket padding relies on it).
"""

from __future__ import annotations

import collections
import functools
import queue
import threading
import time
from typing import Deque, List, Optional, Tuple

import numpy as np

from tuplewise_tpu.obs.ledger import device_section
from tuplewise_tpu.obs.tracing import maybe_span

_MIN_BUCKET = 256


def _next_bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return b


def _splice_merge(base: np.ndarray, new_sorted: np.ndarray) -> np.ndarray:
    """Merge two sorted arrays into ONE pre-sized output buffer.

    The old path (``np.insert(base, np.searchsorted(...), buf)``) built
    an index array and let ``np.insert`` copy the base again through
    its generic slow path; this allocates the output once and writes
    each input exactly once — O(n + b) with a single O(n+b) allocation
    [ISSUE 5 satellite].
    """
    if len(new_sorted) == 0:
        return base
    if len(base) == 0:
        return np.asarray(new_sorted, dtype=base.dtype)
    out = np.empty(len(base) + len(new_sorted), dtype=base.dtype)
    pos = (np.searchsorted(base, new_sorted, side="right")
           + np.arange(len(new_sorted)))
    mask = np.ones(len(out), dtype=bool)
    mask[pos] = False
    out[pos] = new_sorted
    out[mask] = base
    return out


def _remove_sorted(arr: np.ndarray, values: List[float]) -> np.ndarray:
    """Remove one occurrence per entry of ``values`` from sorted
    ``arr`` in a single pass (duplicate values consume consecutive
    slots). Every value must be present — tombstones reference scores
    that were inserted."""
    if not values:
        return arr
    idxs = []
    prev, run = None, 0
    for t in sorted(values):
        run = run + 1 if t == prev else 0
        prev = t
        i = int(np.searchsorted(arr, t, side="left")) + run
        assert i < len(arr) and arr[i] == t, "tombstone value not present"
        idxs.append(i)
    return np.delete(arr, idxs)


@functools.lru_cache(maxsize=None)
def _jit_count_fn(base_bucket: int, q_bucket: int):
    """(sorted base padded with +inf, queries padded) -> (less, leq)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(base, queries):
        less = jnp.searchsorted(base, queries, side="left")
        leq = jnp.searchsorted(base, queries, side="right")
        return less, leq

    return f


@functools.lru_cache(maxsize=None)
def _jit_sort_fn(bucket: int):
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda x: jnp.sort(x))


class _ClassSide:
    """One class's LSM container: sorted base + delta runs + buffer +
    tombstones.

    ``snap_buf``/``snap_tomb`` mark the prefix lengths an in-flight
    background build has snapshotted (0 when idle): mutators must treat
    those prefixes as immutable, and the swap trims exactly them.

    Delta mode [ISSUE 5] adds: ``delta_run`` — the consolidated sorted
    run of every insert not yet folded into the base (mirrored on-mesh
    by the placed ``delta_dev``/``delta_cap``; ``delta_minors`` counts
    the minor compactions merged into it since the last fold);
    ``tomb_run`` — the sorted tombstone multiset (evicted values
    physically inside base/delta) whose counts are subtracted;
    ``placed_base`` — the host array the current device placement
    mirrors (the row-reuse baseline). While a background job runs
    (``building``), the worker owns base, delta_run, tomb_run and
    placed_base exclusively — mutators only append to buf/tomb and
    remove from the unsnapshotted buf suffix.
    """

    def __init__(self, dtype):
        self.dtype = dtype
        self.base = np.empty(0, dtype=dtype)
        self.buf: List[float] = []
        self.tomb: List[float] = []
        self.delta_run = np.empty(0, dtype=dtype)
        self.delta_dev = None
        self.delta_cap = 0
        self.delta_rows = None   # per-shard row occupancy of delta_dev
        self.delta_minors = 0
        self.tomb_run = np.empty(0, dtype=dtype)
        self.base_dev = None     # [S, cap] device shards (sharded mode)
        self.cap = 0
        self.placed_base = None  # host array base_dev mirrors
        # tombstone-run device placement [ISSUE 10]: the count kernel
        # subtracts the tombstone multiset IN-KERNEL (sign −1), so
        # kernel mode mirrors tomb_run on-mesh like the delta run;
        # XLA mode keeps the host-side subtraction and never places it
        self.tomb_dev = None
        self.tomb_cap = 0
        self.placed_tomb = None  # host array tomb_dev mirrors
        self.building = False
        self.snap_buf = 0
        self.snap_tomb = 0

    @property
    def size(self) -> int:
        return (len(self.base) + len(self.delta_run)
                + len(self.buf) - len(self.tomb) - len(self.tomb_run))

    @property
    def pending(self) -> Tuple[int, int]:
        """(buf, tomb) entries NOT already claimed by an in-flight
        build — what a new compaction would consume."""
        return len(self.buf) - self.snap_buf, len(self.tomb) - self.snap_tomb

    def values(self) -> np.ndarray:
        """Current multiset as an array (oracle/debug path, O(n))."""
        out = np.concatenate(
            [self.base, self.delta_run,
             np.asarray(self.buf, dtype=self.dtype)]
        )
        out = np.sort(out, kind="stable")
        return _remove_sorted(out, self.tomb_run.tolist() + self.tomb)


class ExactAucIndex:
    """Streaming exact AUC with O(log n) amortized inserts.

    Args:
      window: retain only the last ``window`` arrivals (across both
        classes); None = unbounded.
      compact_every: buffer/tombstone size that triggers a compaction.
      engine: "jax" — bucket-padded jitted searchsorted + compaction
        sort (values stored float32, jax's default precision); "numpy" —
        host searchsorted (values stored float64).
      shards: None (default) = single-host base runs. An int S >= 1
        shards the base runs over an S-device mesh (engine="jax" only);
        S=1 exercises the mesh path on one device. Counts stay
        bit-identical to the single-host index at every S.
      mesh: an existing ``jax.sharding.Mesh`` to shard over (overrides
        ``shards``); must be 1-D.
      bg_compact: move compaction merges to a side thread with a
        double-buffered base run and an atomic swap; the insert path
        never blocks on a sort.
      delta_fraction: [ISSUE 5] sharded mode only. > 0 (default 0.25)
        enables delta compaction: minor compactions ship an O(buffer)
        delta run instead of re-placing the O(n) base, and a major
        merge folds the delta back in ON-MESH once ``|delta|`` exceeds
        this fraction of the base. 0 restores the PR 2 host-merge +
        full-re-placement path (the comparison baseline in
        ``bench.py --streaming``).
      max_delta_runs: fold the delta run into the base after this many
        minor compactions have been merged into it, regardless of its
        size — bounds the delta run's growth and therefore each
        minor's splice-and-ship cost.
      count_kernel: [ISSUE 10] opt-in Pallas-fused count hot loop
        (engine="jax"): base + delta + tombstone counts run as ONE
        ``ops.pallas_counts`` invocation per device per micro-batch
        (the signed multiset combination accumulates in-kernel; insert
        AND window-eviction queries ride the same dispatch), falling
        back to the XLA searchsorted path automatically on unsupported
        geometry or Mosaic failure. Counts are integers, so
        kernel-vs-XLA results are bit-identical.
        ``TUPLEWISE_SERVING_PALLAS=interpret|off`` overrides
        (interpret force-enables through the Pallas interpreter —
        the CPU/CI mode; off is the kill switch).
      metrics: a ``utils.profiling.MetricsRegistry`` to record
        ``compactions_total`` / ``compaction_pause_s`` into (the engine
        passes its own so pauses surface in ``stats()``); None = a
        private registry.
      chaos: a ``testing.chaos.FaultInjector`` threaded through the
        sharded-count and compactor-build hook points (None = no
        hooks). [ISSUE 3]
      shard_retries: bounded retries of a sharded count query after a
        device failure; each retry is preceded by a self-heal — probe
        the mesh, re-place the host-authoritative base runs over the
        surviving devices — and exponential backoff. Exactness is
        preserved because the host always holds the merged runs; the
        device shards are a pure cache.
      retry_backoff_s: base of the bounded exponential backoff between
        sharded-count retries.
      probe_timeout_s: wall-clock bound on the mesh health probe during
        self-heal (a hung device must not hang the detector).
    """

    def __init__(self, window: Optional[int] = None,
                 compact_every: int = 512, engine: str = "jax",
                 shards: Optional[int] = None, mesh=None,
                 bg_compact: bool = False, metrics=None, chaos=None,
                 shard_retries: int = 3, retry_backoff_s: float = 0.02,
                 probe_timeout_s: float = 5.0,
                 delta_fraction: float = 0.25,
                 max_delta_runs: int = 64,
                 count_kernel: bool = False,
                 tracer=None, flight=None):
        if engine not in ("jax", "numpy"):
            raise ValueError(f"engine must be 'jax' or 'numpy': {engine!r}")
        if window is not None and window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        if compact_every < 1:
            raise ValueError(f"compact_every must be >= 1: {compact_every}")
        if mesh is not None:
            shards = int(np.prod(mesh.devices.shape))
        if shards is not None and shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        if shards is not None and engine != "jax":
            raise ValueError("sharded base runs need engine='jax'")
        if delta_fraction < 0:
            raise ValueError(
                f"delta_fraction must be >= 0: {delta_fraction}")
        if max_delta_runs < 1:
            raise ValueError(
                f"max_delta_runs must be >= 1: {max_delta_runs}")
        self.window = window
        self.compact_every = compact_every
        self.engine = engine
        self.shards = shards
        self.bg_compact = bg_compact
        self.delta_fraction = float(delta_fraction)
        self.max_delta_runs = int(max_delta_runs)
        # delta compaction needs the mesh (the whole point is cutting
        # host->device bytes); single-host mode keeps the plain path
        self._delta = shards is not None and self.delta_fraction > 0
        # Pallas-fused counts [ISSUE 10]: resolve the dispatch mode
        # once (config opt-in + env override via the shared resolver);
        # the resolve costs a jax import, so skip it entirely when the
        # kernel can't be on
        self.count_kernel = bool(count_kernel)
        self._ck = False          # kernel active for this index
        self._ck_interp = False   # Pallas interpret flag when active
        import os as _os

        if engine == "jax" and (count_kernel
                                or _os.environ.get(
                                    "TUPLEWISE_SERVING_PALLAS")):
            import jax

            from tuplewise_tpu.ops.pallas_modes import (
                resolve_serving_counts_mode,
            )

            self._ck, self._ck_interp = resolve_serving_counts_mode(
                jax.default_backend(), count_kernel)
        self.chaos = chaos
        self.shard_retries = shard_retries
        self.retry_backoff_s = retry_backoff_s
        self.probe_timeout_s = probe_timeout_s
        self.dtype = np.float32 if engine == "jax" else np.float64
        self._mesh = mesh
        if shards is not None and mesh is None:
            from tuplewise_tpu.parallel.mesh import make_mesh

            self._mesh = make_mesh(shards)
        self._pos = _ClassSide(self.dtype)
        self._neg = _ClassSide(self.dtype)
        # arrival order for window eviction: (value, is_pos)
        self._log: Deque[Tuple[float, bool]] = collections.deque()
        self._wins2 = 0          # exact: Python int never overflows
        self.n_compactions = 0
        self.n_major_merges = 0
        self.n_evicted = 0
        from tuplewise_tpu.utils.profiling import (
            BYTE_BUCKETS, MetricsRegistry,
        )

        self.metrics = metrics if metrics is not None else MetricsRegistry()
        # observability [ISSUE 6]: span tracing + flight recorder are
        # optional references owned by the engine (or a test); every
        # hook costs one `is not None` check when absent
        self.tracer = tracer
        self.flight = flight
        self._c_compactions = self.metrics.counter("compactions_total")
        self._h_pause = self.metrics.histogram("compaction_pause_s")
        # live container gauges [ISSUE 6 satellite]
        self._g_delta = self.metrics.gauge("delta_run_events")
        self._g_tomb = self.metrics.gauge("tombstone_occupancy")
        self._g_mesh = self.metrics.gauge("mesh_width")
        self._g_mesh.set(shards if shards is not None else 0)
        # shard-balance health [ISSUE 7]: skew statistics over
        # per-shard occupancy (base + delta rows) — contiguous-slice
        # placement keeps shards within one row of the mean, so a skew
        # materially above 1 + 1/rows-per-shard is a placement bug
        self._g_skew = self.metrics.gauge("shard_skew")
        self._g_skew_cv = self.metrics.gauge("shard_balance_cv")
        # terminal-failure counter the SLO layer can gate on (the
        # flight ring records the event; the counter makes it a metric)
        self._c_heal_exhausted = self.metrics.counter(
            "heal_exhausted_total")
        # transfer accounting [ISSUE 5]: host->device bytes are the
        # serving-side shuffle budget; place_base feeds the counters,
        # minor compactions feed the per-event histogram
        self._c_bytes = self.metrics.counter("bytes_h2d")
        self._c_bytes_saved = self.metrics.counter("bytes_h2d_saved")
        self._h_compaction_bytes = self.metrics.histogram(
            "compaction_bytes", buckets=BYTE_BUCKETS)
        self._h_major = self.metrics.histogram("major_merge_s")
        self._c_major = self.metrics.counter("major_merges_total")
        self._c_major_fb = self.metrics.counter("major_merge_fallbacks")
        self.last_major_merge_error = None
        # fault-tolerance observability [ISSUE 3]: the reshard/retry/
        # recovery counters are registered here (create-or-return) so
        # snapshots carry them even before any healer exists, and the
        # shared healer below records into the SAME objects
        self.metrics.counter("reshard_events")
        self.metrics.counter("shard_retries_total")
        self.metrics.histogram("recovery_time_s")
        self._c_bg_restarts = self.metrics.counter("bg_compactor_restarts")
        # fused-count observability [ISSUE 10]: calls = kernel
        # dispatches (the per-micro-batch witness the bench cell
        # asserts), fallbacks = geometries served by the XLA twin
        self.metrics.counter("count_kernel_calls_total")
        self.metrics.counter("count_kernel_fallbacks_total")
        # the heal-and-retry protocol now lives in parallel.self_heal
        # [ISSUE 4] — one implementation for serving AND the batch
        # path; shrink policy (fixed_width=None): counts are additive
        # over any partition, so a narrower mesh stays bit-identical
        # query bucket sizes seen so far — the compactor pre-warms the
        # count kernel for (new placement geometry x these buckets)
        # BEFORE each swap, so a compile never lands on the request
        # thread [ISSUE 5]
        self._q_buckets = set()
        self._warmed = set()    # placement geometries already warmed
        self._healer = None
        if shards is not None:
            from tuplewise_tpu.parallel.self_heal import Backoff, MeshHealer

            self._healer = MeshHealer(
                self._mesh, chaos=chaos,
                probe_timeout_s=probe_timeout_s, metrics=self.metrics,
                backoff=Backoff(base_s=retry_backoff_s, cap_s=1.0),
                tracer=tracer, flight=flight)
        # one re-entrant lock guards ALL container structure; the
        # condition signals build completion (compact() drains on it).
        # Synchronous mode takes the same (uncontended) lock — one code
        # path, negligible cost.
        self._cv = threading.Condition(threading.RLock())
        self._closed = False
        self.last_compactor_error = None   # repr of a crashed build
        self._bg_test_hook = None    # tests: called at build start
        if bg_compact:
            self._jobs: "queue.Queue[Optional[_ClassSide]]" = queue.Queue()
            self._compactor = threading.Thread(
                target=self._compact_worker, name="tuplewise-compactor",
                daemon=True)
            self._compactor.start()

    # ------------------------------------------------------------------ #
    # counting primitives (all integer-exact)                            #
    # ------------------------------------------------------------------ #
    def _base_counts(self, side: _ClassSide,
                     q: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(less, leq) counts of each query against side.base plus —
        in delta mode — every placed delta run (one call, one psum).
        Kernel mode [ISSUE 10] additionally folds the tombstone
        multiset in (sign −1) — callers must then skip the host-side
        tomb_run subtraction (``_host_adjust`` keys on ``self._ck``)."""
        if len(q) == 0:
            z = np.zeros(0, dtype=np.int64)
            return z, z
        if self._ck:
            return self._kernel_base_counts(side, q)
        if self.shards is not None:
            if len(side.base) == 0 and len(side.delta_run) == 0:
                z = np.zeros(len(q), dtype=np.int64)
                return z, z
            return self._sharded_base_counts(side, q)
        if len(side.base) == 0:
            z = np.zeros(len(q), dtype=np.int64)
            return z, z
        if self.engine == "jax":
            bb = _next_bucket(len(side.base))
            qb = _next_bucket(len(q))
            base_p = np.full(bb, np.inf, dtype=self.dtype)
            base_p[: len(side.base)] = side.base
            q_p = np.zeros(qb, dtype=self.dtype)
            q_p[: len(q)] = q
            # host-tax dispatch boundary [ISSUE 14]: the key mirrors
            # the lru cache key of the jit factory, so a first-seen
            # key IS a compile-ladder growth event
            with device_section(("count", bb, qb)) as ds:
                less, leq = _jit_count_fn(bb, qb)(base_p, q_p)
                ds.dispatched()
                less = np.asarray(less)[: len(q)].astype(np.int64)
                leq = np.asarray(leq)[: len(q)].astype(np.int64)
            return less, leq
        less = np.searchsorted(side.base, q, side="left")
        leq = np.searchsorted(side.base, q, side="right")
        return less.astype(np.int64), leq.astype(np.int64)

    def _sharded_base_counts(
        self, side: _ClassSide, q: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Sharded counts with bounded self-healing retries [ISSUE 3].

        A device failure surfaces as the count call raising. The host
        is authoritative for the merged base runs, so recovery
        (``parallel.self_heal.MeshHealer``) is: probe which workers are
        dead, rebuild the mesh over the survivors, re-place BOTH sides'
        base runs, back off, retry — the re-placed counts are
        bit-identical (counting is additive over any partition), so a
        healed query returns exactly what the healthy mesh would have.
        """
        from tuplewise_tpu.parallel.self_heal import HealExhaustedError
        from tuplewise_tpu.parallel.sharded_counts import sharded_counts

        from tuplewise_tpu.parallel.sharded_counts import next_bucket

        self._q_buckets.add(next_bucket(len(q)))

        def attempt():
            # at most TWO runs — base + the consolidated delta run —
            # so compile shapes follow the two bucket ladders alone
            deltas = (((side.delta_dev, side.delta_cap),)
                      if side.delta_dev is not None else ())
            return sharded_counts(self._mesh, side.base_dev, side.cap,
                                  q, self.dtype, chaos=self.chaos,
                                  deltas=deltas)

        try:
            with maybe_span(self.tracer, "index.sharded_count",
                            n_queries=len(q)):
                return self._healer.run(attempt,
                                        retries=self.shard_retries,
                                        on_heal=self._on_heal)
        except HealExhaustedError as e:
            # terminal for this mesh: dump the flight ring NOW — the
            # operator's first question is what led up to exhaustion
            self._c_heal_exhausted.inc()
            if self.flight is not None:
                self.flight.record("heal_exhausted", error=repr(e))
                self.flight.auto_dump()
            raise

    def _on_heal(self, healer) -> None:
        """Re-placement after a heal round: adopt the (possibly
        resharded) mesh and rebuild the device shards — base AND delta
        runs — from the host-authoritative copies (pure cache
        rebuild)."""
        self._mesh = healer.mesh
        self.shards = healer.n_workers
        self._g_mesh.set(self.shards)
        with maybe_span(self.tracer, "heal.replace"):
            for side in (self._pos, self._neg):
                side.placed_base = None   # stale mesh: no row reuse
                self._place(side)
                self._replace_deltas(side)
                side.placed_tomb = None
                side.tomb_dev, side.tomb_cap = None, 0
                self._replace_tomb(side)

    def _replace_deltas(self, side: _ClassSide) -> None:
        """Rebuild the delta run's device placement (mesh change or
        snapshot restore)."""
        if self.shards is None or len(side.delta_run) == 0:
            side.delta_dev, side.delta_cap = None, 0
            side.delta_rows = None
            return
        from tuplewise_tpu.parallel.sharded_counts import (
            mesh_size, place_base,
        )

        side.delta_dev, side.delta_cap, _ = place_base(
            self._mesh, side.delta_run, self.dtype,
            metrics=self.metrics)
        S = mesh_size(self._mesh)
        per = -(-len(side.delta_run) // S)
        side.delta_rows = np.clip(
            len(side.delta_run) - per * np.arange(S), 0, per
        ).astype(np.int64)

    def _counts(self, side: _ClassSide,
                q: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(less, eq) of each query against side's CURRENT multiset."""
        q = np.asarray(q, dtype=self.dtype)
        less, leq = self._base_counts(side, q)
        return self._host_adjust(side, q, less, leq)

    def _host_adjust(self, side: _ClassSide, q: np.ndarray,
                     base_less: np.ndarray, base_leq: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray]:
        """(less, eq) vs the side's CURRENT multiset given precomputed
        base-run counts: the pending buffer (+) and tombstone (−)
        lists adjust on the host; the consolidated tombstone multiset
        subtracts here ONLY when the base counts did not already fold
        it in (the count kernel carries it with sign −1 [ISSUE 10])."""
        less = np.asarray(base_less, dtype=np.int64).copy()
        eq = np.asarray(base_leq, dtype=np.int64) - less
        for vals, sign in ((side.buf, 1), (side.tomb, -1)):
            if not vals:
                continue
            arr = np.sort(np.asarray(vals, dtype=self.dtype))
            l2 = np.searchsorted(arr, q, side="left").astype(np.int64)
            r2 = np.searchsorted(arr, q, side="right").astype(np.int64)
            less += sign * l2
            eq += sign * (r2 - l2)
        if len(side.tomb_run) and not self._ck:
            # the consolidated tombstone multiset: already sorted, its
            # counts subtract — additivity over signed multisets keeps
            # every prefix exact [ISSUE 5]
            l2 = np.searchsorted(side.tomb_run, q,
                                 side="left").astype(np.int64)
            r2 = np.searchsorted(side.tomb_run, q,
                                 side="right").astype(np.int64)
            less -= l2
            eq -= r2 - l2
        return less, eq

    # ------------------------------------------------------------------ #
    # Pallas-fused count path [ISSUE 10]                                 #
    # ------------------------------------------------------------------ #
    def _replace_tomb(self, side: _ClassSide) -> None:
        """(Re)place the tombstone multiset's device mirror — kernel
        mode only (XLA mode subtracts it on the host). Row-reuse via
        the place_base prev-trick, like the base run."""
        if (not self._ck or self.shards is None
                or len(side.tomb_run) == 0):
            side.tomb_dev, side.tomb_cap = None, 0
            side.placed_tomb = None
            return
        from tuplewise_tpu.parallel.sharded_counts import place_base

        side.tomb_dev, side.tomb_cap, _ = place_base(
            self._mesh, side.tomb_run, self.dtype,
            prev=(side.placed_tomb, side.tomb_dev, side.tomb_cap),
            metrics=self.metrics)
        side.placed_tomb = side.tomb_run

    def _kernel_runs(self, side: _ClassSide) -> list:
        """The side's runs for the fused signed count: base and the
        consolidated delta run (+1), the tombstone multiset (−1).
        Sharded mode hands placed device arrays (lazily refreshing the
        tombstone mirror — restore paths leave it stale); single-host
        mode hands the host arrays for in-dispatch padding."""
        from tuplewise_tpu.parallel.sharded_counts import next_bucket

        runs = []
        if self.shards is None:
            if len(side.base):
                runs.append((side.base, next_bucket(len(side.base)), 1))
            if len(side.tomb_run):
                runs.append((side.tomb_run,
                             next_bucket(len(side.tomb_run)), -1))
            return runs
        if side.placed_tomb is not side.tomb_run:
            self._replace_tomb(side)
        if side.base_dev is not None:
            runs.append((side.base_dev, side.cap, 1))
        if side.delta_dev is not None:
            runs.append((side.delta_dev, side.delta_cap, 1))
        if side.tomb_dev is not None:
            runs.append((side.tomb_dev, side.tomb_cap, -1))
        return runs

    def _kernel_base_counts(
        self, side: _ClassSide, q: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Single-side fused count (score path / non-fused callers):
        one kernel invocation covering base + delta + tombstone."""
        less, leq, _, _ = self._fused_counts(
            lambda: (self._kernel_runs(side), ()),
            q, np.zeros(0, dtype=self.dtype))
        return less, leq

    def _fused_pair_base_counts(self, q_vs_neg: np.ndarray,
                                q_vs_pos: np.ndarray):
        """Both sides' base counts in ONE kernel invocation per device
        — the insert hot path's single dispatch [ISSUE 10]."""
        return self._fused_counts(
            lambda: (self._kernel_runs(self._neg),
                     self._kernel_runs(self._pos)),
            q_vs_neg, q_vs_pos)

    def _fused_counts(self, runs_fn, q_a: np.ndarray, q_b: np.ndarray):
        """Dispatch one fused signed count with the shared heal-and-
        retry protocol (sharded mode) — ``runs_fn`` re-reads the
        placements inside each attempt so a heal's re-placement is
        picked up."""
        from tuplewise_tpu.parallel.sharded_counts import (
            next_bucket, signed_pair_counts,
        )

        self._q_buckets.add(next_bucket(max(len(q_a), len(q_b), 1)))
        kernel = self._ck_interp if self._ck else None

        def attempt():
            runs_a, runs_b = runs_fn()
            return signed_pair_counts(
                self._mesh if self.shards is not None else None,
                runs_a, runs_b, q_a, q_b, self.dtype, kernel=kernel,
                chaos=self.chaos, metrics=self.metrics)

        if self._healer is None:
            return attempt()
        from tuplewise_tpu.parallel.self_heal import HealExhaustedError

        try:
            with maybe_span(self.tracer, "index.sharded_count",
                            n_queries=len(q_a) + len(q_b)):
                return self._healer.run(attempt,
                                        retries=self.shard_retries,
                                        on_heal=self._on_heal)
        except HealExhaustedError as e:
            self._c_heal_exhausted.inc()
            if self.flight is not None:
                self.flight.record("heal_exhausted", error=repr(e))
                self.flight.auto_dump()
            raise

    def _cross2(self, p_vals: np.ndarray, n_side: _ClassSide) -> int:
        """sum over p of 2*count_less(p in negs) + count_eq: the wins2
        contribution of positives ``p_vals`` against class ``n_side``."""
        if len(p_vals) == 0 or n_side.size == 0:
            return 0
        less, eq = self._counts(n_side, p_vals)
        return int(2 * less.sum() + eq.sum())

    def _cross2_rev(self, n_vals: np.ndarray, p_side: _ClassSide) -> int:
        """wins2 contribution of pairs (p in p_side, n in n_vals): the
        flipped count — per negative, 2*count_pos_greater + count_pos_eq
        — from the same (less, eq) container searches."""
        if len(n_vals) == 0 or p_side.size == 0:
            return 0
        less, eq = self._counts(p_side, n_vals)
        greater = p_side.size - less - eq
        return int(2 * greater.sum() + eq.sum())

    @staticmethod
    def _cross2_arrays(p: np.ndarray, n: np.ndarray) -> int:
        """wins2 between two plain arrays (intra-batch pairs)."""
        if len(p) == 0 or len(n) == 0:
            return 0
        ns = np.sort(n)
        less = np.searchsorted(ns, p, side="left").astype(np.int64)
        leq = np.searchsorted(ns, p, side="right").astype(np.int64)
        return int(2 * less.sum() + (leq - less).sum())

    # ------------------------------------------------------------------ #
    # mutation                                                           #
    # ------------------------------------------------------------------ #
    def insert_batch(self, scores, labels) -> int:
        """Insert arrivals in order; returns the number inserted.

        ``labels`` truthy = positive class. The pair statistic after the
        call equals the batch statistic over (old set) ∪ (batch) — pair
        sets are order-free — then window eviction trims to the last
        ``window`` arrivals.
        """
        scores = np.asarray(scores, dtype=self.dtype).ravel()
        labels = np.asarray(labels).ravel().astype(bool)
        if scores.shape != labels.shape:
            raise ValueError(
                f"scores/labels length mismatch: {scores.shape} vs "
                f"{labels.shape}")
        if len(scores) and not np.all(np.isfinite(scores)):
            raise ValueError("scores must be finite")
        p_new = scores[labels]
        n_new = scores[~labels]
        with self._cv:
            if self._ck:
                # fused kernel path [ISSUE 10]: insert AND eviction
                # counts in ONE device dispatch
                self._apply_fused(scores, labels, p_new, n_new)
                self._maybe_compact()
                return len(scores)
            # new-vs-old (old sets untouched so far), then new-vs-new
            d = self._cross2(p_new, self._neg)
            d += self._cross2_rev(n_new, self._pos)
            d += self._cross2_arrays(p_new, n_new)
            self._wins2 += d
            self._pos.buf.extend(p_new.tolist())
            self._neg.buf.extend(n_new.tolist())
            for s, is_pos in zip(scores.tolist(), labels.tolist()):
                self._log.append((s, is_pos))
            if self.window is not None and len(self._log) > self.window:
                self._evict(len(self._log) - self.window)
            self._maybe_compact()
        return len(scores)

    def _apply_fused(self, scores: np.ndarray, labels: np.ndarray,
                     p_new: np.ndarray, n_new: np.ndarray) -> None:
        """Kernel-path insert + window eviction with ONE fused device
        count per micro-batch [ISSUE 10]: evictions are planned from
        (log ++ batch) BEFORE the device call, so the evicted values'
        base-run counts ride the same kernel invocation as the insert
        queries — legal because only the host buffer and log mutate
        during an insert; the placed base/delta/tombstone runs cannot.
        The host-side adjustments then run at exactly the container
        states the unfused path uses (pre-insert for the insert term,
        post-insert for the eviction term — the fleet's ``_fold_plan``
        ordering), so wins2 is bit-identical by construction."""
        n_evict = 0
        p_out: List[float] = []
        n_out: List[float] = []
        if self.window is not None:
            n_evict = max(0, len(self._log) + len(scores) - self.window)
        if n_evict:
            import itertools

            pool = itertools.chain(
                self._log, zip(scores.tolist(), labels.tolist()))
            for v, is_pos in itertools.islice(pool, n_evict):
                (p_out if is_pos else n_out).append(v)
        p_out_arr = np.asarray(p_out, dtype=self.dtype)
        n_out_arr = np.asarray(n_out, dtype=self.dtype)
        ln, lqn, lp, lqp = self._fused_pair_base_counts(
            np.concatenate([p_new, p_out_arr]),
            np.concatenate([n_new, n_out_arr]))
        kp, kn = len(p_new), len(n_new)
        # --- insert: new-vs-old (containers pre-insert) --------------- #
        less, eq = self._host_adjust(self._neg, p_new,
                                     ln[:kp], lqn[:kp])
        d = int(2 * less.sum() + eq.sum())
        less2, eq2 = self._host_adjust(self._pos, n_new,
                                       lp[:kn], lqp[:kn])
        greater = self._pos.size - less2 - eq2
        d += int(2 * greater.sum() + eq2.sum())
        d += self._cross2_arrays(p_new, n_new)
        self._wins2 += d
        self._pos.buf.extend(p_new.tolist())
        self._neg.buf.extend(n_new.tolist())
        for s, is_pos in zip(scores.tolist(), labels.tolist()):
            self._log.append((s, is_pos))
        # --- eviction: inclusion-exclusion (containers post-insert) --- #
        if n_evict:
            less, eq = self._host_adjust(self._neg, p_out_arr,
                                         ln[kp:], lqn[kp:])
            d = int(2 * less.sum() + eq.sum())
            less2, eq2 = self._host_adjust(self._pos, n_out_arr,
                                           lp[kn:], lqp[kn:])
            greater = self._pos.size - less2 - eq2
            d += int(2 * greater.sum() + eq2.sum())
            d -= self._cross2_arrays(p_out_arr, n_out_arr)
            self._wins2 -= d
            for _ in range(n_evict):
                v, is_pos = self._log.popleft()
                side = self._pos if is_pos else self._neg
                try:
                    # only the UNSNAPSHOTTED suffix is removable in
                    # place (an in-flight build owns the prefix)
                    i = side.buf.index(v, side.snap_buf)
                    side.buf.pop(i)
                except ValueError:
                    side.tomb.append(v)
            self.n_evicted += n_evict
            self._update_gauges()

    def _evict(self, count: int) -> None:
        """Remove the ``count`` oldest arrivals from the statistic."""
        p_out: List[float] = []
        n_out: List[float] = []
        for _ in range(count):
            v, is_pos = self._log.popleft()
            (p_out if is_pos else n_out).append(v)
        p_arr = np.asarray(p_out, dtype=self.dtype)
        n_arr = np.asarray(n_out, dtype=self.dtype)
        # pairs with >= 1 evicted endpoint, inclusion-exclusion: the
        # P_e x N_e block is inside both cross terms (containers still
        # hold the evicted values here, as the identity requires)
        d = self._cross2(p_arr, self._neg)
        d += self._cross2_rev(n_arr, self._pos)
        d -= self._cross2_arrays(p_arr, n_arr)
        self._wins2 -= d
        for side, vals in ((self._pos, p_out), (self._neg, n_out)):
            for v in vals:
                try:
                    # only the UNSNAPSHOTTED suffix is removable in
                    # place: an in-flight build owns buf[:snap_buf] and
                    # will merge those copies into the new base
                    i = side.buf.index(v, side.snap_buf)
                    side.buf.pop(i)
                except ValueError:
                    side.tomb.append(v)
        self.n_evicted += count
        self._update_gauges()

    def _side_name(self, side: _ClassSide) -> str:
        return "pos" if side is self._pos else "neg"

    def _update_gauges(self) -> None:
        """Refresh the live container gauges (caller holds the lock or
        owns the containers) [ISSUE 6 satellite]."""
        self._g_delta.set(len(self._pos.delta_run)
                          + len(self._neg.delta_run))
        self._g_tomb.set(len(self._pos.tomb_run) + len(self._neg.tomb_run)
                         + len(self._pos.tomb) + len(self._neg.tomb))
        if self.shards is not None:
            self._update_shard_gauges()

    def shard_occupancy(self) -> list:
        """Per-shard placed row counts (base + delta), both classes
        summed — the occupancy the skew gauges judge. Contiguous-slice
        placement: shard s of an n-row run holds
        ``clip(n - s*ceil(n/S), 0, ceil(n/S))`` rows [ISSUE 7]."""
        S = self.shards or 1
        counts = np.zeros(S, dtype=np.int64)
        for side in (self._pos, self._neg):
            for arr in (side.placed_base
                        if side.placed_base is not None else side.base,
                        side.delta_run):
                n = len(arr)
                if n:
                    per = -(-n // S)
                    counts += np.clip(n - per * np.arange(S), 0, per)
        return counts.tolist()

    def _update_shard_gauges(self) -> None:
        from tuplewise_tpu.obs.health import shard_balance

        bal = shard_balance(self.shard_occupancy())
        self._g_skew.set(bal["skew"])
        self._g_skew_cv.set(bal["cv"])

    def _flight_event(self, kind: str, **fields) -> None:
        if self.flight is not None:
            self.flight.record(kind, **fields)

    def _maybe_compact(self) -> None:
        bg_ok = self._ensure_compactor() if self.bg_compact else False
        for side in (self._pos, self._neg):
            buf_pending, tomb_pending = side.pending
            if (buf_pending >= self.compact_every
                    or tomb_pending >= self.compact_every):
                if self.bg_compact and bg_ok:
                    self._submit_compact(side)
                elif not side.building:
                    # watchdog fallback [ISSUE 3]: the compactor thread
                    # is dead (crashed build) — compact synchronously
                    # rather than let the buffer grow unboundedly while
                    # the restarted thread warms up. A side mid-build
                    # is left for the restarted worker (its queued job
                    # owns the snapshot prefixes).
                    self._compact_side(side)

    def _ensure_compactor(self) -> bool:
        """Watchdog (caller holds the lock): True when the background
        compactor thread is alive. A dead worker — a crashed build —
        is restarted (``bg_compactor_restarts``) and False is returned
        so the caller compacts synchronously this once; jobs still
        queued are picked up by the fresh thread."""
        if self._compactor.is_alive():
            return True
        if not self._closed:
            self._c_bg_restarts.inc()
            self._compactor = threading.Thread(
                target=self._compact_worker, name="tuplewise-compactor",
                daemon=True)
            self._compactor.start()
        return False

    def _drain_builds(self, timeout: float, what: str) -> None:
        """Wait until no build is queued or in flight, restarting a
        dead compactor along the way (a crashed worker must not turn a
        drain into a hang)."""
        deadline = time.monotonic() + timeout
        while self._pos.building or self._neg.building:
            if self.bg_compact:
                self._ensure_compactor()
            if (not self._cv.wait(timeout=0.25)
                    and time.monotonic() >= deadline):
                raise TimeoutError(what)

    def wait_idle(self, timeout: float = 30.0) -> None:
        """Block until no background build is queued or in flight —
        after this, pause/compaction metrics are settled (measurement
        code calls it so records don't depend on compactor timing)."""
        with self._cv:
            self._drain_builds(timeout, "background compaction stuck")

    def compact(self) -> None:
        """Force both sides into a single sorted base run — folding
        the delta run and dropping tombstones — after draining any
        in-flight background builds."""
        with self._cv:
            self._drain_builds(30.0, "background compaction stuck")
            for side in (self._pos, self._neg):
                if (side.buf or side.tomb or len(side.delta_run)
                        or len(side.tomb_run)):
                    self._full_compact(side)

    def _merge(self, side_base: np.ndarray, buf: List[float],
               tomb: List[float], on_thread: bool) -> np.ndarray:
        """Pure merge: sorted(base + buf) minus tombstones.

        ``on_thread`` (synchronous jax compaction) keeps the padded
        jitted sort — the caller already owns the device. Background
        and sharded merges MUST stay off the device: a jitted sort
        would serialize with the batcher's jitted searchsorted on the
        same XLA stream, re-creating on the device the very pause the
        side thread exists to remove. The host path exploits that base
        is already sorted: sort only the buffer and splice it in at
        its searchsorted positions — O(n + b log b), not O(n log n).
        Values (hence counts) are identical either way.
        """
        buf_sorted = np.sort(np.asarray(buf, dtype=self.dtype))
        if on_thread and self.engine == "jax" and self.shards is None:
            merged = np.concatenate([side_base, buf_sorted])
            n = len(merged)
            if n:
                b = _next_bucket(n)
                padded = np.full(b, np.inf, dtype=self.dtype)
                padded[:n] = merged
                # on-thread sort runs inside the insert wave: bill the
                # compaction-pause device time honestly [ISSUE 14]
                with device_section(("sort", b)) as ds:
                    out = _jit_sort_fn(b)(padded)
                    ds.dispatched()
                    merged = np.asarray(out)[:n]
        elif len(buf_sorted) == 0:
            merged = side_base
        else:
            # single-allocation splice [ISSUE 5 satellite]: np.insert
            # re-copied the base through its generic path
            merged = _splice_merge(side_base, buf_sorted)
        return _remove_sorted(merged, tomb)

    def _place(self, side: _ClassSide) -> int:
        """(Re)place the base run's device shards after it changed;
        returns the bytes actually shipped (unchanged rows are reused
        when the bucket geometry permits [ISSUE 5 satellite])."""
        if self.shards is None or len(side.base) == 0:
            side.base_dev, side.cap = None, 0
            side.placed_base = None
            return 0
        from tuplewise_tpu.parallel.sharded_counts import place_base

        side.base_dev, side.cap, shipped = place_base(
            self._mesh, side.base, self.dtype,
            prev=(side.placed_base, side.base_dev, side.cap),
            metrics=self.metrics, chaos=self.chaos)
        side.placed_base = side.base
        return shipped

    def _warm_counts(self, base_dev, cap: int, deltas,
                     side: Optional[_ClassSide] = None) -> None:
        """Force-compile the count kernel for a placement geometry the
        request path is ABOUT to see (called on the compactor thread
        before the swap, with every query bucket observed so far):
        XLA compiles of new (base cap, delta cap, q bucket) shapes
        otherwise land on the first post-swap count — a request-thread
        pause the background compactor exists to remove.

        Kernel mode [ISSUE 10] warms the fused Pallas fn instead: the
        single-side shape (score path) AND — when ``side`` is given —
        the two-side insert shape against the OTHER side's current
        runs, so the post-swap insert's combined geometry is compiled
        too."""
        if base_dev is None and not deltas:
            return
        if self._ck:
            self._warm_counts_fused(base_dev, cap, deltas, side)
            return
        from tuplewise_tpu.parallel.sharded_counts import sharded_counts

        for qb in sorted(self._q_buckets):
            key = (cap if base_dev is not None else None,
                   tuple(c for _, c in deltas), qb)
            if key in self._warmed:
                continue
            try:
                sharded_counts(self._mesh, base_dev, cap,
                               np.zeros(qb, dtype=self.dtype),
                               self.dtype, deltas=deltas)
                self._warmed.add(key)
            except Exception:   # noqa: BLE001 — warming is best-effort
                return

    def _warm_counts_fused(self, base_dev, cap: int, deltas,
                           side: Optional[_ClassSide]) -> None:
        """Kernel-variant prewarm: one dispatch per (geometry, q
        bucket) through the same ``signed_pair_counts`` entry the
        request path uses — compiles (and interpret-mode traces) land
        here, on the compactor thread. No metrics: warm dispatches
        must not inflate the per-micro-batch call witness."""
        from tuplewise_tpu.parallel.sharded_counts import (
            signed_pair_counts,
        )

        runs = ([(base_dev, cap, 1)] if base_dev is not None else [])
        runs += [(d, c, 1) for d, c in deltas]
        if side is not None and side.tomb_dev is not None:
            runs.append((side.tomb_dev, side.tomb_cap, -1))
        other_runs = []
        if side is not None:
            # READ the other side's current placements only — no
            # _kernel_runs here: its lazy tombstone re-place mutates
            # placement fields, and this thread does not hold the
            # lock. A torn read just warms a slightly-off geometry;
            # warming is best-effort either way.
            other = self._neg if side is self._pos else self._pos
            if other.base_dev is not None:
                other_runs.append((other.base_dev, other.cap, 1))
            if other.delta_dev is not None:
                other_runs.append((other.delta_dev, other.delta_cap, 1))
            if other.tomb_dev is not None:
                other_runs.append((other.tomb_dev, other.tomb_cap, -1))
        shapes = [(tuple((c, s) for _, c, s in runs), ())]
        if other_runs:
            shapes.append((tuple((c, s) for _, c, s in runs),
                           tuple((c, s) for _, c, s in other_runs)))
        for qb in sorted(self._q_buckets):
            for shape_a, shape_b in shapes:
                key = ("ck", shape_a, shape_b, qb)
                if key in self._warmed:
                    continue
                try:
                    signed_pair_counts(
                        self._mesh, runs,
                        other_runs if shape_b else (),
                        np.zeros(qb, dtype=self.dtype),
                        np.zeros(qb if shape_b else 0,
                                 dtype=self.dtype),
                        self.dtype, kernel=self._ck_interp)
                    self._warmed.add(key)
                except Exception:  # noqa: BLE001 — best-effort
                    return

    # ------------------------------------------------------------------ #
    # compaction tiers [ISSUE 5]                                         #
    # ------------------------------------------------------------------ #
    def _compact_side(self, side: _ClassSide) -> None:
        """Synchronous compaction (caller holds the lock): the work —
        and the pause it bills to the caller — runs inline. Delta mode
        makes that pause O(b): a minor compaction, then whatever
        follow-up tier is due."""
        with maybe_span(self.tracer, "compaction.sync",
                        side=self._side_name(side)):
            if not self._delta:
                self._full_compact(side)
                return
            buf_vals, tomb_vals = list(side.buf), list(side.tomb)
            side.buf = []
            side.tomb = []
            t0 = time.perf_counter()
            new_delta, placed = self._build_delta(side, buf_vals)
            self._commit_minor(side, new_delta, placed, tomb_vals, t0)
            todo = self._followup(side)
            if todo == "major":
                t0 = time.perf_counter()
                with maybe_span(self.tracer, "compaction.major"):
                    merged, dev, cap = self._major_build(side)
                    self._commit_major(side, merged, dev, cap, t0, t0)
            elif todo == "full":
                self._full_compact(side)

    def _build_delta(self, side: _ClassSide, buf_vals: List[float]):
        """Merge the pending buffer into the consolidated delta run —
        host copy via the single-allocation splice, DEVICE copy by
        shipping only the O(b) chunk and rank-merging it into the
        placed delta rows per shard (``delta_append_fn``; counting is
        additive over any partition into sorted runs, so per-row
        unions need no rebalancing). Host→device bytes per minor are
        O(buffer), independent of both the base and the accumulated
        delta. Returns (new_delta_host,
        (dev, cap, bytes, rows) | None). Caller owns the delta state
        (lock or worker claim)."""
        chunk = np.sort(np.asarray(buf_vals, dtype=self.dtype))
        if len(chunk) == 0:
            return side.delta_run, None     # tomb-only minor
        new_delta = _splice_merge(side.delta_run, chunk)
        from tuplewise_tpu.parallel.sharded_counts import (
            delta_append_fn, mesh_size, next_bucket, place_base,
        )

        S = mesh_size(self._mesh)
        if side.delta_dev is None:
            # first minor after a fold: place the (fresh) run
            dev, cap, shipped = place_base(
                self._mesh, new_delta, self.dtype,
                metrics=self.metrics, chaos=self.chaos)
            per = -(-len(new_delta) // S)
            rows = np.clip(len(new_delta) - per * np.arange(S),
                           0, per).astype(np.int64)
            return new_delta, (dev, cap, shipped, rows)
        # append path: ship the chunk, merge rows on device
        chunk_dev, chunk_cap, shipped = place_base(
            self._mesh, chunk, self.dtype, metrics=self.metrics,
            chaos=self.chaos)
        per_c = -(-len(chunk) // S)
        rows = side.delta_rows + np.clip(
            len(chunk) - per_c * np.arange(S), 0, per_c)
        cap_new = next_bucket(int(rows.max()))
        dev = delta_append_fn(self._mesh, side.delta_cap, chunk_cap,
                              cap_new)(side.delta_dev, chunk_dev)
        return new_delta, (dev, cap_new, shipped, rows)

    def _commit_minor(self, side: _ClassSide, new_delta: np.ndarray,
                      placed, tomb_vals: List[float],
                      t0: float) -> None:
        """Adopt a minor compaction's outputs (lock held): swap the
        consolidated delta run, fold fresh tombstones into the sorted
        tombstone multiset. Counts are unchanged by construction — the
        same values moved between containers whose counts
        add/subtract."""
        if placed is not None:
            dev, cap, shipped, rows = placed
            side.delta_run = new_delta
            side.delta_dev, side.delta_cap = dev, cap
            side.delta_rows = rows
            side.delta_minors += 1
            self._h_compaction_bytes.observe(shipped)
        if tomb_vals:
            side.tomb_run = _splice_merge(
                side.tomb_run,
                np.sort(np.asarray(tomb_vals, dtype=self.dtype)))
            # kernel mode mirrors the tombstone multiset on-mesh (the
            # kernel subtracts it in-dispatch) [ISSUE 10]
            self._replace_tomb(side)
        self.n_compactions += 1
        self._c_compactions.inc()
        self._update_gauges()
        self._flight_event(
            "compaction", tier="minor", side=self._side_name(side),
            delta_events=len(side.delta_run),
            bytes_shipped=(placed[2] if placed is not None else 0))
        self._h_pause.observe(time.perf_counter() - t0)

    def _followup(self, side: _ClassSide) -> Optional[str]:
        """Which heavier tier (if any) a minor compaction leaves due.

        "full" when the tombstone multiset outgrew the base fraction —
        only a host rebuild can physically drop tombstones; "major"
        when the delta mass crossed ``delta_fraction·|base|`` or
        ``max_delta_runs`` minor runs have been merged into it (the
        bound on per-minor splice-and-re-ship cost).
        """
        if len(side.tomb_run) >= max(
                self.compact_every,
                int(self.delta_fraction * len(side.base))):
            return "full"
        if len(side.delta_run) and (
                len(side.delta_run)
                > self.delta_fraction * max(len(side.base), 1)
                or side.delta_minors >= self.max_delta_runs):
            return "major"
        return None

    def _major_build(self, side: _ClassSide):
        """Fold the delta run into the base; returns the new
        (merged_host, base_dev, cap). The host copy is the cheap
        single-allocation splice; the device copy is built ON-MESH
        (zero host→device bytes) whenever the host plan fits the
        one-hop neighbor exchange, else by full re-placement
        [ISSUE 5 tentpole]. Caller owns base/delta (lock or worker
        claim)."""
        from tuplewise_tpu.parallel.sharded_counts import (
            mesh_size, place_base, plan_major_merge, sharded_major_merge,
        )

        base, base_dev, cap = side.base, side.base_dev, side.cap
        delta_full = side.delta_run
        merged = _splice_merge(base, delta_full)
        if (len(base) and base_dev is not None
                and side.delta_dev is not None
                and self.shards is not None and self.shards >= 2):
            plan = plan_major_merge(base, delta_full,
                                    mesh_size(self._mesh))
            if plan.ok:
                try:
                    dev, cap_out = sharded_major_merge(
                        self._mesh, base_dev, cap,
                        ((side.delta_dev, side.delta_cap),),
                        plan, chaos=self.chaos)
                    # the bytes the PR 2 path would have re-shipped
                    self._c_bytes_saved.inc(
                        mesh_size(self._mesh) * cap_out
                        * np.dtype(self.dtype).itemsize)
                    return merged, dev, cap_out
                except Exception as e:   # noqa: BLE001 — fallback path
                    self._c_major_fb.inc()
                    self.last_major_merge_error = repr(e)
                    self._flight_event(
                        "major_merge_fallback",
                        side=self._side_name(side), error=repr(e))
        # S=1 / empty-base / out-of-plan / failed-mesh fallback: the
        # host engine re-places the merged run in full
        dev, cap_out, _ = place_base(self._mesh, merged, self.dtype,
                                     metrics=self.metrics)
        return merged, dev, cap_out

    def _commit_major(self, side: _ClassSide, merged: np.ndarray,
                      dev, cap: int, t_build0: float,
                      t_pause0: float) -> None:
        """Swap a major merge in (lock held): rebind base, clear the
        folded delta run (no newer delta can exist — the side is
        owned for the whole job), keep tombstones (counts still
        subtract them)."""
        side.base = merged
        side.placed_base = merged
        side.base_dev, side.cap = dev, cap
        side.delta_run = np.empty(0, dtype=self.dtype)
        side.delta_dev, side.delta_cap = None, 0
        side.delta_rows = None
        side.delta_minors = 0
        self.n_compactions += 1
        self._c_compactions.inc()
        self.n_major_merges += 1
        self._c_major.inc()
        self._update_gauges()
        now = time.perf_counter()
        self._flight_event(
            "major_merge", side=self._side_name(side),
            base_events=len(merged), build_s=now - t_build0)
        self._h_major.observe(now - t_build0)
        self._h_pause.observe(now - t_pause0)

    def _full_compact(self, side: _ClassSide) -> None:
        """Fold EVERYTHING — base, delta run, buffer — into one sorted
        base run, physically dropping tombstones, and re-place (caller
        holds the lock). The PR 2 engine, kept as the explicit
        ``compact()`` semantics, the non-delta compaction, and the
        tombstone-overflow rebuild."""
        t0 = time.perf_counter()
        tombs = side.tomb_run.tolist() + side.tomb
        if len(side.delta_run):
            merged = _remove_sorted(
                _splice_merge(
                    _splice_merge(side.base, side.delta_run),
                    np.sort(np.asarray(side.buf, dtype=self.dtype))),
                tombs)
        else:
            merged = self._merge(side.base, side.buf, tombs,
                                 on_thread=True)
        side.base = merged
        side.buf = []
        side.tomb = []
        side.delta_run = np.empty(0, dtype=self.dtype)
        side.delta_dev, side.delta_cap = None, 0
        side.delta_rows = None
        side.delta_minors = 0
        side.tomb_run = np.empty(0, dtype=self.dtype)
        self._replace_tomb(side)    # clears the device mirror
        shipped = self._place(side)
        if not self._delta:
            # in host-merge mode this IS the minor compaction — the
            # bytes histogram is what the delta mode is judged against
            self._h_compaction_bytes.observe(shipped)
        self.n_compactions += 1
        self._c_compactions.inc()
        self._update_gauges()
        self._flight_event(
            "compaction", tier="full", side=self._side_name(side),
            base_events=len(merged), bytes_shipped=shipped)
        self._h_pause.observe(time.perf_counter() - t0)

    # ------------------------------------------------------------------ #
    # background compaction                                              #
    # ------------------------------------------------------------------ #
    def _submit_compact(self, side: _ClassSide) -> None:
        """Snapshot the side's consumable prefix and enqueue a build
        (caller holds the lock); no-op while a build is in flight."""
        if side.building:
            return
        side.building = True
        side.snap_buf = len(side.buf)
        side.snap_tomb = len(side.tomb)
        self._jobs.put(side)

    def _compact_worker(self) -> None:
        while True:
            side = self._jobs.get()
            if side is None:
                return
            try:
                self._build_and_swap(side)
            except BaseException as e:
                # Roll back the snapshot claim so nothing is lost: the
                # buffer/tombstones still hold every value (prefixes
                # are only trimmed at the swap) and wins2 was never
                # touched, so the statistic is unaffected — the next
                # trigger simply re-compacts. Then die (quietly — the
                # error is kept in ``last_compactor_error`` rather than
                # sprayed through the thread excepthook): the watchdog
                # (`_ensure_compactor`) restarts the thread and counts
                # the restart. [ISSUE 3]
                with self._cv:
                    side.snap_buf = side.snap_tomb = 0
                    side.building = False
                    self.last_compactor_error = repr(e)
                    self._cv.notify_all()
                return

    def _build_and_swap(self, side: _ClassSide) -> None:
        if self._bg_test_hook is not None:
            self._bg_test_hook(side)
        with maybe_span(self.tracer, "compactor.build",
                        side=self._side_name(side)) as bspan:
            if self.chaos is not None:
                self.chaos.fire("compactor_build")
            if self._delta:
                self._bg_delta_build(side)
                return
            with self._cv:
                base = side.base
                prev = (side.placed_base, side.base_dev, side.cap)
                buf_snap = list(side.buf[: side.snap_buf])
                tomb_snap = list(side.tomb[: side.snap_tomb])
            # the expensive part — merge + device placement — runs with
            # the lock RELEASED; inserts keep landing in the buffer
            with maybe_span(self.tracer, "compactor.merge",
                            n_buf=len(buf_snap)):
                merged = self._merge(base, buf_snap, tomb_snap,
                                     on_thread=False)
            if self.shards is not None and len(merged):
                from tuplewise_tpu.parallel.sharded_counts import (
                    place_base,
                )

                with maybe_span(self.tracer, "compactor.place_base"):
                    base_dev, cap, shipped = place_base(
                        self._mesh, merged, self.dtype, prev=prev,
                        metrics=self.metrics, chaos=self.chaos)
            else:
                base_dev, cap, shipped = None, 0, 0
            self._warm_counts(base_dev, cap, (), side=side)
            with self._cv:
                t0 = time.perf_counter()
                side.base = merged
                side.base_dev, side.cap = base_dev, cap
                side.placed_base = merged if base_dev is not None else None
                if self.shards is not None:
                    self._h_compaction_bytes.observe(shipped)
                del side.buf[: side.snap_buf]
                del side.tomb[: side.snap_tomb]
                side.snap_buf = side.snap_tomb = 0
                side.building = False
                self.n_compactions += 1
                self._c_compactions.inc()
                self._update_gauges()
                self._flight_event(
                    "compaction", tier="bg_merge",
                    side=self._side_name(side),
                    base_events=len(merged), bytes_shipped=shipped)
                # the swap is the ONLY pause the hot path can observe
                t1 = time.perf_counter()
                self._h_pause.observe(t1 - t0)
                if self.tracer is not None:
                    self.tracer.record_span("compactor.swap", t0, t1,
                                            parent=bspan)
                # keep draining if the buffer outgrew the threshold
                # while this build ran
                buf_pending, tomb_pending = side.pending
                if (not self._closed
                        and (buf_pending >= self.compact_every
                             or tomb_pending >= self.compact_every)):
                    self._submit_compact(side)
                self._cv.notify_all()

    def _bg_delta_build(self, side: _ClassSide) -> None:
        """Delta-mode background job [ISSUE 5]: an O(b) minor build +
        swap, then — still on the worker thread, with the side still
        claimed (``building``) — whatever heavier tier fell due. The
        request path's only pauses are the atomic swaps; inserts keep
        landing in the (unclaimed) buffer throughout."""
        with self._cv:
            buf_snap = list(side.buf[: side.snap_buf])
            tomb_snap = list(side.tomb[: side.snap_tomb])
        # O(|delta| + b log b) splice + O(|delta|) placement, lock
        # released (the worker owns delta_run for the whole job)
        with maybe_span(self.tracer, "compactor.minor_build",
                        n_buf=len(buf_snap)):
            new_delta, placed = self._build_delta(side, buf_snap)
        if placed is not None:
            self._warm_counts(side.base_dev, side.cap,
                              ((placed[0], placed[1]),), side=side)
        with self._cv:
            t0 = time.perf_counter()
            self._commit_minor(side, new_delta, placed, tomb_snap, t0)
            del side.buf[: side.snap_buf]
            del side.tomb[: side.snap_tomb]
            side.snap_buf = side.snap_tomb = 0
            todo = self._followup(side)
        # base/delta_run/tomb_run stay worker-owned until the job
        # ends: _submit_compact refuses new claims while building, and
        # the watchdog's sync fallback skips building sides
        if todo == "major":
            t0 = time.perf_counter()
            with maybe_span(self.tracer, "compactor.major_build"):
                merged, dev, cap = self._major_build(side)
            self._warm_counts(dev, cap, (), side=side)
            with self._cv:
                self._commit_major(side, merged, dev, cap, t0,
                                   time.perf_counter())
        elif todo == "full":
            # tombstone overflow: host rebuild of base ⊕ delta minus
            # the tombstone multiset, leaving the (unclaimed) buffer
            # and pending tombstones alone
            merged = _remove_sorted(
                _splice_merge(side.base, side.delta_run),
                side.tomb_run.tolist())
            if len(merged):
                from tuplewise_tpu.parallel.sharded_counts import (
                    place_base,
                )

                dev, cap, _ = place_base(self._mesh, merged, self.dtype,
                                         metrics=self.metrics,
                                         chaos=self.chaos)
            else:
                dev, cap = None, 0
            self._warm_counts(dev, cap, (), side=side)
            with self._cv:
                t0 = time.perf_counter()
                side.base = merged
                side.base_dev, side.cap = dev, cap
                side.placed_base = merged if dev is not None else None
                side.delta_run = np.empty(0, dtype=self.dtype)
                side.delta_dev, side.delta_cap = None, 0
                side.delta_rows = None
                side.delta_minors = 0
                side.tomb_run = np.empty(0, dtype=self.dtype)
                self._replace_tomb(side)    # clears the device mirror
                self.n_compactions += 1
                self._c_compactions.inc()
                self._update_gauges()
                self._flight_event(
                    "compaction", tier="full",
                    side=self._side_name(side),
                    base_events=len(merged))
                self._h_pause.observe(time.perf_counter() - t0)
        with self._cv:
            side.building = False
            buf_pending, tomb_pending = side.pending
            if (not self._closed
                    and (buf_pending >= self.compact_every
                         or tomb_pending >= self.compact_every)):
                self._submit_compact(side)
            self._cv.notify_all()

    def close(self, timeout: float = 10.0) -> None:
        """Stop the background compactor (no-op in synchronous mode)."""
        if not self.bg_compact or self._closed:
            self._closed = True
            return
        self._closed = True
        self._jobs.put(None)
        self._compactor.join(timeout=timeout)

    def __enter__(self) -> "ExactAucIndex":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------ #
    # queries                                                            #
    # ------------------------------------------------------------------ #
    @property
    def n_pos(self) -> int:
        with self._cv:
            return self._pos.size

    @property
    def n_neg(self) -> int:
        with self._cv:
            return self._neg.size

    @property
    def n_events(self) -> int:
        with self._cv:
            return len(self._log)

    def auc(self) -> Optional[float]:
        """Exact AUC of the current window; None until both classes
        have at least one member."""
        with self._cv:
            if self._pos.size == 0 or self._neg.size == 0:
                return None
            return self._wins2 / (2.0 * self._pos.size * self._neg.size)

    def score_batch(self, scores) -> np.ndarray:
        """Fractional rank of each score against current negatives:
        (count_less + 0.5*count_eq) / n_neg — exactly the per-positive
        quantity ops.rank_auc averages. NaN when no negatives yet."""
        q = np.asarray(scores, dtype=self.dtype).ravel()
        with self._cv:
            if self._neg.size == 0:
                return np.full(len(q), np.nan)
            less, eq = self._counts(self._neg, q)
            return (less + 0.5 * eq) / float(self._neg.size)

    def oracle_values(self) -> Tuple[np.ndarray, np.ndarray]:
        """(pos, neg) multisets of the current window — feed these to
        the batch oracle in parity tests. O(n); not a hot path."""
        with self._cv:
            return self._pos.values(), self._neg.values()

    # ------------------------------------------------------------------ #
    # state transfer [ISSUE 9]                                           #
    # ------------------------------------------------------------------ #
    def seed_state(self, pos_vals, neg_vals, log, wins2: int,
                   n_evicted: int = 0) -> None:
        """Adopt an externally-maintained exact state: the sorted class
        multisets become the base runs, the arrival log and integer
        ``wins2`` carry over verbatim. Because every count is a pure
        integer function of the current multiset, the index's future
        outputs are bit-identical to the donor's would have been — the
        whale-promotion handoff (``serving.tenancy``) relies on exactly
        this. Call on a FRESH index (no events, no in-flight builds)."""
        with self._cv:
            self._pos.base = np.sort(
                np.asarray(pos_vals, dtype=self.dtype))
            self._neg.base = np.sort(
                np.asarray(neg_vals, dtype=self.dtype))
            self._log = collections.deque(log)
            self._wins2 = int(wins2)
            self.n_evicted = int(n_evicted)
            for side in (self._pos, self._neg):
                side.placed_base = None
                self._place(side)
                self._replace_deltas(side)
                self._replace_tomb(side)
            self._update_gauges()

    def export_state(self) -> Tuple[np.ndarray, np.ndarray, list, int,
                                    int]:
        """The inverse handoff (demotion): ``(pos_sorted, neg_sorted,
        log, wins2, n_evicted)`` of the current window. Consistent at
        any time — the container invariant holds under the lock even
        mid-background-build, and compaction never touches wins2."""
        with self._cv:
            return (self._pos.values(), self._neg.values(),
                    list(self._log), self._wins2, self.n_evicted)

    def state(self) -> dict:
        with self._cv:
            return {
                "n_pos": self._pos.size,
                "n_neg": self._neg.size,
                "n_events": len(self._log),
                "auc": self.auc(),
                "n_compactions": self.n_compactions,
                "n_evicted": self.n_evicted,
                "buf_pos": len(self._pos.buf),
                "buf_neg": len(self._neg.buf),
                "engine": self.engine,
                "window": self.window,
                "shards": self.shards,
                "bg_compact": self.bg_compact,
                "last_compactor_error": self.last_compactor_error,
                # delta-compaction state [ISSUE 5]
                "delta_compact": self._delta,
                "delta_runs": (self._pos.delta_minors
                               + self._neg.delta_minors),
                "delta_events": (len(self._pos.delta_run)
                                 + len(self._neg.delta_run)),
                "tombstones": (len(self._pos.tomb_run)
                               + len(self._neg.tomb_run)
                               + len(self._pos.tomb)
                               + len(self._neg.tomb)),
                "n_major_merges": self.n_major_merges,
                "last_major_merge_error": self.last_major_merge_error,
            }
