"""Async micro-batched request path for the streaming estimators.

Request threads submit small insert/score/query requests; a single
batcher thread drains the bounded queue, coalesces CONSECUTIVE
same-kind requests (order between kinds is preserved, so a query
issued after an insert observes it) and dispatches them as one padded
size-bucketed call — the index's jitted searchsorted/compaction path —
so per-request Python/dispatch overhead is paid once per micro-batch
and the hot path stays inside XLA.

Batching policy: the batcher blocks for the first request, then drains
whatever else arrives within ``flush_timeout_s`` up to ``max_batch``
(flush-on-timeout). Backpressure is explicit at enqueue time:

  * "reject"      — a full queue fails the submit with
                    BackpressureError (the caller sees it immediately;
                    load shedding at the edge).
  * "drop_oldest" — the oldest queued request is failed with
                    BackpressureError and the new one admitted
                    (freshness over completeness).
  * "block"       — the submitting thread waits for capacity
                    (backpressure propagates upstream).

Observability: every engine owns a ``MetricsRegistry`` (no process
globals) with request/batch counters and latency / batch-fill /
queue-depth histograms; ``stats()`` snapshots everything plus the
index/streaming state in one JSON-able dict.

Lifecycle hardening [ISSUE 3]: the batcher worker runs under a
supervisor that restarts it if it dies (``batcher_restarts``);
``close()`` drains the queue and fails unapplied requests — including
producers blocked by the "block" policy — with a typed
``EngineClosedError`` instead of deadlocking; per-request deadlines
(``ServingConfig.deadline_s``) fail stale requests at dispatch with
``DeadlineExceededError``; and insert payloads are validated at the
edge — NaN/inf scores or shape mismatches raise ``PoisonEventError``
(counted in ``poison_rejects``) before they can reach the exact index.
Crash-safe recovery (``ServingConfig.snapshot_dir`` / ``recover``)
write-ahead-logs every admitted insert and snapshots index+reservoir
state periodically (``serving/recovery.py``).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Tuple

import numpy as np

from tuplewise_tpu.serving.index import ExactAucIndex
from tuplewise_tpu.serving.streaming import StreamingIncompleteU
from tuplewise_tpu.utils.profiling import MetricsRegistry

_KINDS = ("insert", "score", "query")


class BackpressureError(RuntimeError):
    """The request was shed by the engine's backpressure policy."""


class EngineClosedError(RuntimeError):
    """The engine shut down before (or while) the request was applied —
    the typed outcome every queued/blocked producer sees at close()
    instead of a hang. [ISSUE 3]"""


class PoisonEventError(ValueError):
    """An insert payload failed edge validation (NaN/inf score, shape
    mismatch) and was rejected before reaching the index. [ISSUE 3]"""


class DeadlineExceededError(RuntimeError):
    """The request aged past ``ServingConfig.deadline_s`` in the queue
    and was failed at dispatch rather than served stale. [ISSUE 3]"""


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Knobs of the online service (defaults favor throughput)."""

    kernel: str = "auc"
    budget: int = 64               # incomplete-U pairs per arrival
    reservoir: int = 4096          # per-class reservoir capacity
    design: str = "swr"            # partner sampling design
    window: Optional[int] = None   # sliding window (arrivals); None = all
    compact_every: int = 512       # index buffer size triggering compaction
    engine: str = "jax"            # index count/compaction engine
    mesh_shards: Optional[int] = None  # shard index base runs over a mesh
    bg_compact: bool = False       # compact on a side thread (no sort pause)
    # delta compaction [ISSUE 5] (sharded index only): > 0 ships O(b)
    # delta runs per minor compaction and folds them back on-mesh once
    # they exceed this fraction of the base; 0 = PR 2 host-merge path
    delta_fraction: float = 0.25
    max_delta_runs: int = 64       # fold after this many minors merged
    max_batch: int = 256           # micro-batch size cap
    flush_timeout_s: float = 0.002  # batcher drain window
    queue_size: int = 1024         # bounded request queue
    policy: str = "reject"         # reject | drop_oldest | block
    deadline_s: Optional[float] = None  # fail requests older than this
    snapshot_dir: Optional[str] = None  # crash-safe snapshots + event WAL
    snapshot_every: int = 4096     # events between snapshots
    recover: bool = False          # restore snapshot_dir state on start
    # WAL durability [ISSUE 4 satellite]: "snapshot" (default) flushes
    # every append past the process boundary (survives SIGKILL) and
    # fsyncs only when a snapshot lands — a power loss can drop the
    # tail since the last snapshot; "batch" fsyncs every append,
    # closing that window at per-batch fsync latency (DESIGN §9).
    wal_fsync: str = "snapshot"
    seed: int = 0

    def __post_init__(self):
        if self.policy not in ("reject", "drop_oldest", "block"):
            raise ValueError(f"unknown backpressure policy {self.policy!r}")
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1: {self.max_batch}")
        if self.queue_size < 1:
            raise ValueError(f"queue_size must be >= 1: {self.queue_size}")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0: {self.deadline_s}")
        if self.snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1: {self.snapshot_every}")
        if self.delta_fraction < 0:
            raise ValueError(
                f"delta_fraction must be >= 0: {self.delta_fraction}")
        if self.max_delta_runs < 1:
            raise ValueError(
                f"max_delta_runs must be >= 1: {self.max_delta_runs}")
        if self.recover and not self.snapshot_dir:
            raise ValueError("recover=True needs snapshot_dir")
        if self.wal_fsync not in ("snapshot", "batch"):
            raise ValueError(
                f"wal_fsync must be 'snapshot' or 'batch': "
                f"{self.wal_fsync!r}")


class _Request:
    __slots__ = ("kind", "scores", "labels", "future", "t_enqueue")

    def __init__(self, kind: str, scores, labels):
        self.kind = kind
        self.scores = scores
        self.labels = labels
        self.future: Future = Future()
        self.t_enqueue = time.perf_counter()


class MicroBatchEngine:
    """Bounded-queue dynamic batcher over the streaming estimators.

    Use as a context manager (or call ``close()``): a worker thread is
    running between construction and close.
    """

    def __init__(self, config: Optional[ServingConfig] = None,
                 chaos=None, **overrides):
        if config is None:
            config = ServingConfig(**overrides)
        elif overrides:
            config = dataclasses.replace(config, **overrides)
        self.config = config
        self.chaos = chaos
        self.metrics = MetricsRegistry()
        # the index records compactions_total / compaction_pause_s into
        # the engine's registry, so stats() carries the pause histogram
        self.index = ExactAucIndex(
            window=config.window, compact_every=config.compact_every,
            engine=config.engine, shards=config.mesh_shards,
            bg_compact=config.bg_compact, metrics=self.metrics,
            chaos=chaos, delta_fraction=config.delta_fraction,
            max_delta_runs=config.max_delta_runs,
        ) if config.kernel == "auc" else None
        self.streaming = StreamingIncompleteU(
            kernel=config.kernel, budget=config.budget,
            reservoir=config.reservoir, design=config.design,
            seed=config.seed,
        )
        m = self.metrics
        self._c_req = {k: m.counter(f"requests_{k}_total") for k in _KINDS}
        self._c_rejected = m.counter("rejected_total")
        self._c_dropped = m.counter("dropped_total")
        self._c_batches = m.counter("batches_total")
        self._c_events = m.counter("events_total")
        self._c_pairs = m.counter("incomplete_pairs_total")
        self._c_poison = m.counter("poison_rejects")
        self._c_deadline = m.counter("deadline_expired_total")
        self._c_batcher_restarts = m.counter("batcher_restarts")
        self._h_latency = m.histogram("request_latency_s")
        # per-event insert latency (enqueue -> applied), the number the
        # compaction-pause work is judged by in bench.py --streaming
        self._h_insert_lat = m.histogram("insert_latency_s")
        self._h_fill = m.histogram(
            "batch_fill", buckets=[i / 16 for i in range(1, 17)])
        self._h_depth = m.histogram(
            "queue_depth", buckets=[1, 2, 4, 8, 16, 32, 64, 128, 256,
                                    512, 1024, 2048])
        self._q: "queue.Queue[Optional[_Request]]" = queue.Queue(
            maxsize=config.queue_size)
        self._lock = threading.Lock()   # guards estimator state
        self._closed = False
        # crash-safe recovery [ISSUE 3]: restore BEFORE the worker
        # starts, so recovered state is in place for the first request
        self._recovery = None
        if config.snapshot_dir:
            from tuplewise_tpu.serving.recovery import RecoveryManager

            self._recovery = RecoveryManager(
                config.snapshot_dir, snapshot_every=config.snapshot_every,
                wal_fsync=config.wal_fsync)
            if config.recover:
                self._recovery.recover(self)
            else:
                self._recovery.start_fresh()
        self._worker = threading.Thread(
            target=self._supervise, name="tuplewise-batcher", daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------ #
    # request side                                                       #
    # ------------------------------------------------------------------ #
    def submit(self, kind: str, scores=None, labels=None) -> Future:
        """Enqueue one request; returns its Future.

        insert: scores + labels (scalars or arrays) — resolves to the
          number of events inserted.
        score: scores — resolves to fractional ranks vs negatives.
        query: no payload — resolves to a state snapshot dict.
        """
        if kind not in _KINDS:
            raise ValueError(f"unknown request kind {kind!r}")
        if self._closed:
            raise EngineClosedError("engine is closed")
        if kind == "insert":
            scores, labels = self._validate_insert(scores, labels)
        elif kind == "score":
            scores = np.atleast_1d(np.asarray(scores, dtype=np.float64))
        req = _Request(kind, scores, labels)
        self._c_req[kind].inc()
        policy = self.config.policy
        if policy == "block":
            self._q.put(req)
            if self._closed:
                # close() raced our enqueue: its drain may already have
                # run, so drain (and fail) ourselves — nothing may be
                # left to dangle in a queue no worker will ever read
                self._fail_queued()
        else:
            try:
                self._q.put_nowait(req)
            except queue.Full:
                if policy == "reject":
                    self._c_rejected.inc()
                    raise BackpressureError(
                        f"queue full ({self.config.queue_size}); request "
                        "rejected") from None
                # drop_oldest: shed the stalest queued request
                try:
                    old = self._q.get_nowait()
                    if old is not None:
                        self._c_dropped.inc()
                        old.future.set_exception(BackpressureError(
                            "dropped by a newer request (drop_oldest)"))
                except queue.Empty:
                    pass
                self._q.put(req)
        return req.future

    def _validate_insert(self, scores, labels):
        """Edge validation [ISSUE 3]: poison events — NaN/inf scores,
        non-finite labels, shape mismatches — must fail the SUBMITTER
        (typed, counted) rather than ride a micro-batch into the index
        and fail every coalesced neighbor."""
        scores = np.atleast_1d(np.asarray(scores, dtype=np.float64))
        labels = np.atleast_1d(np.asarray(labels))
        if scores.shape != labels.shape:
            self._c_poison.inc()
            raise PoisonEventError(
                f"insert: scores/labels shape mismatch: {scores.shape} "
                f"vs {labels.shape}")
        if len(scores) and not np.all(np.isfinite(scores)):
            self._c_poison.inc()
            raise PoisonEventError("insert: non-finite score(s) rejected")
        if labels.dtype.kind == "f" and len(labels) \
                and not np.all(np.isfinite(labels)):
            self._c_poison.inc()
            raise PoisonEventError("insert: non-finite label(s) rejected")
        return scores, labels

    def insert(self, scores, labels) -> Future:
        return self.submit("insert", scores, labels)

    def score(self, scores) -> Future:
        return self.submit("score", scores)

    def query(self) -> Future:
        return self.submit("query")

    def flush(self, timeout: Optional[float] = 30.0) -> dict:
        """Barrier: wait until everything enqueued so far is applied."""
        return self.submit("query").result(timeout=timeout)

    # ------------------------------------------------------------------ #
    # batcher side                                                       #
    # ------------------------------------------------------------------ #
    def _supervise(self) -> None:
        """Batcher supervisor [ISSUE 3]: an unexpected escape from the
        worker loop (chaos fault, estimator bug) must not leave every
        future — and every "block"-policy producer — hanging on a dead
        thread. Restart the loop in place and count it; on close, just
        exit (close() drains)."""
        while True:
            try:
                self._run()
                return
            except BaseException:
                if self._closed:
                    return
                self._c_batcher_restarts.inc()

    def _run(self) -> None:
        while True:
            if self.chaos is not None:
                # fired between batches: no futures are in flight here,
                # so an injected crash exercises the supervisor restart
                # without stranding requests
                self.chaos.fire("batcher")
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._closed:
                    return
                continue
            if first is None or self._closed:
                self._fail_queued(first)
                return
            self._h_depth.observe(self._q.qsize() + 1)
            batch = [first]
            deadline = time.perf_counter() + self.config.flush_timeout_s
            while len(batch) < self.config.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    self._dispatch(batch)
                    self._fail_queued()
                    return
                batch.append(nxt)
            self._dispatch(batch)

    def _fail_queued(self, first: Optional[_Request] = None) -> None:
        """Drain the queue, failing every unapplied request with
        EngineClosedError. Draining is what UNBLOCKS producers stuck in
        a full-queue put under the "block" policy — their requests then
        land here (or in close()'s final drain / their own post-put
        check) and fail typed instead of hanging."""
        exc = EngineClosedError(
            "engine closed before the request was applied")
        r = first
        while True:
            if r is not None and not r.future.done():
                r.future.set_exception(exc)
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                return

    def _dispatch(self, batch: List[_Request]) -> None:
        if self.config.deadline_s is not None:
            batch = self._expire(batch)
            if not batch:
                return
        self._c_batches.inc()
        self._h_fill.observe(len(batch) / self.config.max_batch)
        for kind, run in self._runs(batch):
            try:
                if kind == "insert":
                    self._apply_inserts(run)
                elif kind == "score":
                    self._apply_scores(run)
                else:
                    snap = self.stats()
                    for r in run:
                        r.future.set_result(snap)
            except Exception as e:      # fail the run, keep serving
                for r in run:
                    if not r.future.done():
                        r.future.set_exception(e)
            now = time.perf_counter()
            for r in run:
                self._h_latency.observe(now - r.t_enqueue)
                if kind == "insert":
                    self._h_insert_lat.observe(now - r.t_enqueue)

    def _expire(self, batch: List[_Request]) -> List[_Request]:
        """Deadline enforcement at dispatch [ISSUE 3]: a request that
        aged past ``deadline_s`` in the queue fails typed — serving it
        would return a stale answer late AND delay everything behind
        it."""
        now = time.perf_counter()
        live: List[_Request] = []
        for r in batch:
            if now - r.t_enqueue > self.config.deadline_s:
                self._c_deadline.inc()
                if not r.future.done():
                    r.future.set_exception(DeadlineExceededError(
                        f"request expired after {now - r.t_enqueue:.3f}s "
                        f"in queue (deadline_s="
                        f"{self.config.deadline_s})"))
            else:
                live.append(r)
        return live

    @staticmethod
    def _runs(batch: List[_Request]) -> List[Tuple[str, List[_Request]]]:
        """Split a batch into maximal consecutive same-kind runs —
        coalescing without reordering across kinds."""
        runs: List[Tuple[str, List[_Request]]] = []
        for r in batch:
            if runs and runs[-1][0] == r.kind:
                runs[-1][1].append(r)
            else:
                runs.append((r.kind, [r]))
        return runs

    def _apply_inserts(self, run: List[_Request]) -> None:
        scores = np.concatenate([r.scores for r in run])
        labels = np.concatenate([r.labels for r in run]).astype(bool)
        with self._lock:
            if self._recovery is not None:
                # write-ahead: the WAL records the batch BEFORE it is
                # applied, so a crash mid-apply replays it on recovery
                # (an admitted event is never lost)
                self._recovery.record(scores, labels)
            if self.index is not None:
                self.index.insert_batch(scores, labels)
            spent = self.streaming.extend(scores, labels)
            if self._recovery is not None:
                self._recovery.maybe_snapshot(self)
        self._c_events.inc(len(scores))
        self._c_pairs.inc(spent)
        for r in run:
            r.future.set_result(len(r.scores))

    def _apply_scores(self, run: List[_Request]) -> None:
        if self.index is None:
            raise ValueError(
                "score requests need the exact AUC index "
                "(kernel='auc')")
        scores = np.concatenate([r.scores for r in run])
        with self._lock:
            ranks = self.index.score_batch(scores)
        off = 0
        for r in run:
            n = len(r.scores)
            r.future.set_result(ranks[off:off + n])
            off += n

    # ------------------------------------------------------------------ #
    def stats(self) -> dict:
        with self._lock:
            out = {
                "metrics": self.metrics.snapshot(),
                "streaming": self.streaming.state(),
            }
            if self.index is not None:
                out["index"] = self.index.state()
                out["auc_exact"] = self.index.auc()
            out["estimate_incomplete"] = self.streaming.estimate()
        return out

    def close(self, timeout: float = 10.0) -> None:
        """Shut down without stranding anyone [ISSUE 3]: the worker
        drains the queue (which unblocks "block"-policy producers
        waiting for capacity) and every unapplied request fails with
        ``EngineClosedError``; a final drain here catches requests that
        raced the shutdown. Never blocks on a full queue — the old
        sentinel put could deadlock close() itself."""
        if self._closed:
            return
        self._closed = True
        try:
            self._q.put_nowait(None)    # wake the worker fast; the
        except queue.Full:              # 0.05 s poll catches it anyway
            pass
        self._worker.join(timeout=timeout)
        self._fail_queued()
        if self._recovery is not None:
            self._recovery.checkpoint_and_close(self)
        if self.index is not None:
            self.index.close(timeout=timeout)

    def __enter__(self) -> "MicroBatchEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
