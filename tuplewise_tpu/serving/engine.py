"""Async micro-batched request path for the streaming estimators.

Request threads submit small insert/score/query requests; a single
batcher thread drains the bounded queue, coalesces CONSECUTIVE
same-kind requests (order between kinds is preserved, so a query
issued after an insert observes it) and dispatches them as one padded
size-bucketed call — the index's jitted searchsorted/compaction path —
so per-request Python/dispatch overhead is paid once per micro-batch
and the hot path stays inside XLA.

Batching policy: the batcher blocks for the first request, then drains
whatever else arrives within ``flush_timeout_s`` up to ``max_batch``
(flush-on-timeout). Backpressure is explicit at enqueue time:

  * "reject"      — a full queue fails the submit with
                    BackpressureError (the caller sees it immediately;
                    load shedding at the edge).
  * "drop_oldest" — the oldest queued request is failed with
                    BackpressureError and the new one admitted
                    (freshness over completeness).
  * "block"       — the submitting thread waits for capacity
                    (backpressure propagates upstream).

Observability [ISSUE 6]: every engine owns a ``MetricsRegistry`` (no
process globals) with request/batch counters, latency / batch-fill /
queue-depth histograms, live gauges (queue depth, inflight requests),
and **per-stage insert-latency attribution**: the apply path records
consecutive boundary timestamps (queue_wait → coalesce → wal_append →
index_insert → stream_extend → snapshot → resolve), so each request's
stage values sum exactly to its measured insert latency — the exit
summary and replay records report p99 per stage. ``stats()`` snapshots
everything plus the index/streaming state in one JSON-able dict.

A ``tracer=`` (``obs.tracing.Tracer``) threads trace context through
the full request path: submit opens a per-request root span, the
batcher parents its apply span to the coalesced run's first request,
and the stage intervals land as child spans — exportable as Chrome
trace JSON so perfetto renders the serving timeline. Off by default:
``tracer=None`` costs one ``is not None`` check per hook.

Every engine also owns a ``FlightRecorder`` — a bounded ring of
lifecycle events (poison rejects, deadline expiries, batcher restarts,
compactions, heals, snapshot seals, chaos injections) with trace-id
correlation, auto-dumped next to the recovery snapshots on close /
crash so post-SIGKILL forensics see what the process was doing.

Lifecycle hardening [ISSUE 3]: the batcher worker runs under a
supervisor that restarts it if it dies (``batcher_restarts``);
``close()`` drains the queue and fails unapplied requests — including
producers blocked by the "block" policy — with a typed
``EngineClosedError`` instead of deadlocking; per-request deadlines
(``ServingConfig.deadline_s``) fail stale requests at dispatch with
``DeadlineExceededError``; and insert payloads are validated at the
edge — NaN/inf scores or shape mismatches raise ``PoisonEventError``
(counted in ``poison_rejects``) before they can reach the exact index.
Crash-safe recovery (``ServingConfig.snapshot_dir`` / ``recover``)
write-ahead-logs every admitted insert and snapshots index+reservoir
state periodically (``serving/recovery.py``).
"""

from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Tuple

import numpy as np

from tuplewise_tpu.obs.flight import FlightRecorder
from tuplewise_tpu.obs.ledger import WaveLedger
from tuplewise_tpu.obs.report import INSERT_STAGES, stage_metric
from tuplewise_tpu.obs.tracing import maybe_span
from tuplewise_tpu.serving.index import ExactAucIndex
from tuplewise_tpu.serving.streaming import StreamingIncompleteU
from tuplewise_tpu.utils.profiling import MetricsRegistry

_KINDS = ("insert", "score", "query")


class BackpressureError(RuntimeError):
    """The request was shed by the engine's backpressure policy."""


class EngineClosedError(RuntimeError):
    """The engine shut down before (or while) the request was applied —
    the typed outcome every queued/blocked producer sees at close()
    instead of a hang. [ISSUE 3]

    ``tenant`` carries the request's tenant id when one was tagged
    [ISSUE 8 bugfix]: a fleet shutdown must tell each caller WHOSE
    request died — a generic closed error loses the attribution the
    multi-tenant retry/alerting path routes on."""

    def __init__(self, msg: str, tenant: Optional[str] = None):
        super().__init__(msg)
        self.tenant = tenant


class PoisonEventError(ValueError):
    """An insert payload failed edge validation (NaN/inf score, shape
    mismatch) and was rejected before reaching the index. [ISSUE 3]"""


class DeadlineExceededError(RuntimeError):
    """The request aged past ``ServingConfig.deadline_s`` in the queue
    and was failed at dispatch rather than served stale. [ISSUE 3]"""


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Knobs of the online service (defaults favor throughput)."""

    kernel: str = "auc"
    budget: int = 64               # incomplete-U pairs per arrival
    reservoir: int = 4096          # per-class reservoir capacity
    design: str = "swr"            # partner sampling design
    window: Optional[int] = None   # sliding window (arrivals); None = all
    compact_every: int = 512       # index buffer size triggering compaction
    engine: str = "jax"            # index count/compaction engine
    mesh_shards: Optional[int] = None  # shard index base runs over a mesh
    bg_compact: bool = False       # compact on a side thread (no sort pause)
    # delta compaction [ISSUE 5] (sharded index only): > 0 ships O(b)
    # delta runs per minor compaction and folds them back on-mesh once
    # they exceed this fraction of the base; 0 = PR 2 host-merge path
    delta_fraction: float = 0.25
    max_delta_runs: int = 64       # fold after this many minors merged
    # Pallas-fused serving counts [ISSUE 10]: run the count hot loop
    # (searchsorted rank of base + delta runs − tombstone multiset) as
    # ONE ops.pallas_counts invocation per device per micro-batch.
    # Opt-in (default off); TUPLEWISE_SERVING_PALLAS=interpret|off
    # overrides; automatic fallback to the XLA path on unsupported
    # geometry or Mosaic failure. Integer counts, so kernel-vs-XLA
    # results are bit-identical.
    count_kernel: bool = False
    max_batch: int = 256           # micro-batch size cap
    flush_timeout_s: float = 0.002  # batcher drain window
    queue_size: int = 1024         # bounded request queue
    policy: str = "reject"         # reject | drop_oldest | block
    deadline_s: Optional[float] = None  # fail requests older than this
    snapshot_dir: Optional[str] = None  # crash-safe snapshots + event WAL
    snapshot_every: int = 4096     # events between snapshots
    recover: bool = False          # restore snapshot_dir state on start
    # WAL durability [ISSUE 4 satellite]: "snapshot" (default) flushes
    # every append past the process boundary (survives SIGKILL) and
    # fsyncs only when a snapshot lands — a power loss can drop the
    # tail since the last snapshot; "batch" fsyncs every append,
    # closing that window at per-batch fsync latency (DESIGN §9).
    wal_fsync: str = "snapshot"
    # flight recorder [ISSUE 6]: lifecycle-event ring size; the dump
    # lands next to the recovery snapshots when snapshot_dir is set
    flight_recorder_size: int = 4096
    # statistical health [ISSUE 7]: CI-width tracking of the streaming
    # estimate (obs.health.EstimateHealth gauges) and a windowed drift
    # check of the live incomplete estimate against the exact oracle
    # prefix (AUC kernel only). Cheap enough to default ON — one
    # Welford merge per kernel batch, one deque append per micro-batch.
    health: bool = True
    drift_window: int = 256        # micro-batches in the drift window
    drift_threshold: float = 0.05  # rolling |live - oracle| that alerts
    # tail exemplars [ISSUE 14]: an insert whose measured latency
    # lands at or above this threshold auto-captures its full host-tax
    # ledger + trace id as a `tail_exemplar` flight event, so p99
    # forensics read one dump. None = never capture.
    tail_exemplar_ms: Optional[float] = None
    seed: int = 0

    def __post_init__(self):
        if self.policy not in ("reject", "drop_oldest", "block"):
            raise ValueError(f"unknown backpressure policy {self.policy!r}")
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1: {self.max_batch}")
        if self.queue_size < 1:
            raise ValueError(f"queue_size must be >= 1: {self.queue_size}")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0: {self.deadline_s}")
        if self.snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1: {self.snapshot_every}")
        if self.delta_fraction < 0:
            raise ValueError(
                f"delta_fraction must be >= 0: {self.delta_fraction}")
        if self.max_delta_runs < 1:
            raise ValueError(
                f"max_delta_runs must be >= 1: {self.max_delta_runs}")
        if self.recover and not self.snapshot_dir:
            raise ValueError("recover=True needs snapshot_dir")
        if self.wal_fsync not in ("snapshot", "batch"):
            raise ValueError(
                f"wal_fsync must be 'snapshot' or 'batch': "
                f"{self.wal_fsync!r}")
        if self.flight_recorder_size < 1:
            raise ValueError(
                f"flight_recorder_size must be >= 1: "
                f"{self.flight_recorder_size}")
        if self.drift_window < 1:
            raise ValueError(
                f"drift_window must be >= 1: {self.drift_window}")
        if self.drift_threshold <= 0:
            raise ValueError(
                f"drift_threshold must be > 0: {self.drift_threshold}")
        if self.tail_exemplar_ms is not None and self.tail_exemplar_ms <= 0:
            raise ValueError(
                f"tail_exemplar_ms must be > 0: {self.tail_exemplar_ms}")


class _Request:
    __slots__ = ("kind", "scores", "labels", "future", "t_enqueue",
                 "span", "tenant")

    def __init__(self, kind: str, scores, labels, span=None,
                 tenant=None):
        self.kind = kind
        self.scores = scores
        self.labels = labels
        self.future: Future = Future()
        self.t_enqueue = time.perf_counter()
        # per-request trace root [ISSUE 6]; None when tracing is off
        self.span = span
        # optional tenant tag [ISSUE 8]: carried so failure paths can
        # attribute the loss to the owning tenant
        self.tenant = tenant


class MicroBatchEngine:
    """Bounded-queue dynamic batcher over the streaming estimators.

    Use as a context manager (or call ``close()``): a worker thread is
    running between construction and close.
    """

    def __init__(self, config: Optional[ServingConfig] = None,
                 chaos=None, tracer=None, **overrides):
        if config is None:
            config = ServingConfig(**overrides)
        elif overrides:
            config = dataclasses.replace(config, **overrides)
        self.config = config
        self.chaos = chaos
        self.tracer = tracer
        self.metrics = MetricsRegistry()
        # flight recorder [ISSUE 6]: lifecycle events with trace-id
        # correlation; when recovery is configured the auto-dump lands
        # NEXT TO the snapshots, so post-SIGKILL forensics start from
        # one directory
        self.flight = FlightRecorder(
            capacity=config.flight_recorder_size, tracer=tracer,
            dump_path=(os.path.join(config.snapshot_dir, "flight.jsonl")
                       if config.snapshot_dir else None))
        if chaos is not None:
            # every injected fault logs a correlated flight event
            chaos.attach(flight=self.flight, tracer=tracer)
        # the index records compactions_total / compaction_pause_s into
        # the engine's registry, so stats() carries the pause histogram
        self.index = ExactAucIndex(
            window=config.window, compact_every=config.compact_every,
            engine=config.engine, shards=config.mesh_shards,
            bg_compact=config.bg_compact, metrics=self.metrics,
            chaos=chaos, delta_fraction=config.delta_fraction,
            max_delta_runs=config.max_delta_runs,
            count_kernel=config.count_kernel,
            tracer=tracer, flight=self.flight,
        ) if config.kernel == "auc" else None
        # statistical health [ISSUE 7]: the CI-width monitor is fed by
        # the streaming estimator itself (every kernel-term batch); the
        # drift detector is fed per micro-batch below. Both export live
        # gauges into this registry, so the SLO layer and the flusher's
        # JSONL see estimation health next to latency.
        self._est_health = self._drift = None
        if config.health:
            from tuplewise_tpu.obs.health import (
                DriftDetector, EstimateHealth,
            )

            self._est_health = EstimateHealth(metrics=self.metrics)
            self._drift = DriftDetector(
                window=config.drift_window,
                threshold=config.drift_threshold,
                metrics=self.metrics, flight=self.flight)
        self.streaming = StreamingIncompleteU(
            kernel=config.kernel, budget=config.budget,
            reservoir=config.reservoir, design=config.design,
            seed=config.seed, health=self._est_health,
        )
        m = self.metrics
        self._c_req = {k: m.counter(f"requests_{k}_total") for k in _KINDS}
        self._c_rejected = m.counter("rejected_total")
        self._c_dropped = m.counter("dropped_total")
        self._c_batches = m.counter("batches_total")
        self._c_events = m.counter("events_total")
        self._c_pairs = m.counter("incomplete_pairs_total")
        self._c_poison = m.counter("poison_rejects")
        self._c_deadline = m.counter("deadline_expired_total")
        self._c_batcher_restarts = m.counter("batcher_restarts")
        self._h_latency = m.histogram("request_latency_s")
        # per-event insert latency (enqueue -> applied), the number the
        # compaction-pause work is judged by in bench.py --streaming
        self._h_insert_lat = m.histogram("insert_latency_s")
        self._h_fill = m.histogram(
            "batch_fill", buckets=[i / 16 for i in range(1, 17)])
        self._h_depth = m.histogram(
            "queue_depth", buckets=[1, 2, 4, 8, 16, 32, 64, 128, 256,
                                    512, 1024, 2048])
        # insert-latency stage attribution [ISSUE 6]: consecutive
        # boundary timestamps of the apply path; one request's stage
        # values sum exactly to its measured insert latency
        self._h_stage = {s: m.histogram(stage_metric(s))
                         for s in INSERT_STAGES}
        # host-tax wave ledger [ISSUE 14]: the below-stage-level
        # decomposition (host Python vs dispatch vs device compute vs
        # compile vs GC vs lock/queue wait) whose bucket sums tile the
        # measured insert latency exactly
        self.ledger = WaveLedger(m)
        self._c_exemplars = m.counter("tail_exemplars_total")
        # live gauges [ISSUE 6 satellite]: the current reading, not the
        # cumulative history — what the MetricsFlusher streams out
        self._g_depth = m.gauge("queue_depth_live")
        self._g_inflight = m.gauge("inflight_requests")
        self._q: "queue.Queue[Optional[_Request]]" = queue.Queue(
            maxsize=config.queue_size)
        self._lock = threading.Lock()   # guards estimator state
        self._closed = False
        # crash-safe recovery [ISSUE 3]: restore BEFORE the worker
        # starts, so recovered state is in place for the first request
        self._recovery = None
        if config.snapshot_dir:
            from tuplewise_tpu.serving.recovery import RecoveryManager

            self._recovery = RecoveryManager(
                config.snapshot_dir, snapshot_every=config.snapshot_every,
                wal_fsync=config.wal_fsync, tracer=tracer,
                flight=self.flight)
            if config.recover:
                self._recovery.recover(self)
            else:
                self._recovery.start_fresh()
        self._worker = threading.Thread(
            target=self._supervise, name="tuplewise-batcher", daemon=True)
        self._worker.start()
        # deadline reaper [ISSUE 11 bugfix]: dispatch-time expiry
        # (PR 3) only runs when the batcher dispatches — a wedged
        # batcher (stuck apply, crash-restart loop) or an idle one lets
        # stale "block"-policy requests rot past their deadline with
        # their producers still blocked. A timer scans the queue and
        # fails over-deadline requests typed, whoever gets there first.
        self._reaper = None
        if config.deadline_s is not None:
            self._reaper = threading.Thread(
                target=self._reap_expired, name="tuplewise-reaper",
                daemon=True)
            self._reaper.start()

    # ------------------------------------------------------------------ #
    # request side                                                       #
    # ------------------------------------------------------------------ #
    def submit(self, kind: str, scores=None, labels=None,
               tenant=None) -> Future:
        """Enqueue one request; returns its Future.

        insert: scores + labels (scalars or arrays) — resolves to the
          number of events inserted.
        score: scores — resolves to fractional ranks vs negatives.
        query: no payload — resolves to a state snapshot dict.
        tenant: optional tag carried through the request lifecycle;
          failure paths (close, deadline) attribute the loss to it
          [ISSUE 8].
        """
        if kind not in _KINDS:
            raise ValueError(f"unknown request kind {kind!r}")
        if self._closed:
            raise EngineClosedError("engine is closed", tenant=tenant)
        if kind == "insert":
            scores, labels = self._validate_insert(scores, labels)
        elif kind == "score":
            scores = np.atleast_1d(np.asarray(scores, dtype=np.float64))
        # trace context is born HERE [ISSUE 6]: one root span per
        # request, handed through the queue so the batcher's apply
        # spans continue this trace on its own thread
        span = None
        if self.tracer is not None:
            span = self.tracer.start(f"request.{kind}", parent=None)
        req = _Request(kind, scores, labels, span=span, tenant=tenant)
        if span is not None:
            # anchor the root to t_enqueue, the same reading every
            # stage boundary measures from — child stage spans then
            # tile the root EXACTLY (the >= 95% smoke is really == 100%)
            span.t0 = req.t_enqueue
        self._c_req[kind].inc()
        policy = self.config.policy
        if policy == "block":
            self._q.put(req)
            if self._closed:
                # close() raced our enqueue: its drain may already have
                # run, so drain (and fail) ourselves — nothing may be
                # left to dangle in a queue no worker will ever read
                self._fail_queued()
        else:
            try:
                self._q.put_nowait(req)
            except queue.Full:
                if policy == "reject":
                    self._c_rejected.inc()
                    raise BackpressureError(
                        f"queue full ({self.config.queue_size}); request "
                        "rejected") from None
                # drop_oldest: shed the stalest queued request. The
                # done() guard arbitrates against the deadline reaper
                # [ISSUE 15]: the reaper fails queued requests WITHOUT
                # dequeuing them, so the one we just popped may
                # already hold its typed expiry — set_exception again
                # would raise InvalidStateError on the submit path.
                try:
                    old = self._q.get_nowait()
                    if old is not None and not old.future.done():
                        self._c_dropped.inc()
                        old.future.set_exception(BackpressureError(
                            "dropped by a newer request (drop_oldest)"))
                except queue.Empty:
                    pass
                self._q.put(req)
        return req.future

    def _poison(self, msg: str) -> None:
        """Count + flight-record + raise one poison rejection."""
        self._c_poison.inc()
        self.flight.record("poison_reject", reason=msg)
        raise PoisonEventError(msg)

    def _validate_insert(self, scores, labels):
        """Edge validation [ISSUE 3]: poison events — NaN/inf scores,
        non-finite labels, shape mismatches — must fail the SUBMITTER
        (typed, counted) rather than ride a micro-batch into the index
        and fail every coalesced neighbor."""
        scores = np.atleast_1d(np.asarray(scores, dtype=np.float64))
        labels = np.atleast_1d(np.asarray(labels))
        if scores.shape != labels.shape:
            self._poison(
                f"insert: scores/labels shape mismatch: {scores.shape} "
                f"vs {labels.shape}")
        if len(scores) and not np.all(np.isfinite(scores)):
            self._poison("insert: non-finite score(s) rejected")
        if labels.dtype.kind == "f" and len(labels) \
                and not np.all(np.isfinite(labels)):
            self._poison("insert: non-finite label(s) rejected")
        return scores, labels

    def insert(self, scores, labels, tenant=None) -> Future:
        return self.submit("insert", scores, labels, tenant=tenant)

    def score(self, scores, tenant=None) -> Future:
        return self.submit("score", scores, tenant=tenant)

    def query(self, tenant=None) -> Future:
        return self.submit("query", tenant=tenant)

    def flush(self, timeout: Optional[float] = 30.0) -> dict:
        """Barrier: wait until everything enqueued so far is applied."""
        return self.submit("query").result(timeout=timeout)

    # ------------------------------------------------------------------ #
    # batcher side                                                       #
    # ------------------------------------------------------------------ #
    def _supervise(self) -> None:
        """Batcher supervisor [ISSUE 3]: an unexpected escape from the
        worker loop (chaos fault, estimator bug) must not leave every
        future — and every "block"-policy producer — hanging on a dead
        thread. Restart the loop in place and count it; on close, just
        exit (close() drains)."""
        while True:
            try:
                self._run()
                return
            except BaseException as e:
                if self._closed:
                    return
                self._c_batcher_restarts.inc()
                self.flight.record("batcher_restart", error=repr(e))
                self.flight.auto_dump()

    def _run(self) -> None:
        while True:
            if self.chaos is not None:
                # fired between batches: no futures are in flight here,
                # so an injected crash exercises the supervisor restart
                # without stranding requests
                self.chaos.fire("batcher")
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._closed:
                    return
                continue
            if first is None or self._closed:
                self._fail_queued(first)
                return
            # the queue-depth gauge updates HERE, where qsize is being
            # read anyway — never on the submit hot path (qsize takes
            # the queue mutex; a per-submit read would contend with
            # this very drain loop)
            depth = self._q.qsize() + 1
            self._h_depth.observe(depth)
            self._g_depth.set(depth)
            batch = [first]
            deadline = time.perf_counter() + self.config.flush_timeout_s
            while len(batch) < self.config.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    self._dispatch(batch)
                    self._fail_queued()
                    return
                batch.append(nxt)
            self._dispatch(batch)

    def _fail_queued(self, first: Optional[_Request] = None) -> None:
        """Drain the queue, failing every unapplied request with
        EngineClosedError. Draining is what UNBLOCKS producers stuck in
        a full-queue put under the "block" policy — their requests then
        land here (or in close()'s final drain / their own post-put
        check) and fail typed instead of hanging. Tenant-tagged
        requests fail with the tenant id IN the error [ISSUE 8
        bugfix]: before this, a fleet caller multiplexing tenants over
        one engine got an unattributable generic error at shutdown."""
        r = first
        while True:
            if r is not None and not r.future.done():
                if r.tenant is not None:
                    r.future.set_exception(EngineClosedError(
                        "engine closed before the request was applied "
                        f"(tenant={r.tenant})", tenant=r.tenant))
                else:
                    r.future.set_exception(EngineClosedError(
                        "engine closed before the request was applied"))
                if self.tracer is not None and r.span is not None:
                    self.tracer.finish(r.span)
                    r.span = None
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                return

    def _dispatch(self, batch: List[_Request]) -> None:
        if self.config.deadline_s is not None:
            batch = self._expire(batch)
            if not batch:
                return
        self._g_inflight.set(self._q.qsize() + len(batch))
        self._c_batches.inc()
        self._h_fill.observe(len(batch) / self.config.max_batch)
        for kind, run in self._runs(batch):
            try:
                if kind == "insert":
                    self._apply_inserts(run)
                elif kind == "score":
                    self._apply_scores(run)
                else:
                    snap = self.stats()
                    for r in run:
                        if not r.future.done():
                            r.future.set_result(snap)
            except Exception as e:      # fail the run, keep serving
                for r in run:
                    if not r.future.done():
                        r.future.set_exception(e)
            now = time.perf_counter()
            for r in run:
                self._h_latency.observe(now - r.t_enqueue)
                # insert spans/latency are finished inside
                # _apply_inserts at the exact stage-boundary t_end;
                # score/query (and failed-run) spans end here
                if self.tracer is not None and r.span is not None:
                    self.tracer.finish(r.span, now)
                    r.span = None
        self._g_inflight.set(self._q.qsize())

    def _expire_request(self, r: _Request, now: float) -> bool:
        """Fail ONE over-deadline request typed; returns True when this
        caller won the resolution. Idempotent across the dispatch-time
        check and the reaper timer — ``set_exception`` on an
        already-done future loses the race, and only the winner counts
        the expiry [ISSUE 11 bugfix]."""
        try:
            r.future.set_exception(DeadlineExceededError(
                f"request expired after {now - r.t_enqueue:.3f}s "
                f"in queue (deadline_s={self.config.deadline_s})"))
        except Exception:   # noqa: BLE001 — already resolved elsewhere
            return False
        self._c_deadline.inc()
        self.flight.record(
            "deadline_expired", kind_req=r.kind,
            waited_s=now - r.t_enqueue,
            trace_id=(r.span.trace_id if r.span is not None else None))
        if self.tracer is not None and r.span is not None:
            self.tracer.finish(r.span, now)
            r.span = None
        return True

    def _expire(self, batch: List[_Request]) -> List[_Request]:
        """Deadline enforcement at dispatch [ISSUE 3]: a request that
        aged past ``deadline_s`` in the queue fails typed — serving it
        would return a stale answer late AND delay everything behind
        it. Requests the reaper already failed are dropped silently."""
        now = time.perf_counter()
        live: List[_Request] = []
        for r in batch:
            if r.future.done():
                continue    # reaper got it while it sat in the queue
            if now - r.t_enqueue > self.config.deadline_s:
                self._expire_request(r, now)
            else:
                live.append(r)
        return live

    def _reap_expired(self) -> None:
        """Deadline timer [ISSUE 11 bugfix]: periodically scan the
        QUEUED requests (under the queue's own mutex — a snapshot, no
        dequeue) and fail any that aged past ``deadline_s``. The
        dispatch path skips already-done futures, so a request expires
        exactly once no matter who sees it first; a producer blocked on
        a wedged batcher gets its typed failure in bounded time instead
        of rotting."""
        deadline = self.config.deadline_s
        interval = min(max(deadline / 4.0, 0.005), 0.25)
        while not self._closed:
            time.sleep(interval)
            now = time.perf_counter()
            with self._q.mutex:
                stale = [r for r in self._q.queue
                         if r is not None and not r.future.done()
                         and now - r.t_enqueue > deadline]
            for r in stale:
                self._expire_request(r, now)

    @staticmethod
    def _runs(batch: List[_Request]) -> List[Tuple[str, List[_Request]]]:
        """Split a batch into maximal consecutive same-kind runs —
        coalescing without reordering across kinds."""
        runs: List[Tuple[str, List[_Request]]] = []
        for r in batch:
            if runs and runs[-1][0] == r.kind:
                runs[-1][1].append(r)
            else:
                runs.append((r.kind, [r]))
        return runs

    def _apply_inserts(self, run: List[_Request]) -> None:
        # stage boundaries [ISSUE 6]: consecutive perf_counter readings
        # tile each request's [enqueue, resolve] lifetime, so stage
        # values sum EXACTLY to the measured insert latency
        t_start = time.perf_counter()            # queue_wait ends
        # host-tax wave [ISSUE 14]: device sections and GC pauses on
        # this thread now bill to this wave; closed at resolve
        wave = self.ledger.begin_wave()
        try:
            self._apply_inserts_wave(run, t_start, wave)
        finally:
            # the failure path (exception fails the run upstream)
            # must not leave the wave bound to the batcher thread
            self.ledger.abort_wave(wave)

    def _apply_inserts_wave(self, run: List[_Request], t_start: float,
                            wave) -> None:
        scores = np.concatenate([r.scores for r in run])
        labels = np.concatenate([r.labels for r in run]).astype(bool)
        with maybe_span(self.tracer, "insert.apply",
                        parent=run[0].span, n_requests=len(run),
                        n_events=len(scores)):
            t_lock_req = time.perf_counter()     # lock wait begins
            with self._lock:
                t_lock = time.perf_counter()     # coalesce = concat+lock
                if self._recovery is not None:
                    # write-ahead: the WAL records the batch BEFORE it
                    # is applied, so a crash mid-apply replays it on
                    # recovery (an admitted event is never lost)
                    self._recovery.record(scores, labels)
                t_wal = time.perf_counter()
                if self.index is not None:
                    self.index.insert_batch(scores, labels)
                t_index = time.perf_counter()
                spent = self.streaming.extend(scores, labels)
                t_stream = time.perf_counter()
                if self._recovery is not None:
                    self._recovery.maybe_snapshot(self)
                t_snap = time.perf_counter()
        self._c_events.inc(len(scores))
        self._c_pairs.inc(spent)
        for r in run:
            # a request the reaper expired mid-flight already holds its
            # typed failure; the event is applied either way (WAL-first
            # ordering), the future just reports the deadline truthfully
            if not r.future.done():
                r.future.set_result(len(r.scores))
        t_end = time.perf_counter()              # resolve ends
        n = len(run)
        h = self._h_stage
        h["coalesce"].observe_n(t_lock - t_start, n)
        h["wal_append"].observe_n(t_wal - t_lock, n)
        h["index_insert"].observe_n(t_index - t_wal, n)
        h["stream_extend"].observe_n(t_stream - t_index, n)
        h["snapshot"].observe_n(t_snap - t_stream, n)
        h["resolve"].observe_n(t_end - t_snap, n)
        qw = h["queue_wait"]
        queue_waits = []
        for r in run:
            qw_r = t_start - r.t_enqueue
            queue_waits.append(qw_r)
            qw.observe(qw_r)
            self._h_insert_lat.observe(t_end - r.t_enqueue)
        # close the host-tax wave [ISSUE 14]: bucket sums tile each
        # request's [enqueue, resolve] lifetime exactly (host_python
        # is the remainder after lock wait / device sections / GC)
        buckets = self.ledger.finish_wave(
            wave, t_start=t_start, t_end=t_end,
            queue_waits=queue_waits,
            t_lock_req=t_lock_req, t_lock=t_lock)
        th = self.config.tail_exemplar_ms
        if th is not None:
            for r, qw_r in zip(run, queue_waits):
                lat_ms = (t_end - r.t_enqueue) * 1e3
                if lat_ms >= th:
                    # tail exemplar [ISSUE 14]: the full ledger of the
                    # slow request + its trace id, in the flight ring
                    self._c_exemplars.inc()
                    self.flight.record(
                        "tail_exemplar", kind_req="insert",
                        trace_id=(r.span.trace_id
                                  if r.span is not None else None),
                        lat_ms=lat_ms, n_events=len(r.scores),
                        buckets=dict(buckets, queue_wait=qw_r))
        # drift check [ISSUE 7]: live budgeted estimate vs the exact
        # oracle prefix, once per micro-batch, AFTER the latency
        # boundaries — bookkeeping, not request service
        if self._drift is not None and self.index is not None:
            live = self.streaming.estimate()
            oracle = self.index.auc()
            if live is not None and oracle is not None:
                self._drift.observe(live, oracle)
        if self.tracer is not None:
            self._trace_insert_run(
                run, (t_start, t_lock, t_wal, t_index, t_stream,
                      t_snap, t_end))

    def _trace_insert_run(self, run: List[_Request], ts) -> None:
        """Per-request stage spans [ISSUE 6]: every insert's trace gets
        the consecutive stage intervals as children of its root span.
        Because the children tile [enqueue, resolve], per-trace child
        durations sum to the root's duration by construction — the
        property the observability smoke asserts at >= 95%."""
        t_start, t_lock, t_wal, t_index, t_stream, t_snap, t_end = ts
        tr = self.tracer
        bounds = (("coalesce", t_start, t_lock),
                  ("wal_append", t_lock, t_wal),
                  ("index_insert", t_wal, t_index),
                  ("stream_extend", t_index, t_stream),
                  ("snapshot", t_stream, t_snap),
                  ("resolve", t_snap, t_end))
        for r in run:
            if r.span is None:
                continue
            tr.record_span("insert.queue_wait", r.t_enqueue, t_start,
                           parent=r.span)
            for name, a, b in bounds:
                tr.record_span(f"insert.{name}", a, b, parent=r.span)
            tr.finish(r.span, t_end)
            r.span = None

    def _apply_scores(self, run: List[_Request]) -> None:
        if self.index is None:
            raise ValueError(
                "score requests need the exact AUC index "
                "(kernel='auc')")
        scores = np.concatenate([r.scores for r in run])
        with maybe_span(self.tracer, "score.apply",
                        parent=run[0].span, n_requests=len(run)):
            with self._lock:
                ranks = self.index.score_batch(scores)
        off = 0
        for r in run:
            n = len(r.scores)
            if not r.future.done():
                r.future.set_result(ranks[off:off + n])
            off += n

    # ------------------------------------------------------------------ #
    def stats(self) -> dict:
        with self._lock:
            out = {
                "metrics": self.metrics.snapshot(),
                "streaming": self.streaming.state(),
            }
            if self._drift is not None:
                out["drift"] = self._drift.state()
            if self.index is not None:
                out["index"] = self.index.state()
                out["auc_exact"] = self.index.auc()
            out["estimate_incomplete"] = self.streaming.estimate()
        return out

    def close(self, timeout: float = 10.0) -> None:
        """Shut down without stranding anyone [ISSUE 3]: the worker
        drains the queue (which unblocks "block"-policy producers
        waiting for capacity) and every unapplied request fails with
        ``EngineClosedError``; a final drain here catches requests that
        raced the shutdown. Never blocks on a full queue — the old
        sentinel put could deadlock close() itself."""
        if self._closed:
            return
        self._closed = True
        try:
            self._q.put_nowait(None)    # wake the worker fast; the
        except queue.Full:              # 0.05 s poll catches it anyway
            pass
        self._worker.join(timeout=timeout)
        self._fail_queued()
        if self._recovery is not None:
            self._recovery.checkpoint_and_close(self)
        if self.index is not None:
            self.index.close(timeout=timeout)
        # flight forensics [ISSUE 6]: the close dump is the "what was
        # it doing" record the next --recover session reads first
        self.flight.record("engine_closed")
        self.flight.auto_dump()

    def __enter__(self) -> "MicroBatchEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
