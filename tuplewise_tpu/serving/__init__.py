"""L4/L5 serving layer — the batch estimators turned into an online
service [ISSUE 1].

The batch library answers "given arrays X, Y, what is U_n?". Production
traffic is a *stream* of scored events, so this package adds:

* ``index.ExactAucIndex``      — incremental exact AUC: sorted base runs
                                 + a small merge buffer, amortized
                                 O(log n) insert, periodic jitted
                                 compaction, optional sliding-window
                                 eviction. Its estimate after any prefix
                                 equals the batch ``ops.rank_auc`` /
                                 NumPy oracle on that prefix. Base runs
                                 shard over a device mesh (``shards=S``:
                                 per-shard searchsorted + psum'd integer
                                 win counts, bit-identical at every S)
                                 and compaction can run on a side thread
                                 (``bg_compact=True``: double-buffered
                                 base run, atomic swap — no sort pause
                                 on the request path).
* ``streaming.StreamingIncompleteU`` — the paper's incomplete-U knob in
                                 the online regime: a fixed pair budget
                                 B per arrival, spent against
                                 reservoir-held history.
* ``engine.MicroBatchEngine``  — async request path: bounded queue,
                                 dynamic batcher coalescing
                                 insert/score/query requests into
                                 padded size-bucketed jitted calls,
                                 flush-on-timeout, explicit
                                 backpressure (reject / drop-oldest /
                                 block).
* ``replay``                   — replay a synthetic stream through the
                                 engine and report events/s + latency
                                 percentiles (the ``tuplewise replay``
                                 CLI and the northstar ``serve`` stage).
* ``recovery``                 — crash-safe snapshots + event-tail WAL
                                 (``tuplewise serve --recover``), and
                                 the fault-tolerance layer's typed
                                 errors: ``EngineClosedError``,
                                 ``PoisonEventError``,
                                 ``DeadlineExceededError`` [ISSUE 3].
* ``tenancy``                  — the multi-tenant serving fleet
                                 [ISSUE 8]: ``MultiTenantEngine`` /
                                 ``TenantFleetIndex`` multiplex
                                 thousands of per-tenant statistics
                                 over one mesh through shared packed
                                 device buffers (one jitted count per
                                 coalesced multi-tenant batch),
                                 admission control + weighted-fair
                                 scheduling (``TenantRejectedError``),
                                 per-tenant windows/streams/WAL/SLOs.
                                 Maintenance is O(changed) [ISSUE 9]:
                                 dirty-row pack re-places, whale
                                 promotion to a dedicated delta-tiered
                                 index past ``whale_threshold``,
                                 off-batcher tenant compaction, and a
                                 ``tenant_metric_cap`` cardinality
                                 bound.
* ``control``                  — the SLO-driven control plane
                                 [ISSUE 11]: ``FleetController`` rides
                                 the SLO monitor's actuator hook and
                                 defends the fleet's SLOs before they
                                 breach — typed per-tenant throttling
                                 (``TenantThrottledError`` +
                                 retry-after hint), flush-window /
                                 micro-batch widening, DRR weight
                                 rebalance, mesh grow/shrink, and
                                 slope-based whale promotion; every
                                 actuation hysteretic, rate-limited,
                                 budgeted, reversible, and
                                 flight-evented with its triggering
                                 signal for ``doctor`` attribution.
"""

from tuplewise_tpu.serving.control import (
    ControllerConfig,
    FleetController,
)
from tuplewise_tpu.serving.engine import (
    BackpressureError,
    DeadlineExceededError,
    EngineClosedError,
    MicroBatchEngine,
    PoisonEventError,
    ServingConfig,
)
from tuplewise_tpu.serving.index import ExactAucIndex
from tuplewise_tpu.serving.replay import (
    make_stream,
    make_tenant_stream,
    replay,
    replay_fleet,
)
from tuplewise_tpu.serving.streaming import StreamingIncompleteU
from tuplewise_tpu.serving.tenancy import (
    MultiTenantEngine,
    TenancyConfig,
    TenantFleetIndex,
    TenantRejectedError,
    TenantThrottledError,
)

__all__ = [
    "BackpressureError",
    "ControllerConfig",
    "DeadlineExceededError",
    "EngineClosedError",
    "ExactAucIndex",
    "FleetController",
    "MicroBatchEngine",
    "MultiTenantEngine",
    "PoisonEventError",
    "ServingConfig",
    "StreamingIncompleteU",
    "TenancyConfig",
    "TenantFleetIndex",
    "TenantRejectedError",
    "TenantThrottledError",
    "make_stream",
    "make_tenant_stream",
    "replay",
    "replay_fleet",
]
