"""Streaming incomplete U-statistic — the paper's budget knob, online.

The batch incomplete estimator (arXiv:1501.02629; ``Estimator.
incomplete``) trades variance for a fixed tuple budget B over a static
dataset. In the serving regime the dataset is a stream, so the budget
becomes *per arrival*: each incoming score spends B kernel evaluations
against history held in per-class uniform reservoirs (Vitter's
Algorithm R), bounding per-request work at O(B) regardless of stream
length while the estimate

    U~ = (sum of h over all spent pairs) / (number of pairs spent)

remains an unbiased estimate of E[h(X, Y)] conditionally on each
arrival pairing with a uniform sample of its past (each reservoir is a
uniform sample of the scores seen so far; partners are drawn uniformly
from it). Raising B lowers the Monte-Carlo variance — the
variance-vs-budget trade-off in the online regime; the replay harness
measures it (RESULTS serving section).

Micro-batch semantics: a batch scores against the reservoir state at
batch start and is folded into the reservoirs afterwards — arrivals
within one micro-batch do not pair with each other. That keeps the
estimate independent of how the dynamic batcher happened to slice the
stream ONLY at batch granularity; the estimate at a checkpoint depends
on the batching, the *exact* index does not (that is its job).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from tuplewise_tpu.ops.kernels import Kernel, get_kernel


class _Reservoir:
    """Uniform fixed-capacity sample of a stream (Algorithm R)."""

    def __init__(self, capacity: int, rng: np.random.Generator):
        if capacity < 1:
            raise ValueError(f"reservoir capacity must be >= 1: {capacity}")
        self.capacity = capacity
        self._rng = rng
        self.items = np.empty(capacity, dtype=np.float64)
        self.size = 0
        self.seen = 0

    def add_batch(self, values: np.ndarray) -> None:
        """Fold a batch in, vectorized but sequentially-exact.

        Algorithm R keeps item t with probability capacity/seen_t at a
        uniform slot. The per-item slot draws j_t ~ U[0, seen_t) are
        independent, so one broadcast ``integers`` call with the
        per-item bounds replaces the Python loop; duplicate accepted
        slots resolve last-write-wins under NumPy fancy assignment —
        exactly the sequential overwrite order. The hot path drops from
        O(batch) interpreter iterations to three array ops.
        """
        values = np.asarray(values, dtype=np.float64).ravel()
        if len(values) == 0:
            return
        take = 0
        if self.size < self.capacity:           # fill phase
            take = min(self.capacity - self.size, len(values))
            self.items[self.size: self.size + take] = values[:take]
            self.size += take
            self.seen += take
        rest = values[take:]
        if len(rest) == 0:
            return
        bounds = self.seen + 1 + np.arange(len(rest))
        js = self._rng.integers(0, bounds)      # one draw per arrival
        self.seen += len(rest)
        hit = js < self.capacity
        if hit.any():
            self.items[js[hit]] = rest[hit]

    def sample(self, k: int, replace: bool = True) -> np.ndarray:
        if self.size == 0:
            return np.empty(0, dtype=np.float64)
        idx = self._rng.integers(0, self.size, size=k) if replace else \
            self._rng.choice(self.size, size=min(k, self.size),
                             replace=False)
        return self.items[idx]


class StreamingIncompleteU:
    """Per-arrival budgeted incomplete U-statistic over a score stream.

    Args:
      kernel: a two-sample score-difference kernel name or instance
        ("auc", "hinge", "logistic").
      budget: pairs spent per arrival (B). Each arrival pairs with B
        uniform draws from the opposite class's reservoir.
      reservoir: per-class reservoir capacity.
      design: "swr" (partners drawn with replacement, the default) or
        "swor" (distinct partners per arrival, capped at reservoir
        occupancy — the finite-population variant).
      seed: host RNG seed; the stream is reproducible given arrival
        order and batching.
      health: optional ``obs.health.EstimateHealth`` — receives every
        batch of kernel terms ``h`` as it is folded into the running
        sums, so CI-width / variance tracking sees exactly the terms
        the estimate is built from [ISSUE 7]. None costs one ``is not
        None`` check per class-side per batch.
    """

    def __init__(self, kernel="auc", budget: int = 64,
                 reservoir: int = 4096, design: str = "swr",
                 seed: int = 0, health=None):
        self.kernel: Kernel = (kernel if isinstance(kernel, Kernel)
                               else get_kernel(kernel))
        if self.kernel.kind != "diff" or not self.kernel.two_sample:
            raise ValueError(
                "StreamingIncompleteU needs a two-sample score-difference "
                f"kernel; got {self.kernel.name!r} ({self.kernel.kind})")
        if budget < 1:
            raise ValueError(f"budget must be >= 1, got {budget}")
        if design not in ("swr", "swor"):
            raise ValueError(f"design must be 'swr' or 'swor': {design!r}")
        self.budget = budget
        self.design = design
        self.health = health
        self._rng = np.random.default_rng(seed)
        self._pos = _Reservoir(reservoir, self._rng)
        self._neg = _Reservoir(reservoir, self._rng)
        self._sum_h = 0.0
        self._sum_h2 = 0.0
        self._n_terms = 0
        self.n_arrivals = 0

    # ------------------------------------------------------------------ #
    def extend(self, scores, labels) -> int:
        """Process a micro-batch of arrivals; returns pairs spent.

        Scores pair against the opposite-class reservoir as of batch
        start, then the batch is folded into the reservoirs.
        """
        scores = np.asarray(scores, dtype=np.float64).ravel()
        labels = np.asarray(labels).ravel().astype(bool)
        if scores.shape != labels.shape:
            raise ValueError(
                f"scores/labels length mismatch: {scores.shape} vs "
                f"{labels.shape}")
        spent = 0
        for vals, opp, flip in ((scores[labels], self._neg, False),
                                (scores[~labels], self._pos, True)):
            if len(vals) == 0 or opp.size == 0:
                continue
            if self.design == "swr":
                partners = opp.sample(len(vals) * self.budget)
                arr = np.repeat(vals, self.budget)
            else:
                chunks = [opp.sample(self.budget, replace=False)
                          for _ in range(len(vals))]
                partners = np.concatenate(chunks)
                arr = np.repeat(vals, [len(c) for c in chunks])
            # h(pos, neg) = g(s_pos - s_neg): a negative arrival pairs
            # with positive partners, so the difference flips
            d = (partners - arr) if flip else (arr - partners)
            h = np.asarray(self.kernel.diff(d, np), dtype=np.float64)
            s1 = float(h.sum())
            s2 = float((h * h).sum())
            self._sum_h += s1
            self._sum_h2 += s2
            self._n_terms += h.size
            spent += h.size
            if self.health is not None:
                # the sums above ride along: the monitor's merge is
                # O(1), no second pass over the terms
                self.health.update(h, s1=s1, s2=s2)
        self._pos.add_batch(scores[labels])
        self._neg.add_batch(scores[~labels])
        self.n_arrivals += len(scores)
        return spent

    def observe(self, score: float, label) -> int:
        return self.extend([score], [label])

    # ------------------------------------------------------------------ #
    @property
    def n_terms(self) -> int:
        return self._n_terms

    def estimate(self) -> Optional[float]:
        """Running U~; None until at least one pair has been spent."""
        if self._n_terms == 0:
            return None
        return self._sum_h / self._n_terms

    def std_error(self) -> Optional[float]:
        """Naive i.i.d. standard error of the running mean — a
        diagnostic (terms sharing an arrival or a reservoir slot are
        correlated, so this understates the true error; the replay
        harness measures the real spread across seeds)."""
        if self._n_terms < 2:
            return None
        m = self._sum_h / self._n_terms
        var = max(self._sum_h2 / self._n_terms - m * m, 0.0)
        return float(np.sqrt(var / self._n_terms))

    def state(self) -> dict:
        out = {
            "estimate": self.estimate(),
            "std_error": self.std_error(),
            "n_terms": self._n_terms,
            "n_arrivals": self.n_arrivals,
            "budget": self.budget,
            "design": self.design,
            "reservoir_pos": self._pos.size,
            "reservoir_neg": self._neg.size,
        }
        if self.health is not None:
            out["health"] = self.health.state()
        return out
