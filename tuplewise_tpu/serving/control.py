"""SLO-driven control plane [ISSUE 11]: the fleet defends its own
SLOs.

PR 7 taught the stack to *judge* its SLOs live (``obs.slo.SloMonitor``
riding the metrics flusher) and PR 8 to *reject* on static quotas —
but under a Zipf flash crowd, a tenant-count ramp, or a device loss
the fleet breaches first and recovers after. This module closes the
loop: a :class:`FleetController` rides the SLO engine's burn-rate and
saturation signals (the new actuator hook on ``SloMonitor``, sibling
of the PR 7 observer hook) and actuates through machinery that already
exists — nothing here invents a new mechanism, it only *drives* the
ones PRs 2–10 built:

====================  =================================================
knob                  actuation (existing machinery)
====================  =================================================
``shed``              throttle the tenants flooding the queue with a
                      typed :class:`~tuplewise_tpu.serving.tenancy.
                      TenantThrottledError` (+ ``retry_after_s`` hint)
                      BEFORE the breach — ``MultiTenantEngine.
                      throttle_tenant``; auto-expiring, so release is
                      structural
``flush``             widen the batcher flush window + micro-batch cap
                      under backlog pressure (amortize dispatch,
                      q-bucket targets move UP the compile ladder in
                      power-of-two steps), narrow them under
                      latency-only pressure — ``engine.config``
                      replace, read by the batcher each round
``weights``           boost the DRR quantum of tenants whose observed
                      ``insert_latency_s{tenant=}`` p99 runs far above
                      the fleet median (they are being starved) —
                      ``MultiTenantEngine.set_tenant_weight``
``mesh``              grow the mesh under sustained pressure / shrink
                      back on long calm — ``MeshHealer.resize`` +
                      pack re-placement (``TenantFleetIndex.
                      resize_shards``); counts are width-invariant, so
                      results stay bit-identical through every resize
``promote``           promote whales from traffic *slope* (projected
                      to cross ``whale_threshold`` within the
                      lookahead) instead of waiting for size —
                      ``TenantFleetIndex.promote``; statistically
                      invisible by the PR 9 contract
====================  =================================================

Discipline — every actuation is:

* **hysteretic** — pressure must hold ``up_ticks`` consecutive
  evaluations before a step, calm must hold ``down_ticks`` before a
  revert (asymmetric on purpose: act fast, relax slowly — no
  flapping);
* **rate-limited** — at most one step per knob per ``cooldown_s``;
* **budgeted** — at most ``*_budget`` pressured steps per knob per
  run (reverts don't consume budget — a budget-exhausted knob must
  still be able to come home);
* **reversible** — every knob steps back toward its baseline on calm
  (throttles additionally auto-expire);
* **attributable** — one ``actuation`` flight event per step carrying
  the triggering signal (objective, value, threshold — or the calm
  verdict for reverts), so ``tuplewise doctor`` can correlate
  cause → action → effect.

Crucially, **shed/throttle affects admission, never applied state**:
per-tenant wins2 stays bit-identical to T independent engines fed the
same *admitted* events through any actuation schedule — the invariant
the scenario suite pins.

Spec format (dict, JSON string, or ``@path`` / ``*.json`` — the
``--chaos-spec`` convention), every field optional::

    {"knobs": ["shed", "flush", "mesh"],
     "warn_fraction": 0.7, "release_fraction": 0.4,
     "cooldown_s": 0.25, "up_ticks": 2, "down_ticks": 6,
     "throttle_s": 0.5, "shed_budget": 64,
     "mesh_max_shards": 4, "mesh_budget": 4,
     "promote_lookahead_s": 2.0}

Disabled (no ``--controller-spec`` / ``enabled: false``) is
byte-identical to the pre-controller fleet: no actuator is attached,
the engines' override maps stay empty, and every ``.get(tid,
default)`` resolves to the static config.
"""

from __future__ import annotations

import dataclasses
import json
import math
import time
from typing import Dict, List, Optional, Tuple


class ControllerSpecError(ValueError):
    """The controller spec failed validation (unknown field/knob)."""


_KNOBS = ("shed", "flush", "weights", "mesh", "promote")


@dataclasses.dataclass(frozen=True)
class ControllerConfig:
    """Knobs of the control plane itself (thresholds, budgets,
    hysteresis). Defaults are tuned for service runs measured in
    seconds-to-minutes (a replay, a CI smoke, a short serve); spec
    authors scale the cooldowns/windows for production horizons."""

    enabled: bool = True
    knobs: Tuple[str, ...] = _KNOBS
    # pressure classification: an objective is PRESSURED when its
    # value crosses warn_fraction of its threshold (or its error
    # budget burns faster than warn_burn), CALM when it falls back
    # under release_fraction — the gap is the hysteresis band
    warn_fraction: float = 0.7
    release_fraction: float = 0.4
    warn_burn: float = 1.0
    up_ticks: int = 2
    down_ticks: int = 6
    cooldown_s: float = 0.25
    # shed
    shed_budget: int = 64
    throttle_s: float = 0.5
    shed_min_share: float = 0.2
    max_throttled_fraction: float = 0.5
    # flush / q-bucket targets
    flush_budget: int = 16
    flush_step: float = 2.0
    flush_max_scale: float = 8.0
    batch_max_scale: float = 4.0
    # DRR weight rebalance
    weight_budget: int = 32
    weight_boost: int = 4
    slow_factor: float = 3.0
    # mesh resize
    mesh_budget: int = 4
    mesh_max_shards: Optional[int] = None
    mesh_up_ticks: int = 4
    mesh_down_ticks: int = 12
    # slope-based whale promotion
    promote_budget: int = 8
    promote_lookahead_s: float = 2.0

    def __post_init__(self):
        for k in self.knobs:
            if k not in _KNOBS:
                raise ControllerSpecError(
                    f"unknown knob {k!r}; expected a subset of {_KNOBS}")
        if not 0.0 < self.release_fraction < self.warn_fraction <= 1.0:
            raise ControllerSpecError(
                "need 0 < release_fraction < warn_fraction <= 1, got "
                f"{self.release_fraction} / {self.warn_fraction}")
        if self.up_ticks < 1 or self.down_ticks < 1:
            raise ControllerSpecError(
                f"up_ticks/down_ticks must be >= 1: "
                f"{self.up_ticks}/{self.down_ticks}")
        if self.cooldown_s < 0:
            raise ControllerSpecError(
                f"cooldown_s must be >= 0: {self.cooldown_s}")
        if self.flush_step <= 1.0:
            raise ControllerSpecError(
                f"flush_step must be > 1: {self.flush_step}")
        if not 0.0 < self.shed_min_share <= 1.0:
            raise ControllerSpecError(
                f"shed_min_share must be in (0, 1]: "
                f"{self.shed_min_share}")
        if self.throttle_s <= 0:
            raise ControllerSpecError(
                f"throttle_s must be > 0: {self.throttle_s}")

    @classmethod
    def from_spec(cls, spec) -> "ControllerConfig":
        """Build from a dict, a JSON string, or ``@path`` / ``.json``
        (the ``--chaos-spec`` convention). None = defaults."""
        if spec is None:
            return cls()
        if isinstance(spec, ControllerConfig):
            return spec
        if isinstance(spec, str):
            s = spec.strip()
            if s.startswith("@"):
                with open(s[1:], "r", encoding="utf-8") as f:
                    spec = json.load(f)
            elif s.endswith(".json"):
                with open(s, "r", encoding="utf-8") as f:
                    spec = json.load(f)
            else:
                spec = json.loads(s)
        if not isinstance(spec, dict):
            raise ControllerSpecError(
                f"controller spec must be a dict, got {type(spec)}")
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(spec) - fields
        if unknown:
            raise ControllerSpecError(
                f"unknown controller spec fields: {sorted(unknown)}")
        if "knobs" in spec:
            spec = dict(spec, knobs=tuple(spec["knobs"]))
        return cls(**spec)


class _Knob:
    """Hysteresis + rate limit + budget for ONE knob.

    ``tick(want, now)`` is called once per SLO evaluation with the
    direction the signals ask for (+1 step up, -1 step down, 0 calm,
    None neutral) and returns the step actually taken: pressured
    steps need ``up_ticks`` consecutive same-direction ticks, a
    cooldown gap, remaining budget, and level headroom; calm reverts
    need ``down_ticks`` consecutive calm ticks and step toward level
    0 without consuming budget. Anything else returns 0 — the no-flap
    guarantee is structural, not behavioral."""

    __slots__ = ("name", "cooldown_s", "budget", "up_ticks",
                 "down_ticks", "max_level", "min_level", "level",
                 "used", "_up", "_down", "_calm", "_last")

    def __init__(self, name: str, cooldown_s: float, budget: int,
                 up_ticks: int, down_ticks: int, max_level: int = 1,
                 min_level: int = 0):
        self.name = name
        self.cooldown_s = cooldown_s
        self.budget = budget
        self.up_ticks = up_ticks
        self.down_ticks = down_ticks
        self.max_level = max_level
        self.min_level = min_level
        self.level = 0
        self.used = 0
        self._up = self._down = self._calm = 0
        self._last = -math.inf

    def tick(self, want: Optional[int], now: float) -> int:
        if want is None:                 # neutral: reset all streaks
            self._up = self._down = self._calm = 0
            return 0
        if want > 0:
            self._up += 1
            self._down = self._calm = 0
        elif want < 0:
            self._down += 1
            self._up = self._calm = 0
        else:
            self._calm += 1
            self._up = self._down = 0
        if now - self._last < self.cooldown_s:
            return 0
        step = 0
        if want > 0 and self._up >= self.up_ticks \
                and self.level < self.max_level \
                and self.used < self.budget:
            step = 1
            self.used += 1
        elif want < 0 and self._down >= self.up_ticks \
                and self.level > self.min_level \
                and self.used < self.budget:
            step = -1
            self.used += 1
        elif want == 0 and self._calm >= self.down_ticks \
                and self.level != 0:
            step = -1 if self.level > 0 else 1   # home, budget-free
        if step:
            self.level += step
            self._last = now
            self._up = self._down = self._calm = 0
        return step

    def reset_home(self, now: float) -> None:
        """Snap the level to baseline (used by knobs whose revert is
        all-at-once: clear every throttle, restore every weight)."""
        self.level = 0
        self._last = now
        self._up = self._down = self._calm = 0

    def state(self) -> dict:
        return {"level": self.level, "used": self.used,
                "budget": self.budget}


class FleetController:
    """Closes the SLO loop over a serving engine.

    Args:
      engine: a ``MultiTenantEngine`` (every knob) or a
        ``MicroBatchEngine`` (the ``flush`` knob; tenant/mesh knobs
        no-op without a fleet).
      spec: anything :meth:`ControllerConfig.from_spec` accepts.
      metrics / flight: default to the engine's own.

    Wire-up: ``controller.attach(slo_monitor)`` registers
    :meth:`on_signals` as an actuator — the controller then runs on
    the flusher thread, acting on exactly the snapshots the SLO
    verdicts judge. Every actuation records one ``actuation`` flight
    event with the triggering signal and increments
    ``controller_actuations_total`` (global + ``{knob=}``).
    """

    def __init__(self, engine, spec=None, metrics=None, flight=None):
        self.config = ControllerConfig.from_spec(spec)
        self.engine = engine
        self.fleet = getattr(engine, "fleet", None)
        self.metrics = metrics if metrics is not None else engine.metrics
        self.flight = flight if flight is not None else engine.flight
        self.monitor = None
        c = self.config
        self._base_flush = engine.config.flush_timeout_s
        self._base_batch = engine.config.max_batch
        base_shards = (self.fleet.shards
                       if self.fleet is not None else None) or 0
        self._base_shards = base_shards
        mesh_max = c.mesh_max_shards
        if mesh_max is None and base_shards:
            pool = (len(self.fleet._healer._pool)
                    if self.fleet._healer is not None else base_shards)
            mesh_max = pool
        mesh_levels = (max(0, int(math.log2(mesh_max / base_shards)))
                       if base_shards and mesh_max else 0)
        flush_levels = max(1, int(round(
            math.log(c.flush_max_scale, c.flush_step))))
        self._knobs: Dict[str, _Knob] = {
            "shed": _Knob("shed", c.cooldown_s, c.shed_budget,
                          c.up_ticks, max(1, c.down_ticks // 2),
                          max_level=c.shed_budget),
            "flush": _Knob("flush", c.cooldown_s, c.flush_budget,
                           c.up_ticks, c.down_ticks,
                           max_level=flush_levels, min_level=-2),
            "weights": _Knob("weights", c.cooldown_s, c.weight_budget,
                             c.up_ticks, c.down_ticks,
                             max_level=c.weight_budget),
            "mesh": _Knob("mesh", c.cooldown_s, c.mesh_budget,
                          c.mesh_up_ticks, c.mesh_down_ticks,
                          max_level=mesh_levels),
            "promote": _Knob("promote", c.cooldown_s, c.promote_budget,
                             1, c.down_ticks,
                             max_level=c.promote_budget),
        }
        m = self.metrics
        self._c_act = m.counter("controller_actuations_total")
        self._c_revert = m.counter("controller_reverts_total")
        self._g_flush = m.gauge("controller_flush_scale")
        self._g_flush.set(1.0)
        self._g_batch = m.gauge("controller_max_batch")
        self._g_batch.set(self._base_batch)
        self._g_throttled = m.gauge("controller_throttled_tenants")
        self._g_mesh = m.gauge("controller_mesh_level")
        # per-tenant traffic slopes from the labeled insert histograms
        self._prev_counts: Optional[Tuple[float, Dict[str, int]]] = None
        self._rates: Dict[str, float] = {}
        self._boosted: Dict[str, int] = {}
        self.actuations: List[dict] = []

    # ------------------------------------------------------------------ #
    def attach(self, monitor) -> "FleetController":
        """Register on an ``SloMonitor``'s actuator hook."""
        self.monitor = monitor
        monitor.add_actuator(self.on_signals)
        return self

    # ------------------------------------------------------------------ #
    # signal classification                                              #
    # ------------------------------------------------------------------ #
    def _classify(self, name: str, det: dict):
        """(pressure, calm, value, threshold) for one objective's
        current detail — the warn/release hysteresis band around the
        SLO's own threshold."""
        c = self.config
        typ = det.get("type")
        breached = bool(det.get("breached_now"))
        v = det.get("value")
        if typ == "error_rate":
            burn = v or 0.0
            pressure = breached or burn >= c.warn_burn
            calm = (not breached
                    and burn <= c.warn_burn * c.release_fraction)
            return pressure, calm, burn, c.warn_burn
        if typ == "latency":
            thr = det.get("threshold_ms")
        elif typ == "saturation":
            thr = det.get("max_fraction", 0.9)
        else:   # counter_max: binary — no warn band below the count
            return breached, not breached, v, det.get("max")
        if v is None or not thr:
            return breached, not breached, v, thr
        frac = v / thr
        pressure = breached or frac >= c.warn_fraction
        calm = (not breached) and frac <= c.release_fraction
        return pressure, calm, v, thr

    @staticmethod
    def _is_backlog(typ: str) -> bool:
        """Backlog-shaped pressure (queue filling, budget burning)
        wants MORE throughput; pure latency pressure wants SMALLER
        batches. The flush knob steers by this split."""
        return typ in ("saturation", "error_rate", "counter_max")

    def _tenant_rates(self, metrics: dict, now: float) -> None:
        """Per-tenant insert rates (events/s) from consecutive
        snapshots of the labeled ``tenant_events_total{tenant=}``
        counters — the traffic-slope signal shed ordering and whale
        promotion use. Falls back to the ``insert_latency_s`` request
        counts for registries without the event counters."""
        from tuplewise_tpu.utils.profiling import parse_labeled_name

        counts: Dict[str, int] = {}
        fallback: Dict[str, int] = {}
        for key, snap in metrics.items():
            base, lab = parse_labeled_name(key)
            if not lab or "tenant" not in lab:
                continue
            if base == "tenant_events_total":
                counts[lab["tenant"]] = snap.get("value", 0)
            elif base == "insert_latency_s":
                fallback[lab["tenant"]] = snap.get("count", 0)
        if not counts:
            counts = fallback
        if self._prev_counts is not None:
            pt, pc = self._prev_counts
            dt = now - pt
            if dt > 0:
                self._rates = {
                    t: max(0.0, (n - pc.get(t, 0)) / dt)
                    for t, n in counts.items()}
        self._prev_counts = (now, counts)

    # ------------------------------------------------------------------ #
    # the actuator                                                       #
    # ------------------------------------------------------------------ #
    def on_signals(self, sig: dict) -> None:
        """SloMonitor actuator entry point: one evaluated snapshot."""
        if not self.config.enabled:
            return
        now = sig["ts_mono"]
        metrics = sig["metrics"]
        self._tenant_rates(metrics, now)
        backlog: List[Tuple[str, float, float]] = []
        latency: List[Tuple[str, float, float]] = []
        all_calm = True
        for name, det in sig["objectives"].items():
            pressure, calm, v, thr = self._classify(name, det)
            if not calm:
                all_calm = False
            if pressure:
                bucket = (backlog if self._is_backlog(det.get("type"))
                          else latency)
                bucket.append((name, v, thr))
        knobs = self.config.knobs
        if "shed" in knobs:
            self._knob_shed(backlog, all_calm, now)
        if "flush" in knobs:
            self._knob_flush(backlog, latency, all_calm, now)
        if "weights" in knobs and self.fleet is not None:
            self._knob_weights(metrics, all_calm, now)
        if "mesh" in knobs and self.fleet is not None:
            self._knob_mesh(backlog, latency, all_calm, now)
        if "promote" in knobs and self.fleet is not None:
            self._knob_promote(now)

    # ------------------------------------------------------------------ #
    def _record(self, knob: str, action: str, signal: dict,
                **fields) -> None:
        """One actuation: flight event (the attribution record doctor
        correlates), counters, and the in-memory log records read."""
        ev = dict(knob=knob, action=action, signal=signal, **fields)
        self.flight.record("actuation", **ev)
        self._c_act.inc()
        self.metrics.counter("controller_actuations_total",
                             labels={"knob": knob}).inc()
        if action.startswith(("restore", "release", "narrow_restore")):
            self._c_revert.inc()
        self.actuations.append(dict(ev, t_mono=time.perf_counter()))

    @staticmethod
    def _worst(pressured: List[Tuple[str, float, float]]) -> dict:
        name, v, thr = max(
            pressured,
            key=lambda e: (e[1] / e[2]) if e[1] and e[2] else 0.0)
        return {"reason": "pressure", "objective": name, "value": v,
                "threshold": thr}

    @staticmethod
    def _calm_signal(knob: str) -> dict:
        return {"reason": "calm", "objective": None,
                "detail": f"{knob}: all objectives under the release "
                          "fraction"}

    # ------------------------------------------------------------------ #
    # knobs                                                              #
    # ------------------------------------------------------------------ #
    def _knob_shed(self, backlog, all_calm, now) -> None:
        eng = self.engine
        if not hasattr(eng, "throttle_tenant"):
            return
        k = self._knobs["shed"]
        want = 1 if backlog else (0 if all_calm else None)
        step = k.tick(want, now)
        if step > 0:
            targets = self._shed_targets()
            if not targets:
                k.level -= 1    # nothing attributable to shed: undo
                k.used -= 1
                return
            for tid in targets:
                eng.throttle_tenant(tid,
                                    retry_after_s=self.config.throttle_s)
            self._g_throttled.set(len(eng.throttled_tenants()))
            self._record("shed", "throttle", self._worst(backlog),
                         tenants=targets,
                         retry_after_s=self.config.throttle_s)
        elif step < 0:
            n = eng.clear_throttles()
            k.reset_home(now)
            self._g_throttled.set(0)
            if n:
                self._record("shed", "release",
                             self._calm_signal("shed"), released=n)

    def _shed_targets(self) -> List[str]:
        """The tenants to throttle: whoever owns an outsized share of
        the pending queue right now — the direct culprit signal (a
        high EVENT rate alone is not grounds for shedding: a polite
        bulk inserter with one resolved request at a time never
        floods the queue). Ties broken by traffic slope, never more
        than ``max_throttled_fraction`` of the live tenants, and a
        near-empty queue yields no targets at all."""
        eng = self.engine
        pending = (eng.pending_by_tenant()
                   if hasattr(eng, "pending_by_tenant") else {})
        total = sum(pending.values())
        if total < 4:   # nothing queue-shaped to attribute
            return []
        targets = [
            tid for tid, n in sorted(
                pending.items(),
                key=lambda kv: (-kv[1], -self._rates.get(kv[0], 0.0)))
            if n / total >= self.config.shed_min_share]
        live = (self.fleet.n_tenants if self.fleet is not None
                else len(pending)) or 1
        cap = max(1, int(live * self.config.max_throttled_fraction))
        return targets[:cap]

    def _knob_flush(self, backlog, latency, all_calm, now) -> None:
        k = self._knobs["flush"]
        if backlog:
            want = 1
        elif latency:
            want = -1
        elif all_calm:
            want = 0
        else:
            want = None
        step = k.tick(want, now)
        if not step:
            return
        c = self.config
        scale = c.flush_step ** k.level
        scale = min(max(scale, 1.0 / c.flush_max_scale),
                    c.flush_max_scale)
        # micro-batch cap moves in powers of two so coalesced q-bucket
        # shapes stay on the (T_bucket, cap, q_bucket) compile ladder
        batch = int(self._base_batch * min(2.0 ** max(0, k.level),
                                           c.batch_max_scale))
        self.engine.config = dataclasses.replace(
            self.engine.config,
            flush_timeout_s=self._base_flush * scale,
            max_batch=max(1, batch))
        self._g_flush.set(scale)
        self._g_batch.set(batch)
        if want == 1 and step > 0:
            signal, action = self._worst(backlog), "widen"
        elif want == -1 and step < 0:
            signal, action = self._worst(latency), "narrow"
        else:
            signal = self._calm_signal("flush")
            action = "restore"
        self._record("flush", action, signal, level=k.level,
                     flush_timeout_s=self._base_flush * scale,
                     max_batch=batch)

    def _knob_weights(self, metrics, all_calm, now) -> None:
        """Boost the DRR quantum of tenants whose observed insert p99
        runs ``slow_factor`` past the fleet median — they are being
        starved by the round-robin, not flooding it."""
        from tuplewise_tpu.utils.profiling import parse_labeled_name

        eng = self.engine
        if not hasattr(eng, "set_tenant_weight"):
            return
        p99: Dict[str, float] = {}
        for key, snap in metrics.items():
            base, lab = parse_labeled_name(key)
            if base == "insert_latency_s" and lab \
                    and "tenant" in lab and lab["tenant"] != "__other__":
                v = snap.get("p99")
                if v is not None:
                    p99[lab["tenant"]] = v
        slow: Dict[str, float] = {}
        if len(p99) >= 4:
            med = sorted(p99.values())[len(p99) // 2]
            if med > 0:
                slow = {t: v for t, v in p99.items()
                        if v > self.config.slow_factor * med}
        k = self._knobs["weights"]
        step = k.tick(1 if slow else 0, now)
        if step > 0:
            base_w = eng.tenancy.weight
            boosted = {}
            for tid in slow:
                if self._boosted.get(tid) is None:
                    w = base_w * self.config.weight_boost
                    eng.set_tenant_weight(tid, w)
                    self._boosted[tid] = w
                    boosted[tid] = w
            restored = [t for t in self._boosted if t not in slow]
            for tid in restored:
                eng.set_tenant_weight(tid, None)
                del self._boosted[tid]
            if not boosted and not restored:
                k.level -= 1    # nothing to rebalance: undo the step
                k.used -= 1
                return
            med = sorted(p99.values())[len(p99) // 2]
            self._record(
                "weights", "boost",
                {"reason": "pressure",
                 "metric": "insert_latency_s{tenant=*}",
                 "value": max(slow.values()) * 1e3,
                 "threshold": self.config.slow_factor * med * 1e3},
                weights=boosted, restored=restored)
        elif step < 0:
            n = len(self._boosted)
            for tid in list(self._boosted):
                eng.set_tenant_weight(tid, None)
            self._boosted.clear()
            k.reset_home(now)
            if n:
                self._record("weights", "restore",
                             self._calm_signal("weights"), restored=n)

    def _knob_mesh(self, backlog, latency, all_calm, now) -> None:
        fleet = self.fleet
        if fleet.shards is None or fleet._healer is None:
            return
        k = self._knobs["mesh"]
        pressured = backlog + latency
        want = 1 if pressured else (0 if all_calm else None)
        step = k.tick(want, now)
        if not step:
            return
        target = int(self._base_shards * (2 ** max(0, k.level)))
        if not fleet.resize_shards(target):
            # pool can't supply it (or no-op): undo the step
            k.level -= step
            if step > 0:
                k.used -= 1
            return
        self._g_mesh.set(k.level)
        if step > 0:
            self._record("mesh", "grow", self._worst(pressured),
                         shards=target, level=k.level)
        else:
            self._record("mesh", "shrink", self._calm_signal("mesh"),
                         shards=target, level=k.level)

    def _knob_promote(self, now) -> None:
        """Preemptive whale promotion from traffic slope: a tenant
        whose projected event count crosses ``whale_threshold`` within
        the lookahead is promoted NOW, before its per-compaction splice
        cost drags the fleet — promotion is statistically invisible
        (PR 9), so acting early is free."""
        fleet = self.fleet
        thr = fleet.whale_threshold
        if not thr or not self._rates:
            return
        k = self._knobs["promote"]
        cand = None
        for tid, rate in sorted(self._rates.items(),
                                key=lambda kv: -kv[1])[:8]:
            if tid == "__other__" or rate <= 0:
                continue
            if fleet.is_whale(tid):
                continue
            st = fleet.tenant_state(tid)
            if st is None:
                continue
            projected = st["n_events"] \
                + rate * self.config.promote_lookahead_s
            if st["n_events"] < thr <= projected:
                cand = (tid, rate, st["n_events"], projected)
                break
        step = k.tick(1 if cand is not None else None, now)
        if step > 0 and cand is not None:
            tid, rate, n_events, projected = cand
            if fleet.promote(tid):
                self._record(
                    "promote", "promote_whale",
                    {"reason": "slope", "metric": "tenant_insert_rate",
                     "tenant": tid, "value": rate,
                     "threshold": thr, "n_events": n_events,
                     "projected_events": projected,
                     "lookahead_s": self.config.promote_lookahead_s},
                    tenant=tid)
            else:
                k.level -= 1
                k.used -= 1

    # ------------------------------------------------------------------ #
    def state(self) -> dict:
        """The controller block records/exit summaries embed."""
        return {
            "enabled": self.config.enabled,
            "knobs": {n: k.state() for n, k in self._knobs.items()
                      if n in self.config.knobs},
            "actuations_total": self._c_act.value,
            "reverts_total": self._c_revert.value,
            "throttled_now": (self.engine.throttled_tenants()
                              if hasattr(self.engine,
                                         "throttled_tenants") else []),
            "boosted_weights": dict(self._boosted),
        }
