"""Deterministic chaos injection for the serving stack [ISSUE 3] and
the batch (training/estimation) path [ISSUE 4].

The offline estimators are *naturally* tolerant to worker loss
(``parallel/faults.py``: drop-and-renormalize), but the online serving
path recovers by **repairing state**, not by renormalizing — and repair
code that only runs when hardware dies is code that never runs in CI.
This module makes failures a first-class, reproducible input: a seeded
``FaultInjector`` carries a schedule of faults keyed to named hook
points that the stack fires as it executes —

serving points (ISSUE 3):

    ``sharded_count``   — the mesh count query in
                          ``parallel.sharded_counts`` (a raise here is
                          how a dead device actually surfaces);
    ``compactor_build`` — the background compactor's build step in
                          ``serving/index.py``;
    ``place_base``      — a device placement in
                          ``parallel.sharded_counts.place_base``;
    ``major_merge``     — the on-mesh delta fold in
                          ``parallel.sharded_counts.sharded_major_merge``
                          (a raise here exercises the index's host
                          fallback engine) [ISSUE 5];
    ``batcher``         — the micro-batch engine's worker loop in
                          ``serving/engine.py``;
    ``poison``          — event corruption (NaN/inf scores) applied to
                          the stream by ``serving/replay.py``.

batch-path points (ISSUE 4):

    ``train_step``      — one SGD scan chunk in
                          ``models/pairwise_sgd.py`` / ``triplet_sgd``;
    ``mc_chunk``        — one Monte-Carlo chunk in
                          ``harness/variance.run_variance_experiment``;
    ``mesh_mc``         — one dispatch of the compiled mesh Monte-Carlo
                          program (``harness/mesh_mc.py``);
    ``estimator``       — one Estimator scheme call
                          (``estimators/estimator.py``);
    ``checkpoint``      — fired right AFTER a checkpoint lands (the
                          ``sigkill`` action here is deterministic
                          preemption: die with durable state at a known
                          step);
    ``dist_init``       — multi-process bring-up
                          (``parallel/distributed.initialize``).

Each schedule entry names its point, the 1-based call number at which
it fires, and an action (``error`` raises, ``delay`` sleeps,
``sigkill`` SIGKILLs the whole process — the real preemption signal,
not an exception anything can catch). A mesh-facing fault
(``sharded_count``, ``mesh_mc``, ``train_step``, ``mc_chunk``,
``estimator``) may also declare the worker ids a paired health probe
should report dead (``dropped``), so the self-healing path can be
driven through a *specific* failure topology on a healthy CPU mesh.

Everything is deterministic given the spec (and ``FaultInjector.random``
is deterministic given its seed), so a chaos run is a regression test,
not a flake: the same schedule produces the same recovery sequence and
— the property the tests pin — the same bit-exact AUC as a fault-free
run over the same admitted events.

All hooks are no-ops when no injector is attached: production pays one
``is None`` check per hook point.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

_POINTS = ("sharded_count", "compactor_build", "batcher", "place_base",
           "major_merge", "train_step", "mc_chunk", "mesh_mc",
           "estimator", "checkpoint", "dist_init")
_ACTIONS = ("error", "delay", "sigkill")


class InjectedFault(RuntimeError):
    """A fault raised by a chaos schedule (never by real hardware)."""


class InjectedDeviceError(InjectedFault):
    """Simulated device/collective failure on the mesh path."""


def _parse_value(v) -> float:
    if isinstance(v, str):
        return float(v)            # handles "nan", "inf", "-inf"
    return float(v)


class _Fault:
    __slots__ = ("point", "on_call", "action", "seconds", "dropped",
                 "fired")

    def __init__(self, point: str, on_call: int = 1, action: str = "error",
                 seconds: float = 0.0, dropped=()):
        if point not in _POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; expected one of {_POINTS}")
        if action not in _ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r}; expected {_ACTIONS}")
        if on_call < 1:
            raise ValueError(f"on_call is 1-based, got {on_call}")
        self.point = point
        self.on_call = int(on_call)
        self.action = action
        self.seconds = float(seconds)
        self.dropped = tuple(int(w) for w in dropped)
        self.fired = False


class FaultInjector:
    """Seeded, schedule-driven fault injection with named hook points.

    Spec format (dict, JSON string, or ``@path`` / ``*.json`` path)::

        {"faults": [
          {"point": "sharded_count", "on_call": 3, "action": "error",
           "dropped": [1]},
          {"point": "compactor_build", "on_call": 1, "action": "error"},
          {"point": "batcher", "on_call": 40, "action": "delay",
           "seconds": 0.01},
          {"point": "poison", "at_events": [100, 101], "value": "nan"}
        ]}

    ``fire(point)`` is what the serving stack calls at each hook point;
    ``poison_batch`` is applied by the replay driver to the event
    stream; ``take_dropped`` hands the most recent fault's declared
    dead-worker set to the self-healing path (in place of a real mesh
    probe). Thread-safe — hook points fire from request, batcher, and
    compactor threads concurrently.
    """

    def __init__(self, faults=(), poison_at=(), poison_value=float("nan")):
        self._lock = threading.Lock()
        self._faults: List[_Fault] = list(faults)
        self.poison_at = frozenset(int(i) for i in poison_at)
        self.poison_value = float(poison_value)
        self._calls: Dict[str, int] = {p: 0 for p in _POINTS}
        self._fired: Dict[str, int] = {}
        self._pending_dropped: Optional[Tuple[int, ...]] = None
        self.poisoned = 0
        # observability [ISSUE 6 satellite]: when an engine attaches
        # its flight recorder (and optionally a tracer), every fault
        # that actually FIRES logs a correlated lifecycle event, so a
        # post-mortem dump shows which latency spike was chaos
        self._flight = None
        self._tracer = None

    def attach(self, flight=None, tracer=None) -> None:
        """Attach the flight recorder / tracer that should witness
        injections (called by the engine; idempotent — the most recent
        attachment wins, matching the engine the injector drives)."""
        if flight is not None:
            self._flight = flight
        if tracer is not None:
            self._tracer = tracer

    # ------------------------------------------------------------------ #
    # construction                                                       #
    # ------------------------------------------------------------------ #
    @classmethod
    def from_spec(cls, spec) -> "FaultInjector":
        """Build from a dict, a JSON string, or ``@path`` / ``.json``."""
        if isinstance(spec, FaultInjector):
            return spec
        if isinstance(spec, str):
            s = spec.strip()
            if s.startswith("@"):
                with open(s[1:], "r", encoding="utf-8") as f:
                    spec = json.load(f)
            elif s.endswith(".json"):
                with open(s, "r", encoding="utf-8") as f:
                    spec = json.load(f)
            else:
                spec = json.loads(s)
        if not isinstance(spec, dict):
            raise ValueError(f"chaos spec must be a dict, got {type(spec)}")
        faults, poison_at = [], set()
        poison_value = float("nan")
        for ent in spec.get("faults", ()):
            if ent.get("point") == "poison":
                poison_at.update(int(i) for i in ent.get("at_events", ()))
                poison_value = _parse_value(ent.get("value", "nan"))
                continue
            faults.append(_Fault(
                ent["point"], on_call=ent.get("on_call", 1),
                action=ent.get("action", "error"),
                seconds=ent.get("seconds", 0.0),
                dropped=ent.get("dropped", ()),
            ))
        return cls(faults, poison_at=poison_at, poison_value=poison_value)

    @classmethod
    def random(cls, seed: int, n_events: int,
               n_poison: int = 3) -> "FaultInjector":
        """A randomized-but-reproducible schedule for soak tests: one
        compactor crash, one batcher crash, and a few poison events,
        all at seed-determined positions."""
        rng = np.random.default_rng(seed)
        faults = [
            _Fault("compactor_build", on_call=int(rng.integers(1, 4))),
            _Fault("batcher", on_call=int(rng.integers(2, 200))),
        ]
        k = min(n_poison, max(n_events - 1, 1))
        at = rng.choice(np.arange(1, n_events), size=k, replace=False)
        return cls(faults, poison_at=(int(i) for i in at))

    # ------------------------------------------------------------------ #
    # hook-point API                                                     #
    # ------------------------------------------------------------------ #
    def fire(self, point: str) -> None:
        """Advance ``point``'s call counter; execute any fault scheduled
        at this call number (raise / sleep). Called by the serving
        stack; a no-fault call is one dict increment."""
        with self._lock:
            self._calls[point] = n = self._calls.get(point, 0) + 1
            due = [f for f in self._faults
                   if f.point == point and not f.fired and f.on_call == n]
            for f in due:
                f.fired = True
                self._fired[point] = self._fired.get(point, 0) + 1
                if f.dropped:
                    self._pending_dropped = f.dropped
            delay = sum(f.seconds for f in due if f.action == "delay")
            errors = [f for f in due if f.action == "error"]
            kills = [f for f in due if f.action == "sigkill"]
        if due and self._flight is not None:
            # correlate with the trace active at the injection site
            # (e.g. a compactor build's trace); a fault fired outside
            # any span gets a fresh trace id so the dump still has a
            # non-null correlation key
            tid = None
            if self._tracer is not None:
                tid = self._tracer.current_trace_id()
                if tid is None:
                    tid = self._tracer.new_trace_id()
            for f in due:
                self._flight.record(
                    "chaos_inject", trace_id=tid, point=point,
                    action=f.action, on_call=f.on_call,
                    dropped=list(f.dropped))
        if delay > 0:
            time.sleep(delay)
        if kills:
            # real preemption: the process dies HERE, uncatchably —
            # recovery is whatever the durable state (checkpoint/WAL)
            # plus a --resume restart can reconstruct
            os.kill(os.getpid(), signal.SIGKILL)
        if errors:
            exc = (InjectedDeviceError if point in
                   ("sharded_count", "place_base", "major_merge",
                    "mesh_mc", "train_step", "mc_chunk", "estimator")
                   else InjectedFault)
            raise exc(
                f"chaos: injected {point} fault (call #{errors[0].on_call})")

    def take_dropped(self) -> Optional[Tuple[int, ...]]:
        """The dead-worker set declared by the most recent fired fault,
        consumed once; None when the schedule declared none (the caller
        falls back to a real mesh probe)."""
        with self._lock:
            d, self._pending_dropped = self._pending_dropped, None
            return d

    def poison_batch(self, start: int,
                     scores: np.ndarray) -> Tuple[np.ndarray, int]:
        """Corrupt the scheduled events inside ``scores`` (stream
        positions ``start .. start+len``); returns (possibly-copied
        array, number poisoned)."""
        if not self.poison_at:
            return scores, 0
        hit = [i - start for i in self.poison_at
               if start <= i < start + len(scores)]
        if not hit:
            return scores, 0
        out = np.array(scores, copy=True)
        out[hit] = self.poison_value
        with self._lock:
            self.poisoned += len(hit)
        if self._flight is not None:
            self._flight.record(
                "chaos_poison", n_poisoned=len(hit),
                at_events=[start + i for i in hit])
        return out, len(hit)

    def snapshot(self) -> dict:
        """Fired/called counts per point — for exit summaries."""
        with self._lock:
            return {
                "calls": dict(self._calls),
                "fired": dict(self._fired),
                "poisoned": self.poisoned,
                "unfired": sum(1 for f in self._faults if not f.fired),
            }
