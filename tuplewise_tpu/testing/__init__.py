"""Test-support instrumentation shipped with the library [ISSUE 3].

Production code imports nothing from here unless a chaos injector is
explicitly passed in; the serving stack's fault hooks are no-ops when
no injector is attached, so this package costs the hot path nothing.
"""

from tuplewise_tpu.testing.chaos import (
    FaultInjector,
    InjectedDeviceError,
    InjectedFault,
)

__all__ = ["FaultInjector", "InjectedDeviceError", "InjectedFault"]
