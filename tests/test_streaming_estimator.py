"""StreamingEstimator facade + the online incomplete-U estimator."""

import numpy as np
import pytest

from tuplewise_tpu.estimators import StreamingEstimator
from tuplewise_tpu.models.metrics import auc_score
from tuplewise_tpu.serving import StreamingIncompleteU
from tuplewise_tpu.serving.replay import make_stream


class TestStreamingIncompleteU:
    def test_estimate_tracks_auc(self):
        scores, labels = make_stream(4000, seed=0)
        est = StreamingIncompleteU(kernel="auc", budget=32, seed=1)
        for i in range(0, 4000, 16):
            est.extend(scores[i:i + 16], labels[i:i + 16])
        truth = auc_score(scores[labels], scores[~labels])
        assert est.estimate() == pytest.approx(truth, abs=0.02)
        assert est.n_terms > 100_000

    def test_budget_reduces_variance(self):
        # The online variance-vs-budget trade-off [ISSUE 1 tentpole
        # (2)], measured where it lives: CONDITIONAL on a fixed stream,
        # the across-seed variance (partner-sampling randomness only)
        # shrinks with the per-arrival budget. (The unconditional error
        # has a budget-independent floor from the stream itself — same
        # structure as the batch incomplete estimator's zeta_1 term.)
        scores, labels = make_stream(800, seed=42)

        def var_seeds(budget, n_seeds=12):
            ests = []
            for s in range(n_seeds):
                est = StreamingIncompleteU(budget=budget, seed=s)
                for i in range(0, 800, 8):
                    est.extend(scores[i:i + 8], labels[i:i + 8])
                ests.append(est.estimate())
            return float(np.var(ests))

        # 64x the budget measured ~0.03x the conditional variance;
        # assert a conservative 5x reduction
        assert var_seeds(64) < var_seeds(1) * 0.2

    def test_swor_design_distinct_partners(self):
        est = StreamingIncompleteU(budget=8, reservoir=8, design="swor",
                                   seed=0)
        est.extend(np.arange(8.0), np.zeros(8))       # fill neg reservoir
        spent = est.extend([5.0], [1])
        # swor caps at reservoir occupancy and draws distinct partners
        assert spent == 8

    def test_rejects_non_diff_kernel(self):
        with pytest.raises(ValueError, match="score-difference"):
            StreamingIncompleteU(kernel="scatter")

    def test_reservoir_bounds_memory(self):
        est = StreamingIncompleteU(budget=4, reservoir=64, seed=0)
        scores, labels = make_stream(2000, seed=3)
        est.extend(scores[:1000], labels[:1000])
        est.extend(scores[1000:], labels[1000:])
        st = est.state()
        assert st["reservoir_pos"] <= 64 and st["reservoir_neg"] <= 64
        assert st["n_arrivals"] == 2000


class TestStreamingEstimatorFacade:
    def test_exact_and_incomplete_agree_statistically(self):
        scores, labels = make_stream(2000, seed=5)
        se = StreamingEstimator("auc", budget=32, engine="numpy", seed=2)
        for i in range(0, 2000, 25):
            se.extend(scores[i:i + 25], labels[i:i + 25])
        exact = se.auc()
        truth = auc_score(scores[labels], scores[~labels])
        assert exact == pytest.approx(truth, abs=1e-9)
        assert se.estimate() == pytest.approx(exact, abs=0.03)
        assert se.n_pos + se.n_neg == 2000

    def test_windowed_facade(self):
        scores, labels = make_stream(1000, seed=6)
        se = StreamingEstimator("auc", window=200, engine="numpy")
        for i in range(0, 1000, 11):
            se.extend(scores[i:i + 11], labels[i:i + 11])
        tail_s, tail_l = scores[-200:], labels[-200:]
        truth = auc_score(tail_s[tail_l], tail_s[~tail_l])
        assert se.auc() == pytest.approx(truth, abs=1e-9)

    def test_non_auc_kernel_facade(self):
        scores, labels = make_stream(500, seed=7)
        se = StreamingEstimator("hinge", budget=16, seed=0)
        for i in range(0, 500, 10):
            se.extend(scores[i:i + 10], labels[i:i + 10])
        assert se.auc() is None
        assert se.estimate() is not None
        with pytest.raises(ValueError, match="exact index"):
            se.score([0.0])

    def test_observe_single_events(self):
        se = StreamingEstimator("auc", engine="numpy")
        for s, l in ((1.0, 1), (0.0, 0), (2.0, 1)):
            se.observe(s, l)
        assert se.auc() == 1.0
        assert se.state()["index"]["n_events"] == 3
