"""scripts/perf_gate.py [ISSUE 7]: noise-banded regression gating over
the results/serving.jsonl trajectory, run-id/config-digest joins."""

import importlib.util
import json
import os

import pytest

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "perf_gate", os.path.join(_repo, "scripts", "perf_gate.py"))
perf_gate = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(perf_gate)


def _row(evps, p99, digest=None, run_id=None, stage="bench_streaming"):
    row = {"stage": stage, "metric": "events/sec", "value": evps,
           "insert_latency_p99_ms": p99, "n_events": 300000,
           "bg_compact": True, "max_inflight": 64}
    if digest:
        row["config_digest"] = digest
    if run_id:
        row["run_id"] = run_id
    return row


def _write(path, rows):
    with open(path, "w", encoding="utf-8") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


HIST = [_row(17000 + 200 * i, 4.5 + 0.1 * i) for i in range(4)]


class TestGate:
    def test_within_band_passes(self):
        v = perf_gate.gate(HIST + [_row(16900, 4.8, digest="d1")],
                           0.15, 4.0, 2)
        assert v["ok"]
        assert all(c["ok"] for c in v["checks"])
        assert v["n_history"] == 4

    def test_throughput_regression_fails(self):
        v = perf_gate.gate(HIST + [_row(9000, 4.6, digest="d1")],
                           0.15, 4.0, 2)
        assert not v["ok"]
        bad = [c for c in v["checks"] if not c["ok"]]
        assert [c["metric"] for c in bad] == ["events_per_s"]
        assert bad[0]["new"] < bad[0]["limit"]

    def test_latency_regression_fails(self):
        v = perf_gate.gate(HIST + [_row(17200, 40.0)], 0.15, 4.0, 2)
        assert not v["ok"]
        assert [c["metric"] for c in v["checks"] if not c["ok"]] == \
            ["insert_latency_p99_ms"]

    def test_insufficient_history_passes_vacuously(self):
        v = perf_gate.gate([HIST[0], _row(1.0, 999.0)], 0.15, 4.0, 2)
        assert v["ok"] and "insufficient" in v["note"]

    def test_digest_join_prefers_same_config(self):
        # history carries two configs; only same-digest rows gate
        hist = ([_row(17000, 4.5, digest="dA") for _ in range(3)]
                + [_row(5000, 50.0, digest="dB") for _ in range(3)])
        v = perf_gate.gate(hist + [_row(16800, 4.7, digest="dA")],
                           0.15, 4.0, 2)
        assert v["ok"] and v["n_history"] == 3

    def test_legacy_rows_without_digest_still_join(self):
        # pre-ISSUE-7 history has no digest: joined on config fields
        v = perf_gate.gate(HIST + [_row(16900, 4.7, digest="dNew",
                                        run_id="r1")],
                           0.15, 4.0, 2)
        assert v["n_history"] == 4
        assert v["run_id"] == "r1"

    def test_different_legacy_config_does_not_join(self):
        other = dict(_row(100.0, 400.0), n_events=5)
        v = perf_gate.gate([other] * 3 + [_row(17000, 4.5)],
                           0.15, 4.0, 2)
        assert "note" in v     # nothing comparable -> vacuous pass

    def test_mad_widens_band_for_noisy_history(self):
        noisy = [_row(10000, 4.0), _row(20000, 4.0), _row(14000, 4.0),
                 _row(26000, 4.0)]
        # median 17000, MAD 5000 -> robust sigma ~7413; a 13000 drop
        # clears the 4-sigma band even though it is far below 15%
        v = perf_gate.gate(noisy + [_row(13000, 4.0)], 0.15, 4.0, 2)
        assert v["checks"][0]["ok"]


def _ht_row(host_frac, compile_1k, gc_p99, digest="dH"):
    return {"stage": "host_tax", "host_fraction": host_frac,
            "device_fraction": round(1.0 - host_frac, 3),
            "compile_events": 4,
            "compile_events_per_1k_batches": compile_1k,
            "gc_pause_p99_ms": gc_p99, "coverage": 1.0,
            "config_digest": digest}


HT_HIST = [_ht_row(0.70 + 0.01 * i, 10.0 + i, 1.0 + 0.1 * i)
           for i in range(4)]
HT_SPEC = perf_gate._STAGE_METRICS["host_tax"]


class TestHostTaxStage:
    """[ISSUE 14] the host-tax budget: host-fraction up, compile
    events per 1k batches up, or the GC tail up = breach."""

    def test_stage_registered_and_default(self):
        assert "host_tax" in perf_gate._STAGE_METRICS
        assert "host_tax" in perf_gate._DEFAULT_STAGES

    def test_within_budget_passes(self):
        v = perf_gate.gate(HT_HIST + [_ht_row(0.72, 12.0, 1.1)],
                           0.15, 4.0, 2, metrics=HT_SPEC)
        assert v["ok"], v["checks"]

    def test_host_fraction_up_breaches(self):
        # the silent regression this stage exists for: throughput can
        # stay in band while the host share climbs
        v = perf_gate.gate(HT_HIST + [_ht_row(0.99, 12.0, 1.1)],
                           0.15, 4.0, 2, metrics=HT_SPEC)
        assert not v["ok"]
        assert [c["metric"] for c in v["checks"] if not c["ok"]] == \
            ["host_fraction"]

    def test_compile_churn_up_breaches(self):
        v = perf_gate.gate(HT_HIST + [_ht_row(0.71, 300.0, 1.1)],
                           0.15, 4.0, 2, metrics=HT_SPEC)
        assert not v["ok"]
        assert [c["metric"] for c in v["checks"] if not c["ok"]] == \
            ["compile_events_per_1k"]

    def test_gc_tail_up_breaches(self):
        v = perf_gate.gate(HT_HIST + [_ht_row(0.71, 12.0, 50.0)],
                           0.15, 4.0, 2, metrics=HT_SPEC)
        assert not v["ok"]
        assert [c["metric"] for c in v["checks"] if not c["ok"]] == \
            ["gc_pause_p99_ms"]

    def test_missing_gc_metric_passes_vacuously(self):
        # runs with zero GC pauses record None — no history, no gate
        hist = [dict(_ht_row(0.70, 10.0, None)) for _ in range(3)]
        v = perf_gate.gate(hist + [_ht_row(0.71, 11.0, None)],
                           0.15, 4.0, 2, metrics=HT_SPEC)
        assert v["ok"]

    def test_main_gates_host_tax_rows(self, tmp_path, capsys):
        p = str(tmp_path / "serving.jsonl")
        _write(p, HT_HIST + [_ht_row(0.99, 12.0, 1.1)])
        rc = perf_gate.main(["--history", p, "--mode", "fail",
                             "--stage", "host_tax",
                             "--tolerance-frac", "0.15",
                             "--out", str(tmp_path / "v.jsonl")])
        assert rc == 1
        out = capsys.readouterr().out.strip().splitlines()[-1]
        verdict = json.loads(out)
        assert not verdict["stages"]["host_tax"]["ok"]


class TestMain:
    def test_warn_mode_exits_zero_on_regression(self, tmp_path,
                                                capsys):
        hist = tmp_path / "serving.jsonl"
        _write(hist, HIST + [_row(5000, 4.6)])
        out = tmp_path / "gate.jsonl"
        rc = perf_gate.main(["--history", str(hist), "--mode", "warn",
                             "--out", str(out)])
        assert rc == 0
        verdict = json.loads(capsys.readouterr().out.strip())
        assert not verdict["ok"]
        assert json.loads(out.read_text())["mode"] == "warn"

    def test_fail_mode_exits_nonzero_on_regression(self, tmp_path):
        hist = tmp_path / "serving.jsonl"
        _write(hist, HIST + [_row(5000, 4.6)])
        rc = perf_gate.main(["--history", str(hist), "--mode", "fail",
                             "--out", str(tmp_path / "g.jsonl")])
        assert rc == 1

    def test_fail_mode_passes_clean_history(self, tmp_path):
        hist = tmp_path / "serving.jsonl"
        _write(hist, HIST + [_row(17100, 4.7)])
        rc = perf_gate.main(["--history", str(hist), "--mode", "fail",
                             "--out", str(tmp_path / "g.jsonl")])
        assert rc == 0

    def test_missing_file_and_no_rows_pass(self, tmp_path, capsys):
        assert perf_gate.main(
            ["--history", str(tmp_path / "nope.jsonl")]) == 0
        empty = tmp_path / "serving.jsonl"
        _write(empty, [dict(_row(1, 1), stage="other")])
        assert perf_gate.main(["--history", str(empty),
                               "--out", str(tmp_path / "g.jsonl")]) == 0

    def test_gates_real_repo_history_in_warn_mode(self, tmp_path):
        """The committed trajectory must be gateable as-is (the ci.sh
        leg runs exactly this)."""
        path = os.path.join(_repo, "results", "serving.jsonl")
        if not os.path.exists(path):
            pytest.skip("no committed serving.jsonl")
        rc = perf_gate.main(["--history", path, "--mode", "warn",
                             "--out", str(tmp_path / "g.jsonl")])
        assert rc == 0
