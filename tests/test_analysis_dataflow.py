"""Fixture tests for the flow-sensitive dataflow tier [ISSUE 13]:
the shared engine (call/return/attribute chase, cycle termination),
guard-inference race detection (seeded-bad / clean-twin pairs PLUS
the two historical-bug regression fixtures), integer-exactness +
overflow certification (float-taint, narrow accumulator,
overflow-at-ladder-max, committed-baseline diff), the reworked
flow-sensitive compile-ladder chase, the incremental parse cache, and
the SARIF emitter.
"""

import importlib.util
import os

import pytest

from tuplewise_tpu.analysis import compile_ladder, exactness, races
from tuplewise_tpu.analysis import dataflow
from tuplewise_tpu.analysis.cache import ParseCache
from tuplewise_tpu.analysis.core import ModuleSet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "analysis_gate", os.path.join(REPO, "scripts", "analysis_gate.py"))
analysis_gate = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(analysis_gate)


def ms_of(src: str, path: str = "tuplewise_tpu/fixture.py",
          **extra) -> ModuleSet:
    return ModuleSet.from_sources({path: src, **extra})


def rules(findings):
    return sorted({f.rule for f in findings})


# --------------------------------------------------------------------- #
# dataflow engine                                                        #
# --------------------------------------------------------------------- #

class _ConstDomain(dataflow.Domain):
    """Tiny test domain: integer constants propagate, + adds."""

    top = None

    def const(self, value):
        return value if isinstance(value, int) else None

    def binop(self, op, left, right):
        import ast

        if isinstance(op, ast.Add) and isinstance(left, int) \
                and isinstance(right, int):
            return left + right
        return None


def _eval(src: str, func: str, domain=None):
    ms = ms_of(src)
    engine = dataflow.Engine(ms, domain or _ConstDomain())
    return engine.eval_function(("tuplewise_tpu/fixture.py", "",
                                 func))


def test_dataflow_multi_step_assignment_chase():
    # three assignments deep — the PR 12 one-level chase stopped at one
    assert _eval("""
def f():
    a = 1
    b = a + 2
    c = b + 3
    return c
""", "f") == 6


def test_dataflow_call_return_chase():
    assert _eval("""
def g(x):
    return x + 10


def f():
    return g(1) + 100
""", "f") == 111


def test_dataflow_branch_join():
    # both branches agree -> the value survives the join; disagreement
    # joins to top
    assert _eval("""
def f(cond):
    if cond:
        x = 5
    else:
        x = 5
    return x
""", "f") == 5
    assert _eval("""
def f(cond):
    if cond:
        x = 5
    else:
        x = 6
    return x
""", "f") is None


def test_dataflow_attribute_write_join():
    src = """
class C:
    def __init__(self):
        self.x = 7

    def f(self):
        return self.x + 1
"""
    ms = ms_of(src)
    engine = dataflow.Engine(ms, _ConstDomain())
    assert engine.eval_function(
        ("tuplewise_tpu/fixture.py", "C", "C.f")) == 8


def test_dataflow_struct_field_chase():
    # constructor fields flow through attribute reads (the MergePlan
    # pattern the ladder pass relies on)
    assert _eval("""
class Plan:
    pos: int
    cap: int


def mk():
    return Plan(1, cap=41)


def f():
    p = mk()
    return p.cap + 1
""", "f") == 42


def test_dataflow_cycle_terminates():
    # mutually recursive calls must terminate (summary cut to top)
    assert _eval("""
def a(n):
    return b(n) + 1


def b(n):
    return a(n) + 1


def f():
    return a(0)
""", "f") is None


def test_dataflow_param_values_join_call_sites():
    src = """
def callee(v):
    return v


def one():
    return callee(3)


def two():
    return callee(3)
"""
    ms = ms_of(src)
    engine = dataflow.Engine(ms, _ConstDomain())
    pv = engine.param_values(("tuplewise_tpu/fixture.py", "",
                              "callee"))
    assert pv == {"v": 3}


def test_dataflow_closure_env():
    # nested defs read the enclosing function's environment (the
    # healer's ``attempt`` closures)
    src = """
def f():
    pad = 4

    def attempt():
        return pad + 1
    return attempt
"""
    ms = ms_of(src)
    engine = dataflow.Engine(ms, _ConstDomain())
    assert engine.eval_function(
        ("tuplewise_tpu/fixture.py", "", "f.attempt")) == 5


# --------------------------------------------------------------------- #
# races — guard inference                                                #
# --------------------------------------------------------------------- #

_SCOPE = ("tuplewise_tpu/",)

_INCONSISTENT = """
import threading


class Mixed:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._shared = 0
        self._worker = threading.Thread(
            target=self._run, name="tuplewise-compactor", daemon=True)

    def bump(self):
        with self._a:
            self._shared += 1

    def _run(self):
        with self._b:
            self._shared += 1
"""

_INCONSISTENT_CLEAN = _INCONSISTENT.replace("with self._b:",
                                            "with self._a:")

_UNGUARDED = """
import threading


class Leaky:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._worker = threading.Thread(
            target=self._drain, name="tuplewise-batcher", daemon=True)

    def push(self, x):
        with self._lock:
            self._items.append(x)

    def _drain(self):
        self._items.clear()
"""

_UNGUARDED_CLEAN = _UNGUARDED.replace(
    "    def _drain(self):\n        self._items.clear()",
    "    def _drain(self):\n        with self._lock:\n"
    "            self._items.clear()")


def test_race_inconsistent_guard_flagged():
    fs = races.run(ms_of(_INCONSISTENT), scope=_SCOPE)
    assert any(f.rule == "race-inconsistent-guard"
               and f.symbol == "Mixed._shared" for f in fs)


def test_race_consistent_guard_clean():
    assert races.run(ms_of(_INCONSISTENT_CLEAN), scope=_SCOPE) == []


def test_race_unguarded_shared_flagged():
    fs = races.run(ms_of(_UNGUARDED), scope=_SCOPE)
    assert any(f.rule == "race-unguarded-shared"
               and f.symbol == "Leaky._items" for f in fs)
    # the evidence chain names both roles and the unguarded site
    (f,) = [f for f in fs if f.symbol == "Leaky._items"]
    assert "NO LOCK" in f.message and "batcher" in f.message \
        and "caller" in f.message


def test_race_guarded_everywhere_clean():
    assert races.run(ms_of(_UNGUARDED_CLEAN), scope=_SCOPE) == []


def test_race_single_role_not_flagged():
    # written+read from one role only: not shared
    src = """
import threading


class Solo:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def a(self):
        self._n += 1

    def b(self):
        return self._n
"""
    assert races.run(ms_of(src), scope=_SCOPE) == []


def test_race_init_writes_ignored():
    # constructor writes don't count as sharing (object not published)
    src = """
import threading


class InitOnly:
    def __init__(self):
        self._lock = threading.Lock()
        self._cfg = 3
        self._worker = threading.Thread(
            target=self._run, name="tuplewise-batcher", daemon=True)

    def _run(self):
        return self._cfg
"""
    assert races.run(ms_of(src), scope=_SCOPE) == []


# ---- historical-bug regression fixtures [ISSUE 13 acceptance] ------- #

_DEADLINE_REAPER_HOLE = """
import threading


class WedgedEngine:
    '''The pre-PR-11 deadline hole, race-shaped: deadline expiry ran
    only at dispatch, so the fix added a reaper timer — written
    WITHOUT taking the queue/pending guard, it would race submitters
    exactly like this.'''

    def __init__(self):
        self._lock = threading.Lock()
        self._pending = []
        self._reaper = threading.Thread(
            target=self._reap, name="tuplewise-reaper", daemon=True)

    def submit(self, r):
        with self._lock:
            self._pending.append(r)

    def _reap(self):
        stale = [r for r in self._pending if r.expired]
        for r in stale:
            self._pending.remove(r)
"""

_DEADLINE_REAPER_FIXED = _DEADLINE_REAPER_HOLE.replace(
    "    def _reap(self):\n        stale",
    "    def _reap(self):\n        with self._lock:\n            stale"
).replace(
    "        for r in stale:\n            self._pending.remove(r)",
    "            for r in stale:\n                "
    "self._pending.remove(r)")

_BLOCK_POLICY_HAZARD = """
import queue
import threading


class BlockingEngine:
    '''The pre-PR-3 block-policy shutdown hazard, race-shaped: close()
    flips the draining flag with no lock while submit reads it under
    the lock before blocking on a full queue — the unguarded write is
    exactly the window where a producer blocks forever.'''

    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue(maxsize=8)
        self._draining = False
        self._worker = threading.Thread(
            target=self._run, name="tuplewise-batcher", daemon=True)

    def submit(self, r):
        with self._lock:
            if self._draining:
                raise RuntimeError("closed")
        self._q.put(r)

    def close(self):
        self._draining = True

    def _run(self):
        while not self._draining:
            self._q.get()
"""

_BLOCK_POLICY_FIXED = _BLOCK_POLICY_HAZARD.replace(
    "    def close(self):\n        self._draining = True",
    "    def close(self):\n        with self._lock:\n"
    "            self._draining = True").replace(
    "        while not self._draining:\n            self._q.get()",
    "        while True:\n            with self._lock:\n"
    "                if self._draining:\n                    return\n"
    "            self._q.get()")


def test_race_redetects_deadline_reaper_hole():
    fs = races.run(ms_of(_DEADLINE_REAPER_HOLE), scope=_SCOPE)
    assert any(f.rule == "race-unguarded-shared"
               and f.symbol == "WedgedEngine._pending"
               and "reaper" in f.message for f in fs)


def test_race_deadline_reaper_fixed_clean():
    assert races.run(ms_of(_DEADLINE_REAPER_FIXED),
                     scope=_SCOPE) == []


def test_race_redetects_block_policy_shutdown_hazard():
    fs = races.run(ms_of(_BLOCK_POLICY_HAZARD), scope=_SCOPE)
    assert any(f.rule == "race-unguarded-shared"
               and f.symbol == "BlockingEngine._draining"
               for f in fs)


def test_race_block_policy_fixed_clean():
    assert races.run(ms_of(_BLOCK_POLICY_FIXED), scope=_SCOPE) == []


# --------------------------------------------------------------------- #
# exactness — float taint                                                #
# --------------------------------------------------------------------- #

_TAINT_BAD = """
import numpy as np


class Idx:
    def __init__(self):
        self._wins2 = 0

    def insert(self, p, n):
        ns = np.sort(n)
        less = np.searchsorted(ns, p, side="left").astype(np.int64)
        leq = np.searchsorted(ns, p, side="right").astype(np.int64)
        self._wins2 += 2 * less.sum() + 0.5 * leq.sum()
"""

_TAINT_CLEAN = _TAINT_BAD.replace(
    "2 * less.sum() + 0.5 * leq.sum()",
    "int(2 * less.sum() + (leq - less).sum())")

_NARROW = """
import jax.numpy as jnp


class Idx:
    def __init__(self):
        self._wins2 = 0

    def insert(self, base, q):
        less = jnp.searchsorted(base, q, side="left")
        self._wins2 += less.sum()
"""

_NARROW_CLEAN = _NARROW.replace("less.sum()", "int(less.sum())")


def test_float_taint_flagged():
    fs = exactness.run(ms_of(_TAINT_BAD))
    assert any(f.rule == "count-float-taint"
               and "Idx.insert" in f.symbol for f in fs)


def test_integer_path_clean():
    assert exactness.run(ms_of(_TAINT_CLEAN)) == []


def test_taint_through_helper_return():
    # the float sneaks in one call away — the interprocedural chase
    # still sees it
    src = """
def half(x):
    return 0.5 * x


class Idx:
    def __init__(self):
        self._wins2 = 0

    def bump(self, d):
        self._wins2 += half(d)
"""
    fs = exactness.run(ms_of(src))
    assert any(f.rule == "count-float-taint" for f in fs)


def test_narrow_accumulator_flagged():
    fs = exactness.run(ms_of(_NARROW))
    assert any(f.rule == "count-narrow-accumulator" for f in fs)


def test_widened_accumulator_clean():
    assert exactness.run(ms_of(_NARROW_CLEAN)) == []


# --------------------------------------------------------------------- #
# exactness — overflow certification                                     #
# --------------------------------------------------------------------- #

_PSUM_FACTORY = """
import functools


@functools.lru_cache(maxsize=None)
def count_fn(mesh, cap, q_bucket):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def body(b, q):
        less = jnp.searchsorted(b[0], q, side="left")
        return lax.psum(less, "x")

    return jax.jit(body)
"""


def test_certificate_bounds_psum_count():
    cert = exactness.certificates(ms_of(_PSUM_FACTORY))
    (e,) = cert["bounds"]
    assert e["factory"] == "count_fn"
    assert e["category"] == "psum-count"
    assert e["bound"] == (exactness.DEFAULT_MAXIMA["S"]
                          * exactness.DEFAULT_MAXIMA["cap"])
    assert e["ok"] and cert["ok"]


def test_certificate_overflow_at_ladder_max_flagged():
    # blow the envelope: S * cap no longer fits in int32
    big = dict(exactness.DEFAULT_MAXIMA, S=4096, cap=2 ** 21)
    cert = exactness.certificates(ms_of(_PSUM_FACTORY), maxima=big)
    assert not cert["ok"]
    fs = exactness.overflow_findings(cert)
    assert any(f.rule == "overflow-int32" and f.symbol == "count_fn"
               for f in fs)


def test_certificate_unproved_factory_flagged():
    src = """
import functools


@functools.lru_cache(maxsize=None)
def weird_fn(alpha, beta):
    import jax.numpy as jnp

    return lambda x: x.astype(jnp.int32)
"""
    cert = exactness.certificates(ms_of(src))
    assert cert["unproved"]
    fs = exactness.overflow_findings(cert)
    assert any(f.rule == "overflow-unproved" for f in fs)


def test_baseline_roundtrip_and_drift():
    cert = exactness.certificates(ms_of(_PSUM_FACTORY))
    text = "\n".join(
        ["[maxima]"]
        + [f"{k} = {v}" for k, v in cert["maxima"].items()]
        + sum(([
            "", "[[bound]]",
            f'factory = "{e["factory"]}"',
            f'file = "{e["file"]}"',
            f'bound = {e["bound"]}',
        ] for e in cert["bounds"]), []))
    assert exactness.compare_to_baseline(cert, text) == []
    drift = text.replace(f'bound = {cert["bounds"][0]["bound"]}',
                         "bound = 7")
    errs = exactness.compare_to_baseline(cert, drift)
    assert any("count_fn" in e and "drift" in e for e in errs)
    # maxima drift is named too
    mdrift = text.replace("S = 256", "S = 512")
    errs = exactness.compare_to_baseline(cert, mdrift)
    assert any("maxima" in e for e in errs)


def test_repo_certificate_matches_committed_baseline():
    ms = ModuleSet.from_repo(REPO)
    cert = exactness.certificates(ms)
    with open(os.path.join(REPO, "tuplewise_tpu", "analysis",
                           "exactness_bounds.toml")) as f:
        assert exactness.compare_to_baseline(cert, f.read()) == []
    assert cert["ok"] and not cert["unproved"]
    # the count hot path's device accumulators are all certified
    facs = {e["factory"] for e in cert["bounds"]}
    assert {"sharded_count_fn", "tenant_count_fn",
            "_xla_signed_pair_fn",
            "flat_signed_count_fn"} <= facs


# --------------------------------------------------------------------- #
# compile-ladder — the flow-sensitive chase                              #
# --------------------------------------------------------------------- #

_LADDER = """
import functools

import numpy as np


@functools.lru_cache(maxsize=None)
def count_fn(cap, q_bucket):
    return lambda b, q: (b, q)


def next_bucket(n):
    b = 256
    while b < n:
        b *= 2
    return b
"""


def test_ladder_multi_step_chain_flagged():
    src = _LADDER + """

def serve(base, q):
    a = len(base)
    b = a
    c = b
    return count_fn(c, next_bucket(len(q)))(base, q)
"""
    fs = compile_ladder.run(ms_of(src))
    assert any(f.rule == "ladder-raw-shape" and ":0" in f.symbol
               for f in fs)
    assert not any(":1" in f.symbol for f in fs)


def test_ladder_interprocedural_callsite_proof():
    # the callee reads q.shape, but every caller pads to the bucket —
    # the call-site join proves it clean (the tenant_pack_counts
    # pattern PR 12 had to waive)
    src = _LADDER + """

def dispatch(q_block):
    qb = q_block.shape[0]
    return count_fn(qb, qb)(q_block, q_block)


def caller_a(q):
    q_p = np.zeros(next_bucket(len(q)))
    return dispatch(q_p)


def caller_b(q):
    q_p = np.zeros(next_bucket(len(q)))
    return dispatch(q_p)
"""
    assert compile_ladder.run(ms_of(src)) == []


def test_ladder_raw_callsite_still_flagged():
    src = _LADDER + """

def dispatch(q_block):
    qb = q_block.shape[0]
    return count_fn(qb, qb)(q_block, q_block)


def caller_a(q):
    return dispatch(np.asarray(q))
"""
    fs = compile_ladder.run(ms_of(src))
    assert any(f.rule == "ladder-raw-shape"
               and "dispatch" in f.symbol for f in fs)


def test_ladder_struct_field_chase():
    # a NamedTuple field built with next_bucket proves clean through
    # the constructor (the plan_major_merge / MergePlan pattern)
    src = _LADDER + """

class Plan:
    pos: object
    cap_out: int


def plan(base):
    pos = np.full(next_bucket(len(base)), 0)
    return Plan(pos, next_bucket(len(base)))


def merge(base):
    p = plan(base)
    return count_fn(len(p.pos), p.cap_out)(base, base)
"""
    assert compile_ladder.run(ms_of(src)) == []


def test_ladder_factory_result_shapes_on_ladder():
    # arrays RETURNED by a ladder factory call have ladder shapes by
    # induction — .shape reads of them are clean
    src = _LADDER + """

def two_stage(base, q):
    mid = count_fn(next_bucket(len(base)), 256)(base, q)
    return count_fn(int(mid.shape[0]), 256)(mid, q)
"""
    assert compile_ladder.run(ms_of(src)) == []


# --------------------------------------------------------------------- #
# incremental parse cache                                                #
# --------------------------------------------------------------------- #

def test_parse_cache_hits_on_unchanged_source(tmp_path):
    cache = ParseCache(str(tmp_path))
    src = "def f():\n    return 1\n"
    ms1 = ModuleSet.from_repo  # noqa: F841 (API presence)
    from tuplewise_tpu.analysis.core import ModuleInfo

    mi = ModuleInfo("tuplewise_tpu/x.py", src)
    cache.put("tuplewise_tpu/x.py", src, mi)
    hit = cache.get("tuplewise_tpu/x.py", src)
    assert hit is not None and "f" in hit.functions
    assert cache.hits == 1
    # content change -> miss
    assert cache.get("tuplewise_tpu/x.py", src + "# x\n") is None
    assert cache.misses == 1


def test_from_repo_uses_cache(tmp_path):
    pkg = tmp_path / "tuplewise_tpu" / "sub"
    pkg.mkdir(parents=True)
    (pkg / "mod.py").write_text("def f():\n    return 1\n")
    cache = ParseCache(str(tmp_path))
    ms = ModuleSet.from_repo(str(tmp_path), cache=cache)
    assert cache.misses == 1 and cache.hits == 0
    cache2 = ParseCache(str(tmp_path))
    ms2 = ModuleSet.from_repo(str(tmp_path), cache=cache2)
    assert cache2.hits == 1 and cache2.misses == 0
    assert "f" in ms2.modules["tuplewise_tpu/sub/mod.py"].functions
    assert ms.modules.keys() == ms2.modules.keys()


def test_run_checks_reports_cache_counters(tmp_path):
    from tuplewise_tpu.analysis.runner import run_checks

    report = run_checks(root=REPO, use_cache=False)
    assert report["summary"]["cache"] == {
        "enabled": False, "hits": 0, "misses": 0}
    assert "overflow_certificate" in report
    assert report["overflow_certificate"]["ok"] is True


# --------------------------------------------------------------------- #
# SARIF emitter                                                          #
# --------------------------------------------------------------------- #

def test_sarif_shape():
    report = {
        "findings": [{
            "rule": "race-unguarded-shared", "file": "a.py",
            "line": 3, "symbol": "C.x", "message": "boom",
            "fingerprint": "race-unguarded-shared:a.py:C.x"}],
        "waived": [{
            "rule": "ladder-raw-shape", "file": "b.py", "line": 9,
            "symbol": "f::g:0", "message": "waived thing",
            "fingerprint": "ladder-raw-shape:b.py:f::g:0",
            "reason": "documented protocol"}],
    }
    sarif = analysis_gate.to_sarif(report)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == {
        "race-unguarded-shared", "ladder-raw-shape"}
    errors = [r for r in run["results"] if r["level"] == "error"]
    notes = [r for r in run["results"] if r["level"] == "note"]
    assert len(errors) == 1 and len(notes) == 1
    assert notes[0]["suppressions"][0]["justification"] \
        == "documented protocol"
    loc = errors[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "a.py"
    assert loc["region"]["startLine"] == 3


# --------------------------------------------------------------------- #
# full-repo invariants of the new tier                                   #
# --------------------------------------------------------------------- #

def test_repo_races_and_exactness_clean_modulo_waivers():
    from tuplewise_tpu.analysis.runner import run_checks

    report = run_checks(root=REPO, use_cache=False)
    assert report["ok"] is True
    per_pass = report["summary"]["per_pass"]
    # the new passes RAN and bit on the real tree (waived findings
    # prove the race rules are live, not vacuous)
    assert "races" in per_pass and "exactness" in per_pass
    assert per_pass["races"] > 0
    assert any(w["rule"].startswith("race-")
               for w in report["waived"])
