"""Streaming-vs-batch parity for the incremental exact-AUC index.

The contract [ISSUE 1 acceptance]: after replaying any prefix of a
stream, the incremental estimate equals the batch ``ops.rank_auc`` and
the NumPy midrank oracle on that prefix within 1e-6, bit-stable across
compaction boundaries, including with sliding-window eviction.
"""

import numpy as np
import pytest

from tuplewise_tpu.models.metrics import auc_score
from tuplewise_tpu.serving import ExactAucIndex
from tuplewise_tpu.serving.replay import make_stream


def _stream(n, seed=7, pos_frac=0.45):
    scores, labels = make_stream(n, pos_frac=pos_frac, separation=1.0,
                                 seed=seed)
    # f32 values so the jax engine (f32 storage) and the f64 oracle see
    # identical comparison outcomes
    return scores.astype(np.float32), labels


def _oracle(scores, labels):
    pos, neg = scores[labels], scores[~labels]
    if len(pos) == 0 or len(neg) == 0:
        return None
    return auc_score(pos.astype(np.float64), neg.astype(np.float64))


@pytest.mark.parametrize("engine", ["numpy", "jax"])
class TestPrefixParity:
    def test_every_checkpointed_prefix(self, engine):
        scores, labels = _stream(1500)
        idx = ExactAucIndex(engine=engine, compact_every=96)
        checkpoints = [1, 2, 7, 50, 96, 97, 200, 500, 777, 1024, 1500]
        off = 0
        for c in checkpoints:
            idx.insert_batch(scores[off:c], labels[off:c])
            off = c
            oracle = _oracle(scores[:c], labels[:c])
            if oracle is None:
                assert idx.auc() is None
            else:
                assert idx.auc() == pytest.approx(oracle, abs=1e-6), c
        assert idx.n_compactions > 0, "checkpoints must cross compactions"

    def test_rank_auc_agrees(self, engine):
        from tuplewise_tpu.ops.rank_auc import rank_auc

        scores, labels = _stream(800, seed=3)
        idx = ExactAucIndex(engine=engine, compact_every=64)
        for i in range(0, 800, 37):
            idx.insert_batch(scores[i:i + 37], labels[i:i + 37])
            k = min(i + 37, 800)
            pos, neg = scores[:k][labels[:k]], scores[:k][~labels[:k]]
            if len(pos) and len(neg):
                ra = float(rank_auc(pos, neg))
                assert idx.auc() == pytest.approx(ra, abs=1e-6)

    def test_bit_stable_across_compaction(self, engine):
        scores, labels = _stream(600, seed=11)
        # compact_every large: nothing compacts until we force it
        idx = ExactAucIndex(engine=engine, compact_every=10_000)
        idx.insert_batch(scores, labels)
        before = idx.auc()
        assert idx.n_compactions == 0
        idx.compact()
        assert idx.n_compactions > 0
        assert idx.auc() == before  # exact bit equality, not approx

    def test_window_eviction_tracks_tail_oracle(self, engine):
        scores, labels = _stream(1200, seed=5)
        W = 300
        idx = ExactAucIndex(engine=engine, window=W, compact_every=48)
        for i in range(0, 1200, 29):
            k = min(i + 29, 1200)
            idx.insert_batch(scores[i:k], labels[i:k])
            tail_s, tail_l = scores[max(0, k - W):k], labels[max(0, k - W):k]
            oracle = _oracle(tail_s, tail_l)
            if oracle is not None:
                assert idx.auc() == pytest.approx(oracle, abs=1e-6), k
            assert idx.n_events == len(tail_s)
        assert idx.n_evicted == 1200 - W
        assert idx.n_compactions > 0

    def test_window_smaller_than_one_batch(self, engine):
        scores, labels = _stream(400, seed=9)
        idx = ExactAucIndex(engine=engine, window=64)
        idx.insert_batch(scores, labels)   # single batch >> window
        oracle = _oracle(scores[-64:], labels[-64:])
        assert idx.auc() == pytest.approx(oracle, abs=1e-6)
        assert idx.n_events == 64


class TestIndexBehavior:
    def test_score_batch_is_rank_fraction(self):
        scores, labels = _stream(500, seed=2)
        idx = ExactAucIndex(engine="numpy")
        idx.insert_batch(scores, labels)
        neg = np.sort(scores[~labels])
        q = np.asarray([-3.0, 0.0, 3.0], dtype=np.float32)
        got = idx.score_batch(q)
        want = (np.searchsorted(neg, q, side="left")
                + 0.5 * (np.searchsorted(neg, q, side="right")
                         - np.searchsorted(neg, q, side="left"))) / len(neg)
        np.testing.assert_allclose(got, want, atol=1e-12)

    def test_duplicate_values_and_ties(self):
        # heavy ties: values on a small integer grid
        rng = np.random.default_rng(0)
        scores = rng.integers(0, 4, size=600).astype(np.float32)
        labels = rng.random(600) < 0.5
        idx = ExactAucIndex(engine="numpy", window=200, compact_every=32)
        for i in range(0, 600, 23):
            idx.insert_batch(scores[i:i + 23], labels[i:i + 23])
        oracle = _oracle(scores[-200:], labels[-200:])
        assert idx.auc() == pytest.approx(oracle, abs=1e-9)

    def test_rejects_non_finite(self):
        idx = ExactAucIndex(engine="numpy")
        with pytest.raises(ValueError, match="finite"):
            idx.insert_batch([np.nan], [1])

    def test_oracle_values_roundtrip(self):
        scores, labels = _stream(300, seed=4)
        idx = ExactAucIndex(engine="numpy", window=120, compact_every=16)
        idx.insert_batch(scores, labels)
        pos, neg = idx.oracle_values()
        tail_s, tail_l = scores[-120:], labels[-120:]
        np.testing.assert_array_equal(pos, np.sort(tail_s[tail_l]))
        np.testing.assert_array_equal(neg, np.sort(tail_s[~tail_l]))

    def test_empty_and_one_sided(self):
        idx = ExactAucIndex(engine="numpy")
        assert idx.auc() is None
        idx.insert_batch([1.0, 2.0], [1, 1])
        assert idx.auc() is None          # no negatives yet
        assert np.isnan(idx.score_batch([0.5])).all()
        idx.insert_batch([0.0], [0])
        assert idx.auc() == 1.0
