"""Real-format data ingestion [SURVEY §3 "Dataset loaders"; VERDICT r1
next #6]: canonical adult.data CSV and MNIST IDX files dropped into
TUPLEWISE_DATA_DIR must flow end-to-end with meta["synthetic"]=False,
surrogates kicking in only when nothing is on disk."""

import gzip
import struct

import numpy as np
import pytest

from tuplewise_tpu.data.loaders import (
    load_adult,
    load_mnist_embeddings,
    mnist_pca_embeddings,
    parse_adult_csv,
)

_ADULT_ROW = (
    "{age}, {work}, 77516, Bachelors, 13, Never-married, Adm-clerical, "
    "Not-in-family, White, {sex}, 2174, 0, {hours}, United-States, {label}"
)


def _write_adult(path, n=40):
    rng = np.random.default_rng(0)
    rows = []
    for i in range(n):
        rows.append(_ADULT_ROW.format(
            age=20 + int(rng.integers(40)),
            work="Private" if i % 3 else "State-gov",
            sex="Male" if i % 2 else "Female",
            hours=20 + int(rng.integers(40)),
            label=">50K" if i % 4 == 0 else "<=50K",
        ))
    rows.append("17, ?, 1, Bachelors, 13, Never-married, Adm-clerical, "
                "Not-in-family, White, Male, 0, 0, 40, United-States, <=50K")
    rows.append("not,a,valid,row")
    path.write_text("\n".join(rows) + "\n")
    return n


def _write_idx(dirpath, n=30, side=28, gz=False):
    rng = np.random.default_rng(1)
    images = rng.integers(0, 256, size=(n, side, side), dtype=np.uint8)
    labels = (np.arange(n) % 10).astype(np.uint8)
    suffix = ".gz" if gz else ""
    op = gzip.open if gz else open
    with op(dirpath / f"train-images-idx3-ubyte{suffix}", "wb") as f:
        f.write(struct.pack(">HBBIII", 0, 0x08, 3, n, side, side))
        f.write(images.tobytes())
    with op(dirpath / f"train-labels-idx1-ubyte{suffix}", "wb") as f:
        f.write(struct.pack(">HBBI", 0, 0x08, 1, n))
        f.write(labels.tobytes())
    return images, labels


class TestAdultCSV:
    def test_parse_schema(self, tmp_path):
        p = tmp_path / "adult.data"
        n = _write_adult(p)
        X, y = parse_adult_csv(str(p))
        assert len(X) == n            # '?' row and malformed row dropped
        # 6 continuous + one-hot blocks over the CANONICAL UCI category
        # sets (fixture values are all canonical, so the full vocabulary
        # applies): workclass 8, education 16, marital 7, occupation 14,
        # relationship 6, race 5, sex 2, native-country 41
        assert X.shape[1] == 6 + (8 + 16 + 7 + 14 + 6 + 5 + 2 + 41)
        assert set(y) == {0, 1}
        # each of the 8 categorical columns contributes exactly one
        # indicator 1 per row (no continuous value is 1.0 in the fixture)
        assert np.all(np.sum(X == 1.0, axis=1) == 8)
        # deterministic encoding: same file -> identical matrix
        X2, _ = parse_adult_csv(str(p))
        assert np.array_equal(X, X2)

    def test_train_test_alignment(self, tmp_path):
        """adult.data/adult.test stay column-aligned even when a
        category ('Holand-Netherlands') appears in only one file."""
        a, b = tmp_path / "adult.data", tmp_path / "adult.test"
        row = _ADULT_ROW.replace("United-States", "{country}")
        a.write_text("\n".join([
            row.format(age=30, work="Private", sex="Male", hours=40,
                       label="<=50K", country="Holand-Netherlands"),
            row.format(age=40, work="Private", sex="Male", hours=40,
                       label=">50K", country="United-States"),
        ]) + "\n")
        b.write_text(row.format(
            age=40, work="Private", sex="Male", hours=40,
            label=">50K.", country="United-States",
        ) + "\n")
        Xa, _ = parse_adult_csv(str(a))
        Xb, _ = parse_adult_csv(str(b))
        assert Xa.shape[1] == Xb.shape[1]
        # the shared United-States rows encode identically across files
        assert np.array_equal(Xa[1], Xb[0])

    def test_noncanonical_category_falls_back(self, tmp_path):
        """A column with out-of-vocabulary values gets a file-local
        sorted vocabulary (with a warning) instead of crashing."""
        p = tmp_path / "adult.data"
        p.write_text("\n".join([
            _ADULT_ROW.format(age=30, work="Gig-economy", sex="Male",
                              hours=40, label="<=50K"),
            _ADULT_ROW.format(age=40, work="Artisan", sex="Female",
                              hours=30, label=">50K"),
        ]) + "\n")
        with pytest.warns(UserWarning, match="non-canonical"):
            X, y = parse_adult_csv(str(p))
        assert len(X) == 2
        # workclass block is file-local (2 cols); sex stays canonical
        assert X.shape[1] == 6 + (2 + 16 + 7 + 14 + 6 + 5 + 2 + 41)

    def test_truncated_idx_raises_valueerror(self, tmp_path):
        p = tmp_path / "train-images-idx3-ubyte"
        p.write_bytes(b"\x00\x00")  # 2 bytes: not even a full magic
        from tuplewise_tpu.data.loaders import _read_idx

        with pytest.raises(ValueError, match="IDX"):
            _read_idx(str(p))

    def test_adult_test_trailing_dot(self, tmp_path):
        p = tmp_path / "adult.data"
        p.write_text(_ADULT_ROW.format(
            age=30, work="Private", sex="Male", hours=40, label=">50K.",
        ) + "\n")
        _, y = parse_adult_csv(str(p))
        assert y.tolist() == [1]

    def test_load_adult_from_data_dir(self, tmp_path, monkeypatch):
        _write_adult(tmp_path / "adult.data")
        monkeypatch.setenv("TUPLEWISE_DATA_DIR", str(tmp_path))
        X, y, meta = load_adult(n=20, seed=0)
        assert meta["synthetic"] is False
        assert len(X) == 20 and len(y) == 20
        assert np.allclose(X.mean(0), 0, atol=1e-9)  # standardized

    def test_surrogate_fallback(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TUPLEWISE_DATA_DIR", str(tmp_path / "empty"))
        X, y, meta = load_adult(n=500, seed=0)
        assert meta["synthetic"] is True
        assert X.shape == (500, 14)


class TestMnistIDX:
    @pytest.mark.parametrize("gz", [False, True])
    def test_load_from_idx(self, tmp_path, monkeypatch, gz):
        images, labels = _write_idx(tmp_path, gz=gz)
        monkeypatch.setenv("TUPLEWISE_DATA_DIR", str(tmp_path))
        E, labs, meta = load_mnist_embeddings(n=30, dim=8, seed=0)
        assert meta["synthetic"] is False
        assert E.shape == (30, 8)
        assert labs.tolist() == labels.tolist()

    def test_pca_deterministic_and_centered(self, tmp_path):
        images, _ = _write_idx(tmp_path)
        E1 = mnist_pca_embeddings(images, dim=8)
        E2 = mnist_pca_embeddings(images.copy(), dim=8)
        assert np.array_equal(E1, E2)
        assert abs(np.linalg.norm(E1, axis=1).mean() - 1.0) < 1e-6

    def test_bad_magic_raises(self, tmp_path):
        p = tmp_path / "train-images-idx3-ubyte"
        p.write_bytes(b"\x12\x34\x56\x78" + b"\x00" * 16)
        (tmp_path / "train-labels-idx1-ubyte").write_bytes(
            struct.pack(">HBBI", 0, 0x08, 1, 0))
        from tuplewise_tpu.data.loaders import _read_idx

        with pytest.raises(ValueError, match="IDX"):
            _read_idx(str(p))


class TestEndToEnd:
    def test_triplet_experiment_real_files(self, tmp_path, monkeypatch):
        """Canonical IDX files in TUPLEWISE_DATA_DIR flow through the
        triplet experiment with meta['synthetic']=False."""
        from tuplewise_tpu.harness.triplet_experiment import (
            triplet_mnist_statistic,
        )

        _write_idx(tmp_path, n=60)
        monkeypatch.setenv("TUPLEWISE_DATA_DIR", str(tmp_path))
        r = triplet_mnist_statistic(
            backend="jax", n=60, n_pairs=500, seed=0, triplet_tile=8
        )
        assert r["data_meta"]["synthetic"] is False
        assert 0.0 <= r["mean"] <= 1.0

    def test_train_on_real_adult_csv(self, tmp_path, monkeypatch):
        """adult.data in TUPLEWISE_DATA_DIR feeds the pairwise learner."""
        from tuplewise_tpu.models.pairwise_sgd import (
            TrainConfig, split_by_label, train_pairwise,
        )
        from tuplewise_tpu.models.scorers import LinearScorer

        _write_adult(tmp_path / "adult.data", n=60)
        monkeypatch.setenv("TUPLEWISE_DATA_DIR", str(tmp_path))
        X, y, meta = load_adult(n=60, seed=0)
        assert meta["synthetic"] is False
        Xp, Xn = split_by_label(X, y)
        scorer = LinearScorer(dim=X.shape[1])
        cfg = TrainConfig(kernel="logistic", lr=0.1, steps=5, n_workers=2)
        params, hist = train_pairwise(
            scorer, scorer.init(0), Xp, Xn, cfg
        )
        assert np.all(np.isfinite(hist["loss"]))
