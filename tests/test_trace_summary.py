"""scripts/trace_summary.py [ISSUE 14 satellite]: the span-digest
table and the new host-tax digest pinned against committed fixture
files — the summarizer had zero test coverage while CI legs and
RESULTS.md depended on its output."""

import json
import os

import pytest

from scripts.trace_summary import (
    classify_frame, classify_stack, load_collapsed, load_spans,
    summarize_collapsed, summarize_spans,
)

DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
SPANS = os.path.join(DATA, "trace_summary_spans.jsonl")
COLLAPSED = os.path.join(DATA, "trace_summary_prof.collapsed")

# the pinned span-digest table: self time = total minus DIRECT-child
# time (request.insert owns 30ms total but its children tile it ->
# 0 self), quantiles linear-interpolated over the retained samples
EXPECTED_SPAN_TABLE = """\
trace: {path}
spans: 7  traces: 3  span window: 0.038s

span (by self time)                      n    self_ms   total_ms    p99_ms
insert.index_insert                      2      21.00      21.00    14.910
insert.queue_wait                        2       9.00       9.00     4.990
compaction.sync                          1       8.00       8.00     8.000
request.insert                           2       0.00      30.00    19.900

insert stage                   n    p50_ms    p99_ms    max_ms
insert.index_insert            2    10.500    14.910    15.000
insert.queue_wait              2     4.500     4.990     5.000"""

# the pinned host-tax digest: 100 samples classified leaf-first
EXPECTED_HOST_TAX_TABLE = """\
profile: {path}
samples: 100  distinct stacks: 5

host-tax category         samples   share
serving_python                 40  40.0%
jax_dispatch                   25  25.0%
wait_idle                      20  20.0%
numpy_host                     10  10.0%
mesh_glue                       5   5.0%

top leaf frame                                        samples   share
tuplewise_tpu/serving/index.py:insert_batch                40  40.0%
jax/_src/pjit.py:__call__                                  25  25.0%
lib/python3.11/threading.py:wait                           20  20.0%"""


class TestSpanDigest:
    def test_pinned_table(self):
        assert summarize_spans(SPANS, 5) == EXPECTED_SPAN_TABLE.format(
            path=SPANS)

    def test_load_spans_skips_meta(self):
        spans = load_spans(SPANS)
        assert len(spans) == 7
        assert all("meta" not in s for s in spans)

    def test_chrome_export_same_digest(self, tmp_path):
        # the Chrome trace-event shape must digest identically (modulo
        # the header line naming the file)
        spans = load_spans(SPANS)
        doc = {"traceEvents": [
            {"ph": "X", "name": s["name"], "pid": 1, "tid": 1,
             "ts": s["t0_s"] * 1e6, "dur": s["dur_s"] * 1e6,
             "args": {"trace_id": s["trace_id"],
                      "span_id": s["span_id"],
                      **({"parent_id": s["parent_id"]}
                         if s["parent_id"] is not None else {})}}
            for s in spans]}
        p = str(tmp_path / "trace.json")
        with open(p, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        got = summarize_spans(p, 5).splitlines()[1:]
        assert got == EXPECTED_SPAN_TABLE.format(
            path=SPANS).splitlines()[1:]

    def test_empty_input_raises(self, tmp_path):
        p = str(tmp_path / "empty.jsonl")
        with open(p, "w", encoding="utf-8") as f:
            f.write('{"meta": {}}\n')
        with pytest.raises(ValueError):
            summarize_spans(p)


class TestHostTaxDigest:
    def test_pinned_table(self):
        assert summarize_collapsed(COLLAPSED, 3) == \
            EXPECTED_HOST_TAX_TABLE.format(path=COLLAPSED)

    def test_load_collapsed(self):
        stacks = dict(load_collapsed(COLLAPSED))
        assert sum(stacks.values()) == 100
        assert all(st[0].startswith("thread:") for st in stacks)

    def test_classification_leaf_first(self):
        # a numpy sort called FROM serving code is numpy time
        assert classify_stack(
            ("thread:x", "tuplewise_tpu/serving/index.py:_merge",
             "numpy/_core/fromnumeric.py:sort")) == "numpy_host"
        # an unclassifiable leaf falls back toward the root
        assert classify_stack(
            ("thread:x", "tuplewise_tpu/serving/engine.py:_run",
             "lib/python3.11/json/encoder.py:encode")) \
            == "serving_python"
        assert classify_stack(("thread:x", "mystery.py:f")) \
            == "other_host"

    def test_wait_beats_serving(self):
        # a serving thread blocked in queue.get is WAITING, not serving
        assert classify_frame("lib/python3.11/queue.py:get") \
            == "wait_idle"
        assert classify_stack(
            ("thread:b", "tuplewise_tpu/serving/engine.py:_run",
             "lib/python3.11/queue.py:get")) == "wait_idle"

    def test_recovery_is_io_not_serving(self):
        assert classify_frame(
            "tuplewise_tpu/serving/recovery.py:record") \
            == "wal_snapshot_io"

    def test_speedscope_input(self, tmp_path):
        # the speedscope export digests to the same category split
        frames = []
        index = {}
        samples, weights = [], []
        for stack, n in load_collapsed(COLLAPSED):
            ixs = []
            for fr in stack:
                if fr not in index:
                    index[fr] = len(frames)
                    frames.append({"name": fr})
                ixs.append(index[fr])
            for _ in range(n):
                samples.append(ixs)
                weights.append(0.01)
        doc = {"$schema":
               "https://www.speedscope.app/file-format-schema.json",
               "shared": {"frames": frames},
               "profiles": [{"type": "sampled", "unit": "seconds",
                             "startValue": 0, "endValue": sum(weights),
                             "samples": samples, "weights": weights}]}
        p = str(tmp_path / "prof.speedscope.json")
        with open(p, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        table = summarize_collapsed(p, 3)
        assert "serving_python                 40  40.0%" in table
        assert "samples: 100" in table

    def test_empty_profile_raises(self, tmp_path):
        p = str(tmp_path / "empty.collapsed")
        open(p, "w").close()
        with pytest.raises(ValueError):
            summarize_collapsed(p)
