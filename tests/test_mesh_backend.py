"""Mesh backend on 8 virtual CPU devices [SURVEY §5.1].

The headline property is RING INVARIANCE: the cross-shard all-pairs sum
computed by N-1 ppermute rotations must equal the single-device all-pairs
sum for any shard layout — including ragged sizes that force padding.
"""

import jax
import numpy as np
import pytest

from tuplewise_tpu import Estimator
from tuplewise_tpu.data import make_gaussians

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices"
)


@pytest.fixture(scope="module")
def scores():
    X, Y = make_gaussians(2000, 1600, dim=1, separation=1.0, seed=7)
    return X[:, 0], Y[:, 0]


@pytest.fixture(scope="module")
def mesh_est():
    return Estimator("auc", backend="mesh", n_workers=8,
                     tile_a=128, tile_b=128)


class TestRingInvariance:
    def test_complete_matches_oracle(self, scores, mesh_est):
        s1, s2 = scores
        ref = Estimator("auc", backend="numpy").complete(s1, s2)
        assert abs(mesh_est.complete(s1, s2) - ref) < 1e-6

    @pytest.mark.parametrize("n_workers", [2, 3, 5, 7])
    def test_complete_any_worker_count(self, scores, n_workers):
        """Ring rotation arithmetic holds for odd / non-power-of-2
        worker counts, not just the 8-device default — the ppermute
        step count and shard indexing must be N-agnostic."""
        s1, s2 = scores
        ref = Estimator("auc", backend="numpy").complete(s1, s2)
        got = Estimator("auc", backend="mesh", n_workers=n_workers,
                        tile_a=128, tile_b=128).complete(s1, s2)
        assert abs(got - ref) < 1e-6

    def test_complete_ragged_sizes(self, scores, mesh_est):
        """Sizes not divisible by 8 exercise pad+mask inside the ring."""
        s1, s2 = scores
        s1, s2 = s1[:1237], s2[:1011]
        ref = Estimator("auc", backend="numpy").complete(s1, s2)
        assert abs(mesh_est.complete(s1, s2) - ref) < 1e-6

    def test_one_sample_complete(self):
        rng = np.random.default_rng(0)
        A = rng.standard_normal((300, 3))
        ref = Estimator("scatter", backend="numpy").complete(A)
        got = Estimator("scatter", backend="mesh", n_workers=8,
                        tile_a=64, tile_b=64).complete(A)
        assert abs(got - ref) / abs(ref) < 1e-5

    def test_complete_pallas_ring(self, scores):
        """The Pallas ring hot loop (interpret mode on the CPU mesh)
        must reproduce the oracle on ragged sizes, where shard padding
        runs through the mask-aware kernel."""
        s1, s2 = scores
        s1, s2 = s1[:1237], s2[:1011]
        ref = Estimator("auc", backend="numpy").complete(s1, s2)
        got = Estimator("auc", backend="mesh", n_workers=8,
                        tile_a=128, tile_b=128,
                        impl="pallas").complete(s1, s2)
        assert abs(got - ref) < 1e-6

    def test_complete_pallas_ring_unmasked_fast_path(self, scores):
        """Padding-free, tile-divisible shapes dispatch the ring to the
        UNMASKED Pallas kernel [VERDICT r2 next #3] and still reproduce
        the oracle. The dispatch itself is asserted structurally below
        (test_fast_path_dispatch); this pins the value."""
        s1, s2 = scores
        s1, s2 = s1[:2048], s2[:1024]    # m=256/128, tiles divide
        ref = Estimator("auc", backend="numpy").complete(s1, s2)
        got = Estimator("auc", backend="mesh", n_workers=8,
                        tile_a=128, tile_b=128,
                        impl="pallas").complete(s1, s2)
        assert abs(got - ref) < 1e-6

    def test_fast_path_dispatch(self):
        """_make_stats_fn picks the unmasked (interior/edge-decomposed)
        path exactly when the caller certifies no masks — at ANY block
        size since VERDICT r3 next #1; masks, ids, or impl="xla" fall
        back to the masked/XLA path."""
        from tuplewise_tpu.ops.kernels import auc_kernel
        from tuplewise_tpu.parallel.ring import _make_stats_fn

        def build(**kw):
            base = dict(
                tile_a=128, tile_b=128, use_ids=False, impl="pallas",
                interpret=True, no_masks=True, n_a=256, n_b=128,
            )
            base.update(kw)
            return _make_stats_fn(auc_kernel, None, None, **base)

        assert build().__name__ == "fast_stats_fn"
        # ragged blocks now take the fast path too (decomposed interior)
        assert build(n_a=250).__name__ == "fast_stats_fn"
        assert build(n_b=120).__name__ == "fast_stats_fn"
        # SMEM budget handled inside the decomposition, any n_a
        assert build(n_a=1 << 20, tile_a=128).__name__ == "fast_stats_fn"
        assert build(n_a=3 * 125000, tile_a=8).__name__ == "fast_stats_fn"
        # mask present, ids, or xla impl -> masked/XLA path
        assert build(no_masks=False).__name__ != "fast_stats_fn"
        assert build(use_ids=True).__name__ != "fast_stats_fn"
        assert build(impl="xla").__name__ != "fast_stats_fn"

    def test_triplet_complete_double_ring(self):
        rng = np.random.default_rng(1)
        X = rng.standard_normal((48, 3))
        Y = rng.standard_normal((40, 3))
        ref = Estimator("triplet_indicator", backend="numpy").complete(X, Y)
        got = Estimator("triplet_indicator", backend="mesh", n_workers=8,
                        triplet_tile=8).complete(X, Y)
        assert abs(got - ref) < 1e-6


class TestDistributedSchemes:
    def test_local_average_unbiased(self, scores, mesh_est):
        s1, s2 = scores
        u_n = Estimator("auc", backend="numpy").complete(s1, s2)
        vals = [mesh_est.local_average(s1, s2, seed=m) for m in range(40)]
        se = np.std(vals) / np.sqrt(len(vals))
        assert abs(np.mean(vals) - u_n) < 4 * se + 1e-4

    def test_repartitioned_runs_and_unbiased(self, scores, mesh_est):
        s1, s2 = scores
        u_n = Estimator("auc", backend="numpy").complete(s1, s2)
        vals = [
            mesh_est.repartitioned(s1, s2, n_rounds=4, seed=m)
            for m in range(25)
        ]
        se = np.std(vals) / np.sqrt(len(vals))
        assert abs(np.mean(vals) - u_n) < 4 * se + 1e-4

    def test_incomplete_unbiased(self, scores, mesh_est):
        s1, s2 = scores
        u_n = Estimator("auc", backend="numpy").complete(s1, s2)
        vals = [
            mesh_est.incomplete(s1, s2, n_pairs=4000, seed=m)
            for m in range(60)
        ]
        se = np.std(vals) / np.sqrt(len(vals))
        assert abs(np.mean(vals) - u_n) < 4 * se + 1e-4

    def test_mismatched_workers_raises(self, scores, mesh_est):
        s1, s2 = scores
        with pytest.raises(ValueError, match="mesh backend has 8 shards"):
            mesh_est.local_average(s1, s2, n_workers=4)

    def test_one_sample_local_average_unbiased(self):
        """Regression: one-sample worker blocks must reuse ONE partition
        (same ids both sides) — an independent second draw counts
        self-pairs and biases the estimate low."""
        rng = np.random.default_rng(5)
        A = rng.standard_normal((320, 3))
        est = Estimator("scatter", backend="mesh", n_workers=8,
                        tile_a=64, tile_b=64)
        u_n = Estimator("scatter", backend="numpy").complete(A)
        vals = [est.local_average(A, seed=m) for m in range(30)]
        se = np.std(vals) / np.sqrt(len(vals)) + 1e-6
        assert abs(np.mean(vals) - u_n) < 5 * se

    def test_local_average_ragged_n_unbiased(self):
        """Regression: n not divisible by N must drop a RANDOM remainder
        each round, not a fixed tail — the tail point participates."""
        X, Y = make_gaussians(1001, 993, dim=1, separation=1.0, seed=9)
        s1, s2 = X[:, 0], Y[:, 0]
        # plant an extreme tail value; a fixed-truncation bug would
        # never include it and shift the mean detectably
        s1[-1] = 50.0
        est = Estimator("auc", backend="mesh", n_workers=8,
                        tile_a=64, tile_b=64)
        u_n = Estimator("auc", backend="numpy").complete(s1, s2)
        vals = [est.local_average(s1, s2, seed=m) for m in range(40)]
        se = np.std(vals) / np.sqrt(len(vals)) + 1e-6
        assert abs(np.mean(vals) - u_n) < 5 * se

    def test_small_n_raises_not_nan(self, mesh_est):
        """Regression: n < n_shards must raise like the oracle backend,
        not silently return NaN from empty blocks."""
        with pytest.raises(ValueError, match="too small"):
            mesh_est.local_average(np.arange(5.0), np.arange(20.0), seed=0)

    def test_incomplete_rounds_budget_up(self, scores, mesh_est):
        """n_pairs not divisible by N: at least n_pairs tuples drawn."""
        s1, s2 = scores
        v = mesh_est.incomplete(s1, s2, n_pairs=101, seed=0)
        assert 0.0 <= v <= 1.0
