"""Incremental fleet hot path [ISSUE 9]: dirty-row pack placement
byte accounting, whale promotion/demotion bit-parity (randomized soak,
chaos mid-promotion, SIGKILL recovery), off-batcher tenant builds, the
stale-row reclaim bugfix, and the tenant-metric-cardinality cap."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tuplewise_tpu.serving.engine import ServingConfig
from tuplewise_tpu.serving.index import ExactAucIndex
from tuplewise_tpu.serving.tenancy import (
    MultiTenantEngine, TenancyConfig, TenantFleetIndex,
)
from tuplewise_tpu.testing.chaos import FaultInjector


def _stream(n, seed=0, sep=0.8):
    rng = np.random.default_rng(seed)
    labels = rng.random(n) < 0.5
    scores = rng.standard_normal(n) + sep * labels
    return scores, labels


def _snap(fleet):
    return fleet.metrics.snapshot()


def _v(m, name, default=0):
    return m.get(name, {}).get("value", default)


class TestDirtyRowPlacement:
    """[ISSUE 9 tentpole] geometry-stable re-places ship only dirty
    tenants' rows; growth forces the full ship; counts stay exact."""

    @pytest.mark.parametrize("shards", [None, 2])
    def test_geometry_stable_reuse_saves_bytes(self, shards):
        fleet = TenantFleetIndex(compact_every=32, shards=shards)
        streams = {f"t{k}": _stream(200, seed=k) for k in range(6)}
        for tid, (s, l) in streams.items():
            for i in range(0, 200, 40):
                fleet.apply_inserts([(tid, s[i:i + 40], l[i:i + 40])])
        m = _snap(fleet)
        assert _v(m, "bytes_h2d_saved") > 0
        # partial re-places dominate once the geometry settles
        assert _v(m, "pack_replaces_total") \
            > _v(m, "pack_full_replaces_total")
        # and the counts the partial placements serve stay exact
        for tid, (s, l) in streams.items():
            ref = ExactAucIndex(compact_every=32, engine="jax")
            ref.insert_batch(s, l)
            assert fleet.wins2(tid) == ref._wins2, tid

    def test_one_dirty_tenant_of_256_ships_one_row(self):
        """The acceptance geometry: 1 dirty of 256 ships ~1/256 of the
        pack — saved bytes strictly positive and dominant."""
        fleet = TenantFleetIndex(compact_every=8)
        # 256 tiny tenants, then settle the packs
        items = []
        for k in range(256):
            s, l = _stream(4, seed=k)
            items.append((f"t{k}", s, l))
        fleet.apply_inserts(items)
        fleet.apply_inserts([("t0", *_stream(2, seed=999))])
        m0 = _snap(fleet)
        base_bytes = _v(m0, "bytes_h2d")
        base_saved = _v(m0, "bytes_h2d_saved")
        # dirty exactly one tenant (compaction), then force a re-place
        # through the next count (placement is lazy — it runs inside
        # the next fleet count, not at compaction time)
        s, l = _stream(16, seed=500)
        fleet.apply_inserts([("t7", s, l)])
        fleet.apply_scores([("t0", np.zeros(2))])
        m1 = _snap(fleet)
        shipped = _v(m1, "bytes_h2d") - base_bytes
        saved = _v(m1, "bytes_h2d_saved") - base_saved
        assert shipped > 0
        assert saved > 0
        # one row of 256: the saving dwarfs the ship by ~two orders
        assert saved >= 50 * shipped, (shipped, saved)

    def test_t_bucket_growth_forces_full_ship(self):
        fleet = TenantFleetIndex(compact_every=4,
                                 min_tenant_bucket=4)
        for k in range(4):
            fleet.apply_inserts([(f"t{k}", *_stream(8, seed=k))])
        full_before = _v(_snap(fleet), "pack_full_replaces_total")
        # the 5th tenant outgrows T_bucket=4 -> next placement is full
        fleet.apply_inserts([("t4", *_stream(8, seed=9))])
        assert _v(_snap(fleet), "pack_full_replaces_total") \
            > full_before

    def test_incremental_off_restores_full_pack_path(self):
        fleet = TenantFleetIndex(compact_every=16,
                                 incremental_placement=False)
        for k in range(3):
            s, l = _stream(120, seed=k)
            for i in range(0, 120, 30):
                fleet.apply_inserts([(f"t{k}", s[i:i + 30],
                                      l[i:i + 30])])
        m = _snap(fleet)
        assert _v(m, "pack_replaces_total") \
            == _v(m, "pack_full_replaces_total")
        assert _v(m, "bytes_h2d_saved") == 0


class TestWhalePromotion:
    """[ISSUE 9 tentpole] threshold promotion, shrink demotion, and
    bit-identity through every transition."""

    @pytest.mark.parametrize("shards", [None, 1, 2])
    def test_promotes_and_stays_bit_identical(self, shards):
        fleet = TenantFleetIndex(compact_every=32, shards=shards,
                                 whale_threshold=150)
        ref = ExactAucIndex(compact_every=32, engine="jax")
        small_ref = ExactAucIndex(compact_every=32, engine="jax")
        s, l = _stream(400, seed=3)
        ss, sl = _stream(60, seed=4)
        for i in range(0, 400, 37):
            fleet.apply_inserts([("w", s[i:i + 37], l[i:i + 37])])
            ref.insert_batch(s[i:i + 37], l[i:i + 37])
        fleet.apply_inserts([("small", ss, sl)])
        small_ref.insert_batch(ss, sl)
        assert fleet.is_whale("w")
        assert not fleet.is_whale("small")
        assert _v(_snap(fleet), "fleet_whale_promotions") == 1
        assert fleet.wins2("w") == ref._wins2
        assert fleet.auc("w") == ref.auc()
        assert fleet.wins2("small") == small_ref._wins2
        # scores keep routing correctly post-promotion
        q = np.linspace(-1, 1, 7)
        ranks = fleet.apply_scores([("w", q), ("small", q)])
        np.testing.assert_array_equal(ranks[0], ref.score_batch(q))
        np.testing.assert_array_equal(ranks[1],
                                      small_ref.score_batch(q))
        assert fleet.tenant_state("w")["promoted"] is True

    def test_demotes_on_shrink(self):
        """A promoted tenant under the hysteresis floor folds back
        into the pack at the next apply — bit-identically."""
        fleet = TenantFleetIndex(compact_every=16,
                                 whale_threshold=100)
        ref = ExactAucIndex(compact_every=16, engine="jax")
        s, l = _stream(30, seed=5)
        fleet.apply_inserts([("t", s, l)])
        ref.insert_batch(s, l)
        assert fleet.promote("t")       # explicit (30 < threshold)
        assert fleet.is_whale("t")
        s2, l2 = _stream(10, seed=6)
        fleet.apply_inserts([("t", s2, l2)])    # 40 < 50 -> demote
        ref.insert_batch(s2, l2)
        assert not fleet.is_whale("t")
        assert _v(_snap(fleet), "fleet_whale_demotions") == 1
        assert fleet.wins2("t") == ref._wins2
        assert fleet.auc("t") == ref.auc()

    @pytest.mark.parametrize("shards", [None, 2, 4])
    def test_randomized_promote_demote_soak(self, shards):
        """Zipf-ish arrivals + random explicit promote/demote flips +
        natural threshold crossings: per-tenant wins2/AUC bit-identical
        to independent single-tenant indexes throughout."""
        rng = np.random.default_rng(7 + (shards or 0))
        fleet = TenantFleetIndex(window=160, compact_every=24,
                                 shards=shards, whale_threshold=120)
        singles = {}
        tids = [f"t{k}" for k in range(5)]
        weights = np.asarray([8.0, 3.0, 1.0, 1.0, 1.0])
        weights /= weights.sum()
        for _ in range(40):
            items = []
            for tid in tids:
                if rng.random() > weights[int(tid[1])] * 3:
                    continue
                k = int(rng.integers(1, 30))
                labels = rng.random(k) < 0.5
                scores = rng.standard_normal(k) + 0.8 * labels
                items.append((tid, scores, labels))
                singles.setdefault(
                    tid, ExactAucIndex(window=160, compact_every=24,
                                       engine="jax")
                ).insert_batch(scores, labels)
            if items:
                fleet.apply_inserts(items)
            flip = tids[int(rng.integers(len(tids)))]
            if rng.random() < 0.2:
                if fleet.is_whale(flip):
                    fleet.demote(flip)
                else:
                    fleet.promote(flip)
            if rng.random() < 0.3:
                q = rng.standard_normal(5)
                ranks = fleet.apply_scores([(t, q) for t in tids
                                            if t in singles])
                for rk, t in zip(ranks,
                                 [t for t in tids if t in singles]):
                    np.testing.assert_array_equal(
                        rk, singles[t].score_batch(q))
        for tid, ref in singles.items():
            assert fleet.wins2(tid) == ref._wins2, (shards, tid)
            assert fleet.auc(tid) == ref.auc(), (shards, tid)

    def test_chaos_mid_promotion_aborts_cleanly_then_retries(self):
        """A device fault during the promotion's placement aborts the
        promotion with the pack state untouched; the retry succeeds
        and parity holds end to end."""
        fleet = TenantFleetIndex(compact_every=32, shards=2,
                                 whale_threshold=10_000)
        ref = ExactAucIndex(compact_every=32, engine="jax")
        s, l = _stream(200, seed=8)
        fleet.apply_inserts([("w", s, l)])
        ref.insert_batch(s, l)
        # arm AFTER the data landed: the promote's place_base is the
        # next fire (deterministic — no other placement pending)
        fleet.chaos = FaultInjector.from_spec({"faults": [
            {"point": "place_base", "on_call": 1, "action": "error"}]})
        assert fleet.promote("w") is False
        assert _v(_snap(fleet), "fleet_whale_promote_aborts") == 1
        assert not fleet.is_whale("w")
        assert fleet.wins2("w") == ref._wins2   # pack state untouched
        assert fleet.promote("w") is True       # one-shot fault spent
        assert fleet.wins2("w") == ref._wins2
        s2, l2 = _stream(50, seed=9)
        fleet.apply_inserts([("w", s2, l2)])
        ref.insert_batch(s2, l2)
        assert fleet.wins2("w") == ref._wins2

    def test_device_loss_after_promotion_heals_bit_identical(self):
        # call 1 = the fleet pack count of the first apply; call 2 =
        # the promoted index's first sharded count — the fault lands
        # INSIDE the whale path, and the whale's own healer (inherited
        # from the fleet at promotion) shrinks its mesh
        chaos = FaultInjector.from_spec({"faults": [
            {"point": "sharded_count", "on_call": 2, "action": "error",
             "dropped": [1]}]})
        fleet = TenantFleetIndex(compact_every=32, shards=2,
                                 whale_threshold=100, chaos=chaos)
        ref = ExactAucIndex(compact_every=32, engine="jax")
        s, l = _stream(150, seed=10)
        fleet.apply_inserts([("w", s, l)])
        ref.insert_batch(s, l)
        assert fleet.is_whale("w")
        s2, l2 = _stream(80, seed=11)
        fleet.apply_inserts([("w", s2, l2)])
        ref.insert_batch(s2, l2)
        assert chaos.snapshot()["fired"].get("sharded_count") == 1
        assert fleet.wins2("w") == ref._wins2
        assert fleet.auc("w") == ref.auc()
        assert _v(_snap(fleet), "reshard_events") >= 1


class TestOffBatcherBuilds:
    """[ISSUE 9 tentpole] tenant compaction on the side thread:
    double-buffered claim, atomic swap, crash rollback."""

    def test_bg_parity(self):
        fleet = TenantFleetIndex(compact_every=16, shards=2,
                                 bg_compact=True)
        singles = {}
        rng = np.random.default_rng(12)
        for _ in range(30):
            items = []
            for tid in ("a", "b", "c"):
                k = int(rng.integers(1, 25))
                labels = rng.random(k) < 0.5
                scores = rng.standard_normal(k) + 0.8 * labels
                items.append((tid, scores, labels))
                singles.setdefault(
                    tid, ExactAucIndex(compact_every=16, engine="jax")
                ).insert_batch(scores, labels)
            fleet.apply_inserts(items)
        fleet.wait_idle()
        for tid, ref in singles.items():
            assert fleet.wins2(tid) == ref._wins2, tid
        assert _v(_snap(fleet), "compactions_total") > 0
        fleet.close()

    def test_bg_windowed_eviction_parity(self):
        """Evictions racing a claimed build tombstone instead of
        touching the snapshotted prefix."""
        fleet = TenantFleetIndex(window=60, compact_every=8,
                                 bg_compact=True)
        ref = ExactAucIndex(window=60, compact_every=8, engine="jax")
        s, l = _stream(300, seed=13)
        for i in range(0, 300, 11):
            fleet.apply_inserts([("t", s[i:i + 11], l[i:i + 11])])
            ref.insert_batch(s[i:i + 11], l[i:i + 11])
        fleet.wait_idle()
        assert fleet.wins2("t") == ref._wins2
        assert fleet.auc("t") == ref.auc()
        fleet.close()

    def test_bg_crash_aborts_cleanly_and_recovers(self):
        chaos = FaultInjector.from_spec({"faults": [
            {"point": "compactor_build", "on_call": 1,
             "action": "error"}]})
        fleet = TenantFleetIndex(compact_every=8, bg_compact=True,
                                 chaos=chaos)
        ref = ExactAucIndex(compact_every=8, engine="jax")
        s, l = _stream(120, seed=14)
        for i in range(0, 120, 10):
            fleet.apply_inserts([("t", s[i:i + 10], l[i:i + 10])])
            ref.insert_batch(s[i:i + 10], l[i:i + 10])
        fleet.wait_idle()
        m = _snap(fleet)
        assert _v(m, "fleet_compact_aborts") == 1
        assert chaos.snapshot()["fired"].get("compactor_build") == 1
        # the crashed build lost nothing and later triggers compacted
        assert fleet.wins2("t") == ref._wins2
        assert _v(m, "compactions_total") >= 1
        fleet.close()


class TestStaleRowReclaim:
    """[ISSUE 9 satellite bugfix] dropped/idle-evicted tenants' rows
    are reclaimed at the next placement, and the gauges see truth."""

    def test_drop_marks_row_stale_then_reclaims(self):
        fleet = TenantFleetIndex(compact_every=8)
        for k in range(3):
            fleet.apply_inserts([(f"t{k}", *_stream(24, seed=k))])
        assert _v(_snap(fleet), "pack_occupancy") > 0
        assert fleet.drop("t1")
        m = _snap(fleet)
        assert _v(m, "pack_stale_rows") >= 1       # resident, dead
        # any next count re-places the dirty slot -> reclaimed
        fleet.apply_scores([("t0", np.zeros(3))])
        m = _snap(fleet)
        assert _v(m, "pack_stale_rows") == 0
        # and the freed slot's reuse stays exact (regression guard)
        s, l = _stream(30, seed=9)
        fleet.apply_inserts([("fresh", s, l)])
        ref = ExactAucIndex(compact_every=8, engine="jax")
        ref.insert_batch(s, l)
        assert fleet.wins2("fresh") == ref._wins2


class TestTenantMetricCap:
    """[ISSUE 9 satellite] beyond-cap tenants collapse into ONE
    {tenant=__other__} series; the doctor reports the collapse."""

    def test_cap_bounds_series_and_counts_collapsed(self):
        with MultiTenantEngine(
                ServingConfig(max_batch=16, flush_timeout_s=0.001),
                TenancyConfig(tenant_metric_cap=2)) as eng:
            for k in range(5):
                eng.insert(f"u{k}", float(k), k % 2).result(10.0)
            eng.flush()
            m = eng.metrics.snapshot()
        labeled = sorted(k for k in m
                         if k.startswith("insert_latency_s{"))
        assert len(labeled) == 3, labeled
        assert "insert_latency_s{tenant=__other__}" in labeled
        assert m["tenant_metric_collapsed"]["value"] == 3
        # the collapsed series absorbed every beyond-cap observation
        others = m["insert_latency_s{tenant=__other__}"]["count"]
        assert others >= 3

    def test_doctor_breakdown_reports_collapse(self):
        from tuplewise_tpu.obs.doctor import tenant_breakdown
        from tuplewise_tpu.utils.profiling import MetricsRegistry

        reg = MetricsRegistry()
        for t in ("a", "__other__"):
            h = reg.histogram("insert_latency_s",
                              labels={"tenant": t})
            h.observe(0.01)
        reg.gauge("tenant_metric_collapsed").set(41)
        out = tenant_breakdown([{"ts_mono": 1.0,
                                 "metrics": reg.snapshot()}])
        assert out["__other__"]["collapsed_tenants"] == 41

    def test_uncapped_default_keeps_per_tenant_series(self):
        with MultiTenantEngine(
                ServingConfig(max_batch=16,
                              flush_timeout_s=0.001)) as eng:
            for k in range(4):
                eng.insert(f"u{k}", float(k), k % 2).result(10.0)
            m = eng.metrics.snapshot()
        labeled = [k for k in m if k.startswith("insert_latency_s{")]
        assert len(labeled) == 4


class TestWhaleRecovery:
    """[ISSUE 9] promotion state in the snapshot manifest + WAL replay
    re-derivation; SIGKILL subprocess leg."""

    def test_snapshot_roundtrip_preserves_promotion(self, tmp_path):
        cfg = ServingConfig(compact_every=16,
                            snapshot_dir=str(tmp_path / "d"),
                            snapshot_every=60)
        ten = TenancyConfig(whale_threshold=80)
        rng = np.random.default_rng(15)
        with MultiTenantEngine(cfg, ten) as eng:
            for _ in range(70):
                eng.insert("w", rng.standard_normal(2),
                           rng.random(2) < 0.5).result(10.0)
                eng.insert("s", rng.standard_normal(1),
                           rng.random(1) < 0.5).result(10.0)
            eng.flush()
            assert eng.fleet.is_whale("w")
            ref = {t: eng.fleet.wins2(t)
                   for t in eng.fleet.tenants()}
        with MultiTenantEngine(cfg, ten, recover=True) as eng2:
            assert eng2.fleet.is_whale("w")
            assert not eng2.fleet.is_whale("s")
            got = {t: eng2.fleet.wins2(t)
                   for t in eng2.fleet.tenants()}
            # and the recovered whale keeps serving exactly
            eng2.insert("w", 0.5, 1).result(10.0)
        assert ref == got

    def test_wal_tail_replay_re_promotes(self, tmp_path):
        """Crash BEFORE any snapshot captured the promotion: the tagged
        WAL tail replays through apply_inserts, which re-crosses the
        threshold deterministically."""
        cfg = ServingConfig(compact_every=16,
                            snapshot_dir=str(tmp_path / "d"),
                            snapshot_every=100_000)
        ten = TenancyConfig(whale_threshold=60)
        rng = np.random.default_rng(16)
        eng = MultiTenantEngine(cfg, ten)
        for _ in range(50):
            eng.insert("w", rng.standard_normal(2),
                       rng.random(2) < 0.5).result(10.0)
        eng.flush()
        assert eng.fleet.is_whale("w")
        ref = eng.fleet.wins2("w")
        eng._closed = True              # abandon without checkpoint
        eng._worker.join(timeout=10.0)
        with MultiTenantEngine(cfg, ten, recover=True) as eng2:
            assert eng2.fleet.is_whale("w")
            assert eng2.fleet.wins2("w") == ref

    def test_sigkill_whale_recovers(self, tmp_path):
        """SIGKILL a fleet serve with --whale-threshold mid-stream,
        --recover, finish: the whale's final AUC bit-identical to the
        uninterrupted reference and still promoted."""
        d = str(tmp_path / "rk")
        rng = np.random.default_rng(17)
        events = [("whale" if i % 3 else "small",
                   float(rng.standard_normal() + 0.8 * (i % 2)),
                   int(i % 2)) for i in range(240)]
        lines = [json.dumps({"op": "insert", "tenant": t, "score": s,
                             "label": b}) for t, s, b in events]
        args = [sys.executable, "-m", "tuplewise_tpu.harness.cli",
                "serve", "--max-tenants", "8", "--policy", "block",
                "--whale-threshold", "100", "--snapshot-dir", d,
                "--snapshot-every", "50", "--compact-every", "32"]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        p1 = subprocess.Popen(args, stdin=subprocess.PIPE,
                              stdout=subprocess.PIPE, text=True,
                              env=env, cwd=repo)
        for ln in lines[:160]:
            p1.stdin.write(ln + "\n")
        p1.stdin.flush()
        for _ in range(160):
            assert json.loads(p1.stdout.readline())["ok"]
        os.kill(p1.pid, signal.SIGKILL)
        p1.wait(timeout=30)

        feed = lines[160:] + [
            json.dumps({"op": "query", "tenant": t})
            for t in ("whale", "small")] + [
            json.dumps({"op": "tenants"})]
        p2 = subprocess.Popen(args + ["--recover"],
                              stdin=subprocess.PIPE,
                              stdout=subprocess.PIPE, text=True,
                              env=env, cwd=repo)
        out, _ = p2.communicate("\n".join(feed) + "\n", timeout=180)
        resp = [json.loads(ln) for ln in out.strip().splitlines()]
        assert all(r["ok"] for r in resp)
        got = {r["tenant"]: r["auc_exact"] for r in resp
               if "auc_exact" in r}
        fleet_state = [r["fleet"] for r in resp if "fleet" in r][-1]
        assert fleet_state["whales"] == 1

        ref = TenantFleetIndex(compact_every=32, whale_threshold=100)
        for t, s, b in events:
            ref.apply_inserts([(t, [s], [b])])
        assert got == {"whale": ref.auc("whale"),
                       "small": ref.auc("small")}


class TestEngineWhaleEndToEnd:
    def test_replay_fleet_records_incremental_fields(self):
        from tuplewise_tpu.serving.replay import (
            make_tenant_stream, replay_fleet,
        )

        scores, labels, tenants = make_tenant_stream(1200, 6, skew=1.2,
                                                     seed=18)
        rec = replay_fleet(
            scores, labels, tenants,
            config=ServingConfig(compact_every=64, max_batch=64,
                                 policy="block",
                                 flush_timeout_s=0.001,
                                 bg_compact=True),
            tenancy=TenancyConfig(whale_threshold=150),
            chunk=3, max_inflight=64)
        assert rec["events_applied"] == 1200
        assert rec["tenant_auc_max_abs_err"] < 1e-6
        assert rec["whale_promotions"] >= 1
        assert rec["bytes_h2d"] > 0
        assert rec["pack_replaces"] >= rec["pack_full_replaces"]
        assert rec["report"]["tenancy"]["whale_promotions"] \
            == rec["whale_promotions"]

    def test_idle_evicted_whale_closes_index(self):
        with MultiTenantEngine(
                ServingConfig(max_batch=8, flush_timeout_s=0.001),
                TenancyConfig(whale_threshold=40,
                              idle_evict_s=0.15)) as eng:
            rng = np.random.default_rng(19)
            for _ in range(25):
                eng.insert("w", rng.standard_normal(2),
                           rng.random(2) < 0.5).result(5.0)
            assert eng.fleet.is_whale("w")
            deadline = time.monotonic() + 5.0
            while eng.fleet.has("w") and time.monotonic() < deadline:
                eng.insert("keepalive", 0.1, 1).result(5.0)
                time.sleep(0.05)
            assert not eng.fleet.has("w")
            # a dropped whale's slot is reusable and exact
            eng.insert("w", 1.0, 1).result(5.0)
            assert eng.tenant_stats("w")["n_events"] == 1
