"""L1 kernel unit tests."""

import numpy as np
import pytest

from tuplewise_tpu.ops.kernels import (
    auc_kernel,
    hinge_kernel,
    logistic_kernel,
    scatter_kernel,
    triplet_hinge_kernel,
    triplet_indicator_kernel,
    get_kernel,
)


def test_auc_diff_values():
    d = np.array([-2.0, 0.0, 3.0])
    np.testing.assert_allclose(auc_kernel.diff(d, np), [0.0, 0.5, 1.0])


def test_hinge_values():
    d = np.array([-1.0, 0.5, 2.0])
    np.testing.assert_allclose(hinge_kernel.diff(d, np), [2.0, 0.5, 0.0])


def test_logistic_stable_at_extremes():
    d = np.array([-1000.0, 0.0, 1000.0])
    v = logistic_kernel.diff(d, np)
    assert np.isfinite(v).all()
    np.testing.assert_allclose(v[1], np.log(2.0))
    np.testing.assert_allclose(v[0], 1000.0)  # softplus(-d) ~ -d for d << 0
    assert v[2] < 1e-10


def test_pair_matrix_matches_elementwise_loop():
    rng = np.random.default_rng(0)
    s1, s2 = rng.standard_normal(7), rng.standard_normal(5)
    m = auc_kernel.pair_matrix(s1, s2, np)
    for i in range(7):
        for j in range(5):
            expected = float(s1[i] > s2[j]) + 0.5 * float(s1[i] == s2[j])
            assert m[i, j] == expected


def test_scatter_kernel_matrix_and_elementwise_agree():
    rng = np.random.default_rng(1)
    a, b = rng.standard_normal((6, 3)), rng.standard_normal((4, 3))
    m = scatter_kernel.pair_matrix(a, b, np)
    for i in range(6):
        for j in range(4):
            np.testing.assert_allclose(
                m[i, j], 0.5 * np.sum((a[i] - b[j]) ** 2), atol=1e-10
            )
    elem = scatter_kernel.pair_elementwise(a[:4], b, np)
    np.testing.assert_allclose(elem, np.diagonal(m[:4, :4]), atol=1e-10)


def test_triplet_kernels():
    a = np.array([[0.0, 0.0]])
    p = np.array([[1.0, 0.0]])   # d(a,p) = 1
    n = np.array([[0.0, 2.0]])   # d(a,n) = 4
    assert triplet_indicator_kernel.triplet_values(a, p, n, np)[0] == 1.0
    # hinge: max(0, 1 + 1 - 4) = 0 ; swap p/n: max(0, 1 + 4 - 1) = 4
    assert triplet_hinge_kernel.triplet_values(a, p, n, np)[0] == 0.0
    assert triplet_hinge_kernel.triplet_values(a, n, p, np)[0] == 4.0


def test_registry():
    assert get_kernel("auc") is auc_kernel
    assert get_kernel(auc_kernel) is auc_kernel
    with pytest.raises(KeyError):
        get_kernel("nope")
