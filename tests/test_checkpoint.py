"""Checkpoint/resume [SURVEY §5.5].

The contract is EXACT resume: because every source of randomness is
keyed by absolute step/rep index (utils.rng.fold), a run chunked at any
checkpoint boundary — including one interrupted and resumed in a fresh
process — reproduces the unchunked run bit-for-bit.
"""

import dataclasses
import os

import numpy as np
import pytest

from tuplewise_tpu.data import make_gaussians
from tuplewise_tpu.harness.variance import VarianceConfig, run_variance_experiment
from tuplewise_tpu.models.pairwise_sgd import TrainConfig, train_pairwise
from tuplewise_tpu.models.scorers import LinearScorer
from tuplewise_tpu.utils.checkpoint import (
    check_config,
    load_checkpoint,
    save_checkpoint,
)


class TestCheckpointFile:
    def test_roundtrip(self, tmp_path):
        p = str(tmp_path / "ck.npz")
        save_checkpoint(
            p, step=7,
            params={"w": np.arange(3.0), "b": np.asarray(0.5)},
            extra={"loss": np.asarray([1.0, 0.5])},
            config={"lr": 0.1, "steps": 10},
        )
        ck = load_checkpoint(p)
        assert ck["step"] == 7
        np.testing.assert_array_equal(ck["params"]["w"], np.arange(3.0))
        np.testing.assert_array_equal(ck["extra"]["loss"], [1.0, 0.5])
        assert ck["config"] == {"lr": 0.1, "steps": 10}

    def test_missing_returns_none(self, tmp_path):
        assert load_checkpoint(str(tmp_path / "nope.npz")) is None

    def test_config_mismatch_raises(self):
        with pytest.raises(ValueError, match="config mismatch"):
            check_config({"lr": 0.1}, {"lr": 0.2})

    def test_config_ignore_progress_dim(self):
        check_config({"lr": 0.1, "steps": 5}, {"lr": 0.1, "steps": 50},
                     ignore=("steps",))

    def test_atomic_no_partial_file(self, tmp_path):
        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, step=1)
        save_checkpoint(p, step=2)
        assert load_checkpoint(p)["step"] == 2
        assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp")] == []


@pytest.fixture(scope="module")
def train_data():
    return make_gaussians(128, 128, dim=4, separation=1.0, seed=0)


class TestTrainerResume:
    CFG = TrainConfig(kernel="logistic", lr=0.2, steps=12,
                      n_workers=2, repartition_every=5, tile=32)

    def _straight(self, train_data):
        Xp, Xn = train_data
        scorer = LinearScorer(dim=4)
        return train_pairwise(scorer, scorer.init(0), Xp, Xn, self.CFG)

    def test_chunked_equals_straight(self, train_data, tmp_path):
        Xp, Xn = train_data
        scorer = LinearScorer(dim=4)
        ref_params, ref_hist = self._straight(train_data)
        params, hist = train_pairwise(
            scorer, scorer.init(0), Xp, Xn, self.CFG,
            checkpoint_path=str(tmp_path / "t.npz"), checkpoint_every=5,
        )
        for k in ref_params:
            np.testing.assert_array_equal(params[k], ref_params[k])
        np.testing.assert_array_equal(hist["loss"], ref_hist["loss"])

    def test_interrupt_and_resume(self, train_data, tmp_path):
        """Train 7 of 12 steps, 'crash', resume to 12 — bit-identical
        to the straight 12-step run."""
        Xp, Xn = train_data
        scorer = LinearScorer(dim=4)
        p = str(tmp_path / "t.npz")
        short = dataclasses.replace(self.CFG, steps=7)
        train_pairwise(scorer, scorer.init(0), Xp, Xn, short,
                       checkpoint_path=p)
        params, hist = train_pairwise(
            scorer, scorer.init(0), Xp, Xn, self.CFG, checkpoint_path=p,
        )
        ref_params, ref_hist = self._straight(train_data)
        for k in ref_params:
            np.testing.assert_array_equal(params[k], ref_params[k])
        np.testing.assert_array_equal(hist["loss"], ref_hist["loss"])
        assert len(hist["loss"]) == 12

    def test_resume_rejects_other_config(self, train_data, tmp_path):
        Xp, Xn = train_data
        scorer = LinearScorer(dim=4)
        p = str(tmp_path / "t.npz")
        train_pairwise(scorer, scorer.init(0), Xp, Xn, self.CFG,
                       checkpoint_path=p)
        other = dataclasses.replace(self.CFG, lr=0.9)
        with pytest.raises(ValueError, match="config mismatch"):
            train_pairwise(scorer, scorer.init(0), Xp, Xn, other,
                           checkpoint_path=p)

    def test_shrunk_steps_raises(self, train_data, tmp_path):
        """Params can't be rewound: resuming with fewer steps than the
        checkpoint has trained must refuse, not mislabel the model."""
        Xp, Xn = train_data
        scorer = LinearScorer(dim=4)
        p = str(tmp_path / "t.npz")
        train_pairwise(scorer, scorer.init(0), Xp, Xn, self.CFG,
                       checkpoint_path=p)
        short = dataclasses.replace(self.CFG, steps=5)
        with pytest.raises(ValueError, match="past the requested"):
            train_pairwise(scorer, scorer.init(0), Xp, Xn, short,
                           checkpoint_path=p)

    def test_2d_mesh_trains(self, train_data):
        """The trainer generalizes to 2-D (dcn x ici) meshes: same data
        coverage as the 1-D mesh of equal size, loss decreasing."""
        import jax

        if jax.device_count() < 8:
            pytest.skip("needs 8 virtual devices")
        from tuplewise_tpu.parallel.mesh import make_mesh_2d

        Xp, Xn = train_data
        scorer = LinearScorer(dim=4)
        cfg = dataclasses.replace(self.CFG, n_workers=8)
        params, hist = train_pairwise(
            scorer, scorer.init(0), Xp, Xn, cfg, mesh=make_mesh_2d(2, 4))
        assert np.isfinite(hist["loss"]).all()
        assert hist["loss"][-1] < hist["loss"][0]

    def test_already_done_returns_saved(self, train_data, tmp_path):
        Xp, Xn = train_data
        scorer = LinearScorer(dim=4)
        p = str(tmp_path / "t.npz")
        ref_params, _ = train_pairwise(
            scorer, scorer.init(0), Xp, Xn, self.CFG, checkpoint_path=p)
        fresh = scorer.init(1)  # would train differently if rerun
        params, hist = train_pairwise(
            scorer, fresh, Xp, Xn, self.CFG, checkpoint_path=p)
        for k in ref_params:
            np.testing.assert_array_equal(params[k], ref_params[k])


class TestHarnessResume:
    CFG = VarianceConfig(kernel="auc", scheme="incomplete", backend="jax",
                         n_pos=256, n_neg=256, n_pairs=500, n_reps=9,
                         seed=3)

    def test_interrupt_and_resume_vmapped(self, tmp_path):
        p = str(tmp_path / "v.npz")
        short = dataclasses.replace(self.CFG, n_reps=6)
        run_variance_experiment(short, checkpoint_path=p,
                                checkpoint_every=4)
        res = run_variance_experiment(self.CFG, checkpoint_path=p,
                                      checkpoint_every=4)
        ref = run_variance_experiment(self.CFG)
        assert res["mean"] == pytest.approx(ref["mean"], abs=1e-12)
        assert res["variance"] == pytest.approx(ref["variance"], abs=1e-12)
        assert res["n_reps"] == 9

    def test_interrupt_and_resume_looped(self, tmp_path):
        p = str(tmp_path / "l.npz")
        cfg = dataclasses.replace(self.CFG, backend="numpy", n_reps=5)
        short = dataclasses.replace(cfg, n_reps=3)
        run_variance_experiment(short, checkpoint_path=p,
                                checkpoint_every=2)
        res = run_variance_experiment(cfg, checkpoint_path=p,
                                      checkpoint_every=2)
        ref = run_variance_experiment(cfg)
        assert res["mean"] == pytest.approx(ref["mean"], abs=1e-12)
        assert res["variance"] == pytest.approx(ref["variance"], abs=1e-12)

    def test_resume_rejects_other_config(self, tmp_path):
        p = str(tmp_path / "v.npz")
        run_variance_experiment(
            dataclasses.replace(self.CFG, n_reps=3), checkpoint_path=p)
        with pytest.raises(ValueError, match="config mismatch"):
            run_variance_experiment(
                dataclasses.replace(self.CFG, separation=2.0),
                checkpoint_path=p)

    def test_negative_checkpoint_every_raises(self, tmp_path):
        """Regression: a negative chunk size used to loop forever."""
        with pytest.raises(ValueError, match="checkpoint_every"):
            run_variance_experiment(
                self.CFG, checkpoint_path=str(tmp_path / "v.npz"),
                checkpoint_every=-2)

    def test_shrunk_reps_raises(self, tmp_path):
        """Fewer reps than checkpointed: the accumulated wallclock would
        no longer describe the truncated estimates — refuse."""
        p = str(tmp_path / "v.npz")
        run_variance_experiment(self.CFG, checkpoint_path=p)
        short = dataclasses.replace(self.CFG, n_reps=4)
        with pytest.raises(ValueError, match="past the requested"):
            run_variance_experiment(short, checkpoint_path=p)
