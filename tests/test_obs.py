"""Unit tests for the observability substrate [ISSUE 6]:
obs.tracing.Tracer, obs.flight.FlightRecorder,
obs.metrics_export.MetricsFlusher, obs.report."""

import json
import os
import threading
import time

import numpy as np

import pytest

from tuplewise_tpu.obs import (
    FlightRecorder, MetricsFlusher, Tracer, config_digest,
    recovery_counters, service_report,
)
from tuplewise_tpu.obs.tracing import maybe_span
from tuplewise_tpu.utils.profiling import MetricsRegistry


class TestTracer:
    def test_nesting_parents_same_thread(self):
        tr = Tracer()
        with tr.span("outer") as o:
            assert tr.current() is o
            with tr.span("inner") as i:
                assert i.parent_id == o.span_id
                assert i.trace_id == o.trace_id
        spans = tr.spans()
        assert [s["name"] for s in spans] == ["inner", "outer"]
        assert spans[1]["parent_id"] is None

    def test_separate_roots_get_separate_traces(self):
        tr = Tracer()
        with tr.span("a"):
            pass
        with tr.span("b"):
            pass
        a, b = tr.spans()
        assert a["trace_id"] != b["trace_id"]

    def test_explicit_cross_thread_parent(self):
        tr = Tracer()
        root = tr.start("request")
        out = {}

        def worker():
            with tr.span("apply", parent=root) as sp:
                out["tid"] = sp.trace_id
                out["pid"] = sp.parent_id

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        tr.finish(root)
        assert out["tid"] == root.trace_id
        assert out["pid"] == root.span_id

    def test_record_span_retroactive(self):
        tr = Tracer()
        root = tr.start("r")
        t0 = time.perf_counter()
        t1 = t0 + 0.25
        tr.record_span("wait", t0, t1, parent=root)
        tr.finish(root)
        wait = [s for s in tr.spans() if s["name"] == "wait"][0]
        assert wait["dur_s"] == pytest.approx(0.25)
        assert wait["parent_id"] == root.span_id

    def test_monotonic_durations_nonnegative(self):
        tr = Tracer()
        for _ in range(50):
            with tr.span("x"):
                pass
        assert all(s["dur_s"] >= 0 for s in tr.spans())

    def test_ring_bounds_memory(self):
        tr = Tracer(capacity=8)
        for i in range(20):
            with tr.span(f"s{i}"):
                pass
        assert len(tr) == 8
        assert tr.dropped == 12
        # ring order restored: oldest retained first
        assert [s["name"] for s in tr.spans()] == [
            f"s{i}" for i in range(12, 20)]

    def test_disabled_tracer_allocates_nothing(self):
        tr = Tracer(enabled=False)
        with tr.span("x") as sp:
            assert sp is None
        assert tr.start("y") is None
        assert len(tr) == 0

    def test_maybe_span_none_is_noop(self):
        with maybe_span(None, "anything") as sp:
            assert sp is None

    def test_error_marks_span(self):
        tr = Tracer()
        with pytest.raises(ValueError):
            with tr.span("boom"):
                raise ValueError("x")
        s = tr.spans()[0]
        assert s["attrs"]["error"] == "ValueError"

    def test_export_jsonl_roundtrip(self, tmp_path):
        tr = Tracer()
        with tr.span("a", k=1):
            with tr.span("b"):
                pass
        p = str(tmp_path / "spans.jsonl")
        assert tr.export_jsonl(p) == 2
        lines = [json.loads(x) for x in open(p)]
        assert lines[0]["meta"]["format"] == "tuplewise-spans-v1"
        names = {r["name"] for r in lines[1:]}
        assert names == {"a", "b"}

    def test_export_chrome_schema(self, tmp_path):
        tr = Tracer()
        with tr.span("a"):
            pass
        p = str(tmp_path / "trace.json")
        tr.export_chrome(p)
        doc = json.load(open(p))
        evs = doc["traceEvents"]
        x = [e for e in evs if e["ph"] == "X"]
        m = [e for e in evs if e["ph"] == "M"]
        assert len(x) == 1 and x[0]["name"] == "a"
        assert x[0]["ts"] >= 0 and x[0]["dur"] >= 0
        assert any(e["name"] == "thread_name" for e in m)
        assert any(e["name"] == "process_name" for e in m)

    def test_thread_safety_concurrent_spans(self):
        tr = Tracer()

        def worker(i):
            for _ in range(200):
                with tr.span(f"w{i}"):
                    with tr.span(f"w{i}.child"):
                        pass

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        spans = tr.spans()
        assert len(spans) == 8 * 200 * 2
        # every child's parent is the matching worker's root, never a
        # span from another thread
        by_id = {s["span_id"]: s for s in spans}
        for s in spans:
            if s["parent_id"] is not None:
                parent = by_id[s["parent_id"]]
                assert s["name"] == parent["name"] + ".child"
                assert s["trace_id"] == parent["trace_id"]


class TestFlightRecorder:
    def test_record_and_seq(self):
        fr = FlightRecorder(capacity=16)
        s1 = fr.record("compaction", tier="minor")
        s2 = fr.record("heal")
        assert (s1, s2) == (1, 2)
        evs = fr.events()
        assert [e["kind"] for e in evs] == ["compaction", "heal"]
        assert evs[0]["tier"] == "minor"
        assert fr.counts() == {"compaction": 1, "heal": 1}

    def test_ring_bounded_keeps_latest(self):
        fr = FlightRecorder(capacity=4)
        for i in range(10):
            fr.record("e", i=i)
        evs = fr.events()
        assert len(evs) == 4 and fr.dropped == 6
        assert [e["i"] for e in evs] == [6, 7, 8, 9]
        assert [e["seq"] for e in evs] == [7, 8, 9, 10]

    def test_trace_correlation_via_tracer(self):
        tr = Tracer()
        fr = FlightRecorder(tracer=tr)
        with tr.span("op") as sp:
            fr.record("inside")
        fr.record("outside")
        evs = fr.events()
        assert evs[0]["trace_id"] == sp.trace_id
        assert evs[1]["trace_id"] is None

    def test_dump_roundtrip(self, tmp_path):
        fr = FlightRecorder()
        fr.record("a", x=1)
        fr.record("b")
        p = str(tmp_path / "flight.jsonl")
        assert fr.dump_to(p) == 2
        d = FlightRecorder.load_dump(p)
        assert d["format"] == "tuplewise-flight-v1"
        assert d["n_events"] == 2
        assert [e["kind"] for e in d["events"]] == ["a", "b"]

    def test_auto_dump_path(self, tmp_path):
        p = str(tmp_path / "auto.jsonl")
        fr = FlightRecorder(dump_path=p)
        fr.record("x")
        assert fr.auto_dump()
        assert FlightRecorder.load_dump(p)["n_events"] == 1
        assert not FlightRecorder().auto_dump()   # no path configured

    def test_auto_dump_never_raises(self, tmp_path):
        fr = FlightRecorder(dump_path=str(tmp_path / "nodir" / "x" / "y"))
        fr.record("x")
        assert fr.auto_dump() is False
        assert fr.last_dump_error is not None


class TestMetricsFlusher:
    def test_start_stop_writes_at_least_two_rows(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("c").inc(3)
        p = str(tmp_path / "m.jsonl")
        fl = MetricsFlusher(reg, p, every_s=10.0,
                            meta={"stage": "test"}, config={"a": 1})
        fl.start()
        fl.stop()
        rows = [json.loads(x) for x in open(p)]
        assert len(rows) >= 2
        for r in rows:
            assert r["stage"] == "test"
            assert r["platform"]
            assert r["config_digest"] == config_digest({"a": 1})
            assert r["ts_wall"] > 0 and r["ts_mono"] > 0
            assert r["metrics"]["c"]["value"] == 3
        assert rows[-1]["seq"] > rows[0]["seq"]

    def test_periodic_rows(self, tmp_path):
        reg = MetricsRegistry()
        p = str(tmp_path / "m.jsonl")
        with MetricsFlusher(reg, p, every_s=0.05):
            time.sleep(0.3)
        rows = [json.loads(x) for x in open(p)]
        assert len(rows) >= 4   # start + a few ticks + stop

    def test_flush_error_kept_not_raised(self, tmp_path):
        reg = MetricsRegistry()
        fl = MetricsFlusher(reg, str(tmp_path), every_s=1.0)  # a dir!
        fl.flush()
        assert fl.last_flush_error is not None

    def test_config_digest_stable_and_distinct(self):
        a = config_digest({"x": 1, "y": 2})
        assert a == config_digest({"y": 2, "x": 1})
        assert a != config_digest({"x": 1, "y": 3})
        from tuplewise_tpu.serving import ServingConfig

        assert config_digest(ServingConfig()) \
            == config_digest(ServingConfig())
        assert config_digest(ServingConfig()) \
            != config_digest(ServingConfig(budget=7))


class TestReport:
    def _metrics(self):
        reg = MetricsRegistry()
        reg.counter("poison_rejects").inc(2)
        reg.counter("reshard_events").inc(1)
        reg.histogram("insert_latency_s").observe(0.01)
        from tuplewise_tpu.obs.report import INSERT_STAGES, stage_metric

        # stages that tile the 10ms total
        per = 0.01 / len(INSERT_STAGES)
        for s in INSERT_STAGES:
            reg.histogram(stage_metric(s)).observe(per)
        return reg.snapshot()

    def test_recovery_counters_keys(self):
        rc = recovery_counters(self._metrics())
        assert rc["poison_rejects"] == 2
        assert rc["reshard_events"] == 1
        assert rc["major_merge_fallbacks"] == 0
        assert "shard_retries_total" in rc

    def test_service_report_carries_stages_and_counters(self):
        rep = service_report(self._metrics())
        assert set(recovery_counters(self._metrics())) <= set(rep)
        assert rep["poison_rejects"] == 2
        assert len(rep["insert_stage_p99_ms"]) == 7
        attr = rep["stage_attribution"]
        assert attr["coverage"] == pytest.approx(1.0)

    def test_stage_attribution_none_without_inserts(self):
        rep = service_report(MetricsRegistry().snapshot())
        assert rep["stage_attribution"] is None
        assert rep["insert_stage_p99_ms"] == {}


class TestFlusherRotationAndObservers:
    """[ISSUE 7 satellite] max-bytes rotation + the observer hook the
    SLO monitor rides."""

    def test_max_bytes_rolls_to_dot_one(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("c").inc()
        p = str(tmp_path / "m.jsonl")
        fl = MetricsFlusher(reg, p, every_s=10.0, max_bytes=256)
        n = 40
        for _ in range(n):
            fl.flush()
        fl.stop()
        assert fl.rotations >= 2
        roll = p + ".1"
        assert os.path.exists(roll) and os.path.exists(p)
        # both generations hold only WHOLE rows, seqs stay monotonic
        rows = [json.loads(x) for x in open(roll)] \
            + [json.loads(x) for x in open(p)]
        seqs = [r["seq"] for r in rows]
        assert seqs == sorted(seqs)
        assert seqs[-1] == n + 1    # n flushes + stop()'s final row
        # bounded: live file + one roll, each near the cap
        assert os.path.getsize(p) <= 256 + 512
        assert os.path.getsize(roll) <= 256 + 512

    def test_rotation_validation(self):
        with pytest.raises(ValueError, match="max_bytes"):
            MetricsFlusher(MetricsRegistry(), "x.jsonl", max_bytes=0)

    def test_observers_see_every_row(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("c").inc(7)
        rows = []
        p = str(tmp_path / "m.jsonl")
        fl = MetricsFlusher(reg, p, every_s=10.0,
                            observers=[rows.append])
        fl.start()
        fl.stop()
        assert len(rows) >= 2
        assert rows[0]["metrics"]["c"]["value"] == 7
        disk = [json.loads(x) for x in open(p)]
        assert [r["seq"] for r in rows] == [r["seq"] for r in disk]

    def test_observer_only_flusher_without_path(self):
        reg = MetricsRegistry()
        seen = []
        fl = MetricsFlusher(reg, None, every_s=10.0,
                            observers=[seen.append])
        fl.start()
        fl.stop()
        assert len(seen) >= 2
        assert fl.last_flush_error is None

    def test_observer_exception_never_kills_flusher(self, tmp_path):
        reg = MetricsRegistry()

        def bad(row):
            raise RuntimeError("observer bug")

        p = str(tmp_path / "m.jsonl")
        fl = MetricsFlusher(reg, p, every_s=10.0, observers=[bad])
        fl.flush()
        fl.flush()
        fl.stop()
        assert fl.last_flush_error is not None
        assert len([x for x in open(p)]) == 3

    def test_stop_bounded_by_wedged_observer(self, tmp_path):
        """[ISSUE 14 bugfix] stop() must NOT inherit a wedged
        observer's hang: observers run under the flush lock, so the
        old final-flush-then-close path deadlocked shutdown behind
        whatever the observer was stuck on. Now stop() joins with a
        timeout, counts flusher_late_flushes_total, and the in-flight
        flush closes the file when it finally completes."""
        import threading
        import time as _time

        reg = MetricsRegistry()
        entered = threading.Event()
        release = threading.Event()

        def wedged(row):
            if row["seq"] >= 2:      # the first flush is start()'s
                entered.set()
                release.wait(20.0)   # wedged until the test releases

        p = str(tmp_path / "m.jsonl")
        fl = MetricsFlusher(reg, p, every_s=0.02,
                            observers=[wedged])
        fl.start()
        assert entered.wait(10.0)
        t0 = _time.perf_counter()
        fl.stop(timeout=0.2)         # must return promptly, not hang
        stop_s = _time.perf_counter() - t0
        assert stop_s < 5.0
        snap = reg.snapshot()
        assert snap["flusher_late_flushes_total"]["value"] == 1
        assert "wedged" in (fl.last_flush_error or "")
        # release the observer: the in-flight flush completes, closes
        # the file, and the thread exits
        release.set()
        deadline = _time.perf_counter() + 10.0
        while fl._f is not None and _time.perf_counter() < deadline:
            _time.sleep(0.01)
        assert fl._f is None
        rows = [json.loads(x) for x in open(p) if x.strip()]
        assert rows and rows[-1]["seq"] >= 2

    def test_stop_without_wedge_counts_nothing(self, tmp_path):
        reg = MetricsRegistry()
        p = str(tmp_path / "m.jsonl")
        fl = MetricsFlusher(reg, p, every_s=10.0)
        fl.start()
        fl.stop()
        assert reg.snapshot()[
            "flusher_late_flushes_total"]["value"] == 0
        assert fl._f is None
