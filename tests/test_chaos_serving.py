"""Serving fault tolerance [ISSUE 3]: deterministic chaos schedules,
self-healing sharded counts, engine lifecycle hardening, and crash-safe
recovery.

The invariant every test pins: recovery REPAIRS state, it never
corrupts it — under any scheduled fault (shard death, compactor crash,
batcher crash, poison events) the engine completes without hanging and
wins2 / AUC stay bit-identical to a fault-free run over the same
admitted events. Crash recovery extends the same claim across a
process boundary: snapshot + WAL replay reproduce the uninterrupted
run's every subsequent prefix bit-for-bit.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tuplewise_tpu.serving import (
    DeadlineExceededError,
    EngineClosedError,
    ExactAucIndex,
    MicroBatchEngine,
    PoisonEventError,
    ServingConfig,
    replay,
)
from tuplewise_tpu.serving.replay import make_stream
from tuplewise_tpu.testing.chaos import FaultInjector, InjectedFault


def _stream(n, seed=7):
    scores, labels = make_stream(n, pos_frac=0.45, separation=1.0,
                                 seed=seed)
    return scores, labels


# --------------------------------------------------------------------- #
# chaos injector                                                        #
# --------------------------------------------------------------------- #
class TestFaultInjector:
    def test_fires_at_scheduled_call_once(self):
        inj = FaultInjector.from_spec(
            {"faults": [{"point": "batcher", "on_call": 3}]})
        inj.fire("batcher")
        inj.fire("batcher")
        with pytest.raises(InjectedFault):
            inj.fire("batcher")
        inj.fire("batcher")     # one-shot: no refire
        assert inj.snapshot()["fired"] == {"batcher": 1}

    def test_poison_batch_positions(self):
        inj = FaultInjector.from_spec(
            {"faults": [{"point": "poison", "at_events": [5, 12],
                         "value": "nan"}]})
        arr = np.zeros(10)
        out, k = inj.poison_batch(0, arr)
        assert k == 1 and np.isnan(out[5]) and not np.isnan(arr[5])
        out, k = inj.poison_batch(10, np.zeros(10))
        assert k == 1 and np.isnan(out[2])

    def test_spec_rejects_unknown_point(self):
        with pytest.raises(ValueError, match="unknown fault point"):
            FaultInjector.from_spec({"faults": [{"point": "nope"}]})

    def test_random_is_reproducible(self):
        a = FaultInjector.random(3, 1000)
        b = FaultInjector.random(3, 1000)
        assert a.poison_at == b.poison_at


# --------------------------------------------------------------------- #
# self-healing sharded index                                            #
# --------------------------------------------------------------------- #
class TestShardDeath:
    def test_self_heal_preserves_exactness(self):
        """A device error mid-query triggers probe -> reshard over the
        survivors -> re-place -> retry; counts (hence wins2 and every
        AUC) stay bit-identical to the unfaulted single-host index."""
        scores, labels = _stream(1200, seed=11)
        inj = FaultInjector.from_spec({"faults": [
            {"point": "sharded_count", "on_call": 7, "action": "error",
             "dropped": [1]}]})
        hurt = ExactAucIndex(engine="jax", compact_every=64, shards=2,
                             chaos=inj)
        plain = ExactAucIndex(engine="jax", compact_every=64)
        for i in range(0, 1200, 41):
            j = min(i + 41, 1200)
            hurt.insert_batch(scores[i:j], labels[i:j])
            plain.insert_batch(scores[i:j], labels[i:j])
            assert hurt._wins2 == plain._wins2, i
        assert hurt.auc() == plain.auc()
        assert hurt.shards == 1            # resharded over the survivor
        m = hurt.metrics.snapshot()
        assert m["reshard_events"]["value"] == 1
        assert m["shard_retries_total"]["value"] == 1
        assert m["recovery_time_s"]["count"] == 1
        hurt.close()
        plain.close()

    def test_retry_bound_surfaces_persistent_failure(self):
        """A fault on EVERY retry exhausts the bound and raises — the
        index degrades loudly, never spins forever."""
        scores, labels = _stream(100, seed=1)
        faults = [{"point": "sharded_count", "on_call": k,
                   "action": "error"} for k in range(1, 10)]
        idx = ExactAucIndex(engine="jax", compact_every=8, shards=2,
                            chaos=FaultInjector.from_spec(
                                {"faults": faults}),
                            shard_retries=2, retry_backoff_s=0.001)
        # base run must be non-empty for the sharded path to engage
        idx.insert_batch(scores[:32], labels[:32])
        idx.compact()
        with pytest.raises(InjectedFault):
            idx.insert_batch(scores[32:64], labels[32:64])
        idx.close()


# --------------------------------------------------------------------- #
# compactor watchdog                                                    #
# --------------------------------------------------------------------- #
class TestCompactorCrash:
    def test_watchdog_restarts_and_statistic_survives(self):
        scores, labels = _stream(2000, seed=3)
        inj = FaultInjector.from_spec({"faults": [
            {"point": "compactor_build", "on_call": 1,
             "action": "error"}]})
        hurt = ExactAucIndex(engine="numpy", compact_every=32,
                             bg_compact=True, chaos=inj)
        sync = ExactAucIndex(engine="numpy", compact_every=32)
        for i in range(0, 2000, 37):
            j = min(i + 37, 2000)
            hurt.insert_batch(scores[i:j], labels[i:j])
            sync.insert_batch(scores[i:j], labels[i:j])
            assert hurt._wins2 == sync._wins2, i
        hurt.compact()      # must not hang on the crashed build
        assert hurt.auc() == sync.auc()
        m = hurt.metrics.snapshot()
        assert m["bg_compactor_restarts"]["value"] >= 1
        assert hurt.n_compactions > 0
        assert "InjectedFault" in hurt.state()["last_compactor_error"]
        hurt.close()

    def test_wait_idle_survives_crashed_build(self):
        """wait_idle during a crashed build must resolve (watchdog
        restart), not time out."""
        scores, labels = _stream(400, seed=9)
        inj = FaultInjector.from_spec({"faults": [
            {"point": "compactor_build", "on_call": 1,
             "action": "error"}]})
        idx = ExactAucIndex(engine="numpy", compact_every=32,
                            bg_compact=True, chaos=inj)
        idx.insert_batch(scores, labels)
        idx.wait_idle(timeout=10.0)
        idx.close()


# --------------------------------------------------------------------- #
# engine lifecycle                                                      #
# --------------------------------------------------------------------- #
class TestEngineHardening:
    def test_poison_rejected_at_edge(self):
        with MicroBatchEngine(engine="numpy", policy="block") as eng:
            with pytest.raises(PoisonEventError, match="non-finite"):
                eng.insert([np.nan, 1.0], [1, 0])
            with pytest.raises(PoisonEventError, match="mismatch"):
                eng.insert([1.0, 2.0], [1])
            eng.insert([1.0, 0.0], [1, 0]).result(10)
            snap = eng.flush()
        assert snap["metrics"]["poison_rejects"]["value"] == 2
        assert snap["index"]["n_events"] == 2   # poison never landed

    def test_block_policy_close_wakes_producers(self):
        """[ISSUE 3 satellite] close() with producers blocked on the
        bounded queue: every blocked producer must wake and see a typed
        EngineClosedError, not deadlock."""
        eng = MicroBatchEngine(engine="numpy", policy="block",
                               queue_size=2, max_batch=1,
                               flush_timeout_s=0.0)
        orig = eng._apply_inserts

        def slow(run):
            # hold the batcher long enough for close() to land while
            # producers are still blocked on the full queue
            time.sleep(0.4)
            orig(run)
        eng._apply_inserts = slow
        eng.insert([0.0], [0])          # occupies the batcher
        time.sleep(0.05)
        outcomes = []

        def producer(i):
            try:
                f = eng.insert([float(i)], [i % 2])
                try:
                    f.result(10.0)
                    outcomes.append("ok")
                except EngineClosedError:
                    outcomes.append("closed")
            except EngineClosedError:
                outcomes.append("closed")
        threads = [threading.Thread(target=producer, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.2)                 # let them pile onto the queue
        eng.close(timeout=10.0)         # pre-fix: deadlocked right here
        for t in threads:
            t.join(timeout=10.0)
            assert not t.is_alive(), "producer deadlocked through close()"
        assert outcomes and set(outcomes) == {"closed"}
        assert len(outcomes) == 6

    def test_batcher_supervisor_restarts(self):
        inj = FaultInjector.from_spec({"faults": [
            {"point": "batcher", "on_call": 2, "action": "error"}]})
        with MicroBatchEngine(ServingConfig(engine="numpy",
                                            policy="block"),
                              chaos=inj) as eng:
            for i in range(10):
                eng.insert([float(i)], [i % 2]).result(10)
            snap = eng.flush()
        assert snap["metrics"]["batcher_restarts"]["value"] >= 1
        assert snap["index"]["n_events"] == 10

    def test_deadline_expires_stale_requests(self):
        eng = MicroBatchEngine(engine="numpy", policy="block",
                               deadline_s=0.05, max_batch=4,
                               flush_timeout_s=0.0)
        release = threading.Event()
        orig = eng._apply_inserts

        def slow(run):
            release.wait(timeout=10.0)
            orig(run)
        eng._apply_inserts = slow
        first = eng.insert([0.0], [0])      # holds the batcher...
        time.sleep(0.2)                     # ...past the deadline
        late = eng.insert([1.0], [1])
        time.sleep(0.2)
        release.set()
        eng._apply_inserts = orig
        with pytest.raises(DeadlineExceededError):
            late.result(10.0)
        first.result(10.0)      # already dispatched: deadline unchecked
        snap = eng.flush()
        eng.close()
        assert snap["metrics"]["deadline_expired_total"]["value"] >= 1

    def test_submit_after_close_is_typed(self):
        eng = MicroBatchEngine(engine="numpy")
        eng.close()
        with pytest.raises(EngineClosedError):
            eng.insert([1.0], [1])


# --------------------------------------------------------------------- #
# chaos parity (the acceptance schedule)                                #
# --------------------------------------------------------------------- #
class TestChaosParity:
    def test_combined_schedule_bit_identical(self):
        """Shard death + compactor crash + poison in ONE replay: it
        completes, every recovery counter fires, and the final AUC is
        bit-identical to the fault-free run on the admitted events."""
        n = 1200
        scores, labels = _stream(n, seed=21)
        spec = {"faults": [
            {"point": "sharded_count", "on_call": 25, "action": "error",
             "dropped": [1]},
            {"point": "compactor_build", "on_call": 1,
             "action": "error"},
            {"point": "poison", "at_events": [77, 500, 501],
             "value": "nan"},
        ]}
        cfg = ServingConfig(policy="block", mesh_shards=2,
                            bg_compact=True, compact_every=64)
        rec = replay(scores, labels, config=cfg, chaos=spec,
                     max_inflight=64)
        f = rec["faults"]
        assert f["reshard_events"] > 0
        assert f["bg_compactor_restarts"] > 0
        assert f["poison_rejects"] == 3
        assert rec["shed_events"] == [77, 500, 501]
        assert rec["auc_abs_err"] == 0.0    # oracle over admitted events
        admitted = np.ones(n, dtype=bool)
        admitted[rec["shed_events"]] = False
        ref = replay(scores[admitted], labels[admitted],
                     config=ServingConfig(policy="block",
                                          bg_compact=True,
                                          compact_every=64),
                     max_inflight=64)
        assert rec["auc_exact"] == ref["auc_exact"]

    @pytest.mark.slow
    def test_randomized_soak(self):
        """Randomized-but-reproducible schedules: whatever fires, the
        engine completes and parity holds on the admitted events."""
        n = 1500
        for seed in range(8):
            scores, labels = _stream(n, seed=100 + seed)
            inj = FaultInjector.random(seed, n)
            cfg = ServingConfig(engine="numpy", policy="block",
                                bg_compact=True, compact_every=64)
            rec = replay(scores, labels, config=cfg, chaos=inj,
                         max_inflight=64)
            admitted = np.ones(n, dtype=bool)
            admitted[rec["shed_events"]] = False
            ref = replay(scores[admitted], labels[admitted],
                         config=cfg, max_inflight=64)
            assert rec["auc_exact"] == ref["auc_exact"], seed


# --------------------------------------------------------------------- #
# crash-safe recovery                                                   #
# --------------------------------------------------------------------- #
class TestCrashRecovery:
    def _ref_index(self, scores, labels):
        idx = ExactAucIndex(engine="numpy", compact_every=64)
        idx.insert_batch(scores, labels)
        return idx

    def test_recover_resumes_bit_identical(self, tmp_path):
        """Abandon an engine mid-stream (daemon threads — a process
        crash in miniature), recover from its snapshot + WAL, continue:
        every subsequent prefix must match the uninterrupted run
        bit-for-bit."""
        d = str(tmp_path / "reco")
        scores, labels = _stream(1400, seed=5)
        cfg = ServingConfig(engine="numpy", policy="block",
                            snapshot_dir=d, snapshot_every=300,
                            compact_every=64)
        eng = MicroBatchEngine(cfg)
        for i in range(0, 700, 7):
            eng.insert(scores[i:i + 7], labels[i:i + 7])
        eng.flush()
        del eng     # crash: no close(), no final snapshot

        eng2 = MicroBatchEngine(ServingConfig(
            engine="numpy", policy="block", snapshot_dir=d,
            snapshot_every=300, compact_every=64, recover=True))
        assert eng2._recovery.seq == 700
        ref = ExactAucIndex(engine="numpy", compact_every=64)
        ref.insert_batch(scores[:700], labels[:700])
        assert eng2.index._wins2 == ref._wins2
        for i in range(700, 1400, 11):
            j = min(i + 11, 1400)
            eng2.insert(scores[i:j], labels[i:j]).result(10)
            eng2.flush()
            ref.insert_batch(scores[i:j], labels[i:j])
            assert eng2.index._wins2 == ref._wins2, i
            assert eng2.index.auc() == ref.auc(), i
        # the incomplete-U estimator recovered too (sums + reservoirs +
        # RNG state round-trip through the snapshot)
        assert eng2.streaming.n_arrivals == 1400
        eng2.close()

    def test_recover_rejects_mismatched_config(self, tmp_path):
        d = str(tmp_path / "reco2")
        scores, labels = _stream(100, seed=2)
        eng = MicroBatchEngine(ServingConfig(
            engine="numpy", policy="block", snapshot_dir=d,
            snapshot_every=50))
        eng.insert(scores, labels).result(10)
        eng.flush()
        eng.close()     # graceful: final snapshot
        with pytest.raises(ValueError, match="config mismatch"):
            MicroBatchEngine(ServingConfig(
                engine="numpy", policy="block", snapshot_dir=d,
                window=10, recover=True))

    def test_inserts_proceed_during_slow_snapshot(self, tmp_path):
        """[ISSUE 4 satellite] Snapshot writes run on a side thread
        with an atomic capture handoff: while a (deliberately stuck)
        snapshot write is in flight, inserts must keep completing —
        the batcher never blocks on the writer."""
        d = str(tmp_path / "slow")
        scores, labels = _stream(400, seed=31)
        eng = MicroBatchEngine(ServingConfig(
            engine="numpy", policy="block", snapshot_dir=d,
            snapshot_every=50, compact_every=32))
        gate = threading.Event()
        started = threading.Event()

        def stall(seq):
            started.set()
            assert gate.wait(timeout=20.0)
        eng._recovery._write_test_hook = stall
        for i in range(0, 60, 6):       # cross the snapshot threshold
            eng.insert(scores[i:i + 6], labels[i:i + 6]).result(10)
        eng.flush()
        assert started.wait(timeout=10.0), "snapshot capture never ran"
        # writer is stuck; 300 more events must apply regardless
        for i in range(60, 360, 6):
            assert eng.insert(scores[i:i + 6],
                              labels[i:i + 6]).result(10) == 6
        assert not gate.is_set()
        snap = eng.flush()
        assert snap["index"]["n_events"] == 360
        gate.set()
        eng.close()
        # recovery sees the union of snapshot + sealed segments + live
        # WAL — bit-identical to the uninterrupted reference
        eng2 = MicroBatchEngine(ServingConfig(
            engine="numpy", policy="block", snapshot_dir=d,
            snapshot_every=50, compact_every=32, recover=True))
        ref = self._ref_index(scores[:360], labels[:360])
        assert eng2.index._wins2 == ref._wins2
        eng2.close()

    def test_crash_with_stuck_writer_loses_nothing(self, tmp_path):
        """A crash while the async snapshot writer is stuck: the sealed
        WAL segment + live WAL still replay every admitted event over
        the previous snapshot."""
        d = str(tmp_path / "stuck")
        scores, labels = _stream(300, seed=33)
        eng = MicroBatchEngine(ServingConfig(
            engine="numpy", policy="block", snapshot_dir=d,
            snapshot_every=80, compact_every=32))
        eng._recovery._write_test_hook = (
            lambda seq: threading.Event().wait(60.0))   # wedge forever
        for i in range(0, 300, 5):
            eng.insert(scores[i:i + 5], labels[i:i + 5]).result(10)
        eng.flush()
        del eng     # crash: snapshot never landed, segments remain

        eng2 = MicroBatchEngine(ServingConfig(
            engine="numpy", policy="block", snapshot_dir=d,
            snapshot_every=80, compact_every=32, recover=True))
        assert eng2._recovery.seq == 300
        ref = self._ref_index(scores, labels)
        assert eng2.index._wins2 == ref._wins2
        eng2.close()

    def test_wal_fsync_batch_mode_round_trips(self, tmp_path):
        """[ISSUE 4 satellite] wal_fsync='batch' (fsync every append —
        the power-loss-window knob) changes durability only: recovery
        parity is unchanged."""
        d = str(tmp_path / "fs")
        scores, labels = _stream(200, seed=37)
        eng = MicroBatchEngine(ServingConfig(
            engine="numpy", policy="block", snapshot_dir=d,
            snapshot_every=1000, wal_fsync="batch"))
        eng.insert(scores, labels).result(10)
        eng.flush()
        del eng     # crash: everything lives in the fsync'd WAL

        eng2 = MicroBatchEngine(ServingConfig(
            engine="numpy", policy="block", snapshot_dir=d,
            snapshot_every=1000, wal_fsync="batch", recover=True))
        ref = self._ref_index(scores, labels)
        assert eng2.index._wins2 == ref._wins2
        eng2.close()

    def test_wal_fsync_validated(self):
        with pytest.raises(ValueError, match="wal_fsync"):
            ServingConfig(wal_fsync="always")

    def test_sigkill_mid_stream_recovers(self, tmp_path):
        """The real thing: SIGKILL a serve process mid-stream, restart
        with --recover, finish the stream — the final AUC must equal
        the uninterrupted in-process run bit-for-bit."""
        d = str(tmp_path / "rk")
        scores, labels = _stream(600, seed=13)
        lines = [json.dumps({"op": "insert", "score": float(s),
                             "label": int(b)})
                 for s, b in zip(scores, labels)]
        args = [sys.executable, "-m", "tuplewise_tpu.harness.cli",
                "serve", "--engine", "numpy", "--policy", "block",
                "--snapshot-dir", d, "--snapshot-every", "100",
                "--compact-every", "64"]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

        p1 = subprocess.Popen(args, stdin=subprocess.PIPE,
                              stdout=subprocess.PIPE, text=True,
                              env=env, cwd=repo)
        for ln in lines[:350]:
            p1.stdin.write(ln + "\n")
        p1.stdin.flush()
        # wait until all 350 are ACKed (responses are 1:1, in order),
        # so the WAL provably holds every admitted event, then KILL
        for _ in range(350):
            assert json.loads(p1.stdout.readline())["ok"]
        os.kill(p1.pid, signal.SIGKILL)
        p1.wait(timeout=30)

        # resume, interleaving a query every 50 events: EVERY subsequent
        # prefix must match the uninterrupted run bit-for-bit
        feed, query_prefixes = [], []
        for k in range(350, 600):
            feed.append(lines[k])
            if (k + 1) % 50 == 0 or k == 599:
                feed.append(json.dumps({"op": "query"}))
                query_prefixes.append(k + 1)
        p2 = subprocess.Popen(args + ["--recover"],
                              stdin=subprocess.PIPE,
                              stdout=subprocess.PIPE, text=True,
                              env=env, cwd=repo)
        out, _ = p2.communicate("\n".join(feed) + "\n", timeout=120)
        resp = [json.loads(ln) for ln in out.strip().splitlines()]
        assert all(r["ok"] for r in resp)
        aucs = [r["auc_exact"] for r in resp if "auc_exact" in r]
        assert len(aucs) == len(query_prefixes)
        for prefix, got in zip(query_prefixes, aucs):
            ref = self._ref_index(scores[:prefix], labels[:prefix])
            assert got == ref.auc(), prefix


class TestDeltaRecovery:
    """[ISSUE 5 satellite] Snapshots capture the sharded index's delta
    run + tombstone multiset, so recovery restores MID-DELTA state —
    not just fully-compacted bases — bit-identically."""

    _KW = dict(engine="jax", policy="block", mesh_shards=2,
               compact_every=64, window=500, delta_fraction=4.0,
               max_delta_runs=64, snapshot_every=300)

    def test_snapshot_restores_mid_delta_state(self, tmp_path):
        """Abandon an engine while a delta run and tombstones are
        live; recover and continue: every subsequent prefix matches a
        single-host reference bit-for-bit."""
        d = str(tmp_path / "delta_reco")
        scores, labels = _stream(1200, seed=11)
        eng = MicroBatchEngine(ServingConfig(snapshot_dir=d, **self._KW))
        for i in range(0, 700, 7):
            eng.insert(scores[i:i + 7], labels[i:i + 7]).result(10)
        snap = eng.flush()
        # the property under test needs live mid-delta state at capture
        assert snap["index"]["delta_events"] > 0
        assert snap["index"]["tombstones"] > 0
        del eng     # crash: no close(), no final snapshot

        eng2 = MicroBatchEngine(ServingConfig(
            snapshot_dir=d, recover=True, **self._KW))
        assert eng2.index.state()["delta_events"] > 0
        ref = ExactAucIndex(engine="jax", compact_every=64, window=500)
        ref.insert_batch(scores[:700].astype(np.float32), labels[:700])
        assert eng2.index._wins2 == ref._wins2
        for i in range(700, 1200, 11):
            j = min(i + 11, 1200)
            eng2.insert(scores[i:j], labels[i:j]).result(10)
            eng2.flush()
            ref.insert_batch(scores[i:j].astype(np.float32),
                             labels[i:j])
            assert eng2.index._wins2 == ref._wins2, i
            assert eng2.index.auc() == ref.auc(), i
        eng2.close()

    def test_sigkill_mid_delta_recovers(self, tmp_path):
        """The real thing, sharded: SIGKILL a --mesh-shards serve
        process between compactions (delta run + tombstones in the
        snapshot), restart with --recover, finish the stream — final
        AUC bit-identical to an uninterrupted single-host run."""
        d = str(tmp_path / "delta_rk")
        scores, labels = _stream(600, seed=13)
        lines = [json.dumps({"op": "insert", "score": float(s),
                             "label": int(b)})
                 for s, b in zip(scores, labels)]
        args = [sys.executable, "-m", "tuplewise_tpu.harness.cli",
                "serve", "--policy", "block", "--mesh-shards", "2",
                "--delta-fraction", "4.0", "--max-delta-runs", "64",
                "--window", "400", "--snapshot-dir", d,
                "--snapshot-every", "100", "--compact-every", "64"]
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=8")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

        p1 = subprocess.Popen(args, stdin=subprocess.PIPE,
                              stdout=subprocess.PIPE, text=True,
                              env=env, cwd=repo)
        for ln in lines[:350]:
            p1.stdin.write(ln + "\n")
        p1.stdin.flush()
        for _ in range(350):
            assert json.loads(p1.stdout.readline())["ok"]
        os.kill(p1.pid, signal.SIGKILL)
        p1.wait(timeout=30)

        feed = lines[350:] + [json.dumps({"op": "query"})]
        p2 = subprocess.Popen(args + ["--recover"],
                              stdin=subprocess.PIPE,
                              stdout=subprocess.PIPE, text=True,
                              env=env, cwd=repo)
        out, _ = p2.communicate("\n".join(feed) + "\n", timeout=180)
        resp = [json.loads(ln) for ln in out.strip().splitlines()]
        assert all(r["ok"] for r in resp)
        final = resp[-1]
        ref = ExactAucIndex(engine="jax", compact_every=64, window=400)
        ref.insert_batch(scores.astype(np.float32), labels)
        assert final["auc_exact"] == ref.auc()


# --------------------------------------------------------------------- #
# chaos <-> flight-recorder correlation [ISSUE 6 satellite]              #
# --------------------------------------------------------------------- #
class TestChaosFlightCorrelation:
    def test_every_injected_fault_in_dump_with_trace_id(self, tmp_path):
        """Each chaos trigger must appear exactly once in the flight
        dump, carrying a trace id that resolves into the exported span
        trace — chaos is forensically attributable, not just counted."""
        from tuplewise_tpu.obs import FlightRecorder, Tracer

        scores, labels = _stream(2500, seed=21)
        spec = {"faults": [
            {"point": "compactor_build", "on_call": 1, "action": "error"},
            {"point": "batcher", "on_call": 9, "action": "error"},
            {"point": "sharded_count", "on_call": 30, "action": "error",
             "dropped": [1]},
            {"point": "poison", "at_events": [40, 1800], "value": "nan"},
        ]}
        tracer = Tracer(capacity=1 << 16)
        flight_out = str(tmp_path / "flight.jsonl")
        cfg = ServingConfig(policy="block", compact_every=128,
                            bg_compact=True, mesh_shards=2,
                            flush_timeout_s=0.001)
        rec = replay(scores, labels, config=cfg, max_inflight=128,
                     chaos=spec, tracer=tracer, flight_out=flight_out)
        dump = FlightRecorder.load_dump(flight_out)
        evs = dump["events"]
        injected = [e for e in evs if e["kind"] == "chaos_inject"]
        fired = rec["faults"]["chaos"]["fired"]
        # exactly one dump event per fired fault, matching points
        assert sorted(e["point"] for e in injected) \
            == sorted(p for p, n in fired.items() for _ in range(n))
        trace_ids = {s["trace_id"] for s in tracer.spans()}
        spans_by_trace = {}
        for s in tracer.spans():
            spans_by_trace.setdefault(s["trace_id"], []).append(s)
        for e in injected:
            assert e["trace_id"] is not None, e
        # faults that fire INSIDE traced work correlate to the span
        # that was active at the injection site
        by_point = {e["point"]: e for e in injected}
        cb = by_point["compactor_build"]
        assert cb["trace_id"] in trace_ids
        assert any(s["name"] == "compactor.build"
                   for s in spans_by_trace[cb["trace_id"]])
        sc = by_point["sharded_count"]
        assert sc["trace_id"] in trace_ids
        names = {s["name"] for s in spans_by_trace[sc["trace_id"]]}
        assert "index.sharded_count" in names
        # every heal round (the shard death's, plus any follow-up
        # round a racing background placement forces) is in the dump
        # EXACTLY as many times as the metric counted it
        heals = [e for e in evs if e["kind"] == "heal"]
        assert len(heals) == rec["report"]["reshard_events"] >= 1
        assert heals[0]["mesh_width"] == 1     # shrank to the survivor
        # ... and every compaction lifecycle event is there exactly once
        comps = [e for e in evs
                 if e["kind"] in ("compaction", "major_merge")]
        assert len(comps) == rec["report"]["compactions_total"]
        # poison corruptions were recorded by the injector and the
        # engine edge both
        assert len([e for e in evs if e["kind"] == "chaos_poison"]) >= 1
        assert len([e for e in evs if e["kind"] == "poison_reject"]) == 2
        # parity guardrail unchanged under full observability
        assert rec["auc_abs_err"] == 0.0
