"""Fixture tests for the static invariant checkers [ISSUE 12].

Every pass is demonstrated on a seeded violation (flagged) and its
clean twin (not flagged); waiver + ratchet semantics are pinned; and
the full-repo run must be clean modulo the committed waiver file —
the same invariant scripts/analysis_gate.py enforces in CI.
"""

import os
import types

import pytest

from tuplewise_tpu.analysis import (
    compile_ladder, config_drift, lock_order, modgraph,
    telemetry_xref, traced_purity,
)
from tuplewise_tpu.analysis.core import Finding, ModuleSet
from tuplewise_tpu.analysis.runner import run_checks
from tuplewise_tpu.analysis.waivers import (
    WaiverError, Waiver, apply_waivers, load_waivers,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ms_of(src: str, path: str = "tuplewise_tpu/fixture.py",
          texts=None) -> ModuleSet:
    return ModuleSet.from_sources({path: src}, texts=texts)


def rules(findings):
    return sorted({f.rule for f in findings})


# --------------------------------------------------------------------- #
# pass 1 — lock order / thread discipline                                #
# --------------------------------------------------------------------- #

_LOCK_CYCLE = """
import threading

class A:
    def __init__(self):
        self._l1 = threading.Lock()
        self._l2 = threading.Lock()

    def f(self):
        with self._l1:
            with self._l2:
                pass

    def g(self):
        with self._l2:
            with self._l1:
                pass
"""

_LOCK_CYCLE_CLEAN = _LOCK_CYCLE.replace(
    "with self._l2:\n            with self._l1:",
    "with self._l1:\n            with self._l2:")

_LOCK_BLOCKING = """
import queue
import threading

class B:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()

    def f(self):
        with self._lock:
            return self._q.get()
"""


def test_lock_order_cycle_flagged():
    fs = lock_order.run(ms_of(_LOCK_CYCLE))
    assert "lock-order-cycle" in rules(fs)


def test_lock_order_consistent_clean():
    fs = lock_order.run(ms_of(_LOCK_CYCLE_CLEAN))
    assert "lock-order-cycle" not in rules(fs)


def test_lock_held_blocking_flagged():
    fs = lock_order.run(ms_of(_LOCK_BLOCKING))
    assert any(f.rule == "lock-held-blocking"
               and "queue_get" in f.symbol for f in fs)


def test_lock_held_blocking_bounded_clean():
    clean = _LOCK_BLOCKING.replace("self._q.get()",
                                   "self._q.get(timeout=1.0)")
    assert lock_order.run(ms_of(clean)) == []


def test_lock_held_blocking_through_callee():
    # the blocking op is one resolved call away — still attributed
    src = _LOCK_BLOCKING.replace(
        "            return self._q.get()",
        "            return self.h()\n\n"
        "    def h(self):\n"
        "        return self._q.get()")
    fs = lock_order.run(ms_of(src))
    assert any(f.rule == "lock-held-blocking" and "via B.h" in f.message
               for f in fs)


def test_lock_dispatch_under_lock_flagged():
    src = """
import threading
from tuplewise_tpu.parallel.sharded_counts import sharded_counts

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def f(self, mesh, dev, cap, q, dtype):
        with self._lock:
            return sharded_counts(mesh, dev, cap, q, dtype)
"""
    fs = lock_order.run(ms_of(src))
    assert any(f.rule == "lock-held-blocking"
               and "device_dispatch" in f.symbol for f in fs)


# --------------------------------------------------------------------- #
# pass 2 — traced purity                                                 #
# --------------------------------------------------------------------- #

_TRACED_BAD = """
import time

import jax
import numpy as np


@jax.jit
def f(x):
    t = time.time()
    r = np.random.rand()
    return x + t + r
"""

_TRACED_CLEAN = """
import jax
import jax.numpy as jnp


@jax.jit
def f(x, key):
    return x + jax.random.normal(key, x.shape)
"""


def test_traced_purity_flagged():
    fs = traced_purity.run(ms_of(_TRACED_BAD))
    assert "traced-wall-clock" in rules(fs)
    assert "traced-host-rng" in rules(fs)


def test_traced_purity_clean():
    assert traced_purity.run(ms_of(_TRACED_CLEAN)) == []


def test_traced_purity_reaches_helpers():
    src = """
import time

import jax


def helper(x):
    return x + time.perf_counter()


@jax.jit
def f(x):
    return helper(x)
"""
    fs = traced_purity.run(ms_of(src))
    assert any(f.rule == "traced-wall-clock" and "helper" in f.symbol
               for f in fs)


def test_traced_purity_ignores_host_code():
    src = """
import time


def host_only(x):
    return x + time.time()
"""
    assert traced_purity.run(ms_of(src)) == []


def test_traced_float_and_item_flagged():
    src = """
import jax


@jax.jit
def f(x):
    return float(x) + x.item()
"""
    fs = traced_purity.run(ms_of(src))
    assert "traced-float-coercion" in rules(fs)
    assert "traced-device-sync" in rules(fs)


# --------------------------------------------------------------------- #
# pass 3 — telemetry cross-reference                                     #
# --------------------------------------------------------------------- #

def _telemetry_ms(consumer_metric: str, producer_metric: str = "hits_total"):
    producer = f"""
class Engine:
    def __init__(self, registry):
        self._c = registry.counter("{producer_metric}")
"""
    consumer = f"""
def _v(m, name, default=0):
    return m.get(name, {{}}).get("value", default)


def report(metrics):
    return {{"hits": _v(metrics, "{consumer_metric}")}}
"""
    return ModuleSet.from_sources({
        "tuplewise_tpu/fixture_engine.py": producer,
        "tuplewise_tpu/obs/fixture_report.py": consumer,
    })


def test_telemetry_typo_flagged():
    ms = _telemetry_ms("hist_total")    # typo of hits_total
    fs = telemetry_xref.run(
        ms, consumer_paths=("tuplewise_tpu/obs/fixture_report.py",))
    assert any(f.rule == "telemetry-consumed-unproduced"
               and f.symbol == "hist_total" for f in fs)


def test_telemetry_match_clean():
    ms = _telemetry_ms("hits_total")
    fs = telemetry_xref.run(
        ms, consumer_paths=("tuplewise_tpu/obs/fixture_report.py",))
    assert fs == []


def test_telemetry_flight_kind_xref():
    src = """
class E:
    def go(self, flight):
        flight.record("heal_done", n=1)


def _after(kind, seq):
    return None


def diagnose(by_kind):
    a = by_kind.get("heal_done")
    b = _after("heal_exhasted", 0)    # typo
    return a, b
"""
    fs = telemetry_xref.run(
        ms_of(src), consumer_paths=("tuplewise_tpu/fixture.py",))
    syms = {f.symbol for f in fs}
    assert "flight:heal_exhasted" in syms
    assert "flight:heal_done" not in syms


def test_telemetry_type_conflict_flagged():
    src = """
class E:
    def __init__(self, m):
        self._a = m.counter("depth_live")
        self._b = m.gauge("depth_live")
"""
    fs = telemetry_xref.run(ms_of(src), consumer_paths=())
    assert any(f.rule == "telemetry-type-conflict"
               and f.symbol == "depth_live" for f in fs)


def test_metric_direct_construction_flagged():
    src = """
from tuplewise_tpu.utils.profiling import Counter


def make():
    return Counter("rogue_total")
"""
    fs = telemetry_xref.run(ms_of(src), consumer_paths=())
    assert any(f.rule == "metric-direct-construction" for f in fs)


def test_doc_telemetry_unknown_flagged():
    ms = ModuleSet.from_sources(
        {"tuplewise_tpu/fixture_engine.py":
            'class E:\n    def __init__(self, m):\n'
            '        self._c = m.counter("hits_total")\n'},
        texts={"README.md": "exports `hits_total` and `mists_total`"})
    fs = telemetry_xref.run(ms, consumer_paths=())
    syms = {f.symbol for f in fs if f.rule == "doc-telemetry-unknown"}
    assert syms == {"mists_total"}


def test_fstring_producer_matches_glob():
    src = """
_KINDS = ("insert", "score")


class E:
    def __init__(self, m):
        self._c = {k: m.counter(f"requests_{k}_total") for k in _KINDS}


def _v(m, name, default=0):
    return m.get(name, {}).get("value", default)


def report(metrics):
    return _v(metrics, "requests_insert_total")
"""
    fs = telemetry_xref.run(
        ms_of(src), consumer_paths=("tuplewise_tpu/fixture.py",))
    assert fs == []


# --------------------------------------------------------------------- #
# pass 4 — compile ladder                                                #
# --------------------------------------------------------------------- #

_LADDER_BAD = """
import functools


@functools.lru_cache(maxsize=None)
def count_fn(cap, q_bucket):
    return lambda b, q: (b, q)


def next_bucket(n):
    b = 256
    while b < n:
        b *= 2
    return b


def serve(base, q):
    return count_fn(len(base), next_bucket(len(q)))(base, q)
"""


def test_ladder_raw_shape_flagged():
    fs = compile_ladder.run(ms_of(_LADDER_BAD))
    assert any(f.rule == "ladder-raw-shape" and ":0" in f.symbol
               for f in fs)
    # arg 1 went through next_bucket — must NOT be flagged
    assert not any(":1" in f.symbol for f in fs)


def test_ladder_bucketed_clean():
    clean = _LADDER_BAD.replace("count_fn(len(base), ",
                                "count_fn(next_bucket(len(base)), ")
    assert compile_ladder.run(ms_of(clean)) == []


def test_ladder_chases_one_assignment():
    src = _LADDER_BAD.replace(
        "    return count_fn(len(base), next_bucket(len(q)))(base, q)",
        "    bb = len(base)\n"
        "    return count_fn(bb, next_bucket(len(q)))(base, q)")
    fs = compile_ladder.run(ms_of(src))
    assert any(f.rule == "ladder-raw-shape" for f in fs)


# --------------------------------------------------------------------- #
# pass 5 — config / CLI / doc drift                                      #
# --------------------------------------------------------------------- #

_CONFIG_SRC = """
import dataclasses


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    budget: int = 64
    secret_knob: int = 3


def build(ap):
    ap.add_argument("--budget", type=int, default=64)
"""


def test_config_field_unbound_flagged():
    ms = ModuleSet.from_sources({"tuplewise_tpu/fixture.py": _CONFIG_SRC},
                                texts={"README.md": "uses `--budget`"})
    fs = config_drift.run(ms)
    assert any(f.rule == "config-field-unbound"
               and f.symbol == "ServingConfig.secret_knob" for f in fs)


def test_config_field_documented_clean():
    ms = ModuleSet.from_sources(
        {"tuplewise_tpu/fixture.py": _CONFIG_SRC},
        texts={"README.md": "uses `--budget` and `secret_knob`"})
    assert config_drift.run(ms) == []


def test_doc_flag_unknown_flagged():
    ms = ModuleSet.from_sources(
        {"tuplewise_tpu/fixture.py": _CONFIG_SRC},
        texts={"README.md":
               "run with `--budget 8` and `--budgte 9`"})  # typo
    fs = config_drift.run(ms)
    assert any(f.rule == "doc-flag-unknown"
               and f.symbol == "--budgte" for f in fs)


# --------------------------------------------------------------------- #
# module graph — import cycles                                           #
# --------------------------------------------------------------------- #

def test_import_cycle_flagged():
    ms = ModuleSet.from_sources({
        "tuplewise_tpu/aaa.py": "import tuplewise_tpu.bbb\n",
        "tuplewise_tpu/bbb.py": "import tuplewise_tpu.aaa\n",
    })
    fs = modgraph.run(ms)
    assert rules(fs) == ["import-cycle"]


def test_lazy_import_cycle_clean():
    ms = ModuleSet.from_sources({
        "tuplewise_tpu/aaa.py": "import tuplewise_tpu.bbb\n",
        "tuplewise_tpu/bbb.py":
            "def f():\n    import tuplewise_tpu.aaa\n",
    })
    assert modgraph.run(ms) == []


# --------------------------------------------------------------------- #
# waivers + ratchet                                                      #
# --------------------------------------------------------------------- #

def _finding(sym: str) -> Finding:
    return Finding("lock-held-blocking", "tuplewise_tpu/x.py", 1, sym,
                   "msg")


def test_waiver_matches_and_ratchets():
    w = load_waivers("""
[[waiver]]
rule = "lock-held-blocking"
file = "tuplewise_tpu/x.py"
symbol = "F::*"
count = 1
reason = "intentional hold documented in DESIGN for this fixture"
""")
    unwaived, waived, unused = apply_waivers(
        [_finding("F::l::sleep"), _finding("F::l::fsync")], w)
    # the ratchet: count=1 absorbs the first finding, the second is
    # NEW damage and stays unwaived
    assert len(waived) == 1 and len(unwaived) == 1
    assert unused == []


def test_waiver_unused_reported():
    w = load_waivers("""
[[waiver]]
rule = "lock-held-blocking"
file = "tuplewise_tpu/gone.py"
reason = "this code was deleted; the waiver should be pruned"
""")
    unwaived, waived, unused = apply_waivers([_finding("F::x")], w)
    assert len(unwaived) == 1 and waived == [] and len(unused) == 1


@pytest.mark.parametrize("body", [
    "[[waiver]]\nrule = \"r\"\nfile = \"f\"\nreason = \"short\"",
    "[[waiver]]\nfile = \"f\"\nreason = \"no rule given here at all\"",
    "[[waiver]]\nrule = \"r\"\nfile = \"f\"\ncount = 0\n"
    "reason = \"count zero is meaningless padding text\"",
    "[table]\nrule = \"r\"",
    "rule = \"r\"",
])
def test_waiver_file_validation(body):
    with pytest.raises(WaiverError):
        load_waivers(body)


def test_waiver_symbol_glob():
    w = Waiver(rule="r", file="f", reason="x" * 30, symbol="A.*::lock::*")
    assert w.matches(Finding("r", "f", 1, "A.m::lock::sleep", ""))
    assert not w.matches(Finding("r", "f", 1, "B.m::lock::sleep", ""))


# --------------------------------------------------------------------- #
# full-repo invariants (the CI gate's exact contract)                    #
# --------------------------------------------------------------------- #

@pytest.fixture(scope="module")
def repo_report():
    return run_checks(root=REPO)


def test_repo_clean_modulo_waivers(repo_report):
    assert repo_report["parse_errors"] == {}
    assert "waiver_error" not in repo_report
    assert repo_report["findings"] == [], (
        "unwaived findings — fix them or add a justified waiver:\n"
        + "\n".join(f"{f['rule']}: {f['file']} [{f['symbol']}]"
                    for f in repo_report["findings"]))
    assert repo_report["ok"] is True


def test_repo_no_import_cycles(repo_report):
    assert repo_report["import_cycles"] == []


def test_repo_no_stale_waivers(repo_report):
    assert repo_report["unused_waivers"] == [], (
        "waivers.toml entries matching nothing — prune them")


def test_repo_every_pass_ran(repo_report):
    per_pass = repo_report["summary"]["per_pass"]
    assert set(per_pass) == {"lock-order", "traced-purity",
                             "telemetry-xref", "compile-ladder",
                             "config-drift", "races", "exactness",
                             "hotpath", "lifecycle", "module-graph"}
    # the waived findings prove the passes bite on the real tree
    assert repo_report["summary"]["waived"] > 0


def test_runner_cli_writes_report(tmp_path):
    from tuplewise_tpu.analysis.runner import main

    out = tmp_path / "report.json"
    args = types.SimpleNamespace(root=REPO, waivers=None, json=False,
                                 out=str(out), strict=False)
    assert main(args) == 0
    import json

    rep = json.loads(out.read_text())
    assert rep["ok"] is True


# --------------------------------------------------------------------- #
# drive-by [ISSUE 12 satellite]: the registry's single                   #
# create-or-return path                                                  #
# --------------------------------------------------------------------- #

def test_registry_create_or_return_shared_across_call_sites():
    from tuplewise_tpu.utils.profiling import MetricsRegistry

    m = MetricsRegistry()
    # two independent call sites (engine + flusher pattern) must share
    # ONE object per (name, labels) — never twin series
    g1 = m.gauge("queue_depth_live")
    g2 = m.gauge("queue_depth_live")
    assert g1 is g2
    h1 = m.histogram("insert_latency_s", labels={"tenant": "a"})
    h2 = m.histogram("insert_latency_s", labels={"tenant": "a"})
    assert h1 is h2
    assert m.histogram("insert_latency_s") is not h1  # distinct series
    with pytest.raises(TypeError):
        m.counter("queue_depth_live")    # type conflict raises loudly


def test_registry_create_or_return_concurrent():
    import threading as th

    from tuplewise_tpu.utils.profiling import MetricsRegistry

    m = MetricsRegistry()
    got = []
    barrier = th.Barrier(8)

    def reg():
        barrier.wait()
        got.append(m.counter("races_total"))

    threads = [th.Thread(target=reg) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len({id(c) for c in got}) == 1
    got[0].inc()
    assert m.snapshot()["races_total"]["value"] == 1
