"""Pallas pair-sum kernel (interpret mode on CPU) and the O(n log n)
rank-AUC fast path: both must match the oracle exactly."""

import numpy as np
import pytest

from tuplewise_tpu import Estimator
from tuplewise_tpu.data import make_gaussians
from tuplewise_tpu.models.metrics import auc_score


@pytest.fixture(scope="module")
def scores():
    X, Y = make_gaussians(2048, 1024, dim=1, separation=1.0, seed=5)
    return X[:, 0], Y[:, 0]


class TestPallasKernel:
    def test_parity_with_xla(self, scores):
        import jax.numpy as jnp

        from tuplewise_tpu.ops import pair_tiles
        from tuplewise_tpu.ops.kernels import get_kernel
        from tuplewise_tpu.ops.pallas_pairs import pallas_pair_sum

        s1, s2 = scores
        a = jnp.asarray(s1, jnp.float32)
        b = jnp.asarray(s2, jnp.float32)
        for name in ("auc", "hinge", "logistic"):
            sp = float(pallas_pair_sum(
                a, b, kernel=get_kernel(name), tile_a=256, tile_b=512,
                interpret=True,
            ))
            sx = float(pair_tiles.pair_stats(
                get_kernel(name), a, b, tile_a=256, tile_b=512)[0])
            assert abs(sp - sx) / max(abs(sx), 1) < 1e-6, name

    def test_rejects_non_multiple_sizes(self, scores):
        import jax.numpy as jnp

        from tuplewise_tpu.ops.pallas_pairs import pallas_pair_sum

        s1, s2 = scores
        from tuplewise_tpu.ops.kernels import auc_kernel

        with pytest.raises(ValueError, match="multiples"):
            pallas_pair_sum(
                jnp.asarray(s1[:1000], jnp.float32),
                jnp.asarray(s2, jnp.float32),
                kernel=auc_kernel, tile_a=256, tile_b=512, interpret=True,
            )

    def test_backend_impl_option(self, scores):
        s1, s2 = scores
        ref = Estimator("hinge", backend="numpy").complete(s1, s2)
        got = Estimator("hinge", backend="jax", impl="pallas",
                        tile_a=256, tile_b=512).complete(s1, s2)
        assert abs(got - ref) / abs(ref) < 1e-5
        with pytest.raises(ValueError, match="impl"):
            Estimator("hinge", backend="jax", impl="cuda")

    def test_any_size_decomposition_parity(self, scores):
        """pallas_pair_sum_any (unmasked interior + masked edge strips)
        must match the XLA tile reduction at ARBITRARY sizes — the
        n=10^7 headline path where n % 128 != 0 [VERDICT r3 next #1].
        Shapes cover: both ragged, divisible (pure interior), thinner
        than one tile each way (no interior), and single-row."""
        import jax.numpy as jnp

        from tuplewise_tpu.ops import pair_tiles
        from tuplewise_tpu.ops.kernels import get_kernel
        from tuplewise_tpu.ops.pallas_pairs import pallas_pair_sum_any

        s1, s2 = scores
        a_all = jnp.asarray(s1, jnp.float32)
        b_all = jnp.asarray(s2, jnp.float32)
        shapes = [(2048, 1024), (2047, 1023), (2048, 1000), (130, 1024),
                  (100, 70), (1, 513)]
        for name in ("auc", "hinge", "logistic"):
            k = get_kernel(name)
            for n1, n2 in shapes:
                a, b = a_all[:n1], b_all[:n2]
                sp = float(pallas_pair_sum_any(
                    a, b, kernel=k, tile_a=256, tile_b=512, interpret=True,
                ))
                sx = float(pair_tiles.pair_stats(
                    k, a, b, tile_a=256, tile_b=512)[0])
                assert abs(sp - sx) / max(abs(sx), 1) < 1e-6, (name, n1, n2)

    def test_any_size_vmaps(self, scores):
        """The harness local path vmaps the hot loop over worker blocks;
        the decomposed kernel must batch correctly."""
        import jax
        import jax.numpy as jnp

        from tuplewise_tpu.ops import pair_tiles
        from tuplewise_tpu.ops.kernels import auc_kernel
        from tuplewise_tpu.ops.pallas_pairs import pallas_pair_sum_any

        s1, s2 = scores
        b1 = jnp.asarray(s1[:1200], jnp.float32).reshape(4, 300)
        b2 = jnp.asarray(s2[:1000], jnp.float32).reshape(4, 250)
        got = jax.vmap(lambda a, b: pallas_pair_sum_any(
            a, b, kernel=auc_kernel, tile_a=128, tile_b=128,
            interpret=True,
        ))(b1, b2)
        want = jnp.stack([
            pair_tiles.pair_stats(
                auc_kernel, b1[i], b2[i], tile_a=128, tile_b=128)[0]
            for i in range(4)
        ])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6)

    def test_masked_parity_with_xla(self, scores):
        """The mask-aware kernel (the ring hot loop) must match the XLA
        tile reduction on ragged, partially-masked inputs — including
        its internal zero-mask padding to tile multiples."""
        import jax.numpy as jnp

        from tuplewise_tpu.ops import pair_tiles
        from tuplewise_tpu.ops.kernels import get_kernel
        from tuplewise_tpu.ops.pallas_pairs import pallas_masked_pair_sum

        s1, s2 = scores
        rng = np.random.default_rng(3)
        a = jnp.asarray(s1[:1237], jnp.float32)   # not tile multiples
        b = jnp.asarray(s2[:1011], jnp.float32)
        ma = jnp.asarray(rng.integers(0, 2, 1237), jnp.float32)
        mb = jnp.asarray(rng.integers(0, 2, 1011), jnp.float32)
        for name in ("auc", "hinge", "logistic"):
            k = get_kernel(name)
            sp = float(pallas_masked_pair_sum(
                a, b, ma, mb, kernel=k, tile_a=256, tile_b=512,
                interpret=True,
            ))
            sx, cx = pair_tiles.pair_stats(
                k, a, b, mask_a=ma, mask_b=mb, tile_a=256, tile_b=512
            )
            assert abs(sp - float(sx)) / max(abs(float(sx)), 1) < 1e-6, name
            # the caller-side count identity used by the pallas ring path
            assert float(jnp.sum(ma) * jnp.sum(mb)) == float(cx)


class TestPallasTripletFactorization:
    """Degree-3 via distance factorization [VERDICT r3 next #3]: MXU
    distance matmuls + the vmapped masked pair kernel must match the
    XLA triple tile scan exactly, including masks, global ids, and the
    ring's visiting-positives form."""

    @pytest.mark.parametrize("kname",
                             ["triplet_indicator", "triplet_hinge"])
    def test_parity_with_xla_tiles(self, kname):
        import jax.numpy as jnp

        from tuplewise_tpu.ops.kernels import get_kernel
        from tuplewise_tpu.ops.pair_tiles import triplet_stats
        from tuplewise_tpu.ops.pallas_triplets import pallas_triplet_stats

        k = get_kernel(kname)
        rng = np.random.default_rng(0)
        X = jnp.asarray(rng.normal(size=(45, 5)).astype(np.float32))
        Y = jnp.asarray(rng.normal(size=(37, 5)).astype(np.float32)) + 0.3
        mx = jnp.asarray((rng.random(45) > 0.2).astype(np.float32))
        my = jnp.asarray((rng.random(37) > 0.3).astype(np.float32))
        ids = jnp.arange(45, dtype=jnp.int32)
        sp, cp = pallas_triplet_stats(
            k, X, Y, mask_x=mx, mask_y=my, ids_x=ids,
            anchor_chunk=16, tile_p=8, tile_k=128, interpret=True,
        )
        sx, cx = triplet_stats(k, X, Y, mask_x=mx, mask_y=my,
                               ids_x=ids, tile=16)
        assert float(sp) == pytest.approx(float(sx), rel=1e-6)
        assert float(cp) == pytest.approx(float(cx), rel=1e-6)
        # visiting-positives (the double ring's generalized block)
        Pv = jnp.asarray(rng.normal(size=(29, 5)).astype(np.float32))
        ip = 100 + jnp.arange(29, dtype=jnp.int32)
        sp, cp = pallas_triplet_stats(
            k, X, Y, mask_y=my, ids_x=ids, positives=Pv, ids_p=ip,
            anchor_chunk=16, tile_p=8, tile_k=128, interpret=True,
        )
        sx, cx = triplet_stats(k, X, Y, mask_y=my, ids_x=ids,
                               positives=Pv, ids_p=ip, tile=16)
        assert float(sp) == pytest.approx(float(sx), rel=1e-6)
        assert float(cp) == float(cx)

    def test_custom_kernel_has_no_factorization(self):
        from tuplewise_tpu.ops.kernels import Kernel
        from tuplewise_tpu.ops.pallas_triplets import (
            pallas_triplet_stats, triplet_combine_kernel,
        )

        custom = Kernel(
            name="triplet_custom", degree=3, two_sample=True,
            kind="triplet",
            triplet_fn=lambda a, p, n, xp: xp.zeros(a.shape[:-1]),
        )
        assert triplet_combine_kernel(custom) is None
        import jax.numpy as jnp

        with pytest.raises(ValueError, match="factorization"):
            pallas_triplet_stats(
                custom, jnp.zeros((4, 2)), jnp.zeros((4, 2)),
                interpret=True,
            )

    def test_jax_backend_impl_pallas_triplet(self):
        from tuplewise_tpu.data import make_gaussians

        X, Y = make_gaussians(40, 32, 3, 1.0, seed=5)
        ref = Estimator("triplet_hinge", backend="numpy").complete(X, Y)
        got = Estimator("triplet_hinge", backend="jax",
                        impl="pallas").complete(X, Y)
        assert got == pytest.approx(ref, rel=1e-5)


class TestRankAucFastPath:
    def test_matches_rank_oracle(self, scores):
        s1, s2 = scores
        from tuplewise_tpu.ops.rank_auc import rank_auc

        assert abs(float(rank_auc(s1, s2)) - auc_score(s1, s2)) < 1e-6

    def test_handles_ties(self):
        rng = np.random.default_rng(0)
        s1 = rng.integers(0, 5, 300).astype(float)  # heavy ties
        s2 = rng.integers(0, 5, 200).astype(float)
        from tuplewise_tpu.ops.rank_auc import rank_auc

        assert abs(float(rank_auc(s1, s2)) - auc_score(s1, s2)) < 1e-6

    def test_imbalanced_large_no_cancellation(self):
        """Regression: the classical rank-sum formula loses 3-4 decimals
        in f32 at large/imbalanced sizes; the per-positive-fraction
        formulation must stay at ~1e-6."""
        rng = np.random.default_rng(1)
        s1 = rng.standard_normal(200_000) + 0.5
        s2 = rng.standard_normal(1_000)
        from tuplewise_tpu.ops.rank_auc import rank_auc

        assert abs(float(rank_auc(s1, s2)) - auc_score(s1, s2)) < 2e-6

    def test_backend_complete_uses_it(self, scores):
        """jax backend complete('auc') goes through the rank path by
        default and still equals the oracle."""
        s1, s2 = scores
        ref = auc_score(s1, s2)
        assert abs(Estimator("auc", backend="jax").complete(s1, s2) - ref) < 1e-6
        # opting out still works (tiled path)
        assert abs(
            Estimator("auc", backend="jax", auc_fast=False,
                      tile_a=256, tile_b=256).complete(s1, s2) - ref
        ) < 1e-6


class TestTripletPreferredDispatch:
    """preferred_anchor_chunk / preferred_triplet_tile_k [VERDICT r4
    next #4]: the HBM-aware chunk and K-dependent lane tile, pinned so
    a future change cannot silently regress the large-n path into the
    16 GB wall the r4 layout hit."""

    def test_anchor_chunk_regimes(self):
        from tuplewise_tpu.ops.pallas_triplets import (
            preferred_anchor_chunk,
        )

        # small grids take the deep chunk; 256 wherever the big-grid
        # distance matrices must fit
        assert preferred_anchor_chunk(4096, 4096) == 1024
        assert preferred_anchor_chunk(16384, 16384) == 256
        assert preferred_anchor_chunk(65536, 65536) == 256
        # ~2 GB budget: C * (P + K) * 4 bytes bounded
        c = preferred_anchor_chunk(10**7, 10**7)
        assert c * (2 * 10**7) * 4 <= 2 * (1 << 30)
        assert c >= 8

    def test_tile_k_regimes(self):
        from tuplewise_tpu.ops.pallas_triplets import (
            preferred_triplet_tile_k,
        )

        assert preferred_triplet_tile_k(4096) == 4096
        assert preferred_triplet_tile_k(16384) == 8192
        assert preferred_triplet_tile_k(65536) == 8192

    def test_segmented_path_matches_unsegmented(self, monkeypatch):
        """The P/K segmentation (the large-n path the v5e worker limit
        forces) is an EXACT partition: shrinking _SEG so a small input
        crosses it must reproduce the unsegmented statistic bit-for-bit
        — including ragged segment tails and the id exclusion."""
        import jax.numpy as jnp

        from tuplewise_tpu.ops import pallas_triplets as pt
        from tuplewise_tpu.ops.kernels import get_kernel

        k = get_kernel("triplet_indicator")
        rng = np.random.default_rng(9)
        X = jnp.asarray(rng.standard_normal((50, 4)), jnp.float32)
        Y = jnp.asarray(rng.standard_normal((43, 4)) + 0.3, jnp.float32)
        s0, c0 = pt.pallas_triplet_stats(k, X, Y, interpret=True)
        monkeypatch.setattr(pt, "_SEG", 24)   # 50 -> 24+24+2, 43 -> 24+19
        s1, c1 = pt.pallas_triplet_stats(k, X, Y, interpret=True)
        assert float(c0) == float(c1) == 50 * 49 * 43
        assert float(s0) == float(s1)

    def test_auto_dispatch_matches_explicit(self):
        """anchor_chunk=0 / tile_k=0 resolve to the preferred values
        and produce the exact same statistic (interpret mode)."""
        import jax.numpy as jnp

        from tuplewise_tpu.ops.kernels import get_kernel
        from tuplewise_tpu.ops.pallas_triplets import (
            pallas_triplet_stats,
        )

        k = get_kernel("triplet_indicator")
        rng = np.random.default_rng(3)
        X = jnp.asarray(rng.standard_normal((60, 4)), jnp.float32)
        Y = jnp.asarray(rng.standard_normal((52, 4)) + 0.3, jnp.float32)
        s0, c0 = pallas_triplet_stats(k, X, Y, interpret=True)
        s1, c1 = pallas_triplet_stats(
            k, X, Y, anchor_chunk=1024, tile_k=4096, interpret=True
        )
        assert float(s0) == float(s1) and float(c0) == float(c1)
