"""Fixture + repo tests for the exception-flow / resource-lifecycle
pass [ISSUE 15]: seeded-bad vs clean-twin pairs for every rule family
(future-leak, future-double-resolve, future-close-leak,
thread-undisciplined, handle-leak, error taxonomy), the two
historical-bug regression fixtures (the pre-PR-8 fleet close
future-leak and the pre-PR-11 reaper-vs-apply double-resolution), and
the live-repo clean-modulo-waivers contract.
"""

import os

import pytest

from tuplewise_tpu.analysis import lifecycle
from tuplewise_tpu.analysis.core import ModuleSet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ms_of(src: str, path: str = "tuplewise_tpu/serving/fixture.py",
          texts=None, **extra) -> ModuleSet:
    return ModuleSet.from_sources({path: src, **extra}, texts=texts)


def rules(findings):
    return sorted({f.rule for f in findings})


def by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


# --------------------------------------------------------------------- #
# future-leak                                                            #
# --------------------------------------------------------------------- #

LEAK_BAD = '''
from concurrent.futures import Future


class _Req:
    def __init__(self):
        self.future = Future()


class Engine:
    def _dispatch(self, batch):
        for r in batch:
            self._apply(r)

    def _apply(self, r):
        out = compute(r)
        r.future.set_result(out)


def compute(r):
    return r
'''

LEAK_CLEAN = '''
from concurrent.futures import Future


class _Req:
    def __init__(self):
        self.future = Future()


class Engine:
    def _dispatch(self, batch):
        try:
            for r in batch:
                self._apply(r)
        except Exception as e:
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)

    def _apply(self, r):
        out = compute(r)
        if not r.future.done():
            r.future.set_result(out)


def compute(r):
    return r
'''


def test_future_leak_flagged():
    fs = lifecycle.run(ms_of(LEAK_BAD))
    leaks = by_rule(fs, "future-leak")
    assert len(leaks) == 1
    assert leaks[0].symbol == "Engine._apply::set_result"
    assert "pre-PR-8" in leaks[0].message


def test_future_leak_caller_umbrella_clean():
    fs = lifecycle.run(ms_of(LEAK_CLEAN))
    assert by_rule(fs, "future-leak") == []
    assert by_rule(fs, "future-double-resolve") == []


def test_future_leak_local_try_clean():
    src = LEAK_BAD.replace(
        """        out = compute(r)
        r.future.set_result(out)""",
        """        try:
            out = compute(r)
            if not r.future.done():
                r.future.set_result(out)
        except Exception as e:
            if not r.future.done():
                r.future.set_exception(e)""")
    fs = lifecycle.run(ms_of(src))
    assert by_rule(fs, "future-leak") == []


# --------------------------------------------------------------------- #
# future-double-resolve — the pre-PR-11 reaper-vs-apply regression       #
# --------------------------------------------------------------------- #

PRE_PR11_BAD = '''
from concurrent.futures import Future


class _Req:
    def __init__(self):
        self.future = Future()


class Engine:
    def _dispatch(self, run):
        try:
            vals = compute(run)
            for r in run:
                r.future.set_result(vals)
        except Exception as e:
            for r in run:
                r.future.set_exception(e)

    def _reap_expired(self, queued):
        for r in queued:
            r.future.set_exception(TimeoutError("expired in queue"))


def compute(run):
    return run
'''

PRE_PR11_FIXED = '''
from concurrent.futures import Future


class _Req:
    def __init__(self):
        self.future = Future()


class Engine:
    def _dispatch(self, run):
        try:
            vals = compute(run)
            for r in run:
                if not r.future.done():
                    r.future.set_result(vals)
        except Exception as e:
            for r in run:
                if not r.future.done():
                    r.future.set_exception(e)

    def _reap_expired(self, queued):
        for r in queued:
            if r.future.done():
                continue
            try:
                r.future.set_exception(TimeoutError("expired"))
            except Exception:
                continue


def compute(run):
    return run
'''


def test_redetects_reaper_vs_apply_double_resolution():
    """The pre-PR-11 hole: the deadline reaper and the apply path both
    resolve the same futures from different threads, neither arbitrated
    — the loser raised InvalidStateError on its thread."""
    fs = lifecycle.run(ms_of(PRE_PR11_BAD))
    dbl = by_rule(fs, "future-double-resolve")
    syms = {f.symbol for f in dbl}
    assert "Engine._reap_expired::set_exception" in syms
    assert "Engine._dispatch::set_result" in syms
    assert any("pre-PR-11" in f.message for f in dbl)


def test_reaper_vs_apply_fixed_clean():
    fs = lifecycle.run(ms_of(PRE_PR11_FIXED))
    assert by_rule(fs, "future-double-resolve") == []
    assert by_rule(fs, "future-leak") == []


def test_single_resolver_class_not_flagged():
    """One resolving method = no cross-thread race surface: the guard
    requirement only binds multi-resolver classes."""
    src = '''
from concurrent.futures import Future


class Engine:
    def _apply(self, run):
        try:
            for r in run:
                r.future.set_result(1)
        except Exception as e:
            raise
'''
    fs = lifecycle.run(ms_of(src))
    assert by_rule(fs, "future-double-resolve") == []


# --------------------------------------------------------------------- #
# future-close-leak — the pre-PR-8 fleet close regression                #
# --------------------------------------------------------------------- #

PRE_PR8_BAD = '''
import queue
import threading
from concurrent.futures import Future


class _Req:
    def __init__(self):
        self.future = Future()


class Engine:
    def __init__(self):
        self._q = queue.Queue(maxsize=8)
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._closed = False

    def submit(self):
        r = _Req()
        self._q.put(r)
        return r.future

    def _run(self):
        while not self._closed:
            r = self._q.get()
            try:
                if not r.future.done():
                    r.future.set_result(1)
            except Exception as e:
                if not r.future.done():
                    r.future.set_exception(e)

    def close(self):
        self._closed = True
        self._worker.join(timeout=1.0)
'''

PRE_PR8_FIXED = PRE_PR8_BAD.replace(
    '''    def close(self):
        self._closed = True
        self._worker.join(timeout=1.0)''',
    '''    def close(self):
        self._closed = True
        self._worker.join(timeout=1.0)
        self._fail_queued()

    def _fail_queued(self):
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                return
            if not r.future.done():
                r.future.set_exception(RuntimeError("engine closed"))''')


def test_redetects_fleet_close_future_leak():
    """The pre-PR-8 hole: close() joined the worker but never drained
    the queue — every queued future (and every 'block'-policy producer
    waiting on capacity) hung forever."""
    fs = lifecycle.run(ms_of(PRE_PR8_BAD))
    leaks = by_rule(fs, "future-close-leak")
    assert len(leaks) == 1
    assert leaks[0].symbol == "Engine.close"
    assert "pre-PR-8" in leaks[0].message


def test_fleet_close_drain_clean():
    fs = lifecycle.run(ms_of(PRE_PR8_FIXED))
    assert by_rule(fs, "future-close-leak") == []


def test_close_missing_entirely_flagged():
    src = PRE_PR8_BAD.replace('''    def close(self):
        self._closed = True
        self._worker.join(timeout=1.0)''', "")
    fs = lifecycle.run(ms_of(src))
    leaks = by_rule(fs, "future-close-leak")
    assert len(leaks) == 1
    assert "no close()/shutdown() at all" in leaks[0].message


# --------------------------------------------------------------------- #
# thread-undisciplined                                                   #
# --------------------------------------------------------------------- #

def test_thread_not_daemon_not_joined_flagged():
    src = '''
import threading


class Owner:
    def start(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass
'''
    fs = lifecycle.run(ms_of(src))
    (f,) = by_rule(fs, "thread-undisciplined")
    assert "Thread" in f.symbol


def test_thread_daemon_clean():
    src = '''
import threading


class Owner:
    def start(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        pass
'''
    assert by_rule(lifecycle.run(ms_of(src)),
                   "thread-undisciplined") == []


def test_thread_joined_in_close_clean():
    src = '''
import threading


class Owner:
    def start(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass

    def close(self):
        self._t.join(timeout=5.0)
'''
    assert by_rule(lifecycle.run(ms_of(src)),
                   "thread-undisciplined") == []


def test_timer_cancelled_clean_uncancelled_flagged():
    src = '''
import threading


class Owner:
    def arm(self):
        self._timer = threading.Timer(1.0, self._fire)
        self._timer.start()

    def _fire(self):
        pass
'''
    (f,) = by_rule(lifecycle.run(ms_of(src)), "thread-undisciplined")
    assert "Timer" in f.symbol
    cancelled = src + '''
    def close(self):
        self._timer.cancel()
'''
    assert by_rule(lifecycle.run(ms_of(cancelled)),
                   "thread-undisciplined") == []


# --------------------------------------------------------------------- #
# handle-leak                                                            #
# --------------------------------------------------------------------- #

def test_local_open_without_finally_flagged():
    src = '''
def write_wal(path, rec):
    f = open(path, "a")
    f.write(rec)
    f.close()
'''
    (f,) = by_rule(lifecycle.run(ms_of(src)), "handle-leak")
    assert f.symbol == "write_wal::open"


def test_local_open_with_finally_clean():
    src = '''
def write_wal(path, rec):
    f = open(path, "a")
    try:
        f.write(rec)
    finally:
        f.close()
'''
    assert by_rule(lifecycle.run(ms_of(src)), "handle-leak") == []


def test_with_open_clean():
    src = '''
def write_wal(path, rec):
    with open(path, "a") as f:
        f.write(rec)
'''
    assert by_rule(lifecycle.run(ms_of(src)), "handle-leak") == []


def test_attr_open_with_owner_close_clean():
    src = '''
class Log:
    def __init__(self, path):
        self._f = open(path, "a")

    def close(self):
        self._f.close()
'''
    assert by_rule(lifecycle.run(ms_of(src)), "handle-leak") == []


def test_attr_open_without_owner_close_flagged():
    src = '''
class Log:
    def __init__(self, path):
        self._f = open(path, "a")
'''
    (f,) = by_rule(lifecycle.run(ms_of(src)), "handle-leak")
    assert f.symbol == "Log.__init__::open"


def test_ownership_transfer_via_return_clean():
    src = '''
def open_wal(path):
    f = open(path, "a")
    return f
'''
    assert by_rule(lifecycle.run(ms_of(src)), "handle-leak") == []


# --------------------------------------------------------------------- #
# error taxonomy                                                         #
# --------------------------------------------------------------------- #

ERR_MOD = '''
class DemoError(RuntimeError):
    """typed serving error."""


def admit(x):
    if x is None:
        raise DemoError("no payload")
    return x
'''

HANDLER_MOD = '''
def serve_loop(req):
    from tuplewise_tpu.serving.fixture import DemoError, admit

    try:
        return {"ok": True, "value": admit(req)}
    except DemoError as e:
        return {"ok": False, "error": f"demo: {e}"}
'''


def test_error_taxonomy_all_three_gaps_flagged():
    fs = lifecycle.run(ms_of(ERR_MOD))
    assert "error-unhandled-protocol" in rules(fs)
    assert "error-not-doctor-visible" in rules(fs)
    assert "error-undocumented" in rules(fs)
    assert all(f.symbol == "DemoError" for f in fs
               if f.rule.startswith("error-"))


def test_error_taxonomy_fully_wired_clean():
    fs = lifecycle.run(ms_of(
        ERR_MOD,
        texts={"README.md": "raises `DemoError` when ..."},
        **{"tuplewise_tpu/harness/fixture_cli.py": HANDLER_MOD,
           "tuplewise_tpu/obs/report.py":
               "# consumes DemoError counts\n"}))
    assert [f for f in fs if f.rule.startswith("error-")] == []


def test_error_unraised_class_not_in_scope():
    src = '''
class NeverRaisedError(RuntimeError):
    pass
'''
    fs = lifecycle.run(ms_of(src))
    assert [f for f in fs if f.rule.startswith("error-")] == []


# --------------------------------------------------------------------- #
# the live repo                                                          #
# --------------------------------------------------------------------- #

@pytest.fixture(scope="module")
def repo_findings():
    return lifecycle.run(ModuleSet.from_repo(REPO))


def test_repo_clean_modulo_documented_waivers(repo_findings):
    """The live repo's only lifecycle findings are the two
    ControllerSpecError entries carried (with written justifications)
    in waivers.toml: a config-time error has no wire/doctor surface
    by construction. Everything else was FIXED in this PR: the fleet
    query-wave future leak + unguarded resolution (tenancy._dispatch
    umbrella), the drop_oldest-vs-reaper double resolution
    (engine.submit done() guard), and the stat_check handle leak."""
    leftovers = [f for f in repo_findings
                 if f.symbol != "ControllerSpecError"]
    assert leftovers == [], [
        (f.rule, f.file, f.symbol) for f in leftovers]
    waived = {(f.rule, f.symbol) for f in repo_findings}
    assert waived == {
        ("error-unhandled-protocol", "ControllerSpecError"),
        ("error-not-doctor-visible", "ControllerSpecError"),
    }


def test_repo_serving_error_taxonomy_is_protocol_handled(
        repo_findings):
    """Every request-path typed error stays wire-handled: the rules
    that would fire on a regression are active (fixture tests above)
    and silent on the live tree."""
    assert by_rule(repo_findings, "error-undocumented") == []
    assert [f for f in by_rule(repo_findings,
                               "error-unhandled-protocol")
            if f.symbol != "ControllerSpecError"] == []


def test_repo_futures_and_threads_disciplined(repo_findings):
    assert by_rule(repo_findings, "future-leak") == []
    assert by_rule(repo_findings, "future-double-resolve") == []
    assert by_rule(repo_findings, "future-close-leak") == []
    assert by_rule(repo_findings, "thread-undisciplined") == []
    assert by_rule(repo_findings, "handle-leak") == []
