"""Incomplete-U sampling designs [SURVEY §1.1; PAPERS.md:6].

swr (with replacement) / swor (distinct tuples) / bernoulli (independent
inclusion). All three are unbiased for E[h]; swor carries the
finite-population variance reduction, which is the testable signature.
"""

import numpy as np
import pytest

from tuplewise_tpu import Estimator
from tuplewise_tpu.data import make_gaussians
from tuplewise_tpu.parallel.partition import (
    draw_pair_design,
    draw_triplet_design,
)


class TestDrawPairDesign:
    def test_swor_distinct(self):
        rng = np.random.default_rng(0)
        i, j = draw_pair_design(rng, 50, 40, 1500, "swor")
        assert len(set(zip(i.tolist(), j.tolist()))) == 1500
        assert i.min() >= 0 and i.max() < 50
        assert j.min() >= 0 and j.max() < 40

    def test_swor_huge_grid_dedup_path(self):
        rng = np.random.default_rng(1)
        i, j = draw_pair_design(rng, 10**6, 10**6, 5000, "swor")
        assert len(set(zip(i.tolist(), j.tolist()))) == 5000

    def test_swor_cannot_exceed_grid(self):
        rng = np.random.default_rng(2)
        with pytest.raises(ValueError, match="distinct"):
            draw_pair_design(rng, 4, 4, 17, "swor")

    def test_bernoulli_realized_size_binomial(self):
        rng = np.random.default_rng(3)
        sizes = [
            len(draw_pair_design(rng, 100, 100, 2000, "bernoulli")[0])
            for _ in range(50)
        ]
        # Binomial(10^4, 0.2): mean 2000, sd ~40
        assert 1800 < np.mean(sizes) < 2200
        assert np.std(sizes) > 1.0  # actually random, not fixed

    def test_one_sample_off_diagonal(self):
        rng = np.random.default_rng(4)
        i, j = draw_pair_design(rng, 30, 29, 600, "swor", one_sample=True)
        assert np.all(i != j)
        assert len(set(zip(i.tolist(), j.tolist()))) == 600

    def test_unknown_design(self):
        with pytest.raises(ValueError, match="unknown sampling design"):
            draw_pair_design(np.random.default_rng(0), 5, 5, 3, "systematic")


class TestDrawTripletDesign:
    def test_swor_distinct_and_off_diagonal(self):
        rng = np.random.default_rng(0)
        i, j, k = draw_triplet_design(rng, 12, 9, 800, "swor")
        assert len(set(zip(i.tolist(), j.tolist(), k.tolist()))) == 800
        assert np.all(i != j)
        assert i.max() < 12 and j.max() < 12 and k.max() < 9

    def test_swor_covers_full_grid(self):
        """Drawing the WHOLE grid enumerates every valid triple exactly
        once — the linearization is a bijection."""
        rng = np.random.default_rng(1)
        n1, n2 = 5, 3
        grid = n1 * (n1 - 1) * n2
        i, j, k = draw_triplet_design(rng, n1, n2, grid, "swor")
        assert len(set(zip(i.tolist(), j.tolist(), k.tolist()))) == grid
        assert np.all(i != j)

    def test_swr_matches_legacy_call_sequence(self):
        """swr reproduces the rng call order the NumPy backend always
        used (i, shifted j, k) — committed config-4 results depend on
        seed stability."""
        rng1 = np.random.default_rng(5)
        i1 = rng1.integers(0, 20, size=100)
        j1 = rng1.integers(0, 19, size=100)
        j1 = np.where(j1 >= i1, j1 + 1, j1)
        k1 = rng1.integers(0, 7, size=100)
        i2, j2, k2 = draw_triplet_design(
            np.random.default_rng(5), 20, 7, 100, "swr"
        )
        assert np.array_equal(i1, i2)
        assert np.array_equal(j1, j2)
        assert np.array_equal(k1, k2)

    def test_bernoulli_realized_size_binomial(self):
        rng = np.random.default_rng(3)
        sizes = [
            len(draw_triplet_design(rng, 10, 10, 300, "bernoulli")[0])
            for _ in range(50)
        ]
        # Binomial(900, 1/3): mean 300, sd ~14
        assert 250 < np.mean(sizes) < 350
        assert np.std(sizes) > 1.0

    def test_tiny_n1_raises(self):
        with pytest.raises(ValueError, match="n1"):
            draw_triplet_design(np.random.default_rng(0), 1, 5, 3, "swor")


@pytest.fixture(scope="module")
def scores():
    X, Y = make_gaussians(400, 400, dim=1, separation=1.0, seed=6)
    return X[:, 0], Y[:, 0]


class TestEstimatorDesigns:
    @pytest.mark.parametrize("backend", ["numpy", "jax"])
    @pytest.mark.parametrize("design", ["swor", "bernoulli"])
    def test_unbiased(self, scores, backend, design):
        s1, s2 = scores
        u_n = Estimator("auc", backend="numpy").complete(s1, s2)
        est = Estimator("auc", backend=backend)
        vals = [
            est.incomplete(s1, s2, n_pairs=4000, seed=m, design=design)
            for m in range(40)
        ]
        se = np.std(vals) / np.sqrt(len(vals)) + 1e-6
        assert abs(np.mean(vals) - u_n) < 5 * se

    def test_one_sample_swor(self):
        rng = np.random.default_rng(7)
        A = rng.standard_normal((120, 3))
        u_n = Estimator("scatter", backend="numpy").complete(A)
        est = Estimator("scatter", backend="numpy")
        vals = [
            est.incomplete(A, n_pairs=3000, seed=m, design="swor")
            for m in range(40)
        ]
        se = np.std(vals) / np.sqrt(len(vals)) + 1e-6
        assert abs(np.mean(vals) - u_n) < 5 * se

    def test_swor_variance_reduction(self):
        """B close to the grid size: SWOR variance must approach the
        complete-U variance, far below SWR's extra Var(h)/B term."""
        X, Y = make_gaussians(32, 32, dim=1, separation=1.0, seed=8)
        s1, s2 = X[:, 0], Y[:, 0]
        est = Estimator("auc", backend="numpy")
        B = 32 * 32 - 64  # 93.75% of the grid
        swor = [est.incomplete(s1, s2, n_pairs=B, seed=m, design="swor")
                for m in range(300)]
        swr = [est.incomplete(s1, s2, n_pairs=B, seed=m, design="swr")
               for m in range(300)]
        assert np.var(swor) < 0.6 * np.var(swr)

    @pytest.mark.parametrize("design", ["swor", "bernoulli"])
    def test_mesh_design_distribution_matches_oracle(self, scores, design):
        """jax/mesh draw their designs ON DEVICE (ops.device_design)
        while numpy keeps the host oracle [VERDICT r4 next #6]: same
        DISTRIBUTION, not the same tuple set — Monte-Carlo means over
        seeds must agree within joint SE, and each is unbiased for the
        complete U."""
        import jax

        if jax.device_count() < 8:
            pytest.skip("needs 8 virtual devices")
        s1, s2 = scores
        u_n = Estimator("auc", backend="numpy").complete(s1, s2)
        est = Estimator("auc", backend="mesh", n_workers=8)
        ref = Estimator("auc", backend="numpy")
        M = 30
        got = np.asarray([
            est.incomplete(s1, s2, n_pairs=4000, seed=m, design=design)
            for m in range(M)
        ])
        want = np.asarray([
            ref.incomplete(s1, s2, n_pairs=4000, seed=m, design=design)
            for m in range(M)
        ])
        se = np.sqrt((got.var(ddof=1) + want.var(ddof=1)) / M) + 1e-7
        assert abs(got.mean() - want.mean()) < 5 * se, design
        assert abs(got.mean() - u_n) < 5 * got.std(ddof=1) / np.sqrt(M) + 1e-6

    def test_mesh_one_sample_swor(self):
        """One-sample (off-diagonal encoded) device designs on the mesh
        stay unbiased for the complete scatter statistic."""
        import jax

        if jax.device_count() < 8:
            pytest.skip("needs 8 virtual devices")
        rng = np.random.default_rng(7)
        A = rng.standard_normal((120, 3))
        u_n = Estimator("scatter", backend="numpy").complete(A)
        est = Estimator("scatter", backend="mesh", n_workers=8)
        vals = np.asarray([
            est.incomplete(A, n_pairs=3000, seed=m, design="swor")
            for m in range(20)
        ])
        se = vals.std(ddof=1) / np.sqrt(len(vals)) + 1e-7
        assert abs(vals.mean() - u_n) < 5 * se

    @pytest.mark.parametrize("design", ["swor", "bernoulli"])
    def test_triplet_designs_all_backends_agree(self, design):
        """The three-design matrix is complete for degree 3 [VERDICT r2
        next #4]: numpy draws on host, jax/mesh on device
        [VERDICT r4 next #6] — the same DESIGN, so Monte-Carlo means
        over seeds agree within joint SE."""
        import jax

        rng = np.random.default_rng(9)
        X = rng.standard_normal((48, 3))
        Y = rng.standard_normal((40, 3))
        M = 25
        npy = Estimator("triplet_indicator", backend="numpy")
        jx = Estimator("triplet_indicator", backend="jax")
        want = np.asarray([
            npy.incomplete(X, Y, n_pairs=900, seed=m, design=design)
            for m in range(M)
        ])
        got = np.asarray([
            jx.incomplete(X, Y, n_pairs=900, seed=m, design=design)
            for m in range(M)
        ])
        se = np.sqrt((got.var(ddof=1) + want.var(ddof=1)) / M) + 1e-7
        assert abs(got.mean() - want.mean()) < 5 * se, design
        if jax.device_count() >= 8:
            mesh = Estimator(
                "triplet_indicator", backend="mesh", n_workers=8,
            )
            got_m = np.asarray([
                mesh.incomplete(X, Y, n_pairs=900, seed=m, design=design)
                for m in range(M)
            ])
            se_m = np.sqrt((got_m.var(ddof=1) + want.var(ddof=1)) / M) + 1e-7
            assert abs(got_m.mean() - want.mean()) < 5 * se_m, design

    @pytest.mark.parametrize("design", ["swor", "bernoulli"])
    def test_device_host_inclusion_distribution_parity(self, design):
        """Sampler-level design-distribution parity [VERDICT r4 next
        #6]: on a 20x20 grid at B = G/4, the per-cell inclusion counts
        of the DEVICE sampler (ops.device_design) and the HOST oracle
        (parallel.partition) are both Binomial(M, B/G) — every cell
        equally likely under either implementation."""
        import jax
        import jax.numpy as jnp

        from tuplewise_tpu.ops.device_design import (
            draw_pair_design_device,
        )

        n1 = n2 = 20
        B, M = 100, 400
        p_cell = B / (n1 * n2)

        f = jax.jit(jax.vmap(
            lambda k: draw_pair_design_device(k, n1, n2, B, design)
        ))
        i_d, j_d, w_d = (np.asarray(x) for x in f(
            jax.vmap(jax.random.PRNGKey)(jnp.arange(M))
        ))
        counts_dev = np.zeros((n1, n2))
        counts_host = np.zeros((n1, n2))
        for t in range(M):
            sel = w_d[t] > 0
            counts_dev[i_d[t][sel], j_d[t][sel]] += 1
            ih, jh = draw_pair_design(
                np.random.default_rng(t), n1, n2, B, design
            )
            counts_host[ih, jh] += 1
        sd = np.sqrt(M * p_cell * (1 - p_cell))
        for name, counts in (("device", counts_dev),
                             ("host", counts_host)):
            # each sampler realizes ~B inclusions per draw on average;
            # bernoulli's size varies, so compare against the EMPIRICAL
            # per-cell mean (uniformity is the property under test)
            z = (counts - counts.mean()) / sd
            assert np.max(np.abs(z)) < 5.0, (name, np.max(np.abs(z)))
            # and the average inclusion rate matches B/G
            tot_sd = np.sqrt(M * B * (1 - p_cell))
            assert abs(counts.sum() - M * B) < 5 * tot_sd, name

    def test_triplet_swor_unbiased(self):
        """SWOR triplet sampling stays unbiased for the complete
        degree-3 statistic."""
        rng = np.random.default_rng(11)
        X = rng.standard_normal((30, 3))
        Y = rng.standard_normal((24, 3))
        est = Estimator("triplet_hinge", backend="numpy")
        u_n = est.complete(X, Y)
        vals = [est.incomplete(X, Y, n_pairs=2000, seed=m, design="swor")
                for m in range(40)]
        se = np.std(vals) / np.sqrt(len(vals)) + 1e-6
        assert abs(np.mean(vals) - u_n) < 5 * se

    def test_cpp_backend_inherits_designs(self, scores):
        from tuplewise_tpu.native import load_pair_lib

        if load_pair_lib() is None:
            pytest.skip("no native lib")
        s1, s2 = scores
        a = Estimator("auc", backend="numpy").incomplete(
            s1, s2, n_pairs=2000, seed=9, design="swor")
        b = Estimator("auc", backend="cpp").incomplete(
            s1, s2, n_pairs=2000, seed=9, design="swor")
        assert a == pytest.approx(b, rel=1e-12)
