"""Pairwise-SGD learner [SURVEY §1.3, §4.4]: gradient parity with the
analytic oracle, and end-to-end AUC improvement on both BASELINE-style
configs (Gaussians + Adult)."""

import numpy as np
import pytest

from tuplewise_tpu.data import load_adult, make_gaussians
from tuplewise_tpu.models.pairwise_sgd import (
    TrainConfig,
    split_by_label,
    evaluate_auc,
    train_pairwise,
    train_pairwise_numpy,
)
from tuplewise_tpu.models.scorers import LinearScorer, MLPScorer


@pytest.fixture(scope="module")
def gauss():
    X, Y = make_gaussians(1200, 1200, dim=5, separation=1.2, seed=3)
    return X, Y


class TestGradientParity:
    def test_one_step_matches_analytic_oracle(self, gauss):
        """One full-pair SGD step on a 1-chip mesh == the closed-form
        pairwise gradient step (exact modulo f32)."""
        Xp, Xn = gauss
        Xp, Xn = Xp[:300], Xn[:300]
        scorer = LinearScorer(dim=5)
        p0 = scorer.init(seed=1)
        cfg = TrainConfig(kernel="logistic", lr=0.5, steps=1,
                          n_workers=1, repartition_every=1, tile=128)
        p_mesh, _ = train_pairwise(scorer, dict(p0), Xp, Xn, cfg)
        p_np, _ = train_pairwise_numpy(scorer, dict(p0), Xp, Xn, cfg)
        np.testing.assert_allclose(p_mesh["w"], p_np["w"], rtol=2e-4, atol=1e-6)

    def test_multi_worker_multi_step_close_to_oracle(self, gauss):
        """Same schedule, 4 workers, 10 steps: trajectories use different
        PRNGs for partitioning, so compare final losses loosely."""
        Xp, Xn = gauss
        Xp, Xn = Xp[:400], Xn[:400]
        scorer = LinearScorer(dim=5)
        p0 = scorer.init(seed=1)
        cfg = TrainConfig(kernel="logistic", lr=0.3, steps=10,
                          n_workers=4, repartition_every=5, tile=128)
        p_mesh, h_mesh = train_pairwise(scorer, dict(p0), Xp, Xn, cfg)
        p_np, h_np = train_pairwise_numpy(scorer, dict(p0), Xp, Xn, cfg)
        assert abs(h_mesh["loss"][-1] - h_np["loss"][-1]) < 0.02


class TestEndToEnd:
    def test_gaussians_auc_improves(self, gauss):
        Xp, Xn = gauss
        scorer = LinearScorer(dim=5)
        p0 = scorer.init(seed=7)
        auc0 = evaluate_auc(scorer, p0, Xp, Xn)
        cfg = TrainConfig(kernel="logistic", lr=0.5, steps=60,
                          n_workers=8, repartition_every=10, tile=128)
        p1, hist = train_pairwise(scorer, dict(p0), Xp, Xn, cfg)
        auc1 = evaluate_auc(scorer, p1, Xp, Xn)
        assert hist["loss"][-1] < hist["loss"][0]
        assert auc1 > max(auc0, 0.75)

    def test_sampled_pairs_trains(self, gauss):
        """B sampled pairs per worker per step (the incomplete-gradient
        path of SURVEY §4.4) still learns."""
        Xp, Xn = gauss
        scorer = LinearScorer(dim=5)
        p0 = scorer.init(seed=7)
        cfg = TrainConfig(kernel="hinge", lr=0.2, steps=80,
                          n_workers=8, repartition_every=10,
                          pairs_per_worker=256, tile=128)
        p1, hist = train_pairwise(scorer, dict(p0), Xp, Xn, cfg)
        assert evaluate_auc(scorer, p1, Xp, Xn) > 0.75

    def test_adult_config(self):
        """BASELINE config 2: bipartite ranking on (surrogate) Adult."""
        X, y, meta = load_adult(n=4000, seed=0)
        Xp, Xn = split_by_label(X, y)
        scorer = LinearScorer(dim=X.shape[1])
        p0 = scorer.init(seed=0)
        auc0 = evaluate_auc(scorer, p0, Xp, Xn)
        cfg = TrainConfig(kernel="hinge", lr=0.3, steps=60,
                          n_workers=8, repartition_every=15, tile=128)
        p1, _ = train_pairwise(scorer, dict(p0), Xp, Xn, cfg)
        auc1 = evaluate_auc(scorer, p1, Xp, Xn)
        # surrogate Adult has deliberate nonlinear structure; a linear
        # scorer plateaus just under 0.8
        assert auc1 > max(auc0 + 0.05, 0.78)

    def test_ragged_sizes_train(self, gauss):
        """Regression: sizes not divisible by N are padded, with a random
        remainder sitting out each repartition (no fixed-tail exclusion)."""
        Xp, Xn = gauss
        Xp, Xn = Xp[:1001], Xn[:997]
        scorer = LinearScorer(dim=5)
        cfg = TrainConfig(kernel="logistic", lr=0.3, steps=20,
                          n_workers=8, repartition_every=5, tile=128)
        p1, hist = train_pairwise(scorer, scorer.init(0), Xp, Xn, cfg)
        assert np.isfinite(hist["loss"]).all()
        assert hist["loss"][-1] < hist["loss"][0]

    def test_mlp_scorer_trains(self, gauss):
        Xp, Xn = gauss
        scorer = MLPScorer(dim=5, hidden=16)
        p0 = scorer.init(seed=2)
        cfg = TrainConfig(kernel="logistic", lr=0.3, steps=60,
                          n_workers=8, repartition_every=10, tile=128)
        p1, _ = train_pairwise(scorer, dict(p0), Xp, Xn, cfg)
        assert evaluate_auc(scorer, p1, Xp, Xn) > 0.75


class TestLossFreeSteps:
    """cfg.loss_every > 1 [VERDICT r4 next #1]: the trajectory is
    IDENTICAL to per-step loss recording (gradients unchanged); only
    the loss history changes (NaN off the boundary)."""

    def test_trajectory_identical_and_nan_pattern(self, gauss):
        Xp, Xn = gauss
        Xp, Xn = Xp[:400], Xn[:400]
        scorer = LinearScorer(dim=5)
        p0 = scorer.init(seed=4)
        base = TrainConfig(kernel="hinge", lr=0.3, steps=12,
                           n_workers=4, repartition_every=5, tile=128)
        import dataclasses
        p_ref, h_ref = train_pairwise(scorer, dict(p0), Xp, Xn, base)
        p_lf, h_lf = train_pairwise(
            scorer, dict(p0), Xp, Xn,
            dataclasses.replace(base, loss_every=3),
        )
        np.testing.assert_allclose(p_ref["w"], p_lf["w"],
                                   rtol=1e-6, atol=1e-7)
        rec = np.arange(12) % 3 == 0
        assert np.isfinite(h_lf["loss"][rec]).all()
        assert np.isnan(h_lf["loss"][~rec]).all()
        np.testing.assert_allclose(h_ref["loss"][rec],
                                   h_lf["loss"][rec], rtol=1e-6)

    def test_chunked_run_reproduces_unchunked(self, gauss, tmp_path):
        """loss_every composes with checkpoint chunking: record is a
        function of the ABSOLUTE step, so chunk boundaries cannot shift
        which steps record."""
        Xp, Xn = gauss
        Xp, Xn = Xp[:320], Xn[:320]
        scorer = LinearScorer(dim=5)
        p0 = scorer.init(seed=5)
        import dataclasses
        cfg = TrainConfig(kernel="logistic", lr=0.3, steps=10,
                          n_workers=4, repartition_every=4, tile=128,
                          loss_every=4)
        p_a, h_a = train_pairwise(scorer, dict(p0), Xp, Xn, cfg)
        p_b, h_b = train_pairwise(
            scorer, dict(p0), Xp, Xn, cfg,
            checkpoint_path=str(tmp_path / "ck.npz"),
            checkpoint_every=3,
        )
        np.testing.assert_array_equal(p_a["w"], p_b["w"])
        np.testing.assert_array_equal(
            np.isnan(h_a["loss"]), np.isnan(h_b["loss"])
        )
        m = np.isfinite(h_a["loss"])
        np.testing.assert_array_equal(h_a["loss"][m], h_b["loss"][m])

    def test_budgeted_path_masks_only(self, gauss):
        """pairs_per_worker + loss_every: gradient path unchanged
        (loss is a byproduct there); history masking still applies."""
        Xp, Xn = gauss
        Xp, Xn = Xp[:256], Xn[:256]
        scorer = LinearScorer(dim=5)
        p0 = scorer.init(seed=6)
        import dataclasses
        base = TrainConfig(kernel="hinge", lr=0.2, steps=8,
                           n_workers=4, repartition_every=4,
                           pairs_per_worker=64, tile=128)
        p_ref, h_ref = train_pairwise(scorer, dict(p0), Xp, Xn, base)
        p_lf, h_lf = train_pairwise(
            scorer, dict(p0), Xp, Xn,
            dataclasses.replace(base, loss_every=2),
        )
        np.testing.assert_array_equal(p_ref["w"], p_lf["w"])
        rec = np.arange(8) % 2 == 0
        np.testing.assert_allclose(h_ref["loss"][rec], h_lf["loss"][rec])
        assert np.isnan(h_lf["loss"][~rec]).all()

    def test_sim_trainer_matches_mesh_with_loss_every(self, gauss):
        """The sim instrument honors loss_every too: same NaN record,
        same trajectory as its own loss_every=1 run."""
        import dataclasses

        from tuplewise_tpu.models.sim_learner import train_curves

        Xp, Xn = gauss
        Xp, Xn = Xp[:200], Xn[:200]
        scorer = LinearScorer(dim=5)
        p0 = scorer.init(seed=8)
        base = TrainConfig(kernel="hinge", lr=0.2, steps=6,
                           n_workers=4, repartition_every=3, tile=128)
        out_ref = train_curves(scorer, p0, Xp, Xn, Xp[:50], Xn[:50],
                               base, n_seeds=2, eval_every=6)
        out_lf = train_curves(scorer, p0, Xp, Xn, Xp[:50], Xn[:50],
                              dataclasses.replace(base, loss_every=2),
                              n_seeds=2, eval_every=6)
        np.testing.assert_array_equal(
            np.asarray(out_ref["final_params"]["w"]),
            np.asarray(out_lf["final_params"]["w"]),
        )
        rec = np.arange(6) % 2 == 0
        np.testing.assert_allclose(out_ref["loss"][:, rec],
                                   out_lf["loss"][:, rec])
        assert np.isnan(out_lf["loss"][:, ~rec]).all()


class TestAnalyticPairGradient:
    """diff_pair_mean's custom VJP (streamed g' row/col reductions)
    must match autodiff of the dense pair mean exactly."""

    @pytest.mark.parametrize("kname", ["hinge", "logistic"])
    def test_matches_dense_autodiff(self, kname):
        import jax
        import jax.numpy as jnp

        from tuplewise_tpu.ops import pair_tiles
        from tuplewise_tpu.ops.kernels import get_kernel

        k = get_kernel(kname)
        rng = np.random.default_rng(3)
        s1 = jnp.asarray(rng.standard_normal(70), jnp.float32)
        s2 = jnp.asarray(rng.standard_normal(90), jnp.float32)

        def dense(a, b):
            return jnp.mean(k.diff(a[:, None] - b[None, :], jnp))

        v0, (g1d, g2d) = jax.value_and_grad(dense, argnums=(0, 1))(s1, s2)
        v1, (g1s, g2s) = jax.value_and_grad(
            lambda a, b: pair_tiles.diff_pair_mean(k, a, b, 32, 32),
            argnums=(0, 1),
        )(s1, s2)
        assert abs(float(v0 - v1)) < 1e-6
        np.testing.assert_allclose(g1d, g1s, atol=1e-7)
        np.testing.assert_allclose(g2d, g2s, atol=1e-7)

    @pytest.mark.parametrize("kname", ["hinge", "logistic"])
    def test_pallas_grad_kernel_parity(self, kname):
        """The one-pass Pallas grad kernel (interpret mode) must match
        the XLA streamed pair_grad_sums on ragged sizes [VERDICT r3
        next #2]."""
        import jax.numpy as jnp

        from tuplewise_tpu.ops.kernels import get_kernel
        from tuplewise_tpu.ops.pair_tiles import pair_grad_sums
        from tuplewise_tpu.ops.pallas_pairs import pallas_pair_grad_sums

        k = get_kernel(kname)
        rng = np.random.default_rng(7)
        for n1, n2 in [(70, 90), (256, 512), (300, 517)]:
            s1 = jnp.asarray(rng.standard_normal(n1), jnp.float32)
            s2 = jnp.asarray(rng.standard_normal(n2), jnp.float32)
            rp, cp = pallas_pair_grad_sums(
                s1, s2, kernel=k, tile_a=256, tile_b=256, interpret=True
            )
            rx, cx = pair_grad_sums(k, s1, s2, tile_a=64, tile_b=64)
            np.testing.assert_allclose(rp, rx, rtol=2e-5, atol=1e-5)
            np.testing.assert_allclose(cp, cx, rtol=2e-5, atol=1e-5)

    def test_dispatch_env_override_routes_to_pallas(self, monkeypatch):
        """TUPLEWISE_HARNESS_PALLAS=interpret forces the Pallas grad
        fused Pallas branch of diff_pair_mean's VJP on CPU; it must
        still match dense autodiff through it end-to-end."""
        import jax
        import jax.numpy as jnp

        from tuplewise_tpu.ops import pair_tiles
        from tuplewise_tpu.ops.kernels import get_kernel

        monkeypatch.setenv("TUPLEWISE_HARNESS_PALLAS", "interpret")
        k = get_kernel("logistic")
        rng = np.random.default_rng(11)
        s1 = jnp.asarray(rng.standard_normal(130), jnp.float32)
        s2 = jnp.asarray(rng.standard_normal(70), jnp.float32)

        def dense(a, b):
            return jnp.mean(k.diff(a[:, None] - b[None, :], jnp))

        g1d, g2d = jax.grad(dense, argnums=(0, 1))(s1, s2)
        g1p, g2p = jax.grad(
            lambda a, b: pair_tiles.diff_pair_mean(k, a, b, 32, 32),
            argnums=(0, 1),
        )(s1, s2)
        np.testing.assert_allclose(g1d, g1p, atol=1e-7)
        np.testing.assert_allclose(g2d, g2p, atol=1e-7)

    def test_unfused_backward_takes_pallas_grad_kernel(self, monkeypatch):
        """When the fused kernel's n1 SMEM-cell bound rejects a shape,
        the backward still runs the one-pass Pallas grad kernel (its
        row output has no cell budget); gradients must match dense
        autodiff."""
        import jax
        import jax.numpy as jnp

        from tuplewise_tpu.ops import pair_tiles
        from tuplewise_tpu.ops.kernels import get_kernel

        monkeypatch.setenv("TUPLEWISE_HARNESS_PALLAS", "interpret")
        monkeypatch.setattr(
            pair_tiles, "_use_fused_pallas", lambda k, a, b: (False, True)
        )
        k = get_kernel("hinge")
        rng = np.random.default_rng(5)
        s1 = jnp.asarray(rng.standard_normal(90), jnp.float32)
        s2 = jnp.asarray(rng.standard_normal(110), jnp.float32)

        def dense(a, b):
            return jnp.mean(k.diff(a[:, None] - b[None, :], jnp))

        g1d, g2d = jax.grad(dense, argnums=(0, 1))(s1, s2)
        g1p, g2p = jax.grad(
            lambda a, b: pair_tiles.diff_pair_mean(k, a, b, 32, 32),
            argnums=(0, 1),
        )(s1, s2)
        np.testing.assert_allclose(g1d, g1p, atol=1e-7)
        np.testing.assert_allclose(g2d, g2p, atol=1e-7)

    @pytest.mark.parametrize("kname", ["hinge", "logistic"])
    def test_loss_free_vjp_matches_dense_autodiff(self, kname):
        """diff_pair_mean_loss_free: NaN value, gradient identical to
        diff_pair_mean's [VERDICT r4 next #1]."""
        import jax
        import jax.numpy as jnp

        from tuplewise_tpu.ops import pair_tiles
        from tuplewise_tpu.ops.kernels import get_kernel

        k = get_kernel(kname)
        rng = np.random.default_rng(13)
        s1 = jnp.asarray(rng.standard_normal(70), jnp.float32)
        s2 = jnp.asarray(rng.standard_normal(90), jnp.float32)

        def dense(a, b):
            return jnp.mean(k.diff(a[:, None] - b[None, :], jnp))

        g1d, g2d = jax.grad(dense, argnums=(0, 1))(s1, s2)
        v, (g1, g2) = jax.value_and_grad(
            lambda a, b: pair_tiles.diff_pair_mean_loss_free(
                k, a, b, 32, 32
            ),
            argnums=(0, 1),
        )(s1, s2)
        assert np.isnan(float(v))
        np.testing.assert_allclose(g1d, g1, atol=1e-7)
        np.testing.assert_allclose(g2d, g2, atol=1e-7)

    def test_loss_free_vjp_pallas_interpret(self, monkeypatch):
        """The loss-free forward routes to the one-pass Pallas grad
        kernel when Pallas serves; gradients still match dense."""
        import jax
        import jax.numpy as jnp

        from tuplewise_tpu.ops import pair_tiles
        from tuplewise_tpu.ops.kernels import get_kernel

        monkeypatch.setenv("TUPLEWISE_HARNESS_PALLAS", "interpret")
        k = get_kernel("hinge")
        rng = np.random.default_rng(17)
        s1 = jnp.asarray(rng.standard_normal(130), jnp.float32)
        s2 = jnp.asarray(rng.standard_normal(70), jnp.float32)

        def dense(a, b):
            return jnp.mean(k.diff(a[:, None] - b[None, :], jnp))

        g1d, g2d = jax.grad(dense, argnums=(0, 1))(s1, s2)
        g1, g2 = jax.grad(
            lambda a, b: pair_tiles.diff_pair_mean_loss_free(
                k, a, b, 32, 32
            ),
            argnums=(0, 1),
        )(s1, s2)
        np.testing.assert_allclose(g1d, g1, atol=1e-7)
        np.testing.assert_allclose(g2d, g2, atol=1e-7)

    def test_learner_uses_it_and_still_learns(self):
        """End-to-end: hinge training (analytic path) still lifts AUC."""
        from tuplewise_tpu.data import make_gaussians
        from tuplewise_tpu.models.pairwise_sgd import (
            TrainConfig, evaluate_auc, train_pairwise,
        )
        from tuplewise_tpu.models.scorers import LinearScorer

        Xp, Xn = make_gaussians(300, 300, dim=4, separation=1.0, seed=9)
        scorer = LinearScorer(dim=4)
        p0 = scorer.init(9)
        cfg = TrainConfig(kernel="hinge", lr=0.3, steps=60, n_workers=1,
                          repartition_every=20, seed=9, tile=128)
        params, hist = train_pairwise(scorer, p0, Xp, Xn, cfg)
        assert evaluate_auc(scorer, params, Xp, Xn) > \
            evaluate_auc(scorer, p0, Xp, Xn) + 0.05
        assert hist["loss"][-1] < hist["loss"][0]
