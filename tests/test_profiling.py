"""Profiling/tracing utilities [SURVEY §5.2]."""

import os

import numpy as np

import pytest

from tuplewise_tpu.utils.profiling import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    annotate,
    device_memory_stats,
    labeled_name,
    timer,
    trace,
)


def test_timer():
    with timer() as t:
        sum(range(1000))
    assert t["seconds"] is not None and t["seconds"] >= 0.0


def test_trace_none_is_noop():
    with trace(None):
        pass
    with trace(""):
        pass


def test_trace_writes_profile(tmp_path):
    import jax
    import jax.numpy as jnp

    d = str(tmp_path / "prof")
    with trace(d):
        with annotate("tiny-matmul"):
            jnp.dot(jnp.ones((8, 8)), jnp.ones((8, 8))).block_until_ready()
    found = []
    for root, _, files in os.walk(d):
        found += files
    assert found, f"no profile artifacts written under {d}"


def test_device_memory_stats_shape():
    stats = device_memory_stats()
    assert isinstance(stats, dict)  # may be empty on CPU


def test_harness_threads_trace_dir(tmp_path):
    from tuplewise_tpu.harness.variance import (
        VarianceConfig, run_variance_experiment,
    )

    d = str(tmp_path / "prof")
    cfg = VarianceConfig(kernel="auc", scheme="incomplete", backend="jax",
                         n_pos=128, n_neg=128, n_pairs=200, n_reps=3)
    res = run_variance_experiment(cfg, trace_dir=d)
    assert res["trace_dir"] == d
    assert np.isfinite(res["mean"])


class TestCounter:
    def test_inc_and_value(self):
        c = Counter("requests")
        c.inc()
        c.inc(41)
        assert c.value == 42
        assert c.snapshot() == {"type": "counter", "value": 42}

    def test_negative_inc_rejected(self):
        c = Counter("requests")
        with pytest.raises(ValueError, match="negative"):
            c.inc(-1)

    def test_thread_safety(self):
        import threading

        c = Counter("n")
        threads = [
            threading.Thread(target=lambda: [c.inc() for _ in range(1000)])
            for _ in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 8000


class TestHistogram:
    def test_observe_count_sum_minmax(self):
        h = Histogram("lat")
        for v in (0.001, 0.002, 0.01):
            h.observe(v)
        assert h.count == 3
        assert h.sum == pytest.approx(0.013)
        snap = h.snapshot()
        assert snap["min"] == 0.001 and snap["max"] == 0.01
        assert snap["mean"] == pytest.approx(0.013 / 3)
        assert sum(snap["buckets"].values()) == 3

    def test_quantiles_exact_on_small_samples(self):
        h = Histogram("q")
        for v in range(1, 101):
            h.observe(float(v))
        assert h.quantile(0.0) == 1.0
        assert h.quantile(1.0) == 100.0
        assert h.quantile(0.5) == pytest.approx(50.5)
        assert h.quantile(0.99) == pytest.approx(99.01)
        with pytest.raises(ValueError):
            h.quantile(1.5)

    def test_empty_quantile_is_none(self):
        h = Histogram("q")
        assert h.quantile(0.5) is None
        assert h.snapshot()["p99"] is None
        assert h.mean() is None

    def test_sample_window_bounds_memory(self):
        h = Histogram("q", max_samples=16)
        for v in range(1000):
            h.observe(float(v))
        assert h.count == 1000
        assert len(h._samples) == 16
        # the window holds the most recent values
        assert h.quantile(0.0) >= 984.0

    def test_bucket_edges(self):
        h = Histogram("b", buckets=[1.0, 10.0])
        for v in (0.5, 1.0, 5.0, 50.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["buckets"]["1.0"] == 2      # 0.5 and the exact 1.0
        assert snap["buckets"]["10.0"] == 1     # 5.0
        assert snap["buckets"]["+inf"] == 1     # 50.0


class TestGauge:
    def test_set_add_value(self):
        g = Gauge("queue_depth")
        g.set(5)
        g.add(3)
        g.add(-6)
        assert g.value == 2.0
        assert g.snapshot() == {"type": "gauge", "value": 2.0}

    def test_gauge_goes_negative(self):
        g = Gauge("drift")
        g.add(-4)
        assert g.value == -4.0

    def test_thread_safety(self):
        import threading

        g = Gauge("n")
        threads = [
            threading.Thread(target=lambda: [g.add(1) for _ in range(500)])
            for _ in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert g.value == 4000.0


class TestLabels:
    def test_labeled_name_canonical(self):
        assert labeled_name("m", None) == "m"
        assert labeled_name("m", {"b": 1, "a": "x"}) == "m{a=x,b=1}"

    def test_labels_in_snapshots(self):
        c = Counter("reqs", labels={"tenant": "t1"})
        c.inc()
        assert c.snapshot()["labels"] == {"tenant": "t1"}
        g = Gauge("depth", labels={"shard": 2})
        assert g.snapshot()["labels"] == {"shard": 2}
        h = Histogram("lat", labels={"stage": "wal"})
        h.observe(0.1)
        assert h.snapshot()["labels"] == {"stage": "wal"}

    def test_registry_keeps_label_series_distinct(self):
        r = MetricsRegistry()
        a = r.counter("reqs", labels={"tenant": "a"})
        b = r.counter("reqs", labels={"tenant": "b"})
        assert a is not b
        a.inc(2)
        b.inc(5)
        snap = r.snapshot()
        assert snap["reqs{tenant=a}"]["value"] == 2
        assert snap["reqs{tenant=b}"]["value"] == 5
        # create-or-return works per label set, for every metric type
        assert r.counter("reqs", labels={"tenant": "a"}) is a
        g = r.gauge("w", labels={"shard": 1})
        assert r.gauge("w", labels={"shard": 1}) is g
        h = r.histogram("h", labels={"s": 1})
        assert r.histogram("h", labels={"s": 1}) is h


class TestObserveN:
    def test_observe_n_matches_n_observes(self):
        a = Histogram("a")
        b = Histogram("b")
        a.observe_n(0.02, 7)
        for _ in range(7):
            b.observe(0.02)
        sa, sb = a.snapshot(), b.snapshot()
        for k in ("count", "sum", "min", "max", "p50", "p99"):
            assert sa[k] == sb[k], k
        assert sa["buckets"] == sb["buckets"]

    def test_observe_n_zero_is_noop_negative_raises(self):
        h = Histogram("h")
        h.observe_n(1.0, 0)
        assert h.count == 0
        with pytest.raises(ValueError, match="negative"):
            h.observe_n(1.0, -1)

    def test_observe_n_bounded_by_sample_window(self):
        h = Histogram("h", max_samples=8)
        h.observe_n(1.0, 1000)
        assert h.count == 1000
        assert len(h._samples) == 8


class TestMetricsRegistry:
    def test_create_or_return(self):
        r = MetricsRegistry()
        assert r.counter("a") is r.counter("a")
        assert r.histogram("h") is r.histogram("h")
        assert r.gauge("g") is r.gauge("g")

    def test_type_conflict_raises(self):
        r = MetricsRegistry()
        r.counter("x")
        with pytest.raises(TypeError, match="already registered"):
            r.histogram("x")

    def test_snapshot_all(self):
        r = MetricsRegistry()
        r.counter("c").inc(3)
        r.histogram("h").observe(0.5)
        snap = r.snapshot()
        assert snap["c"]["value"] == 3
        assert snap["h"]["count"] == 1


class TestLabeledNameRoundTrip:
    """[ISSUE 7 satellite] the `name{k=v}` registry-key codec: format
    and parse must invert each other — JSONL consumers (SLO engine,
    doctor, the future multi-tenant surface) group series by it."""

    @pytest.mark.parametrize("name,labels", [
        ("m", None),
        ("m", {"k": "v"}),
        ("insert_latency_s", {"tenant": "t42", "shard": "3"}),
        ("g", {"b": "2", "a": "1", "c": "0"}),      # sorted keys
    ])
    def test_round_trip(self, name, labels):
        from tuplewise_tpu.utils.profiling import (
            labeled_name, parse_labeled_name,
        )

        key = labeled_name(name, labels)
        back_name, back_labels = parse_labeled_name(key)
        assert back_name == name
        want = ({k: str(v) for k, v in labels.items()}
                if labels else None)
        assert back_labels == want
        # the codec is canonical: re-encoding parses back identically
        assert labeled_name(back_name, back_labels) == key

    def test_registry_keys_parse(self):
        from tuplewise_tpu.utils.profiling import parse_labeled_name

        r = MetricsRegistry()
        r.gauge("slo_breached", labels={"objective": "p99"}).set(1)
        r.counter("plain").inc()
        keys = sorted(r.snapshot())
        parsed = dict(parse_labeled_name(k) for k in keys)
        assert parsed["plain"] is None
        assert parsed["slo_breached"] == {"objective": "p99"}

    def test_malformed_label_raises(self):
        from tuplewise_tpu.utils.profiling import parse_labeled_name

        with pytest.raises(ValueError, match="malformed"):
            parse_labeled_name("m{novalue}")

    def test_braceless_value_passthrough(self):
        from tuplewise_tpu.utils.profiling import parse_labeled_name

        assert parse_labeled_name("m{a=1") == ("m{a=1", None)


class TestGaugeConcurrency:
    def test_concurrent_set_add_and_snapshot(self):
        """[ISSUE 7 satellite] a Gauge hammered by set/add from
        batcher-like and flusher-like threads must neither lose adds
        nor tear reads."""
        import threading

        g = Gauge("g")
        g.set(0.0)
        N = 2000
        seen = []
        stop = threading.Event()

        def adder(sign):
            for _ in range(N):
                g.add(sign)

        def reader():
            while not stop.is_set():
                v = g.value          # must never raise / tear
                seen.append(v)

        threads = [threading.Thread(target=adder, args=(+1,)),
                   threading.Thread(target=adder, args=(+1,)),
                   threading.Thread(target=adder, args=(-1,)),
                   threading.Thread(target=reader)]
        for t in threads:
            t.start()
        for t in threads[:3]:
            t.join()
        stop.set()
        threads[3].join()
        # two +N adders and one -N adder: every delta retained
        assert g.value == N
        assert all(isinstance(v, float) for v in seen)

    def test_interleaved_set_wins_are_last_write(self):
        import threading

        g = Gauge("depth")
        barrier = threading.Barrier(2)

        def setter(val):
            barrier.wait()
            for _ in range(1000):
                g.set(val)

        t1 = threading.Thread(target=setter, args=(3.0,))
        t2 = threading.Thread(target=setter, args=(7.0,))
        t1.start(); t2.start(); t1.join(); t2.join()
        assert g.value in (3.0, 7.0)   # a real write, never a tear
