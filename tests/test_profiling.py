"""Profiling/tracing utilities [SURVEY §5.2]."""

import os

import numpy as np

from tuplewise_tpu.utils.profiling import (
    annotate,
    device_memory_stats,
    timer,
    trace,
)


def test_timer():
    with timer() as t:
        sum(range(1000))
    assert t["seconds"] is not None and t["seconds"] >= 0.0


def test_trace_none_is_noop():
    with trace(None):
        pass
    with trace(""):
        pass


def test_trace_writes_profile(tmp_path):
    import jax
    import jax.numpy as jnp

    d = str(tmp_path / "prof")
    with trace(d):
        with annotate("tiny-matmul"):
            jnp.dot(jnp.ones((8, 8)), jnp.ones((8, 8))).block_until_ready()
    found = []
    for root, _, files in os.walk(d):
        found += files
    assert found, f"no profile artifacts written under {d}"


def test_device_memory_stats_shape():
    stats = device_memory_stats()
    assert isinstance(stats, dict)  # may be empty on CPU


def test_harness_threads_trace_dir(tmp_path):
    from tuplewise_tpu.harness.variance import (
        VarianceConfig, run_variance_experiment,
    )

    d = str(tmp_path / "prof")
    cfg = VarianceConfig(kernel="auc", scheme="incomplete", backend="jax",
                         n_pos=128, n_neg=128, n_pairs=200, n_reps=3)
    res = run_variance_experiment(cfg, trace_dir=d)
    assert res["trace_dir"] == d
    assert np.isfinite(res["mean"])
