"""Sampling profiler [ISSUE 14]: folded-stack capture, collapsed and
speedscope exports, and the <= 5% guarded-overhead throttle law."""

import json
import threading
import time

import pytest

from tuplewise_tpu.obs.prof import SamplingProfiler, export_profile
from tuplewise_tpu.utils.profiling import MetricsRegistry


def _busy(stop_ev):
    # a recognizable frame to find in the folded stacks
    while not stop_ev.wait(0.0005):
        sum(i * i for i in range(200))


class TestSampling:
    def test_captures_named_thread_stacks(self):
        stop_ev = threading.Event()
        t = threading.Thread(target=_busy, args=(stop_ev,),
                             name="busy-victim", daemon=True)
        t.start()
        try:
            prof = SamplingProfiler(hz=500.0)
            with prof:
                time.sleep(0.15)
        finally:
            stop_ev.set()
            t.join()
        folded = prof.folded()
        assert prof.samples > 0 and folded
        stacks = list(folded)
        # root frame is the thread name; the victim appears
        assert any(st[0] == "thread:busy-victim" for st in stacks)
        assert any("test_prof.py:_busy" in fr
                   for st in stacks for fr in st)
        # the sampler never samples itself
        assert not any(st[0] == "thread:tuplewise-prof"
                       for st in stacks)

    def test_hard_off_without_start(self):
        prof = SamplingProfiler()
        time.sleep(0.02)
        assert prof.samples == 0 and not prof.folded()
        assert prof.overhead_fraction() == 0.0

    def test_validation(self):
        with pytest.raises(ValueError):
            SamplingProfiler(hz=0)
        with pytest.raises(ValueError):
            SamplingProfiler(max_overhead=0.0)


class TestOverheadGuard:
    def test_throttle_doubles_interval_past_cap(self):
        prof = SamplingProfiler(hz=100.0, max_overhead=0.05)
        i0 = prof._interval
        # a sample costing 10x the cap must throttle
        prof._note_cost(10 * prof.max_overhead * i0)
        assert prof._interval == pytest.approx(2 * i0)
        assert prof.throttles == 1

    def test_cheap_samples_do_not_throttle(self):
        prof = SamplingProfiler(hz=100.0, max_overhead=0.05)
        i0 = prof._interval
        for _ in range(20):
            prof._note_cost(0.1 * prof.max_overhead * i0)
        assert prof._interval == i0 and prof.throttles == 0

    def test_interval_capped_at_one_second(self):
        prof = SamplingProfiler(hz=2.0, max_overhead=0.01)
        for _ in range(10):
            prof._note_cost(10.0)
        assert prof._interval == 1.0

    def test_metrics_exported(self):
        reg = MetricsRegistry()
        prof = SamplingProfiler(hz=1000.0, metrics=reg)
        prof.sample_once()
        prof._note_cost(1.0)   # force a throttle
        snap = reg.snapshot()
        assert snap["prof_samples_total"]["value"] == 1
        assert snap["prof_throttles_total"]["value"] == 1
        assert "prof_overhead_fraction" in snap


class TestExports:
    @pytest.fixture()
    def sampled(self):
        stop_ev = threading.Event()
        t = threading.Thread(target=_busy, args=(stop_ev,),
                             name="export-victim", daemon=True)
        t.start()
        prof = SamplingProfiler(hz=500.0)
        with prof:
            time.sleep(0.1)
        stop_ev.set()
        t.join()
        assert prof.folded()
        return prof

    def test_collapsed_roundtrip(self, sampled, tmp_path):
        p = str(tmp_path / "prof.collapsed")
        n = sampled.export_collapsed(p)
        assert n == len(sampled.folded())
        from scripts.trace_summary import load_collapsed

        back = dict(load_collapsed(p))
        assert back == {tuple(k): v for k, v in sampled.folded().items()}

    def test_speedscope_schema(self, sampled, tmp_path):
        p = str(tmp_path / "prof.speedscope.json")
        n = sampled.export_speedscope(p)
        with open(p, "r", encoding="utf-8") as f:
            doc = json.load(f)
        assert "speedscope" in doc["$schema"]
        prof = doc["profiles"][0]
        assert prof["type"] == "sampled"
        assert len(prof["samples"]) == n == len(prof["weights"])
        nf = len(doc["shared"]["frames"])
        assert all(0 <= i < nf for s in prof["samples"] for i in s)
        assert sum(prof["weights"]) == pytest.approx(
            prof["endValue"], abs=1e-9)

    def test_export_profile_suffix_dispatch(self, sampled, tmp_path):
        c = str(tmp_path / "x.collapsed")
        s = str(tmp_path / "x.speedscope.json")
        assert export_profile(sampled, c) == c
        assert export_profile(sampled, s) == s
        assert export_profile(None, c) is None
        assert export_profile(sampled, None) is None
        with open(c, encoding="utf-8") as f:
            line = f.readline().strip()
        stack, _, count = line.rpartition(" ")
        assert ";" in stack and int(count) >= 1
