"""Micro-batch engine: ordering, coalescing, backpressure, metrics."""

import threading
import time

import numpy as np
import pytest

from tuplewise_tpu.serving import (
    BackpressureError, MicroBatchEngine, ServingConfig,
)
from tuplewise_tpu.serving.replay import make_stream, replay


def _cfg(**kw):
    kw.setdefault("engine", "numpy")   # host counting: fast, no compiles
    kw.setdefault("policy", "block")
    return ServingConfig(**kw)


class TestRequestPath:
    def test_insert_then_query_sees_events(self):
        with MicroBatchEngine(_cfg()) as eng:
            eng.insert([1.0, 2.0, 0.5], [1, 1, 0]).result(10)
            snap = eng.query().result(10)
        assert snap["index"]["n_events"] == 3
        assert snap["auc_exact"] == 1.0

    def test_score_matches_index(self):
        scores, labels = make_stream(400, seed=1)
        with MicroBatchEngine(_cfg()) as eng:
            eng.insert(scores, labels).result(10)
            ranks = eng.score([0.0, 1.0]).result(10)
            direct = eng.index.score_batch([0.0, 1.0])
        np.testing.assert_allclose(ranks, direct, atol=0)

    def test_coalescing_preserves_kind_order(self):
        # a query issued AFTER an insert must observe it, even when both
        # land in the same micro-batch
        with MicroBatchEngine(_cfg(flush_timeout_s=0.05,
                                   max_batch=64)) as eng:
            futs = []
            for i in range(10):
                futs.append(eng.insert([float(i)], [i % 2]))
                futs.append(eng.query())
            results = [f.result(10) for f in futs]
        for i in range(10):
            snap = results[2 * i + 1]
            assert snap["index"]["n_events"] >= i + 1

    def test_runs_split_consecutive_kinds(self):
        reqs = []

        class R:
            def __init__(self, kind):
                self.kind = kind
        for k in ("insert", "insert", "score", "query", "query", "insert"):
            reqs.append(R(k))
        runs = MicroBatchEngine._runs(reqs)
        assert [(k, len(rs)) for k, rs in runs] == [
            ("insert", 2), ("score", 1), ("query", 2), ("insert", 1)]

    def test_non_auc_kernel_has_no_index(self):
        with MicroBatchEngine(_cfg(kernel="hinge")) as eng:
            eng.insert([1.0, 0.0], [1, 0]).result(10)
            with pytest.raises(ValueError, match="exact AUC index"):
                eng.score([0.5]).result(10)
            snap = eng.query().result(10)
        assert "index" not in snap
        assert "estimate_incomplete" in snap

    def test_close_idempotent_and_rejects_after(self):
        eng = MicroBatchEngine(_cfg())
        eng.close()
        eng.close()
        with pytest.raises(RuntimeError, match="closed"):
            eng.insert([1.0], [1])


class TestBackpressure:
    def _stalled_engine(self, **kw):
        """Engine whose batcher is busy long enough to fill the queue."""
        eng = MicroBatchEngine(_cfg(**kw))
        orig = eng._apply_inserts
        release = threading.Event()

        def slow(run):
            release.wait(timeout=10.0)
            orig(run)
        eng._apply_inserts = slow
        return eng, release

    def test_reject_policy_raises_and_counts(self):
        eng, release = self._stalled_engine(policy="reject", queue_size=4,
                                            max_batch=1,
                                            flush_timeout_s=0.0)
        try:
            eng.insert([0.0], [0])          # occupies the batcher
            time.sleep(0.05)                # let the batcher pick it up
            ok, rejected = 0, 0
            for i in range(20):
                try:
                    eng.insert([float(i)], [i % 2])
                    ok += 1
                except BackpressureError:
                    rejected += 1
            assert rejected > 0
            assert eng.metrics.snapshot()["rejected_total"]["value"] \
                == rejected
        finally:
            release.set()
            eng.close()

    def test_drop_oldest_fails_stale_future(self):
        eng, release = self._stalled_engine(policy="drop_oldest",
                                            queue_size=2, max_batch=1,
                                            flush_timeout_s=0.0)
        try:
            first = eng.insert([0.0], [0])
            time.sleep(0.05)
            futs = [eng.insert([float(i)], [i % 2]) for i in range(8)]
            release.set()
            outcomes = []
            for f in futs:
                try:
                    f.result(10)
                    outcomes.append("ok")
                except BackpressureError:
                    outcomes.append("dropped")
            assert "dropped" in outcomes
            assert outcomes.count("ok") >= 1
            assert first.result(10) == 1
            assert eng.metrics.snapshot()["dropped_total"]["value"] \
                == outcomes.count("dropped")
        finally:
            release.set()
            eng.close()


class TestReplayHarness:
    def test_replay_reports_and_parity(self):
        scores, labels = make_stream(1200, seed=6)
        rec = replay(scores, labels, config=_cfg(max_batch=64,
                                                 flush_timeout_s=0.001))
        assert rec["events_applied"] == 1200
        assert rec["events_per_s"] > 0
        assert rec["latency_p99_ms"] is not None
        assert rec["auc_abs_err"] < 1e-6
        assert rec["batches"] >= 1200 / 64

    def test_replay_windowed_parity(self):
        scores, labels = make_stream(900, seed=8)
        rec = replay(scores, labels,
                     config=_cfg(window=250, max_batch=32,
                                 flush_timeout_s=0.001))
        assert rec["auc_abs_err"] < 1e-6
        assert rec["index"]["n_evicted"] == 900 - 250

    def test_replay_mixed_workload(self):
        scores, labels = make_stream(600, seed=9)
        rec = replay(scores, labels, config=_cfg(max_batch=32),
                     score_every=5, query_every=7)
        assert rec["events_applied"] == 600
        assert rec["auc_abs_err"] < 1e-6

    def test_metrics_snapshot_shape(self):
        scores, labels = make_stream(300, seed=10)
        with MicroBatchEngine(_cfg(max_batch=16)) as eng:
            for i in range(0, 300, 3):
                eng.insert(scores[i:i + 3], labels[i:i + 3])
            snap = eng.flush()
        m = snap["metrics"]
        assert m["events_total"]["value"] == 300
        assert m["batches_total"]["value"] >= 1
        assert m["request_latency_s"]["count"] >= 100
        assert 0 < m["batch_fill"]["mean"] <= 1.0
        assert m["incomplete_pairs_total"]["value"] > 0
