"""Host-tax wave ledger [ISSUE 14]: bucket tiling invariant, compile
first-seen classification, GC attribution, device sections, tail
exemplars, and the engine-integrated coverage == 1.0 contract."""

import gc
import time

import numpy as np
import pytest

from tuplewise_tpu.obs import ledger as ledger_mod
from tuplewise_tpu.obs.ledger import (
    BUCKETS, WaveLedger, device_section, reset_seen,
)
from tuplewise_tpu.obs.report import (
    HOST_TAX_BUCKETS, host_tax_block, host_tax_metric,
)
from tuplewise_tpu.utils.profiling import MetricsRegistry


def _bucket_sums(snap):
    return {b: snap.get(host_tax_metric(b), {}).get("sum", 0.0)
            for b in BUCKETS}


class TestWaveLedger:
    def test_bucket_taxonomy_matches_report(self):
        # one taxonomy, two modules — they can never drift
        assert BUCKETS == HOST_TAX_BUCKETS

    def test_tiling_exact_no_device_work(self):
        reg = MetricsRegistry()
        led = WaveLedger(reg)
        w = led.begin_wave()
        t0 = time.perf_counter()
        time.sleep(0.002)
        t1 = time.perf_counter()
        buckets = led.finish_wave(w, t_start=t0, t_end=t1,
                                  queue_waits=[0.001, 0.003])
        snap = reg.snapshot()
        sums = _bucket_sums(snap)
        total = sum(sums.values())
        # 2 requests bill the wave window each + their queue waits
        expect = 0.001 + 0.003 + 2 * (t1 - t0)
        assert total == pytest.approx(expect, rel=1e-9)
        # everything but queue_wait landed in host_python
        assert sums["host_python"] == pytest.approx(2 * (t1 - t0),
                                                    rel=1e-9)
        assert buckets["dispatch"] == 0.0
        assert buckets["device_compute"] == 0.0

    def test_lock_wait_split_out(self):
        reg = MetricsRegistry()
        led = WaveLedger(reg)
        w = led.begin_wave()
        t0 = time.perf_counter()
        t_req = time.perf_counter()
        time.sleep(0.002)
        t_lock = time.perf_counter()
        time.sleep(0.001)
        t1 = time.perf_counter()
        led.finish_wave(w, t_start=t0, t_end=t1, queue_waits=[0.0],
                        t_lock_req=t_req, t_lock=t_lock)
        sums = _bucket_sums(reg.snapshot())
        assert sums["lock_wait"] == pytest.approx(t_lock - t_req,
                                                  rel=1e-9)
        assert sum(sums.values()) == pytest.approx(t1 - t0, rel=1e-9)

    def test_device_section_first_seen_is_compile(self):
        reset_seen()
        reg = MetricsRegistry()
        led = WaveLedger(reg)
        w = led.begin_wave()
        t0 = time.perf_counter()
        with device_section(("test_fn", 256, 256)) as ds:
            time.sleep(0.001)
            ds.dispatched()
            time.sleep(0.001)
        with device_section(("test_fn", 256, 256)) as ds:
            time.sleep(0.001)
            ds.dispatched()
        t1 = time.perf_counter()
        led.finish_wave(w, t_start=t0, t_end=t1, queue_waits=[0.0])
        snap = reg.snapshot()
        # first key occurrence billed compile, second dispatch
        assert snap["xla_compile_events_total"]["value"] == 1
        sums = _bucket_sums(snap)
        assert sums["xla_compile"] > 0
        assert sums["dispatch"] > 0
        assert sums["device_compute"] > 0
        assert sum(sums.values()) == pytest.approx(t1 - t0, rel=1e-9)

    def test_device_section_offwave_is_noop(self):
        reset_seen()
        reg = MetricsRegistry()
        WaveLedger(reg)   # no wave begun on this thread
        with device_section(("offwave", 1)) as ds:
            ds.dispatched()
        snap = reg.snapshot()
        assert snap["xla_compile_events_total"]["value"] == 0
        # the key was NOT consumed: a later on-wave dispatch of the
        # same key still classifies as its first (compiling) call
        assert ledger_mod._note_key(("offwave", 1)) is True

    def test_gc_pause_attributed_and_tiled(self):
        reg = MetricsRegistry()
        led = WaveLedger(reg)
        w = led.begin_wave()
        t0 = time.perf_counter()
        gc.collect()
        t1 = time.perf_counter()
        led.finish_wave(w, t_start=t0, t_end=t1, queue_waits=[0.0])
        snap = reg.snapshot()
        assert snap["gc_pauses_total"]["value"] >= 1
        assert snap["gc_pause_s"]["count"] >= 1
        sums = _bucket_sums(snap)
        assert sums["gc_pause"] >= 0.0
        assert sum(sums.values()) == pytest.approx(t1 - t0, rel=1e-9)

    def test_gc_outside_wave_not_recorded(self):
        reg = MetricsRegistry()
        WaveLedger(reg)
        gc.collect()
        assert reg.snapshot()["gc_pauses_total"]["value"] == 0

    def test_abort_wave_clears_binding(self):
        reg = MetricsRegistry()
        led = WaveLedger(reg)
        w = led.begin_wave()
        led.abort_wave(w)
        with device_section(("aborted", 1)) as ds:
            ds.dispatched()
        assert reg.snapshot()["host_tax_waves_total"]["value"] == 0

    def test_fraction_gauges_partition(self):
        reset_seen()
        reg = MetricsRegistry()
        led = WaveLedger(reg)
        w = led.begin_wave()
        t0 = time.perf_counter()
        with device_section(("frac", 1)) as ds:
            ds.dispatched()
            time.sleep(0.002)
        t1 = time.perf_counter()
        led.finish_wave(w, t_start=t0, t_end=t1, queue_waits=[0.0])
        snap = reg.snapshot()
        host = snap["host_tax_host_fraction"]["value"]
        dev = snap["host_tax_device_fraction"]["value"]
        assert 0.0 <= host <= 1.0 and 0.0 <= dev <= 1.0
        # host + device + compile fractions tile 1 (compile here is
        # the first-seen "frac" key's dispatch interval, ~0)
        assert host + dev <= 1.0 + 1e-9
        assert dev > 0.0


class TestEngineIntegration:
    def test_coverage_exactly_one_and_exemplars(self):
        from tuplewise_tpu.serving import (
            MicroBatchEngine, ServingConfig,
        )

        reset_seen()
        rng = np.random.default_rng(0)
        cfg = ServingConfig(policy="block", compact_every=64,
                            engine="numpy", tail_exemplar_ms=1e-4)
        with MicroBatchEngine(cfg) as eng:
            for i in range(40):
                eng.insert(rng.standard_normal(8),
                           rng.random(8) < 0.5)
            eng.flush()
            snap = eng.metrics.snapshot()
            flight = eng.flight
            ht = host_tax_block(snap)
            assert ht is not None
            assert ht["coverage"] == pytest.approx(1.0, abs=1e-6)
            assert ht["waves"] >= 1
            # threshold of 0.1us means every insert is an exemplar
            exemplars = flight.events("tail_exemplar")
            assert exemplars
            ev = exemplars[0]
            assert ev["lat_ms"] >= 1e-4
            # the exemplar carries the FULL ledger: every bucket,
            # including its own per-request queue_wait
            assert set(ev["buckets"]) == set(BUCKETS)
        assert snap["tail_exemplars_total"]["value"] == len(exemplars)

    def test_no_exemplars_without_threshold(self):
        from tuplewise_tpu.serving import (
            MicroBatchEngine, ServingConfig,
        )

        cfg = ServingConfig(policy="block", engine="numpy")
        with MicroBatchEngine(cfg) as eng:
            eng.insert([1.0, -1.0], [True, False]).result(10)
            eng.flush()
            assert not eng.flight.events("tail_exemplar")
            assert eng.metrics.snapshot()[
                "tail_exemplars_total"]["value"] == 0

    def test_jax_engine_compile_events_and_coverage(self):
        from tuplewise_tpu.serving import (
            MicroBatchEngine, ServingConfig,
        )

        reset_seen()
        rng = np.random.default_rng(1)
        cfg = ServingConfig(policy="block", compact_every=128)
        with MicroBatchEngine(cfg) as eng:
            for _ in range(10):
                eng.insert(rng.standard_normal(64),
                           rng.random(64) < 0.5)
            eng.flush()
            snap = eng.metrics.snapshot()
        ht = host_tax_block(snap)
        assert ht["coverage"] == pytest.approx(1.0, abs=1e-6)
        # the bucket ladder compiled at least one count shape inside
        # the waves — the first-call events the ledger must see
        assert snap["xla_compile_events_total"]["value"] >= 1
        sums = _bucket_sums(snap)
        assert sums["xla_compile"] > 0

    def test_validation_rejects_bad_threshold(self):
        from tuplewise_tpu.serving import ServingConfig

        with pytest.raises(ValueError):
            ServingConfig(tail_exemplar_ms=0.0)

    def test_fleet_ledger_coverage(self):
        from tuplewise_tpu.serving import (
            MultiTenantEngine, ServingConfig, TenancyConfig,
        )

        reset_seen()
        rng = np.random.default_rng(2)
        cfg = ServingConfig(policy="block", compact_every=128,
                            tail_exemplar_ms=1e-4)
        with MultiTenantEngine(cfg, TenancyConfig()) as eng:
            for i in range(12):
                eng.insert(f"t{i % 3}", rng.standard_normal(16),
                           rng.random(16) < 0.5)
            eng.flush()
            snap = eng.metrics.snapshot()
            exemplars = eng.flight.events("tail_exemplar")
        ht = host_tax_block(snap)
        assert ht is not None
        assert ht["coverage"] == pytest.approx(1.0, abs=1e-6)
        # fleet exemplars carry the owning tenant
        assert exemplars and all("tenant" in e for e in exemplars)


class TestConfigDigestCompat:
    def test_tail_exemplar_default_keeps_digest(self):
        # additive-config contract [ISSUE 10 satellite]: the new field
        # at its default must not orphan committed perf-gate history
        from tuplewise_tpu.obs.metrics_export import config_digest
        from tuplewise_tpu.serving import ServingConfig

        base = config_digest(ServingConfig())
        assert config_digest(
            ServingConfig(tail_exemplar_ms=None)) == base
        assert config_digest(
            ServingConfig(tail_exemplar_ms=5.0)) != base
